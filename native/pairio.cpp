// Native pair-corpus reader: tokenize + vocab count + encode in one pass
// over mmap'd files.
//
// TPU-native replacement for the corpus-ingest work the reference delegates
// to gensim's Python/Cython loader (src/gene2vec.py:30-47 reads every file
// into a Python list of 2-element lists — hundreds of millions of Python
// objects at full-corpus scale). Here the host-side runtime cost is one
// byte scan per file; the output is the (N, 2) int32 pair array that goes
// straight to the device.
//
// Behavior contract (must match gene2vec_tpu/io/pair_reader.py exactly):
//   * tokens are maximal runs of non-whitespace bytes (Python str.split());
//   * every token of every non-empty line counts toward the vocab;
//   * only lines with exactly 2 tokens yield a pair;
//   * vocab ids are assigned by count descending, ties by first appearance
//     (stable sort — gensim's ordering, io/vocab.py);
//   * min_count filters tokens, dropping pairs with a filtered member;
//   * bytes are treated as windows-1252 (single-byte, order-preserving —
//     the Python wrapper decodes token bytes with that codec).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct TokenInfo {
  int64_t count = 0;
  int32_t first_appearance = -1;
};

inline bool is_space(unsigned char c) {
  // Python str.split() splits on unicode whitespace. For windows-1252 input
  // that is ASCII whitespace, the 0x1C-0x1F separator controls, and 0xA0
  // (NBSP). NOT 0x85: cp1252 decodes it to U+2026 "...", a printable char.
  return c == ' ' || (c >= '\t' && c <= '\r') || (c >= 0x1C && c <= 0x1F) ||
         c == 0xA0;
}

struct MappedFile {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open_file(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      ::close(fd);
      fd = -1;  // destructor must not close it again
      return false;
    }
    size = static_cast<size_t>(st.st_size);
    if (size == 0) {
      data = nullptr;
      return true;
    }
    void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) {
      ::close(fd);
      fd = -1;
      return false;
    }
    data = static_cast<const char*>(p);
    return true;
  }

  ~MappedFile() {
    if (data) munmap(const_cast<char*>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

}  // namespace

extern "C" {

// Bump on ANY change to PairioResult's layout or pairio_load_files'
// signature.  The Python wrapper refuses to call a library reporting a
// different version (a stale .so dlopened across an ABI change would
// misread arguments — e.g. a flag landing where a pointer used to be).
enum { PAIRIO_ABI_VERSION = 2 };

int64_t pairio_abi_version(void) { return PAIRIO_ABI_VERSION; }

struct PairioResult {
  int64_t num_pairs = 0;
  int32_t* pairs = nullptr;      // 2 * num_pairs, row-major
  int64_t vocab_size = 0;
  int64_t* counts = nullptr;     // vocab_size, id order
  char* tokens = nullptr;        // '\n'-joined token bytes, id order
  int64_t tokens_len = 0;
  // set when strict_cp1252 rejects a byte (return code -3)
  int32_t err_file = -1;         // index into `paths`
  int64_t err_offset = -1;       // byte offset within that file
  uint8_t err_byte = 0;
};

// Returns 0 on success, negative on error (-1 io, -2 alloc, -3 a byte
// undefined in cp1252 under strict_cp1252 — position in err_file/
// err_offset/err_byte).
int pairio_load_files(const char** paths, int32_t n_paths, int64_t min_count,
                      int32_t strict_cp1252, PairioResult* out) {
  std::unordered_map<std::string_view, TokenInfo> table;
  std::vector<std::string_view> by_first;           // first-appearance order
  std::vector<std::pair<int32_t, int32_t>> raw_pairs;  // first-appearance ids
  std::vector<MappedFile> files(n_paths);

  for (int32_t f = 0; f < n_paths; ++f) {
    if (!files[f].open_file(paths[f])) return -1;
    const char* p = files[f].data;
    const char* end = p + files[f].size;
    while (p < end) {
      // one line
      const char* line_end = static_cast<const char*>(
          memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!line_end) line_end = end;
      int32_t ids[2];
      int n_tok = 0;
      const char* q = p;
      while (q < line_end) {
        while (q < line_end && is_space(static_cast<unsigned char>(*q))) ++q;
        if (q == line_end) break;
        const char* tok_start = q;
        while (q < line_end && !is_space(static_cast<unsigned char>(*q))) {
          // cp1252 leaves exactly these five bytes undefined; Python's
          // strict decoder raises on them anywhere in a file.  Checking
          // during the token scan keeps the native path behavior-identical
          // without the wrapper's former extra full-file pre-pass (every
          // non-whitespace byte lands in a token, and none of the five is
          // whitespace, so token bytes cover them).
          const unsigned char c = static_cast<unsigned char>(*q);
          if (strict_cp1252 &&
              (c == 0x81 || c == 0x8D || c == 0x8F || c == 0x90 ||
               c == 0x9D)) {
            out->err_file = f;
            out->err_offset = static_cast<int64_t>(q - files[f].data);
            out->err_byte = c;
            return -3;
          }
          ++q;
        }
        std::string_view tok(tok_start, static_cast<size_t>(q - tok_start));
        auto it = table.find(tok);
        if (it == table.end()) {
          TokenInfo info;
          info.count = 1;
          info.first_appearance = static_cast<int32_t>(by_first.size());
          it = table.emplace(tok, info).first;
          by_first.push_back(tok);
        } else {
          ++it->second.count;
        }
        if (n_tok < 2) ids[n_tok] = it->second.first_appearance;
        ++n_tok;
      }
      if (n_tok == 2) raw_pairs.emplace_back(ids[0], ids[1]);
      p = (line_end < end) ? line_end + 1 : end;
    }
  }

  const int64_t n_all = static_cast<int64_t>(by_first.size());
  // order: count desc, first appearance asc (stable tie-break)
  std::vector<int32_t> order(static_cast<size_t>(n_all));
  for (int64_t i = 0; i < n_all; ++i) order[static_cast<size_t>(i)] = static_cast<int32_t>(i);
  std::stable_sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    return table[by_first[static_cast<size_t>(a)]].count >
           table[by_first[static_cast<size_t>(b)]].count;
  });

  std::vector<int32_t> id_of(static_cast<size_t>(n_all), -1);
  int64_t vocab_size = 0;
  size_t tokens_bytes = 0;
  for (int32_t fa : order) {
    const auto& info = table[by_first[static_cast<size_t>(fa)]];
    if (info.count < min_count) break;  // sorted: all later are rarer
    id_of[static_cast<size_t>(fa)] = static_cast<int32_t>(vocab_size++);
    tokens_bytes += by_first[static_cast<size_t>(fa)].size() + 1;
  }

  out->vocab_size = vocab_size;
  out->counts = static_cast<int64_t*>(malloc(sizeof(int64_t) * static_cast<size_t>(vocab_size ? vocab_size : 1)));
  // +1: NUL-terminate the blob.  tokens_len excludes the terminator; the
  // Python wrapper reads length-bounded, but a terminator keeps any
  // C-string consumer (and ASAN's string interceptors) inside the
  // allocation.
  out->tokens = static_cast<char*>(malloc(tokens_bytes + 1));
  if (!out->counts || !out->tokens) return -2;
  char* tp = out->tokens;
  for (int64_t i = 0; i < vocab_size; ++i) {
    std::string_view tok = by_first[static_cast<size_t>(order[static_cast<size_t>(i)])];
    out->counts[i] = table[tok].count;
    memcpy(tp, tok.data(), tok.size());
    tp += tok.size();
    *tp++ = '\n';
  }
  out->tokens_len = static_cast<int64_t>(tp - out->tokens);
  *tp = '\0';

  // encode pairs, dropping any with a filtered token
  out->pairs = static_cast<int32_t*>(
      malloc(sizeof(int32_t) * 2 * (raw_pairs.size() ? raw_pairs.size() : 1)));
  if (!out->pairs) return -2;
  int64_t np = 0;
  for (const auto& pr : raw_pairs) {
    int32_t a = id_of[static_cast<size_t>(pr.first)];
    int32_t b = id_of[static_cast<size_t>(pr.second)];
    if (a >= 0 && b >= 0) {
      out->pairs[2 * np] = a;
      out->pairs[2 * np + 1] = b;
      ++np;
    }
  }
  out->num_pairs = np;
  return 0;
}

void pairio_free(PairioResult* r) {
  if (!r) return;
  free(r->pairs);
  free(r->counts);
  free(r->tokens);
  r->pairs = nullptr;
  r->counts = nullptr;
  r->tokens = nullptr;
  r->num_pairs = r->vocab_size = r->tokens_len = 0;
}

}  // extern "C"
