// Hogwild SGNS CPU oracle — the measured stand-in for gensim's Cython
// kernel (the engine behind src/gene2vec.py:70,87: 32 lock-free threads,
// negative-sampling table, linear alpha decay, classic word2vec exp table).
//
// This is the framework's honest CPU baseline: bench.py divides the TPU
// rate by this kernel's rate, so it must be a competent multithreaded
// implementation, not a strawman. Matches word2vec semantics:
//   * per (center, context) example (both directions of each pair),
//     k negatives drawn from unigram^0.75 via a Vose alias table;
//   * a negative equal to the positive target is skipped;
//   * lock-free (racy-by-design) SGD updates shared tables — Hogwild;
//   * learning rate decays linearly with global progress.
//
// C ABI for ctypes; built by native/Makefile.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kExpTableSize = 1024;
constexpr float kMaxExp = 6.0f;

struct ExpTable {
  float sigmoid[kExpTableSize];
  float logsig[kExpTableSize];  // log(sigmoid(x)) for loss reporting
  ExpTable() {
    for (int i = 0; i < kExpTableSize; ++i) {
      float x = (2.0f * i / kExpTableSize - 1.0f) * kMaxExp;
      float e = std::exp(x);
      sigmoid[i] = e / (e + 1.0f);
      logsig[i] = std::log(sigmoid[i] > 1e-12f ? sigmoid[i] : 1e-12f);
    }
  }
  inline int idx(float x) const {
    if (x >= kMaxExp) return kExpTableSize - 1;
    if (x <= -kMaxExp) return 0;
    return static_cast<int>((x + kMaxExp) * (kExpTableSize / (2.0f * kMaxExp)));
  }
  inline float sig(float x) const { return sigmoid[idx(x)]; }
  inline float logsigf(float x) const { return logsig[idx(x)]; }
};

const ExpTable g_exp;

struct XorShift {
  uint64_t state;
  explicit XorShift(uint64_t seed) : state(seed ? seed : 0x9E3779B97F4A7C15ull) {}
  inline uint64_t next() {
    state ^= state << 13;
    state ^= state >> 7;
    state ^= state << 17;
    return state;
  }
  inline float uniform() {  // [0, 1)
    return (next() >> 40) * (1.0f / (1ull << 24));
  }
  inline int64_t below(int64_t n) {
    return static_cast<int64_t>(next() % static_cast<uint64_t>(n));
  }
};

}  // namespace

extern "C" {

// Bumped when symbols/signatures change; the ctypes loader rebuilds a
// stale .so instead of dlopening across an ABI change (pairio pattern).
enum { SGNS_HOGWILD_ABI_VERSION = 2 };
int64_t sgns_hogwild_abi_version(void) { return SGNS_HOGWILD_ABI_VERSION; }

// Trains one epoch in place. Returns the mean per-example loss.
float sgns_hogwild_epoch(
    float* emb, float* ctx, int64_t vocab, int32_t dim,
    const int32_t* pairs, int64_t n_pairs,
    const float* alias_prob, const int32_t* alias_alias,
    int32_t negatives, float lr_start, float lr_end,
    int32_t n_threads, uint64_t seed, int32_t both_directions) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> progress{0};
  std::vector<double> thread_loss(static_cast<size_t>(n_threads), 0.0);
  std::vector<int64_t> thread_examples(static_cast<size_t>(n_threads), 0);

  auto worker = [&](int tid) {
    XorShift rng(seed + 0x9E3779B97F4A7C15ull * static_cast<uint64_t>(tid + 1));
    std::vector<float> grad(static_cast<size_t>(dim));
    int64_t lo = n_pairs * tid / n_threads;
    int64_t hi = n_pairs * (tid + 1) / n_threads;
    double loss_sum = 0.0;
    int64_t examples = 0;
    const int64_t kProgressChunk = 4096;
    float lr = lr_start;

    for (int64_t p = lo; p < hi; ++p) {
      if ((p - lo) % kProgressChunk == 0) {
        int64_t done = progress.fetch_add(kProgressChunk);
        float frac = static_cast<float>(done) / static_cast<float>(n_pairs);
        if (frac > 1.0f) frac = 1.0f;
        lr = lr_start + (lr_end - lr_start) * frac;
      }
      for (int dir = 0; dir < (both_directions ? 2 : 1); ++dir) {
        int32_t center = pairs[2 * p + dir];
        int32_t context = pairs[2 * p + 1 - dir];
        float* v = emb + static_cast<int64_t>(center) * dim;
        std::memset(grad.data(), 0, sizeof(float) * static_cast<size_t>(dim));

        // positive + k negatives against the ctx table
        for (int k = 0; k < negatives + 1; ++k) {
          int32_t target;
          float label;
          if (k == 0) {
            target = context;
            label = 1.0f;
          } else {
            int64_t j = rng.below(vocab);
            target = (rng.uniform() < alias_prob[j])
                         ? static_cast<int32_t>(j)
                         : alias_alias[j];
            if (target == context) continue;  // word2vec skip
            label = 0.0f;
          }
          float* u = ctx + static_cast<int64_t>(target) * dim;
          float dot = 0.0f;
          for (int d = 0; d < dim; ++d) dot += v[d] * u[d];
          float s = g_exp.sig(dot);
          loss_sum -= (label > 0.5f) ? g_exp.logsigf(dot) : g_exp.logsigf(-dot);
          float g = (s - label) * lr;
          for (int d = 0; d < dim; ++d) {
            grad[d] += g * u[d];
            u[d] -= g * v[d];
          }
        }
        for (int d = 0; d < dim; ++d) v[d] -= grad[d];
        ++examples;
      }
    }
    thread_loss[static_cast<size_t>(tid)] = loss_sum;
    thread_examples[static_cast<size_t>(tid)] = examples;
  };

  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& t : threads) t.join();

  double loss = 0.0;
  int64_t examples = 0;
  for (int t = 0; t < n_threads; ++t) {
    loss += thread_loss[static_cast<size_t>(t)];
    examples += thread_examples[static_cast<size_t>(t)];
  }
  return examples ? static_cast<float>(loss / static_cast<double>(examples))
                  : 0.0f;
}

// Hierarchical-softmax Hogwild epoch (the reference engine's hs=1
// variants, gensim src/gene2vec.py:59 with sg=0/1): per example the
// input row trains against the internal nodes of the TARGET token's
// Huffman path — per node, label = 1 - code, g = (sigmoid(v.u) - label)
// * lr, u and v update lock-free (word2vec hs semantics; the same
// objective gene2vec_tpu/sgns/cbow_hs.py computes batched).  The tree
// arrives as the framework's own (V, L) padded points/codes/lengths
// (huffman.py), so both implementations score the identical tree.
// cbow != 0 swaps roles: input = context, path of center — the 1-token-
// window CBOW degeneration (SURVEY §2.2 #1).  Returns mean per-example
// loss.
float hs_hogwild_epoch(
    float* emb, float* node, int32_t dim,
    const int32_t* pairs, int64_t n_pairs,
    const int32_t* points, const float* codes, const int32_t* lengths,
    int32_t max_len,
    float lr_start, float lr_end,
    int32_t n_threads, int32_t both_directions, int32_t cbow) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> progress{0};
  std::vector<double> thread_loss(static_cast<size_t>(n_threads), 0.0);
  std::vector<int64_t> thread_examples(static_cast<size_t>(n_threads), 0);

  auto worker = [&](int tid) {
    std::vector<float> grad(static_cast<size_t>(dim));
    int64_t lo = n_pairs * tid / n_threads;
    int64_t hi = n_pairs * (tid + 1) / n_threads;
    double loss_sum = 0.0;
    int64_t examples = 0;
    const int64_t kProgressChunk = 4096;
    float lr = lr_start;

    for (int64_t p = lo; p < hi; ++p) {
      if ((p - lo) % kProgressChunk == 0) {
        int64_t done = progress.fetch_add(kProgressChunk);
        float frac = static_cast<float>(done) / static_cast<float>(n_pairs);
        if (frac > 1.0f) frac = 1.0f;
        lr = lr_start + (lr_end - lr_start) * frac;
      }
      for (int dir = 0; dir < (both_directions ? 2 : 1); ++dir) {
        int32_t center = pairs[2 * p + dir];
        int32_t context = pairs[2 * p + 1 - dir];
        int32_t input = cbow ? context : center;
        int32_t target = cbow ? center : context;
        float* v = emb + static_cast<int64_t>(input) * dim;
        std::memset(grad.data(), 0, sizeof(float) * static_cast<size_t>(dim));

        int32_t len = lengths[target];
        const int32_t* pts = points + static_cast<int64_t>(target) * max_len;
        const float* cds = codes + static_cast<int64_t>(target) * max_len;
        for (int32_t l = 0; l < len; ++l) {
          float* u = node + static_cast<int64_t>(pts[l]) * dim;
          float dot = 0.0f;
          for (int d = 0; d < dim; ++d) dot += v[d] * u[d];
          float s = g_exp.sig(dot);
          loss_sum -=
              (cds[l] < 0.5f) ? g_exp.logsigf(dot) : g_exp.logsigf(-dot);
          float g = (s - (1.0f - cds[l])) * lr;
          for (int d = 0; d < dim; ++d) {
            grad[d] += g * u[d];
            u[d] -= g * v[d];
          }
        }
        for (int d = 0; d < dim; ++d) v[d] -= grad[d];
        ++examples;
      }
    }
    thread_loss[static_cast<size_t>(tid)] = loss_sum;
    thread_examples[static_cast<size_t>(tid)] = examples;
  };

  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& t : threads) t.join();

  double loss = 0.0;
  int64_t examples = 0;
  for (int t = 0; t < n_threads; ++t) {
    loss += thread_loss[static_cast<size_t>(t)];
    examples += thread_examples[static_cast<size_t>(t)];
  }
  return examples ? static_cast<float>(loss / static_cast<double>(examples))
                  : 0.0f;
}

}  // extern "C"
