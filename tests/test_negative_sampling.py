import numpy as np

import jax

from gene2vec_tpu.data.negative_sampling import NegativeSampler, noise_distribution


def test_noise_distribution_unigram_exponent():
    counts = np.array([8, 4, 2, 1], dtype=np.int64)
    p = noise_distribution(counts, 0.75)
    expected = counts.astype(np.float64) ** 0.75
    expected /= expected.sum()
    np.testing.assert_allclose(p, expected, rtol=1e-6)
    assert abs(p.sum() - 1.0) < 1e-6


def test_sampler_matches_distribution():
    rngc = np.random.RandomState(0)
    counts = rngc.randint(1, 1000, size=50)
    sampler = NegativeSampler(counts, 0.75)
    draws = sampler.sample(jax.random.PRNGKey(0), (200_000,))
    draws = np.asarray(draws)
    assert draws.min() >= 0 and draws.max() < 50
    emp = np.bincount(draws, minlength=50) / draws.size
    expected = noise_distribution(counts, 0.75)
    # generous tolerance: 200k draws, compare in absolute probability
    np.testing.assert_allclose(emp, expected, atol=5e-3)


def test_sampler_covers_rare_tokens():
    counts = np.array([10_000] * 5 + [1], dtype=np.int64)
    sampler = NegativeSampler(counts, 0.75)
    draws = np.asarray(sampler.sample(jax.random.PRNGKey(1), (500_000,)))
    assert (draws == 5).sum() > 0  # the rare token is reachable
