"""SLO alerting & incident capture: rule engine edge cases, aggregator
staleness, the shared dump/bundle rate limiter, incident bundles, the
cli.obs alerts/incident contracts, and the passes_alerts budget gate
(docs/OBSERVABILITY.md#alerting)."""

import json
import os
import subprocess
import sys

import pytest

from gene2vec_tpu.obs.aggregate import FleetAggregator, parse_prometheus
from gene2vec_tpu.obs.alerts import (
    AlertEvaluator,
    AlertRule,
    RateLimiter,
    collect_transitions,
    default_rules,
    format_timeline,
    parse_rules,
)
from gene2vec_tpu.obs.flight import FlightRecorder
from gene2vec_tpu.obs.incident import IncidentManager, verify_bundle
from gene2vec_tpu.obs.registry import MetricsRegistry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _threshold_rule(**kw):
    base = dict(
        name="q", metric="fleet_queue_depth", op=">", value=5.0,
        clear_value=2.0, for_s=2.0, clear_for_s=3.0,
    )
    base.update(kw)
    return AlertRule(**base)


def _firing(transitions):
    return [t for t in transitions if t["to"] == "firing"]


# -- rule parsing ------------------------------------------------------------


def test_parse_rules_validates():
    rules = parse_rules({"rules": [
        {"name": "a", "metric": "m", "op": ">", "value": 1.0},
        {"name": "b", "kind": "burn_rate", "good": "ok", "total": "all"},
    ]})
    assert [r.name for r in rules] == ["a", "b"]
    with pytest.raises(ValueError, match="unknown field"):
        parse_rules({"rules": [{"name": "a", "metric": "m",
                                "treshold": 3}]})
    with pytest.raises(ValueError, match="duplicate"):
        parse_rules({"rules": [
            {"name": "a", "metric": "m"}, {"name": "a", "metric": "m"},
        ]})
    with pytest.raises(ValueError, match="kind"):
        parse_rules({"rules": [{"name": "a", "kind": "nope"}]})
    with pytest.raises(ValueError, match="'good' and 'total'"):
        parse_rules({"rules": [{"name": "a", "kind": "burn_rate"}]})
    with pytest.raises(ValueError, match="op"):
        parse_rules({"rules": [{"name": "a", "metric": "m", "op": "!="}]})
    with pytest.raises(ValueError, match="rules"):
        parse_rules({})


def test_default_rules_cover_the_slo_signals():
    rules = default_rules()
    for r in rules:
        r.validate()
    by_name = {r.name: r for r in rules}
    assert by_name["availability-burn"].kind == "burn_rate"
    assert by_name["availability-burn"].good == "fleet_ok"
    assert by_name["availability-burn"].total == "fleet_responses"
    assert "fleet_route_p99_seconds" in by_name["route-p99"].metric
    assert by_name["rejection-rate"].metric == "fleet_rejection_rate"
    assert by_name["queue-depth"].metric == "fleet_queue_depth"


# -- threshold state machine -------------------------------------------------


def test_debounce_fires_exactly_at_the_for_duration_boundary():
    clk = FakeClock()
    ev = AlertEvaluator([_threshold_rule(for_s=2.0)], clock=clk)
    snap = {"fleet_queue_depth": 10.0, "_fresh_targets": 1.0}
    assert _firing(ev.observe(snap)) == []          # t=0: pending
    clk.t = 1.999
    assert _firing(ev.observe(snap)) == []          # just inside
    clk.t = 2.0
    fired = _firing(ev.observe(snap))               # the boundary FIRES
    assert len(fired) == 1 and fired[0]["rule"] == "q"
    assert ev.states()["q"] == "firing"


def test_breach_lost_during_debounce_never_fires():
    clk = FakeClock()
    ev = AlertEvaluator([_threshold_rule(for_s=2.0)], clock=clk)
    ev.observe({"fleet_queue_depth": 10.0, "_fresh_targets": 1.0})
    clk.t = 1.0
    out = ev.observe({"fleet_queue_depth": 0.0, "_fresh_targets": 1.0})
    assert [t["to"] for t in out] == ["inactive"]
    clk.t = 5.0  # breach again much later: the old pending must not leak
    assert _firing(
        ev.observe({"fleet_queue_depth": 10.0, "_fresh_targets": 1.0})
    ) == []
    assert ev.states()["q"] == "pending"


def test_hysteresis_clear_vs_immediate_refire():
    clk = FakeClock()
    ev = AlertEvaluator(
        [_threshold_rule(for_s=0.0, clear_for_s=3.0)], clock=clk
    )
    hot = {"fleet_queue_depth": 10.0, "_fresh_targets": 1.0}
    # between value (5) and clear_value (2): no longer breaching, but
    # still too hot to start clearing from scratch after a re-breach
    warm = {"fleet_queue_depth": 3.0, "_fresh_targets": 1.0}
    cold = {"fleet_queue_depth": 1.0, "_fresh_targets": 1.0}
    assert len(_firing(ev.observe(hot))) == 1
    clk.t = 1.0
    assert ev.observe(cold) == []                  # clear timer starts
    clk.t = 2.0
    assert ev.observe(warm) == []                  # re-hot: timer RESETS
    assert ev.states()["q"] == "firing"            # no flap, no re-fire
    clk.t = 4.5                                    # cold again: new timer
    assert ev.observe(cold) == []
    clk.t = 7.4                                    # 2.9s cold < clear_for_s
    assert ev.observe(cold) == []
    assert ev.states()["q"] == "firing"
    clk.t = 7.5                                    # 3.0s cold: clears
    out = ev.observe(cold)
    assert [t["to"] for t in out] == ["inactive"]
    # a fresh breach after a full clear fires AGAIN (one transition)
    clk.t = 8.0
    assert len(_firing(ev.observe(hot))) == 1


def test_missing_metric_holds_the_rule():
    clk = FakeClock()
    ev = AlertEvaluator([_threshold_rule(for_s=0.0)], clock=clk)
    assert ev.observe({"_fresh_targets": 1.0}) == []
    assert ev.states()["q"] == "inactive"


# -- burn-rate rules ---------------------------------------------------------


def _burn_rule(**kw):
    base = dict(
        name="burn", kind="burn_rate", good="ok", total="all",
        max_bad_frac=0.02, short_window_s=5.0, long_window_s=10.0,
        min_count=10.0, for_s=0.0, clear_for_s=5.0,
    )
    base.update(kw)
    return AlertRule(**base)


def test_burn_rate_fires_on_sustained_bad_fraction():
    clk = FakeClock()
    ev = AlertEvaluator([_burn_rule()], clock=clk)
    # clean traffic: 100 events/tick, all good
    for i in range(3):
        clk.t = float(i)
        assert ev.observe({"ok": 100.0 * (i + 1),
                           "all": 100.0 * (i + 1)}) == []
    # 50% of new events fail
    fired = []
    for i in range(3, 6):
        clk.t = float(i)
        fired += _firing(ev.observe(
            {"ok": 300.0 + (i - 2) * 50.0, "all": 100.0 * (i + 1)}
        ))
    assert len(fired) == 1
    assert 0.02 < fired[0]["value"] <= 0.5


def test_burn_rate_counter_reset_is_not_a_spike():
    """A replica restart zeroes its counters; the fleet sums rebase, and
    so must the evaluator — a reset must never read as a burn."""
    clk = FakeClock()
    ev = AlertEvaluator([_burn_rule()], clock=clk)
    feeds = [
        (100.0, 100.0), (200.0, 200.0),
        (20.0, 20.0),          # restart: both counters back near zero
        (120.0, 120.0), (220.0, 220.0), (320.0, 320.0),
    ]
    out = []
    for i, (g, t) in enumerate(feeds):
        clk.t = float(i)
        out += ev.observe({"ok": g, "all": t})
    assert _firing(out) == []
    assert ev.states()["burn"] == "inactive"


def test_burn_rate_needs_min_count_evidence():
    clk = FakeClock()
    ev = AlertEvaluator([_burn_rule(min_count=10.0)], clock=clk)
    # 100% bad fraction but only 4 events in the window: no evidence
    out = []
    for i, (g, t) in enumerate([(0.0, 1.0), (0.0, 2.0), (0.0, 4.0)]):
        clk.t = float(i)
        out += ev.observe({"ok": g, "all": t})
    assert _firing(out) == []


def test_availability_burn_pages_through_a_total_scrape_outage():
    """The default availability rule's counter pair is PROXY-local: it
    stays fresh when every replica stops answering scrapes (the
    worst outage class), so the staleness hold must not silence it."""
    clk = FakeClock()
    rule = next(r for r in default_rules()
                if r.name == "availability-burn")
    assert rule.min_fresh_targets == 0
    ev = AlertEvaluator([rule], clock=clk)
    fired = []
    # every replica wedged: zero fresh targets, 100% burn at the proxy
    for i in range(8):
        clk.t = float(i * 10)
        fired += _firing(ev.observe({
            "fleet_ok": 10.0,                       # frozen
            "fleet_responses": 10.0 + 50.0 * i,     # all failures
            "_fresh_targets": 0.0,
        }))
    assert len(fired) == 1 and fired[0]["rule"] == "availability-burn"


def test_staleness_holds_rules_on_frozen_data():
    clk = FakeClock()
    ev = AlertEvaluator([_threshold_rule(for_s=0.0)], clock=clk)
    hot_stale = {"fleet_queue_depth": 10.0, "_fresh_targets": 0.0}
    assert ev.observe(hot_stale) == []             # held, not evaluated
    assert ev.states()["q"] == "inactive"
    # freshness returns: the rule evaluates (and fires) normally
    clk.t = 1.0
    assert len(_firing(ev.observe(
        {"fleet_queue_depth": 10.0, "_fresh_targets": 2.0}
    ))) == 1
    # ... and a firing rule cannot CLEAR on frozen data either
    clk.t = 100.0
    assert ev.observe(
        {"fleet_queue_depth": 0.0, "_fresh_targets": 0.0}
    ) == []
    assert ev.states()["q"] == "firing"


def test_evaluator_exports_state_and_log(tmp_path):
    clk = FakeClock()
    reg = MetricsRegistry()
    log_path = str(tmp_path / "alerts.jsonl")
    fired = []
    ev = AlertEvaluator(
        [_threshold_rule(for_s=0.0)],
        registry=reg, log_path=log_path,
        on_fire=lambda rule, snap, rec: fired.append(rule.name),
        clock=clk,
    )
    text = reg.prometheus_text()
    assert 'fleet_alert_active{rule="q"} 0' in text  # visible pre-fire
    ev.observe({"fleet_queue_depth": 10.0, "_fresh_targets": 1.0})
    text = reg.prometheus_text()
    assert 'fleet_alert_active{rule="q"} 1' in text
    assert 'fleet_alert_transitions_total{rule="q",to="firing"} 1' in text
    assert fired == ["q"]
    records = collect_transitions(str(tmp_path))
    assert [r["to"] for r in records] == ["pending", "firing"]
    rendered = format_timeline(records)
    assert "FIRING" in rendered and "currently firing: q" in rendered


# -- aggregator staleness ----------------------------------------------------


def _replica_text(requests, route_ms):
    r = MetricsRegistry()
    r.counter("serve_requests_total").inc(requests)
    h = r.histogram(
        "serve_route_seconds", labels={"route": "/v1/similar"},
        buckets=(0.001, 0.008, 0.064, 0.512),
    )
    for ms in route_ms:
        h.observe(ms / 1000.0)
    return r.prometheus_text()


def test_aggregator_marks_series_stale_and_quantiles_go_fresh_only():
    texts = {
        "http://fast": _replica_text(100, [2.0] * 100),
        "http://slow": _replica_text(100, [400.0] * 100),
    }
    alive = dict(texts)

    def fetch(url, timeout):
        return alive[url]

    snapshots = []

    class Sink:
        def observe(self, snapshot, wall=None):
            snapshots.append(snapshot)

    agg = FleetAggregator(
        lambda: list(texts), fetch=fetch, stale_after=2, evaluator=Sink(),
    )
    agg.scrape_once()
    samples = {
        (s.name, s.labels): s.value
        for s in parse_prometheus(agg.fleet_text())
    }
    key99 = ("fleet_route_p99_seconds", (("route", "/v1/similar"),))
    assert samples[key99] >= 0.064      # the slow replica weighs the p99
    assert samples[
        ("fleet_scrape_staleness", (("target", "http://slow"),))
    ] == 0
    assert snapshots[-1]["_fresh_targets"] == 2.0
    assert (
        "fleet_route_p99_seconds{route=/v1/similar}" in snapshots[-1]
    )
    # the slow replica stops answering scrapes (still listed = wedged,
    # not dead); first miss is not yet stale
    del alive["http://slow"]
    agg.scrape_once()
    samples = {
        (s.name, s.labels): s.value
        for s in parse_prometheus(agg.fleet_text())
    }
    assert samples[
        ("fleet_scrape_staleness", (("target", "http://slow"),))
    ] == 1
    assert samples[("fleet_stale_targets", ())] == 0
    assert samples[key99] >= 0.064      # history still counts pre-stale
    # second consecutive miss: stale — its frozen histogram no longer
    # freezes the quantile the alert rules watch
    agg.scrape_once()
    samples = {
        (s.name, s.labels): s.value
        for s in parse_prometheus(agg.fleet_text())
    }
    assert samples[
        ("fleet_scrape_staleness", (("target", "http://slow"),))
    ] == 2
    assert samples[("fleet_stale_targets", ())] == 1
    assert samples[key99] <= 0.008      # fresh-replica latency only
    assert snapshots[-1]["_fresh_targets"] == 1.0
    # counters NEVER go backward on staleness (sums keep the history)
    assert samples[("fleet_requests", ())] == 200
    # recovery resets the miss count and restores its histogram weight
    alive["http://slow"] = texts["http://slow"]
    agg.scrape_once()
    samples = {
        (s.name, s.labels): s.value
        for s in parse_prometheus(agg.fleet_text())
    }
    assert samples[
        ("fleet_scrape_staleness", (("target", "http://slow"),))
    ] == 0
    assert samples[key99] >= 0.064
    assert samples[("fleet_requests", ())] == 200  # no double count
    # a DEPARTED target (restarted replica, fresh ephemeral port) sheds
    # its staleness series entirely — dead target= label sets must not
    # accumulate in /metrics/fleet across restarts
    del texts["http://slow"], alive["http://slow"]
    agg.scrape_once()
    samples = {
        (s.name, s.labels): s.value
        for s in parse_prometheus(agg.fleet_text())
    }
    assert (
        "fleet_scrape_staleness", (("target", "http://slow"),)
    ) not in samples
    assert samples[
        ("fleet_scrape_staleness", (("target", "http://fast"),))
    ] == 0
    agg.view.close()


# -- the shared rate limiter -------------------------------------------------


def test_rate_limiter_per_key_and_global_budget():
    clk = FakeClock()
    lim = RateLimiter(min_interval_s=30.0, max_per_window=3,
                      window_s=100.0, clock=clk)
    assert lim.allow("a")
    assert not lim.allow("a")           # per-key interval
    assert lim.allow("b")               # other keys unaffected
    clk.t = 31.0
    assert lim.allow("a")
    clk.t = 32.0
    assert not lim.allow("c")           # global window cap (3 events)
    clk.t = 131.0                       # old events age out
    assert lim.allow("c")
    assert lim.denied == 2


def test_flight_burst_configurable_and_shared_limiter():
    clk = FakeClock()
    lim = RateLimiter(min_interval_s=60.0, max_per_window=10,
                      window_s=3600.0, clock=clk)
    rec = FlightRecorder(burst_threshold=3, burst_window_s=1.0,
                        clock=clk, limiter=lim)
    assert rec.record("/v1/similar", 500, 0.01) is False
    assert rec.record("/v1/similar", 500, 0.01) is False
    assert rec.record("/v1/similar", 500, 0.01) is True  # 3rd 5xx dumps
    # the burst consumed the SHARED budget: an incident for the same
    # window is arbitrated by the same limiter instance
    assert rec.record("/v1/similar", 500, 0.01) is False
    assert not lim.allow("5xx-burst")
    assert lim.allow("incident:availability-burn")  # different key ok
    clk.t = 61.0
    rec2 = FlightRecorder(burst_threshold=2, burst_window_s=1.0,
                          clock=clk, limiter=lim)
    assert rec2.record("/x", 503, 0.01) is False
    assert rec2.record("/x", 503, 0.01) is True
    doc = rec2.snapshot_doc("debug")
    assert doc["schema"] == "gene2vec-tpu/flight/v1"
    assert len(doc["records"]) == 2 and doc["pid"] == os.getpid()


# -- incident bundles --------------------------------------------------------


def _span_end(path, trace, tsid, name, pid, wall, dur, tpid=None):
    rec = {
        "type": "span_end", "name": name, "trace": trace, "tsid": tsid,
        "pid": pid, "wall": wall, "dur": dur, "span": None,
    }
    if tpid:
        rec["tpid"] = tpid
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


@pytest.fixture
def bundle_env(tmp_path):
    """A fake two-process trace on disk + replica flight fetch stubs."""
    import time as time_mod

    now = time_mod.time()
    scan = tmp_path / "export"
    (scan / "fleet_runs" / "1").mkdir(parents=True)
    (scan / "serve_runs" / "1").mkdir(parents=True)
    proxy_events = str(scan / "fleet_runs" / "1" / "events.jsonl")
    replica_events = str(scan / "serve_runs" / "1" / "events.jsonl")
    slow_tid, fast_tid = "a" * 32, "b" * 32
    _span_end(proxy_events, slow_tid, "11", "proxy_request", 100,
              now - 2.0, 0.5)
    _span_end(replica_events, slow_tid, "22", "serve_request", 200,
              now - 1.9, 0.45, tpid="11")
    _span_end(proxy_events, fast_tid, "33", "proxy_request", 100,
              now - 1.0, 0.002)

    local = FlightRecorder()
    local.record("/v1/similar", 200, 0.5, trace_id=slow_tid)
    local.record("/v1/similar", 200, 0.002, trace_id=fast_tid)

    def fetch(url, timeout):
        if url == "http://dead":
            raise OSError("replica mid-incident")
        return {
            "schema": "gene2vec-tpu/flight/v1", "reason": "debug",
            "pid": 200,
            "records": [{
                "wall": now - 1.9, "pid": 200, "route": "/v1/similar",
                "status": 200, "dur_s": 0.45, "trace": slow_tid,
            }],
        }

    class Agg:
        def raw_recent(self):
            return [{"wall": now, "target": "http://r0",
                     "samples": {"serve_requests_total": 7.0}}]

    return {
        "scan": str(scan), "local": local, "fetch": fetch, "agg": Agg(),
        "slow_tid": slow_tid, "fast_tid": fast_tid,
        "incidents": str(tmp_path / "run" / "incidents"),
    }


def test_incident_bundle_assembly_and_verification(bundle_env):
    clk = FakeClock()
    lim = RateLimiter(min_interval_s=30.0, clock=clk)
    reg = MetricsRegistry()
    mgr = IncidentManager(
        bundle_env["incidents"],
        scan_roots=[bundle_env["scan"]],
        targets=lambda: ["http://r0", "http://dead"],
        local_flight=bundle_env["local"],
        aggregator=bundle_env["agg"],
        limiter=lim,
        metrics=reg,
        fetch=bundle_env["fetch"],
        max_traces=1,
    )
    rule = _threshold_rule(name="route-p99")
    bundle = mgr.on_fire(
        rule, {"fleet_queue_depth": 9.0, "_fresh_targets": 2.0},
        {"rule": "route-p99", "from": "pending", "to": "firing",
         "value": 9.0},
    )
    assert bundle and os.path.basename(bundle).endswith("_route-p99")
    names = sorted(os.listdir(bundle))
    assert "rule.json" in names
    assert "metrics_window.json" in names
    assert "incident.MANIFEST.json" in names
    # flight dumps: the local (proxy) ring + the one answering replica;
    # the dead replica is counted, not fatal
    dumps = [n for n in names if n.startswith("flightdump-")]
    assert len(dumps) == 2
    assert reg.counter("incident_flight_fetch_errors_total").value == 1
    # max_traces=1 picks the SLOWEST trace, reassembled cross-process
    traces = [n for n in names if n.startswith("trace-")]
    assert traces == [f"trace-{bundle_env['slow_tid']}.json"]
    with open(os.path.join(bundle, traces[0])) as f:
        doc = json.load(f)
    assert set(doc["processes"]) == {100, 200}
    assert doc["picked_for"]["dur_s"] == 0.5
    # the manifest CRC-verifies through the resilience primitives...
    assert verify_bundle(bundle)
    # ... and catches post-commit rot
    with open(os.path.join(bundle, "rule.json"), "a") as f:
        f.write("rot")
    v = verify_bundle(bundle)
    assert not v and v.reason.startswith(("size:", "crc:"))
    # a flapping rule is rate-limited: same rule, same window -> None
    assert mgr.on_fire(rule, {}, {}) is None
    assert reg.counter("incident_rate_limited_total").value == 1


def test_incident_bundle_disk_caps(bundle_env):
    clk = FakeClock()
    mgr = IncidentManager(
        bundle_env["incidents"],
        scan_roots=[bundle_env["scan"]],
        local_flight=bundle_env["local"],
        limiter=RateLimiter(min_interval_s=0.0, max_per_window=100,
                            clock=clk),
        max_bundles=2,
        metrics=MetricsRegistry(),
    )
    rule_a = _threshold_rule(name="a")
    rule_b = _threshold_rule(name="b")
    rule_c = _threshold_rule(name="c")
    b1 = mgr.on_fire(rule_a, {}, {"to": "firing"})
    b2 = mgr.on_fire(rule_b, {}, {"to": "firing"})
    b3 = mgr.on_fire(rule_c, {}, {"to": "firing"})
    assert b1 and b2 and b3
    kept = sorted(os.listdir(bundle_env["incidents"]))
    assert len(kept) == 2                      # oldest pruned
    assert os.path.basename(b3) in kept
    # the hard byte ceiling refuses outright
    mgr.max_total_bytes = 1
    assert mgr.on_fire(rule_a, {}, {"to": "firing"}) is None


# -- CLI contracts -----------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.obs", *args],
        capture_output=True, text=True, timeout=120,
    )


def test_cli_obs_alerts_contract(tmp_path):
    run_dir = tmp_path / "fleet_runs" / "1"
    run_dir.mkdir(parents=True)
    # exit 1: directory exists, no transitions recorded
    r = _run_cli("alerts", str(tmp_path))
    assert r.returncode == 1
    # exit 2: not a directory
    r = _run_cli("alerts", str(tmp_path / "nope"))
    assert r.returncode == 2
    with open(run_dir / "alerts.jsonl", "w") as f:
        f.write(json.dumps({
            "wall": 1000.0, "rule": "availability-burn",
            "severity": "page", "from": "pending", "to": "firing",
            "value": 0.2,
        }) + "\n")
        f.write("{torn")  # torn trailing line: skipped, not fatal
    r = _run_cli("alerts", str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "availability-burn" in r.stdout and "FIRING" in r.stdout
    r = _run_cli("alerts", "--json", str(tmp_path))
    assert r.returncode == 0
    assert json.loads(r.stdout)[0]["rule"] == "availability-burn"


def test_cli_obs_incident_contract(bundle_env):
    mgr = IncidentManager(
        bundle_env["incidents"],
        scan_roots=[bundle_env["scan"]],
        local_flight=bundle_env["local"],
        metrics=MetricsRegistry(),
    )
    bundle = mgr.on_fire(
        _threshold_rule(name="queue-depth"),
        {"fleet_queue_depth": 9.0},
        {"rule": "queue-depth", "from": "pending", "to": "firing",
         "value": 9.0},
    )
    r = _run_cli("incident", bundle)
    assert r.returncode == 0, r.stderr
    assert "VERIFIED" in r.stdout and "queue-depth" in r.stdout
    r = _run_cli("incident", "--json", bundle)
    assert r.returncode == 0 and json.loads(r.stdout)["verified"] is True
    # torn bundle -> exit 1 with the manifest's machine reason
    os.unlink(os.path.join(bundle, "rule.json"))
    r = _run_cli("incident", bundle)
    assert r.returncode == 1
    assert "missing:rule.json" in r.stdout + r.stderr
    # bad dir -> 2 (the cli.obs trace contract)
    r = _run_cli("incident", bundle + "-nope")
    assert r.returncode == 2


# -- the analysis gate -------------------------------------------------------


def _alerts_doc(**over):
    section = {
        "replicas": 3, "scrape_interval_s": 0.25, "proxy_attempts": 3,
        "detection_latency_s": 4.2, "warmup_false_positives": 0,
        "bundle_verified": True,
        "bundle_trace_through_faulty_replica": True,
    }
    section.update(over)
    return {"schema_version": 1, "alerts": section}


def test_passes_alerts_budget_gate(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_alerts import alerts_findings

    # missing bench = info (fresh checkout must not fail lint)
    missing = alerts_findings(root=str(tmp_path / "absent"))
    assert [f.severity for f in missing] == ["info"]

    def run(doc):
        root = tmp_path / "root"
        root.mkdir(exist_ok=True)
        with open(root / "BENCH_ALERTS_r13.json", "w") as f:
            json.dump(doc, f)
        return alerts_findings(root=str(root))

    fs = run(_alerts_doc())
    assert gating(fs) == [], [f.format() for f in fs]

    # each planted violation fires EXACTLY once
    for doc in (
        _alerts_doc(detection_latency_s=120.0),       # too slow
        _alerts_doc(warmup_false_positives=2),        # twitchy rules
        _alerts_doc(bundle_verified=False),           # torn bundle
        _alerts_doc(bundle_trace_through_faulty_replica=False),
        _alerts_doc(detection_latency_s=None),        # dropped key
        _alerts_doc(scrape_interval_s=5.0),           # off-recipe
        {"schema_version": 1},                        # no section
    ):
        fs = run(doc)
        assert len(gating(fs)) == 1, doc

    # the newest round wins: a violating r14 beats a stale clean r13
    root = tmp_path / "root"
    with open(root / "BENCH_ALERTS_r14.json", "w") as f:
        json.dump(_alerts_doc(detection_latency_s=120.0), f)
    with open(root / "BENCH_ALERTS_r13.json", "w") as f:
        json.dump(_alerts_doc(), f)
    fs = alerts_findings(root=str(root))
    assert len(gating(fs)) == 1
    assert gating(fs)[0].path == "BENCH_ALERTS_r14.json"


def test_cli_analyze_gates_on_planted_alerts_violation(tmp_path):
    """The env-override path: a violating BENCH_ALERTS under
    GENE2VEC_TPU_ALERTS_ROOT makes the real cli.analyze exit 1 with
    exactly one alerts-detection-budget finding."""
    root = tmp_path / "root"
    root.mkdir()
    with open(root / "BENCH_ALERTS_r13.json", "w") as f:
        json.dump(_alerts_doc(detection_latency_s=120.0), f)
    env = {**os.environ, "GENE2VEC_TPU_ALERTS_ROOT": str(root)}
    r = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 1, r.stderr
    doc = json.loads(r.stdout)
    mine = [f for f in doc["findings"]
            if f["pass"] == "alerts-detection-budget"]
    assert len(mine) == 1
    assert mine[0]["severity"] != "info"
    assert "detection latency 120.00s" in mine[0]["message"]


def test_ledger_adapts_alerts_family(tmp_path):
    from gene2vec_tpu.obs import ledger

    with open(tmp_path / "BENCH_ALERTS_r13.json", "w") as f:
        json.dump({
            "schema_version": 1, "command": "chaos_drill --only alerts",
            "created_unix": 1000.0, "passed": True,
            "alerts": {
                "detection_latency_s": 4.2, "warmup_false_positives": 0,
                "bundle_verified": True, "bundle_traces": 3,
                "bundle_trace_through_faulty_replica": True,
            },
        }, f)
    records = ledger.ingest_root(str(tmp_path))
    assert len(records) == 1
    rec = records[0]
    assert rec["family"] == "alerts" and rec["round"] == 13
    assert rec["headline_metric"] == "alert_detection_latency_s"
    assert rec["metrics"]["alert_detection_latency_s"] == 4.2
    assert rec["metrics"]["alert_bundle_verified"] == 1.0
    assert not rec["legacy_unstamped"]


# -- /debug/flight over real HTTP --------------------------------------------


def test_debug_flight_endpoint(tmp_path):
    import threading
    import urllib.request

    import numpy as np

    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import (
        ServeApp,
        ServeConfig,
        make_server,
    )
    from gene2vec_tpu.sgns.model import SGNSParams

    export = str(tmp_path / "export")
    rng = np.random.RandomState(0)
    save_iteration(
        export, 4, 1,
        SGNSParams(emb=rng.randn(16, 4).astype(np.float32),
                   ctx=np.zeros((16, 4), np.float32)),
        Vocab([f"G{i}" for i in range(16)], np.arange(16, 0, -1)),
    )
    registry = ModelRegistry(export)
    assert registry.refresh()
    app = ServeApp(
        registry,
        config=ServeConfig(burst_threshold=3, burst_window_s=1.0),
    ).start()
    assert app.flight.burst_threshold == 3     # ServeConfig plumbs through
    server = make_server(app, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(f"{base}/v1/similar?gene=G1&k=3",
                                    timeout=10) as r:
            assert r.status == 200
        # the ring append happens just AFTER the response write on the
        # worker thread — poll briefly instead of racing it
        import time as time_mod

        doc = {}
        for _ in range(50):
            with urllib.request.urlopen(f"{base}/debug/flight",
                                        timeout=10) as r:
                doc = json.loads(r.read().decode("utf-8"))
            if any(rec["route"] == "/v1/similar"
                   for rec in doc["records"]):
                break
            time_mod.sleep(0.05)
        assert doc["schema"] == "gene2vec-tpu/flight/v1"
        assert doc["reason"] == "debug"
        assert any(
            rec["route"] == "/v1/similar" for rec in doc["records"]
        )
    finally:
        server.shutdown()
        server.server_close()
        app.stop()
