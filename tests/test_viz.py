"""Visualization subsystem: t-SNE quality, sweep file parity, plot exports,
GTEx figures, dash logic layer."""

import json
import os

import numpy as np
import pytest

from gene2vec_tpu.config import TSNEConfig
from gene2vec_tpu.io.emb_io import write_matrix_txt
from gene2vec_tpu.viz.tsne import TSNE, pca_reduce, run_tsne_sweep


def _blobs(n_per=50, d=20, k=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, d) * 6.0
    x = np.concatenate(
        [centers[i] + rng.randn(n_per, d) for i in range(k)], axis=0
    ).astype(np.float32)
    labels = np.repeat(np.arange(k), n_per)
    return x, labels


def test_pca_reduce_shapes_and_variance():
    x, _ = _blobs()
    r = pca_reduce(x, 5)
    assert r.shape == (x.shape[0], 5)
    # first component captures the most variance
    var = r.var(axis=0)
    assert np.all(np.diff(var) <= 1e-6)


def test_tsne_separates_blobs():
    x, labels = _blobs()
    cfg = TSNEConfig(pca_dims=10, n_iter=500, seed=0)
    out = TSNE(config=cfg).fit(x, log=lambda s: None)
    y = out[500]
    assert y.shape == (x.shape[0], 2)
    # mean intra-cluster distance well below inter-cluster distance
    dists = np.linalg.norm(y[:, None] - y[None, :], axis=-1)
    same = labels[:, None] == labels[None, :]
    np.fill_diagonal(same, False)
    intra = dists[same].mean()
    inter = dists[~same & ~np.eye(len(y), dtype=bool)].mean()
    assert inter > 2.0 * intra, (intra, inter)


def test_tsne_snapshots_share_one_run():
    x, _ = _blobs(n_per=30)
    cfg = TSNEConfig(pca_dims=10, seed=0)
    out = TSNE(config=cfg).fit(x, snapshot_iters=[50, 150], log=lambda s: None)
    assert set(out) == {50, 150}
    assert not np.allclose(out[50], out[150])  # training continued


def test_tsne_sweep_file_parity(tmp_path):
    """labels.txt + one coord file per snapshot, row-aligned."""
    x, _ = _blobs(n_per=20)
    toks = [f"G{i}" for i in range(len(x))]
    emb = tmp_path / "emb.txt"
    write_matrix_txt(str(emb), toks, x)
    out = tmp_path / "tsne"
    written = run_tsne_sweep(
        str(emb), str(out), iters=[30, 60],
        config=TSNEConfig(pca_dims=10), log=lambda s: None,
    )
    assert (out / "labels.txt").exists()
    assert (out / "tsne_iter_30.txt").exists()
    assert (out / "tsne_iter_60.txt").exists()
    labels = (out / "labels.txt").read_text().split()
    coords = np.loadtxt(out / "tsne_iter_60.txt")
    assert len(labels) == coords.shape[0] == len(x)
    assert set(labels) == set(toks)  # shuffled but complete
    assert len(written) == 3


def test_plot_exports_json_and_figure(tmp_path):
    from gene2vec_tpu.viz.plot import plot_gene2vec

    x, _ = _blobs(n_per=15, d=8)
    toks = [f"G{i}" for i in range(len(x))]
    emb = tmp_path / "emb.txt"
    write_matrix_txt(str(emb), toks, x)
    written = plot_gene2vec(
        str(emb), str(tmp_path / "fig"), method="pca", log=lambda s: None
    )
    payload = json.load(open(tmp_path / "fig.json"))
    assert payload["data"][0]["customdata"] == toks
    assert len(payload["data"][0]["x"]) == len(toks)
    # html (plotly) or png (matplotlib fallback) — exactly one of them
    assert any(w.endswith((".html", ".png")) for w in written)


def test_infer_gene_rep():
    """src/plot_gene2vec.py:62-72 semantics: int -> Entrez, 'ENS' -> Ensembl,
    other strings -> symbol; numeric strings (text files) also Entrez."""
    from gene2vec_tpu.viz.plot import infer_gene_rep

    assert infer_gene_rep(7157) == "Entrez ID"
    assert infer_gene_rep("7157") == "Entrez ID"
    assert infer_gene_rep("ENSG00000141510") == "Ensembl ID"
    assert infer_gene_rep("TP53") == "Gene Symbol"
    with pytest.raises(TypeError):
        infer_gene_rep(3.14)


def test_gtex_figure(tmp_path):
    from gene2vec_tpu.viz.gtex import run_gtex_figures

    rng = np.random.RandomState(0)
    genes = [f"G{i}" for i in range(40)]
    (tmp_path / "labels.txt").write_text("\n".join(genes) + "\n")
    np.savetxt(tmp_path / "coords.txt", rng.randn(40, 2))
    (tmp_path / "Liver_specific_genes.txt").write_text(
        "gene z\n" + "\n".join(f"G{i} {rng.randn() + 2:.3f}" for i in range(10))
    )
    written = run_gtex_figures(
        str(tmp_path / "labels.txt"),
        str(tmp_path / "coords.txt"),
        str(tmp_path / "*specific_genes.txt"),
        str(tmp_path / "figs"),
        log=lambda s: None,
    )
    assert len(written) == 1
    assert os.path.getsize(written[0]) > 10_000  # a real png


def test_dash_logic_highlight_and_tables(tmp_path):
    from gene2vec_tpu.viz.dash_app import (
        ACTIVE_COLOR,
        BASE_COLOR,
        INACTIVE_COLOR,
        highlight_genes,
        load_gmt_terms,
        parse_annotation_table,
        term_options,
    )

    figure = {
        "data": [
            {"type": "scattergl", "customdata": ["A", "B", "C"], "x": [0, 1, 2]}
        ],
        "layout": {},
    }
    hi = highlight_genes(figure, ["B"])
    assert hi["data"][0]["marker"]["color"] == [
        INACTIVE_COLOR, ACTIVE_COLOR, INACTIVE_COLOR,
    ]
    assert figure["data"][0].get("marker") is None  # pure function
    reset = highlight_genes(figure, [])
    assert reset["data"][0]["marker"]["color"] == BASE_COLOR

    tsv = tmp_path / "go.tsv"
    tsv.write_text("GO:1\tA\tthing one\nGO:1\tB\tthing one\nGO:2\tC\tother\n")
    members, desc = parse_annotation_table(str(tsv))
    assert members == {"GO:1": ["A", "B"], "GO:2": ["C"]}
    assert desc["GO:2"] == "other"
    opts = term_options(members, desc)
    assert opts[0]["value"] == "GO:1" and "thing one" in opts[0]["label"]

    gmt = tmp_path / "p.gmt"
    gmt.write_text("P1\thttp://u\tA\tB\n")
    m2, d2 = load_gmt_terms(str(gmt))
    assert m2 == {"P1": ["A", "B"]} and d2["P1"] == "http://u"


def test_dash_serve_gated():
    try:
        import dash  # noqa: F401

        pytest.skip("dash installed; gating not exercised")
    except ImportError:
        pass
    from gene2vec_tpu.viz.dash_app import serve

    with pytest.raises(ImportError, match="dash"):
        serve("/nonexistent.json")


def test_umap_3d_gated():
    """n_components != 2 still needs umap-learn; 2-D is served in-repo."""
    try:
        import umap  # noqa: F401

        pytest.skip("umap installed; gating not exercised")
    except ImportError:
        pass
    from gene2vec_tpu.viz.plot import reduce_embedding

    with pytest.raises(ImportError, match="umap"):
        reduce_embedding(
            np.zeros((10, 4), np.float32), method="umap", n_components=3
        )


def test_umap_fit_ab_canonical():
    """The kernel fit at default min_dist/spread must land on the
    canonical umap-learn values (a ~= 1.58, b ~= 0.90)."""
    from gene2vec_tpu.viz.umap import fit_ab

    a, b = fit_ab(0.1, 1.0)
    assert abs(a - 1.58) < 0.12, a
    assert abs(b - 0.90) < 0.08, b
    # fast-kernel path: b pinned to 7/8, a refit to the same curve
    a8, b8 = fit_ab(0.1, 1.0, fixed_b=0.875)
    assert b8 == 0.875
    assert abs(a8 - 1.58) < 0.25, a8


def test_umap_separates_blobs_like_tsne():
    """TPU UMAP (full-batch CE optimizer) must separate planted blobs at
    least as cleanly as the t-SNE sanity bound (VERDICT r4 item 8)."""
    from gene2vec_tpu.viz.umap import UMAPConfig, umap_layout

    x, labels = _blobs()
    y = umap_layout(
        x, UMAPConfig(pca_dims=10, n_iters=200, n_neighbors=10, seed=0)
    )
    assert y.shape == (x.shape[0], 2)
    assert np.isfinite(y).all()
    dists = np.linalg.norm(y[:, None] - y[None, :], axis=-1)
    same = labels[:, None] == labels[None, :]
    np.fill_diagonal(same, False)
    intra = dists[same].mean()
    inter = dists[~same & ~np.eye(len(y), dtype=bool)].mean()
    assert inter > 2.0 * intra, (intra, inter)


def test_umap_via_reduce_embedding():
    from gene2vec_tpu.viz.plot import reduce_embedding

    x, _ = _blobs()
    y = reduce_embedding(x, method="umap")
    assert y.shape == (x.shape[0], 2) and np.isfinite(y).all()


_OBO = """format-version: 1.2

[Term]
id: GO:0000001
name: root process
namespace: biological_process

[Term]
id: GO:0000002
name: child process
namespace: biological_process
alt_id: GO:0000099
is_a: GO:0000001 ! root process

[Term]
id: GO:0000003
name: grandchild
namespace: biological_process
is_a: GO:0000002 ! child process
is_a: GO:0000001 ! root process

[Term]
id: GO:0000004
name: gone
is_obsolete: true

[Typedef]
id: part_of
"""


def test_parse_obo_levels_and_depths(tmp_path):
    from gene2vec_tpu.viz.dash_app import parse_obo

    obo = tmp_path / "go-basic.obo"
    obo.write_text(_OBO)
    dag = parse_obo(str(obo))
    assert "GO:0000004" not in dag  # obsolete dropped
    assert dag["GO:0000001"].level == 0 and dag["GO:0000001"].depth == 0
    assert dag["GO:0000002"].parents == ("GO:0000001",)
    # grandchild: shortest path 1 (direct is_a root), longest 2
    assert dag["GO:0000003"].level == 1
    assert dag["GO:0000003"].depth == 2
    assert dag["GO:0000099"].name == "child process"  # alt_id alias


def test_parse_gene2go_and_reactome(tmp_path):
    from gene2vec_tpu.viz.dash_app import load_reactome_table, parse_gene2go

    g2g = tmp_path / "gene2go"
    g2g.write_text(
        "#tax_id\tGeneID\tGO_ID\tEvidence\n"
        "9606\t7157\tGO:0000002\tIEA\n"
        "9606\t7158\tGO:0000002\tIDA\n"
        "9606\t7157\tGO:0000002\tIDA\n"     # duplicate gene, second evidence
        "10090\t999\tGO:0000002\tIEA\n"     # mouse, filtered out
    )
    members = parse_gene2go(str(g2g), taxids=[9606])
    assert members == {"GO:0000002": ["7157", "7158"]}

    rt = tmp_path / "reactome.txt"
    rt.write_text(
        "7157\tR-HSA-1\thttp://r/1\tApoptosis\tTAS\tHomo sapiens\n"
        "7158\tR-HSA-1\thttp://r/1\tApoptosis\tTAS\tHomo sapiens\n"
        "999\tR-MMU-9\thttp://r/9\tMouse thing\tTAS\tMus musculus\n"
    )
    m, info = load_reactome_table(str(rt), species=["Homo sapiens"])
    assert m == {"R-HSA-1": ["7157", "7158"]}
    assert info["R-HSA-1"]["name"] == "Apoptosis"


def test_dash_descriptions_and_app_state(tmp_path):
    """The description panel text (src/gene2vec_dash_app.py:252-276) and
    the full serve()-side state assembled without dash."""
    import json as _json

    from gene2vec_tpu.viz.dash_app import build_app_state

    obo = tmp_path / "go.obo"
    obo.write_text(_OBO)
    g2g = tmp_path / "gene2go"
    g2g.write_text("9606\tA\tGO:0000002\tIEA\n9606\tB\tGO:0000002\tIEA\n")
    rt = tmp_path / "reactome.txt"
    rt.write_text("A\tR-HSA-1\thttp://r/1\tApoptosis\tTAS\tHomo sapiens\n")
    fig = tmp_path / "fig.json"
    fig.write_text(_json.dumps(
        {"data": [{"customdata": ["A", "B"], "x": [0, 1]}], "layout": {}}
    ))

    state = build_app_state(
        str(fig), go_obo=str(obo), gene2go=str(g2g), reactome_file=str(rt)
    )
    go = state["sources"]["GO"]
    assert go["members"] == {"GO:0000002": ["A", "B"]}
    desc = go["describe"]("GO:0000002", ["A", "B"])
    assert "GO ID: GO:0000002" in desc
    assert "Name: child process" in desc
    assert "Namespace: biological_process" in desc
    assert "Level: 1" in desc and "Depth: 1" in desc
    assert "A, B" in desc
    assert go["options"][0]["label"].startswith("GO:0000002")

    r = state["sources"]["Reactome"]
    rdesc = r["describe"]("R-HSA-1", ["A"])
    assert "Reactome ID: R-HSA-1" in rdesc
    assert "Name: Apoptosis" in rdesc
    assert "Species: Homo sapiens" in rdesc
    assert "url: http://r/1" in rdesc


def test_tsne_bfloat16_separates_blobs():
    """The halved-traffic bf16 kernel path must reach the same qualitative
    layout (cluster separation) as f32 — reductions accumulate f32, so
    only the (N, N) kernel values carry bf16 rounding."""
    x, labels = _blobs()
    cfg = TSNEConfig(
        pca_dims=10, n_iter=500, seed=0, compute_dtype="bfloat16"
    )
    y = TSNE(config=cfg).fit(x, log=lambda s: None)[500]
    dists = np.linalg.norm(y[:, None] - y[None, :], axis=-1)
    same = labels[:, None] == labels[None, :]
    np.fill_diagonal(same, False)
    intra = dists[same].mean()
    inter = dists[~same & ~np.eye(len(y), dtype=bool)].mean()
    assert inter > 2.0 * intra, (intra, inter)
