"""Visualization subsystem: t-SNE quality, sweep file parity, plot exports,
GTEx figures, dash logic layer."""

import json
import os

import numpy as np
import pytest

from gene2vec_tpu.config import TSNEConfig
from gene2vec_tpu.io.emb_io import write_matrix_txt
from gene2vec_tpu.viz.tsne import TSNE, pca_reduce, run_tsne_sweep


def _blobs(n_per=50, d=20, k=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, d) * 6.0
    x = np.concatenate(
        [centers[i] + rng.randn(n_per, d) for i in range(k)], axis=0
    ).astype(np.float32)
    labels = np.repeat(np.arange(k), n_per)
    return x, labels


def test_pca_reduce_shapes_and_variance():
    x, _ = _blobs()
    r = pca_reduce(x, 5)
    assert r.shape == (x.shape[0], 5)
    # first component captures the most variance
    var = r.var(axis=0)
    assert np.all(np.diff(var) <= 1e-6)


def test_tsne_separates_blobs():
    x, labels = _blobs()
    cfg = TSNEConfig(pca_dims=10, n_iter=500, seed=0)
    out = TSNE(config=cfg).fit(x, log=lambda s: None)
    y = out[500]
    assert y.shape == (x.shape[0], 2)
    # mean intra-cluster distance well below inter-cluster distance
    dists = np.linalg.norm(y[:, None] - y[None, :], axis=-1)
    same = labels[:, None] == labels[None, :]
    np.fill_diagonal(same, False)
    intra = dists[same].mean()
    inter = dists[~same & ~np.eye(len(y), dtype=bool)].mean()
    assert inter > 2.0 * intra, (intra, inter)


def test_tsne_snapshots_share_one_run():
    x, _ = _blobs(n_per=30)
    cfg = TSNEConfig(pca_dims=10, seed=0)
    out = TSNE(config=cfg).fit(x, snapshot_iters=[50, 150], log=lambda s: None)
    assert set(out) == {50, 150}
    assert not np.allclose(out[50], out[150])  # training continued


def test_tsne_sweep_file_parity(tmp_path):
    """labels.txt + one coord file per snapshot, row-aligned."""
    x, _ = _blobs(n_per=20)
    toks = [f"G{i}" for i in range(len(x))]
    emb = tmp_path / "emb.txt"
    write_matrix_txt(str(emb), toks, x)
    out = tmp_path / "tsne"
    written = run_tsne_sweep(
        str(emb), str(out), iters=[30, 60],
        config=TSNEConfig(pca_dims=10), log=lambda s: None,
    )
    assert (out / "labels.txt").exists()
    assert (out / "tsne_iter_30.txt").exists()
    assert (out / "tsne_iter_60.txt").exists()
    labels = (out / "labels.txt").read_text().split()
    coords = np.loadtxt(out / "tsne_iter_60.txt")
    assert len(labels) == coords.shape[0] == len(x)
    assert set(labels) == set(toks)  # shuffled but complete
    assert len(written) == 3


def test_plot_exports_json_and_figure(tmp_path):
    from gene2vec_tpu.viz.plot import plot_gene2vec

    x, _ = _blobs(n_per=15, d=8)
    toks = [f"G{i}" for i in range(len(x))]
    emb = tmp_path / "emb.txt"
    write_matrix_txt(str(emb), toks, x)
    written = plot_gene2vec(
        str(emb), str(tmp_path / "fig"), method="pca", log=lambda s: None
    )
    payload = json.load(open(tmp_path / "fig.json"))
    assert payload["data"][0]["customdata"] == toks
    assert len(payload["data"][0]["x"]) == len(toks)
    # html (plotly) or png (matplotlib fallback) — exactly one of them
    assert any(w.endswith((".html", ".png")) for w in written)


def test_gtex_figure(tmp_path):
    from gene2vec_tpu.viz.gtex import run_gtex_figures

    rng = np.random.RandomState(0)
    genes = [f"G{i}" for i in range(40)]
    (tmp_path / "labels.txt").write_text("\n".join(genes) + "\n")
    np.savetxt(tmp_path / "coords.txt", rng.randn(40, 2))
    (tmp_path / "Liver_specific_genes.txt").write_text(
        "gene z\n" + "\n".join(f"G{i} {rng.randn() + 2:.3f}" for i in range(10))
    )
    written = run_gtex_figures(
        str(tmp_path / "labels.txt"),
        str(tmp_path / "coords.txt"),
        str(tmp_path / "*specific_genes.txt"),
        str(tmp_path / "figs"),
        log=lambda s: None,
    )
    assert len(written) == 1
    assert os.path.getsize(written[0]) > 10_000  # a real png


def test_dash_logic_highlight_and_tables(tmp_path):
    from gene2vec_tpu.viz.dash_app import (
        ACTIVE_COLOR,
        BASE_COLOR,
        INACTIVE_COLOR,
        highlight_genes,
        load_gmt_terms,
        parse_annotation_table,
        term_options,
    )

    figure = {
        "data": [
            {"type": "scattergl", "customdata": ["A", "B", "C"], "x": [0, 1, 2]}
        ],
        "layout": {},
    }
    hi = highlight_genes(figure, ["B"])
    assert hi["data"][0]["marker"]["color"] == [
        INACTIVE_COLOR, ACTIVE_COLOR, INACTIVE_COLOR,
    ]
    assert figure["data"][0].get("marker") is None  # pure function
    reset = highlight_genes(figure, [])
    assert reset["data"][0]["marker"]["color"] == BASE_COLOR

    tsv = tmp_path / "go.tsv"
    tsv.write_text("GO:1\tA\tthing one\nGO:1\tB\tthing one\nGO:2\tC\tother\n")
    members, desc = parse_annotation_table(str(tsv))
    assert members == {"GO:1": ["A", "B"], "GO:2": ["C"]}
    assert desc["GO:2"] == "other"
    opts = term_options(members, desc)
    assert opts[0]["value"] == "GO:1" and "thing one" in opts[0]["label"]

    gmt = tmp_path / "p.gmt"
    gmt.write_text("P1\thttp://u\tA\tB\n")
    m2, d2 = load_gmt_terms(str(gmt))
    assert m2 == {"P1": ["A", "B"]} and d2["P1"] == "http://u"


def test_dash_serve_gated():
    try:
        import dash  # noqa: F401

        pytest.skip("dash installed; gating not exercised")
    except ImportError:
        pass
    from gene2vec_tpu.viz.dash_app import serve

    with pytest.raises(ImportError, match="dash"):
        serve("/nonexistent.json")


def test_umap_gated():
    try:
        import umap  # noqa: F401

        pytest.skip("umap installed; gating not exercised")
    except ImportError:
        pass
    from gene2vec_tpu.viz.plot import reduce_embedding

    with pytest.raises(ImportError, match="umap"):
        reduce_embedding(np.zeros((10, 4), np.float32), method="umap")
