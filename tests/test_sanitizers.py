"""graftcheck tier-3 (slow, sanitizer): native parity under ASAN/UBSAN
(+ TSAN with Hogwild's intended races suppressed).

Each test builds the instrumented libraries via ``make -C native <kind>``
and runs the pairio + Hogwild parity workload in a sanitized child
process (see gene2vec_tpu/analysis/sanitize.py for the preload
mechanics).  A nonzero child exit carries the sanitizer report in the
failure message.  Skips cleanly when the toolchain lacks a runtime —
but a failed *build* on a present toolchain FAILS (with the make stderr)
rather than skipping, so build breakage cannot silently disable the
memory-safety gate.

Run: ``pytest tests/test_sanitizers.py -m sanitizer`` or
``scripts/run_static_analysis.sh --with-sanitizers``.
"""

import pytest

from gene2vec_tpu.analysis.sanitize import (
    KINDS,
    build,
    run_parity,
    toolchain_available,
)

pytestmark = [pytest.mark.slow, pytest.mark.sanitizer]


def _built(kind):
    """Skip on missing toolchain; fail loudly on a broken build."""
    if not toolchain_available(kind):
        pytest.skip(f"{kind} toolchain unavailable")
    ok, detail = build(kind)
    assert ok, f"{kind} instrumented build failed (gates, not skips):\n{detail}"


@pytest.mark.parametrize("kind", KINDS)
def test_parity_under_sanitizer(kind):
    _built(kind)
    proc = run_parity(kind)
    assert proc.returncode == 0, (
        f"{kind} parity run failed (exit {proc.returncode}); report tail:\n"
        + proc.stderr[-4000:]
    )
    assert "PARITY_OK" in proc.stderr


def test_tsan_suppressions_are_load_bearing():
    """Without native/tsan.supp the Hogwild kernel MUST report races —
    they are the algorithm.  This guards against a future build change
    (e.g. accidentally serializing the workers) silently turning the
    suppressed TSAN run into a vacuous pass."""
    _built("tsan")
    proc = run_parity(
        "tsan", options="halt_on_error=0",
        extra_env={"GRAFTCHECK_SMALL": "1"},
    )
    assert "WARNING: ThreadSanitizer: data race" in proc.stderr, (
        "unsuppressed TSAN saw no races — the Hogwild workers are no "
        "longer racing (serialized build?) or TSAN is not engaging:\n"
        + proc.stderr[-2000:]
    )


def test_tsan_control_findings_confirm_supp_entries():
    """The ``--sanitizers tsan`` control run (sanitize.
    tsan_control_findings): races must be reported AND every tsan.supp
    entry must match one, so stale suppressions surface as warnings
    instead of silently hiding future real races."""
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.sanitize import tsan_control_findings

    _built("tsan")
    findings = tsan_control_findings()
    assert gating(findings) == [], (
        "tsan control run gated:\n"
        + "\n".join(f.message for f in findings)
    )
    assert any("load-bearing" in f.message for f in findings)
