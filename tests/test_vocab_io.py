import numpy as np
import pytest

from gene2vec_tpu.io.emb_io import (
    load_embedding_any,
    load_embedding_for_vocab,
    read_matrix_txt,
    read_word2vec_format,
    write_matrix_txt,
    write_word2vec_format,
)
from gene2vec_tpu.io.pair_reader import load_corpus, read_pair_files
from gene2vec_tpu.io.vocab import Vocab


def test_vocab_frequency_order():
    pairs = [["A", "B"], ["A", "C"], ["A", "B"], ["D", "C"]]
    v = Vocab.from_pairs(pairs)
    assert v.id_to_token[0] == "A"  # count 3
    # ties (B:2, C:2) break by first appearance
    assert v.id_to_token[1] == "B" and v.id_to_token[2] == "C"
    assert v.id_to_token[3] == "D"
    assert v.counts.tolist() == [3, 2, 2, 1]


def test_vocab_min_count_and_encode():
    pairs = [["A", "B"], ["A", "C"], ["B", "A"]]
    v = Vocab.from_pairs(pairs, min_count=2)
    assert "C" not in v
    enc = v.encode_pairs(pairs)
    # the A-C pair is dropped
    assert enc.shape == (2, 2)
    assert set(map(tuple, enc.tolist())) == {
        (v.token_to_id["A"], v.token_to_id["B"]),
        (v.token_to_id["B"], v.token_to_id["A"]),
    }


def test_vocab_roundtrip(tmp_path):
    v = Vocab.from_pairs([["X", "Y"], ["X", "Z"]])
    p = tmp_path / "vocab.tsv"
    v.save(str(p))
    v2 = Vocab.load(str(p))
    assert v2.id_to_token == v.id_to_token
    assert v2.counts.tolist() == v.counts.tolist()
    assert v2.token_to_id == v.token_to_id


def test_read_pair_files_filters_pattern(synthetic_corpus_dir):
    pairs = read_pair_files(synthetic_corpus_dir, "txt")
    assert len(pairs) == 300
    assert all(len(p) == 2 for p in pairs)


def test_load_corpus(synthetic_corpus_dir):
    vocab, enc = load_corpus(synthetic_corpus_dir, "txt")
    assert enc.shape == (300, 2)
    assert enc.max() < len(vocab)
    # counts must equal occurrences in the corpus
    flat = enc.reshape(-1)
    binc = np.bincount(flat, minlength=len(vocab))
    assert binc.tolist() == vocab.counts.tolist()


def test_matrix_txt_roundtrip(tmp_path):
    toks = ["TP53", "BRCA1", "EGFR"]
    m = np.random.RandomState(0).randn(3, 5).astype(np.float32)
    p = str(tmp_path / "emb.txt")
    write_matrix_txt(p, toks, m)
    # format check: gene \t v v v ... v<space>\n  (src/generateMatrix.py:19-23)
    first = open(p).readline()
    assert first.startswith("TP53\t") and first.endswith(" \n")
    toks2, m2 = read_matrix_txt(p)
    assert toks2 == toks
    np.testing.assert_allclose(m2, m, rtol=1e-6)


def test_word2vec_format_roundtrip(tmp_path):
    toks = ["TP53", "BRCA1"]
    m = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    p = str(tmp_path / "emb_w2v.txt")
    write_word2vec_format(p, toks, m)
    header = open(p).readline().split()
    assert header == ["2", "4"]  # "<count> <dim>" header the reference detects
    toks2, m2 = read_word2vec_format(p)
    assert toks2 == toks
    np.testing.assert_allclose(m2, m, rtol=1e-6)


def test_load_embedding_any_detects_format(tmp_path):
    toks = ["A", "B"]
    m = np.eye(2, 3, dtype=np.float32)
    p1, p2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    write_matrix_txt(p1, toks, m)
    write_word2vec_format(p2, toks, m)
    for p in (p1, p2):
        t, mm = load_embedding_any(p)
        assert t == toks
        np.testing.assert_allclose(mm, m)


def test_load_embedding_for_vocab_missing_fallback(tmp_path):
    # present genes get file vectors; missing genes keep U(-0.25,0.25)
    # random init (src/GGIPNN_util.py:6-14)
    toks = ["A", "B"]
    m = np.full((2, 4), 3.0, dtype=np.float32)
    p = str(tmp_path / "emb.txt")
    write_matrix_txt(p, toks, m)
    vocab = {"A": 0, "MISSING": 1, "B": 2}
    out = load_embedding_for_vocab(vocab, p, 4)
    np.testing.assert_allclose(out[0], 3.0)
    np.testing.assert_allclose(out[2], 3.0)
    assert np.all(np.abs(out[1]) <= 0.25) and not np.allclose(out[1], 3.0)
