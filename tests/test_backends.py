"""Backend parity: the numpy CPU oracle and the jax path must land in the
same statistical regime (SURVEY §7 hard part 2 — the gate is embedding
quality, not bitwise equality)."""

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns.backends import make_backend_trainer

from conftest import cluster_separation


def test_numpy_and_jax_backends_recover_structure(
    tmp_path, synthetic_corpus_dir
):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    seps = {}
    for backend in ("numpy", "jax"):
        # 60 epochs: measured separation is ~0.006 @ 15, ~0.22 @ 30,
        # ~0.6 @ 60 for BOTH backends (trajectories track closely)
        cfg = SGNSConfig(dim=16, num_iters=60, batch_pairs=64, seed=0)
        trainer = make_backend_trainer(corpus, cfg, backend=backend)
        params = trainer.run(str(tmp_path / backend), log=lambda s: None)
        seps[backend] = cluster_separation(
            np.asarray(params.emb), vocab.id_to_token
        )
    # both must separate the planted clusters decisively
    assert seps["numpy"] > 0.3, seps
    assert seps["jax"] > 0.3, seps


def test_numpy_backend_resume_matches_uninterrupted(
    tmp_path, synthetic_corpus_dir
):
    """ADVICE r1: a resumed run must continue the per-iteration RNG streams,
    not replay iteration 1's."""
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(dim=8, num_iters=3, batch_pairs=64, seed=2)

    straight = make_backend_trainer(corpus, cfg, backend="numpy")
    p_straight = straight.run(str(tmp_path / "a"), log=lambda s: None)

    partial_cfg = SGNSConfig(dim=8, num_iters=2, batch_pairs=64, seed=2)
    part = make_backend_trainer(corpus, partial_cfg, backend="numpy")
    part.run(str(tmp_path / "b"), log=lambda s: None)
    resumed = make_backend_trainer(corpus, cfg, backend="numpy")
    p_resumed = resumed.run(str(tmp_path / "b"), log=lambda s: None)

    np.testing.assert_allclose(
        np.asarray(p_resumed.emb), np.asarray(p_straight.emb), atol=1e-6
    )
