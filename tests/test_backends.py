"""Backend parity: the numpy CPU oracle and the jax path must land in the
same statistical regime (SURVEY §7 hard part 2 — the gate is embedding
quality, not bitwise equality)."""

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns.backends import make_backend_trainer

from conftest import cluster_separation


def test_numpy_and_jax_backends_recover_structure(
    tmp_path, synthetic_corpus_dir
):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    seps = {}
    for backend in ("numpy", "jax"):
        # 60 epochs: measured separation is ~0.006 @ 15, ~0.22 @ 30,
        # ~0.6 @ 60 for BOTH backends (trajectories track closely)
        cfg = SGNSConfig(dim=16, num_iters=60, batch_pairs=64, seed=0)
        trainer = make_backend_trainer(corpus, cfg, backend=backend)
        params = trainer.run(str(tmp_path / backend), log=lambda s: None)
        seps[backend] = cluster_separation(
            np.asarray(params.emb), vocab.id_to_token
        )
    # both must separate the planted clusters decisively
    assert seps["numpy"] > 0.3, seps
    assert seps["jax"] > 0.3, seps


def test_numpy_backend_resume_matches_uninterrupted(
    tmp_path, synthetic_corpus_dir
):
    """ADVICE r1: a resumed run must continue the per-iteration RNG streams,
    not replay iteration 1's."""
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(dim=8, num_iters=3, batch_pairs=64, seed=2)

    straight = make_backend_trainer(corpus, cfg, backend="numpy")
    p_straight = straight.run(str(tmp_path / "a"), log=lambda s: None)

    partial_cfg = SGNSConfig(dim=8, num_iters=2, batch_pairs=64, seed=2)
    part = make_backend_trainer(corpus, partial_cfg, backend="numpy")
    part.run(str(tmp_path / "b"), log=lambda s: None)
    resumed = make_backend_trainer(corpus, cfg, backend="numpy")
    p_resumed = resumed.run(str(tmp_path / "b"), log=lambda s: None)

    np.testing.assert_allclose(
        np.asarray(p_resumed.emb), np.asarray(p_straight.emb), atol=1e-6
    )


class _FakeWv:
    def __init__(self, tokens, dim, seed):
        self.index_to_key = list(tokens)
        self.vectors = np.random.RandomState(seed).randn(
            len(tokens), dim
        ).astype(np.float32)


class _FakeWord2Vec:
    """Minimal gensim.models.Word2Vec stand-in: records how many train()
    calls it has absorbed and round-trips through save/load, so the
    GensimTrainer resume logic is exercisable without the real package."""

    def __init__(self, sentences, **kwargs):
        dim = kwargs.get("vector_size") or kwargs.get("size")
        toks = sorted({t for s in sentences for t in s})
        self.wv = _FakeWv(toks, dim, seed=kwargs.get("seed", 0))
        self.corpus_count = len(sentences)
        self.trained_epochs = 1  # constructor trains once

    def train(self, sentences, total_examples=None, epochs=1):
        self.trained_epochs += epochs
        self.wv.vectors += 0.01  # visible effect per epoch

    def save(self, path):
        import pickle

        with open(path, "wb") as f:
            pickle.dump(self, f)

    @classmethod
    def load(cls, path):
        import pickle

        with open(path, "rb") as f:
            return pickle.load(f)


def test_gensim_backend_resumes_mid_run(
    tmp_path, synthetic_corpus_dir, monkeypatch
):
    """The reference's resume semantics (src/gene2vec.py:86-88): a restarted
    run reloads the previous iteration's saved gensim model and continues —
    it must NOT retrain from iteration 1.  Runs against the real gensim when
    installed, else a minimal fake (the wrapper logic is what's under test)."""
    import sys
    import types

    try:
        import gensim  # noqa: F401
    except ImportError:
        fake = types.ModuleType("gensim")
        fake.models = types.SimpleNamespace(Word2Vec=_FakeWord2Vec)
        monkeypatch.setitem(sys.modules, "gensim", fake)

    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(dim=8, num_iters=4, seed=0)
    out = str(tmp_path / "gensim_run")
    logs = []

    # interrupted run: iterations 1..2 only
    trainer = make_backend_trainer(corpus, cfg, backend="gensim")
    trainer.run(out, start_iter=None, log=logs.append)
    # simulate the interruption by deleting iterations 3+ artifacts — train
    # only up to 2 by running with num_iters=2 instead
    import shutil

    shutil.rmtree(out)
    cfg2 = SGNSConfig(dim=8, num_iters=2, seed=0)
    trainer = make_backend_trainer(corpus, cfg2, backend="gensim")
    model2 = trainer.run(out, log=logs.append)

    # restart with the full iteration budget: must resume from 3
    logs.clear()
    trainer = make_backend_trainer(corpus, cfg, backend="gensim")
    model = trainer.run(out, log=logs.append)
    assert any("resuming from iteration 2" in m for m in logs), logs
    assert not any("retraining from iteration 1" in m for m in logs), logs
    if model is not None and hasattr(model, "trained_epochs"):
        # fake backend: 1 (ctor) + 1 (iter 2) from the first run persisted
        # in the save file, + 2 more (iters 3, 4) after resume
        assert model2.trained_epochs == 2
        assert model.trained_epochs == 4
    # all four iterations' npz + gensim model files exist
    import os

    for it in range(1, 5):
        assert os.path.exists(
            os.path.join(out, f"gene2vec_dim_8_iter_{it}.npz")
        )
        assert os.path.exists(
            os.path.join(out, f"gene2vec_dim_8_iter_{it}.gensim")
        )
