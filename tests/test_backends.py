"""Backend parity: the numpy CPU oracle and the jax path must land in the
same statistical regime (SURVEY §7 hard part 2 — the gate is embedding
quality, not bitwise equality)."""

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns.backends import make_backend_trainer

from conftest import cluster_separation


def test_numpy_and_jax_backends_recover_structure(
    tmp_path, synthetic_corpus_dir
):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    seps = {}
    for backend in ("numpy", "jax"):
        # 60 epochs: measured separation is ~0.006 @ 15, ~0.22 @ 30,
        # ~0.6 @ 60 for BOTH backends (trajectories track closely)
        cfg = SGNSConfig(dim=16, num_iters=60, batch_pairs=64, seed=0)
        trainer = make_backend_trainer(corpus, cfg, backend=backend)
        params = trainer.run(str(tmp_path / backend), log=lambda s: None)
        seps[backend] = cluster_separation(
            np.asarray(params.emb), vocab.id_to_token
        )
    # both must separate the planted clusters decisively
    assert seps["numpy"] > 0.3, seps
    assert seps["jax"] > 0.3, seps
