"""Continuous-learning loop: ingest cursor, journal/state machine,
warm-start bit-exactness (vocab extension included), shadow scoring,
publish/promotion plumbing, model-freshness telemetry, and the
passes_loop budget gate (docs/CONTINUOUS.md)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.loop import ingest as ing
from gene2vec_tpu.loop.promote import (
    CycleDriver,
    LoopJournal,
    LoopState,
    journal_path,
    quarantine_candidate,
)
from gene2vec_tpu.loop.shadow import ShadowManager, ShadowScorer, topk_churn


def _mk_vocab(tokens):
    return Vocab(list(tokens), np.arange(len(tokens), 0, -1))


def _lines(n, seed=0, clusters=3, per=6):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        c = rng.randint(clusters)
        a, b = rng.choice(per, 2, replace=False) + per * c
        out.append(f"G{a} G{b}")
    return out


# -- ingest cursor -----------------------------------------------------------


def test_ingest_commit_idempotent_and_torn_append_recovery(tmp_path):
    root = str(tmp_path / "loop")
    base = _mk_vocab([f"G{i}" for i in range(6)])
    assert ing.init_ingest(root, base)
    assert not ing.init_ingest(root, base)  # idempotent

    f1 = ing.ingest_batch(root, "b1", ["G0 G1", "G2 G3"])
    assert f1["appended_pairs"] == 2 and not f1["skipped"]
    # idempotent replay
    f2 = ing.ingest_batch(root, "b1", ["G0 G1", "G2 G3"])
    assert f2["skipped"] and f2["corpus_bytes"] == f1["corpus_bytes"]

    # torn append: bytes past the committed offset (a SIGKILL mid-
    # write) are truncated away on the next ingest — the half-counted
    # batch never existed
    pairs = os.path.join(ing.ingest_dir(root), ing.PAIRS_NAME)
    with open(pairs, "ab") as f:
        f.write(b"G4 G5\nGARBAGE")
    f3 = ing.ingest_batch(root, "b2", ["G4 G5"])
    assert f3["appended_pairs"] == 1
    corpus, _held = ing.load_loop_corpus(root, holdout_fraction=0.0)
    assert corpus.num_pairs == 3  # b1's 2 + b2's 1, garbage gone


def test_ingest_cursor_self_crc_and_prev_fallback(tmp_path):
    root = str(tmp_path / "loop")
    ing.init_ingest(root, _mk_vocab(["A", "B"]))
    ing.ingest_batch(root, "b1", ["A B"])
    good = ing.load_cursor(root)
    cur = os.path.join(ing.ingest_dir(root), ing.CURSOR_NAME)
    # bit-rot the live cursor: load falls back to the prev commit
    with open(cur, "r+") as f:
        doc = json.load(f)
        doc["corpus_bytes"] = 999999
        f.seek(0)
        json.dump(doc, f)
        f.truncate()
    fallback = ing.load_cursor(root)
    assert fallback["corpus_bytes"] != 999999
    assert fallback["batches"] in ([], good["batches"][:-1], good["batches"])


def test_ingest_post_commit_corpus_rot_detected(tmp_path):
    root = str(tmp_path / "loop")
    ing.init_ingest(root, _mk_vocab(["A", "B", "C"]))
    ing.ingest_batch(root, "b1", ["A B", "B C"])
    pairs = os.path.join(ing.ingest_dir(root), ing.PAIRS_NAME)
    with open(pairs, "r+b") as f:
        f.seek(0)
        f.write(b"X")
    with pytest.raises(IOError, match="CRC"):
        ing.ingest_batch(root, "b2", ["A C"])


def test_loop_vocab_tail_extension_is_stable(tmp_path):
    root = str(tmp_path / "loop")
    base = _mk_vocab(["G0", "G1", "G2"])
    ing.init_ingest(root, base)
    ing.ingest_batch(root, "b1", ["G0 NEWB", "NEWA G1", "NEWB G2"])
    v = ing.loop_vocab(root)
    # base ids untouched; new genes appended in FIRST-APPEARANCE order
    assert v.id_to_token[:3] == ["G0", "G1", "G2"]
    assert v.id_to_token[3:] == ["NEWB", "NEWA"]
    # counts accumulate on top of the base counts
    assert v.counts[v.token_to_id["G0"]] == base.counts[0] + 1
    assert v.counts[v.token_to_id["NEWB"]] == 2
    # a second batch keeps earlier extensions' ids stable
    ing.ingest_batch(root, "b2", ["NEWC G0"])
    v2 = ing.loop_vocab(root)
    assert v2.id_to_token[:5] == v.id_to_token
    assert v2.id_to_token[5] == "NEWC"


def test_seed_reingest_does_not_double_count_base_vocab(tmp_path):
    # the serving vocab's counts already reflect the original corpus;
    # re-ingesting that corpus as the seed batch must REPLACE the base
    # counts, not stack on top of them — a double count would skew the
    # negative-sampling unigram distribution against new genes
    root = str(tmp_path / "loop")
    base = _mk_vocab(["G0", "G1", "G2"])  # counts 3, 2, 1
    ing.init_ingest(root, base)
    ing.ingest_batch(root, "seed", ["G0 G1", "G0 G2"],
                     replaces_base_counts=True)
    ing.ingest_batch(root, "b1", ["G0 NEW"])
    v = ing.loop_vocab(root)
    # counts come from the committed corpus alone (3x G0, 1x each
    # other), never base + corpus; the flag survives later batches
    assert v.counts[v.token_to_id["G0"]] == 3
    assert v.counts[v.token_to_id["G1"]] == 1
    assert v.counts[v.token_to_id["G2"]] == 1
    assert v.counts[v.token_to_id["NEW"]] == 1
    # id order still anchored by the base vocab
    assert v.id_to_token == ["G0", "G1", "G2", "NEW"]


def test_pair_held_is_stable_and_direction_symmetric():
    assert ing.pair_held("A", "B", 0.2) == ing.pair_held("B", "A", 0.2)
    held = [p for p in _lines(500, seed=3)
            if ing.pair_held(*p.split(), 0.2)]
    assert 0.05 < len(held) / 500 < 0.45  # roughly the asked fraction
    # and membership never flips between calls
    assert held == [p for p in _lines(500, seed=3)
                    if ing.pair_held(*p.split(), 0.2)]


# -- journal + state machine -------------------------------------------------


def test_journal_replay_ignores_torn_tail_only(tmp_path):
    path = journal_path(str(tmp_path), "c1")
    j = LoopJournal(path, "c1")
    j.enter(LoopState.INGESTING)
    j.done(LoopState.INGESTING, appended_pairs=3)
    with open(path, "a") as f:
        f.write('{"torn": tru')  # SIGKILL mid-append
    j2 = LoopJournal(path, "c1")
    assert [r["event"] for r in j2.replay()] == ["enter", "done"]
    assert j2.done_facts()[LoopState.INGESTING]["appended_pairs"] == 3
    # a torn record BEFORE the tail is post-commit corruption: raise
    with open(path, "w") as f:
        f.write('{"torn": tru\n')
        f.write(json.dumps({"state": "X", "event": "done"}) + "\n")
    with pytest.raises(IOError):
        LoopJournal(path, "c1").replay()


def _steps(trace, **overrides):
    def mk(state, facts=None):
        def fn(context):
            trace.append(state)
            return dict(facts or {})
        return fn

    steps = {
        LoopState.INGESTING: mk(LoopState.INGESTING),
        LoopState.TRAINING: mk(
            LoopState.TRAINING, {"final_iteration": 5}
        ),
        LoopState.QUALITY_GATE: mk(
            LoopState.QUALITY_GATE, {"passed": True}
        ),
        LoopState.SHADOWING: mk(
            LoopState.SHADOWING, {"verdict": "promote"}
        ),
        LoopState.PROMOTING: mk(LoopState.PROMOTING),
        LoopState.SERVING: mk(LoopState.SERVING),
    }
    steps.update(overrides)
    return steps


def test_cycle_driver_runs_to_serving_and_resume_skips_done(tmp_path):
    path = journal_path(str(tmp_path), "c1")
    trace = []
    out = CycleDriver(LoopJournal(path, "c1"), _steps(trace)).run()
    assert out["state"] == LoopState.SERVING
    assert trace == list(
        s for s in
        (LoopState.INGESTING, LoopState.TRAINING,
         LoopState.QUALITY_GATE, LoopState.SHADOWING,
         LoopState.PROMOTING, LoopState.SERVING)
    )
    # resume: every state is committed — nothing re-runs
    trace2 = []
    out2 = CycleDriver(LoopJournal(path, "c1"), _steps(trace2)).run()
    assert out2["state"] == LoopState.SERVING and trace2 == []


def test_cycle_driver_resumes_mid_cycle(tmp_path):
    path = journal_path(str(tmp_path), "c2")
    trace = []

    def boom(context):
        trace.append("boom")
        raise RuntimeError("killed mid-state")

    with pytest.raises(RuntimeError):
        CycleDriver(
            LoopJournal(path, "c2"),
            _steps(trace, **{LoopState.SHADOWING: boom}),
        ).run()
    # resume re-runs ONLY the un-committed states
    trace2 = []
    out = CycleDriver(LoopJournal(path, "c2"), _steps(trace2)).run()
    assert out["state"] == LoopState.SERVING
    assert trace2 == [
        LoopState.SHADOWING, LoopState.PROMOTING, LoopState.SERVING
    ]


def test_cycle_driver_demotes_on_failed_gate_and_shadow(tmp_path):
    for cid, overrides, reason_frag in (
        ("q", {LoopState.QUALITY_GATE: lambda c: {
            "passed": False, "reason": "auc low"}}, "auc low"),
        ("s", {LoopState.SHADOWING: lambda c: {
            "verdict": "demote", "reason": "churny"}}, "churny"),
    ):
        path = journal_path(str(tmp_path), cid)
        trace = []
        demoted = []
        out = CycleDriver(
            LoopJournal(path, cid), _steps(trace, **overrides),
            demote_step=lambda c: demoted.append(1) or {"quarantined": "q"},
        ).run()
        assert out["state"] == LoopState.DEMOTED
        assert demoted == [1]
        assert reason_frag in out["context"][LoopState.DEMOTED]["reason"]
        assert LoopState.PROMOTING not in trace
        # resume of a demoted cycle is terminal, no re-run
        out2 = CycleDriver(LoopJournal(path, cid), _steps([])).run()
        assert out2["state"] == LoopState.DEMOTED


def test_quarantine_candidate_moves_dir(tmp_path):
    cand = tmp_path / "candidates" / "b1"
    cand.mkdir(parents=True)
    (cand / "x.npz").write_bytes(b"data")
    dst = quarantine_candidate(str(tmp_path), str(cand), "b1")
    assert dst and os.path.exists(os.path.join(dst, "x.npz"))
    assert not cand.exists()
    assert quarantine_candidate(str(tmp_path), str(cand), "b1") is None


# -- warm-start bit-exactness (the satellite contract) -----------------------


def _train_serving(tmp_path, lines, cfg):
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.sgns.train import SGNSTrainer

    vocab = Vocab.from_pairs([ln.split() for ln in lines])
    corpus = PairCorpus(vocab, vocab.encode_pairs(
        [ln.split() for ln in lines]
    ))
    serving = str(tmp_path / "serving")
    SGNSTrainer(corpus, cfg).run(serving, log=lambda s: None)
    return serving, vocab


def test_warm_start_continuation_bit_exact_with_vocab_extension(tmp_path):
    """Continuation from iteration N equals an uninterrupted run to
    N+k bit-for-bit — including the new-gene vocab-extension case and
    a kill-between-iterations resume (the on-disk state a SIGKILL
    mid-continuation leaves behind)."""
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.loop import trainer as ltr

    cfg = SGNSConfig(
        dim=8, batch_pairs=64, num_iters=2, txt_output=False, seed=1
    )
    lines = _lines(200, seed=5)
    serving, base_vocab = _train_serving(tmp_path, lines, cfg)

    root = str(tmp_path / "loop")
    ing.init_ingest(root, base_vocab)
    ing.ingest_batch(root, "seed", lines)
    ing.ingest_batch(
        root, "b1", ["GNEWX G0", "GNEWX G2", "GNEWY G7", "GNEWY G8"] * 3
    )
    corpus, _held = ing.load_loop_corpus(root, holdout_fraction=0.2)
    assert corpus.vocab_size == len(base_vocab) + 2

    # uninterrupted continuation
    cand_a = str(tmp_path / "cand_a")
    pa, base_a, fin_a = ltr.train_candidate(
        serving, cand_a, corpus, cfg, 2, log=lambda s: None
    )
    # interrupted continuation: stop after 1 iter (= the committed
    # state a SIGKILL leaves), then resume to the same target
    cand_b = str(tmp_path / "cand_b")
    ltr.train_candidate(serving, cand_b, corpus, cfg, 1,
                        log=lambda s: None)
    pb, base_b, fin_b = ltr.train_candidate(
        serving, cand_b, corpus, cfg, 2, log=lambda s: None
    )
    assert (base_a, fin_a) == (base_b, fin_b)
    assert np.array_equal(np.asarray(pa.emb), np.asarray(pb.emb))
    assert np.array_equal(np.asarray(pa.ctx), np.asarray(pb.ctx))

    # adoption seeded the extension deterministically: base rows are
    # the serving table bit-for-bit, new rows the init-slice
    adopted, avocab, meta = ckpt.load_iteration(
        cand_a, cfg.dim, base_a, table_dtype=None
    )
    src, _sv, _sm = ckpt.load_iteration(
        serving, cfg.dim, base_a, table_dtype=None
    )
    assert np.array_equal(
        np.asarray(adopted.emb)[: len(base_vocab)], np.asarray(src.emb)
    )
    assert meta["warm_start"]["new_genes"] == 2
    assert avocab.id_to_token[: len(base_vocab)] == base_vocab.id_to_token


def test_extend_params_is_deterministic_and_guards_shrink():
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.loop.trainer import extend_params
    from gene2vec_tpu.sgns.model import SGNSParams

    cfg = SGNSConfig(seed=3)
    p = SGNSParams(
        emb=np.ones((4, 8), np.float32), ctx=np.zeros((4, 8), np.float32)
    )
    a = extend_params(p, 6, cfg)
    b = extend_params(p, 6, cfg)
    assert np.array_equal(np.asarray(a.emb), np.asarray(b.emb))
    assert np.array_equal(np.asarray(a.emb)[:4], p.emb)
    assert np.all(np.asarray(a.ctx)[4:] == 0)
    assert extend_params(p, 4, cfg) is p
    with pytest.raises(ValueError, match="shrank"):
        extend_params(p, 2, cfg)


def test_quality_report_gate_band(tmp_path):
    from gene2vec_tpu.loop.trainer import quality_report

    tokens = [f"G{i}" for i in range(18)]
    vocab = _mk_vocab(tokens)
    # 3 tight clusters: held intra-cluster pairs separate cleanly
    rng = np.random.RandomState(0)
    centers = rng.randn(3, 12) * 5
    emb = np.vstack([
        centers[i // 6] + 0.05 * rng.randn(12) for i in range(18)
    ]).astype(np.float32)
    held = [[f"G{a}", f"G{b}"] for c in range(3)
            for a, b in [(c * 6, c * 6 + 1), (c * 6 + 2, c * 6 + 3),
                         (c * 6 + 4, c * 6 + 5)]]
    rep = quality_report(vocab, emb, held, min_auc=0.6, max_auc=1.01)
    assert rep["passed"] and rep["auc"] > 0.8
    # a random table fails the floor
    bad = quality_report(
        vocab, rng.randn(18, 12).astype(np.float32), held,
        min_auc=0.95, max_auc=1.01,
    )
    assert not bad["passed"] and "outside the gate band" in bad["reason"]
    # too little evidence refuses to pass
    thin = quality_report(vocab, emb, held[:2], min_auc=0.1, max_auc=1.0)
    assert not thin["passed"]


# -- shadow scoring ----------------------------------------------------------


def test_topk_churn_and_rank_displacement():
    assert topk_churn(["a", "b", "c"], ["a", "b", "c"]) == (0.0, 0.0)
    c, d = topk_churn(["a", "b"], ["x", "y"])
    assert c == 1.0 and d is None
    c, d = topk_churn(["a", "b", "c", "d"], ["b", "a", "c", "d"])
    assert 0.0 < 1.0 and c == 0.0 and d == pytest.approx(0.125)
    c, _ = topk_churn(["a", "b", "c", "d"], ["a", "b", "c", "x"])
    assert c == pytest.approx(1 - 3 / 5)


def _similar_doc(iteration, neighbors):
    return {
        "model": {"dim": 8, "iteration": iteration},
        "results": [{
            "query": "G0",
            "neighbors": [{"gene": g, "score": 0.5} for g in neighbors],
        }],
    }


def test_shadow_scorer_aggregates():
    s = ShadowScorer()
    s.score(_similar_doc(1, ["a", "b", "c"]),
            _similar_doc(2, ["a", "b", "c"]), 0.010, 0.012)
    s.score(_similar_doc(1, ["a", "b", "c"]),
            _similar_doc(2, ["a", "b", "x"]), 0.010, 0.030)
    s.record_error()
    rep = s.report()
    assert rep["scored"] == 2 and rep["errors"] == 1
    assert rep["answer_churn"] == pytest.approx((0.0 + 0.5) / 2)
    assert rep["p99_live_ms"] == pytest.approx(10.0)
    assert rep["p99_shadow_ms"] == pytest.approx(30.0)
    assert rep["p99_delta_ms"] == pytest.approx(20.0)
    assert rep["live_iterations"] == [1]
    assert rep["shadow_iterations"] == [2]


def test_shadow_manager_samples_and_scores():
    calls = []

    def fake_fetch(url, method, target, body, headers, timeout_s):
        calls.append((url, method, target, headers.get("traceparent")))
        return 200, json.dumps(_similar_doc(2, ["a", "b"])).encode()

    m = ShadowManager(fetch=fake_fetch, workers=1)
    try:
        # inactive: observe is a no-op
        m.observe("POST", "/v1/similar", {"genes": ["G0"]},
                  b"{}", 0.01, None)
        assert m.report()["report"]["scored"] == 0
        with pytest.raises(ValueError):
            m.start("not-a-url")
        m.start("http://cand:1", sample=1.0)
        live = json.dumps(_similar_doc(1, ["a", "b"])).encode()
        from gene2vec_tpu.obs.tracecontext import new_trace

        ctx = new_trace()
        for _ in range(5):
            m.observe("POST", "/v1/similar", {"genes": ["G0"]},
                      live, 0.01, ctx)
        deadline = time.monotonic() + 5.0
        while (m.scorer.scored < 5 and time.monotonic() < deadline):
            time.sleep(0.02)
        rep = m.stop()["report"]
        assert rep["scored"] == 5 and rep["answer_churn"] == 0.0
        # shadow legs carried the live request's trace id
        assert all(c[3] and c[3].split("-")[1] == ctx.trace_id
                   for c in calls)
    finally:
        m.close()


def test_shadow_manager_counts_errors():
    def bad_fetch(*a, **k):
        raise IOError("down")

    m = ShadowManager(fetch=bad_fetch, workers=1)
    try:
        m.start("http://cand:1", sample=1.0)
        m.observe("POST", "/v1/similar", {}, b"{}", 0.01, None)
        deadline = time.monotonic() + 5.0
        while m.scorer.errors < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert m.scorer.errors == 1 and m.scorer.scored == 0
    finally:
        m.close()


# -- publish + promotion plumbing --------------------------------------------


def test_publish_iteration_sidecar_registry_and_routing(tmp_path):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.loop import trainer as ltr
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.shardgroup import RoutingTable

    cfg = SGNSConfig(
        dim=8, batch_pairs=64, num_iters=1, txt_output=False, seed=1
    )
    lines = _lines(120, seed=9)
    serving, base_vocab = _train_serving(tmp_path, lines, cfg)
    root = str(tmp_path / "loop")
    ing.init_ingest(root, base_vocab)
    ing.ingest_batch(root, "seed", lines)
    ing.ingest_batch(root, "b1", ["GNEWP G0", "GNEWP G1"] * 3)
    corpus, _ = ing.load_loop_corpus(root, holdout_fraction=0.0)
    cand = str(tmp_path / "cand")
    _p, _b, fin = ltr.train_candidate(
        serving, cand, corpus, cfg, 1, log=lambda s: None
    )
    # publish: npz + per-iteration vocab sidecar + manifest LAST
    dst = ckpt.publish_iteration(cand, serving, cfg.dim, fin)
    assert os.path.exists(dst)
    sidecar = dst[: -len(".npz")] + ".vocab.tsv"
    assert os.path.exists(sidecar), "tail-extended vocab needs a sidecar"
    # vocab.tsv untouched: older manifests still verify
    from gene2vec_tpu.resilience import snapshot as snap

    assert snap.verify_manifest(
        ckpt.ckpt_prefix(serving, cfg.dim, 1), use_cache=False
    )
    assert ckpt.latest_iteration(serving, cfg.dim) == fin
    # the registry serves the promoted iteration with the extended vocab
    reg = ModelRegistry(serving)
    assert reg.refresh()
    m = reg.model
    assert m.iteration == fin and len(m) == corpus.vocab_size
    assert "GNEWP" in m.index and m.created_unix > 0
    # the routing table routes the NEW gene (sidecar-aware)
    rt = RoutingTable(serving, num_shards=2)
    assert rt.reload()
    assert rt.total_rows == corpus.vocab_size
    assert rt.owner("GNEWP") is not None


def test_publish_refuses_unverified_and_non_extension(tmp_path):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.io import checkpoint as ckpt

    cfg = SGNSConfig(
        dim=8, batch_pairs=64, num_iters=1, txt_output=False, seed=1
    )
    serving, _ = _train_serving(tmp_path, _lines(80, seed=2), cfg)
    with pytest.raises(IOError, match="unverified"):
        ckpt.publish_iteration(
            str(tmp_path / "nowhere"), serving, cfg.dim, 1
        )
    # a source whose vocab is NOT a tail extension refuses
    other_dir, _ = _train_serving(
        tmp_path / "other", ["X0 X1", "X1 X2", "X2 X0"] * 20, cfg
    )
    with pytest.raises(ValueError, match="tail extension"):
        ckpt.publish_iteration(other_dir, serving, cfg.dim, 1)


# -- model freshness telemetry (satellite 2) ---------------------------------


def test_aggregator_exports_per_replica_model_facts():
    from gene2vec_tpu.obs.aggregate import FleetAggregator

    texts = {
        "http://a": "model_iteration 3\nmodel_age_seconds 120.5\n",
        "http://b": "model_iteration 5\nmodel_age_seconds 12.0\n",
    }
    captured = {}

    class Ev:
        def observe(self, snapshot, wall=None):
            captured.update(snapshot)

    agg = FleetAggregator(
        ["http://a", "http://b"],
        fetch=lambda url, t: texts[url],
        evaluator=Ev(),
    )
    agg.scrape_once()
    view = agg.fleet_text()
    assert 'fleet_model_iteration{target="http://a"} 3' in view
    assert 'fleet_model_age_seconds{target="http://b"} 12' in view
    assert captured["fleet_model_iteration_min"] == 3.0
    assert captured["fleet_model_iteration_max"] == 5.0
    assert captured["fleet_model_iteration_skew"] == 2.0
    assert captured["fleet_model_age_seconds_max"] == 120.5


def test_default_rules_cover_model_freshness():
    from gene2vec_tpu.obs.alerts import default_rules

    by_name = {r.name: r for r in default_rules()}
    stale = by_name["model-staleness"]
    assert stale.metric == "fleet_model_age_seconds_max"
    skew = by_name["model-iteration-skew"]
    assert skew.metric == "fleet_model_iteration_skew"
    # a swap wave must not page: skew needs to HOLD for for_s
    assert skew.for_s >= 60.0
    for r in (stale, skew):
        r.validate()


def test_replica_exports_model_age(tmp_path):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.obs.registry import MetricsRegistry
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig

    cfg = SGNSConfig(
        dim=8, batch_pairs=64, num_iters=1, txt_output=False, seed=1
    )
    serving, _ = _train_serving(tmp_path, _lines(80, seed=4), cfg)
    reg = ModelRegistry(serving)
    assert reg.refresh()
    metrics = MetricsRegistry()
    app = ServeApp(reg, config=ServeConfig(), metrics=metrics)
    try:
        app.publish_engine_metrics()
        age = metrics.gauge("model_age_seconds").value
        assert 0.0 <= age < 3600.0
    finally:
        app.stop()


# -- the budget gate (passes_loop) -------------------------------------------


def _loop_doc(**over):
    section = {
        "replicas": 2,
        "train_iters": 2,
        "shadow_sample": 1.0,
        "min_shadow_requests": 30,
        "states_killed": 4,
        "answer_churn": 0.3,
        "shadow_p99_delta_ms": 40.0,
        "wrong_answers": 0,
        "mixed_iteration_answers": 0,
        "promotion_decision_s": 8.0,
        "promoted": True,
        "resume_bit_exact": True,
    }
    for k, v in over.items():
        if v is None:
            section.pop(k, None)
        else:
            section[k] = v
    return {"schema_version": 1, "loop": section}


def test_passes_loop_budget_gate(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_loop import loop_findings

    # missing bench = info (fresh checkout must not fail lint)
    missing = loop_findings(root=str(tmp_path / "absent"))
    assert [f.severity for f in missing] == ["info"]

    def run(doc):
        root = tmp_path / "root"
        root.mkdir(exist_ok=True)
        with open(root / "BENCH_LOOP_r16.json", "w") as f:
            json.dump(doc, f)
        return loop_findings(root=str(root))

    fs = run(_loop_doc())
    assert gating(fs) == [], [f.format() for f in fs]

    # each planted violation fires EXACTLY once
    for doc in (
        _loop_doc(answer_churn=0.9),                # reshuffled answers
        _loop_doc(shadow_p99_delta_ms=5000.0),      # slow candidate
        _loop_doc(wrong_answers=1),
        _loop_doc(mixed_iteration_answers=1),
        _loop_doc(promotion_decision_s=600.0),      # wedged promotion
        _loop_doc(promoted=False),                  # never promoted
        _loop_doc(resume_bit_exact=False),          # resume diverged
        _loop_doc(answer_churn=None),               # dropped key
        _loop_doc(states_killed=0),                 # off-recipe: no kills
        _loop_doc(shadow_sample=0.01),              # off-recipe
        {"schema_version": 1},                      # no section
    ):
        fs = run(doc)
        assert len(gating(fs)) == 1, doc

    # the newest round wins: a violating r17 beats a stale clean r16
    root = tmp_path / "root"
    with open(root / "BENCH_LOOP_r17.json", "w") as f:
        json.dump(_loop_doc(wrong_answers=3), f)
    with open(root / "BENCH_LOOP_r16.json", "w") as f:
        json.dump(_loop_doc(), f)
    fs = loop_findings(root=str(root))
    assert len(gating(fs)) == 1
    assert gating(fs)[0].path == "BENCH_LOOP_r17.json"


def test_cli_analyze_gates_on_planted_loop_violation(tmp_path):
    """The env-override path: a violating BENCH_LOOP under
    GENE2VEC_TPU_LOOP_ROOT makes the real cli.analyze exit 1 with
    exactly one loop-promotion-budget finding."""
    root = tmp_path / "root"
    root.mkdir()
    with open(root / "BENCH_LOOP_r16.json", "w") as f:
        json.dump(_loop_doc(resume_bit_exact=False), f)
    env = {**os.environ, "GENE2VEC_TPU_LOOP_ROOT": str(root)}
    r = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 1, r.stderr
    doc = json.loads(r.stdout)
    mine = [f for f in doc["findings"]
            if f["pass"] == "loop-promotion-budget"
            and f["severity"] != "info"]
    assert len(mine) == 1
    assert "resume_bit_exact" in mine[0]["message"]


def test_ledger_adapts_bench_loop(tmp_path):
    from gene2vec_tpu.obs import ledger

    path = tmp_path / "BENCH_LOOP_r16.json"
    doc = _loop_doc()
    doc["loop"].update(ingest_to_promoted_s=55.0, shadow_scored=40)
    doc["passed"] = True
    with open(path, "w") as f:
        json.dump(doc, f)
    rec = ledger.adapt_file(str(path))
    assert rec is not None and rec["family"] == "loop"
    assert rec["round"] == 16
    assert not rec["legacy_unstamped"]
    assert rec["headline_metric"] == "loop_answer_churn"
    m = rec["metrics"]
    assert m["loop_answer_churn"] == 0.3
    assert m["loop_ingest_to_promoted_s"] == 55.0
    assert m["loop_resume_bit_exact"] == 1.0


def test_evaluate_cli_stamps_json_product(tmp_path):
    """cli.evaluate --json emits a provenance-stamped document (the
    ledger contract: schema_version/command/created_unix present)."""
    from gene2vec_tpu.io.emb_io import write_word2vec_format

    emb = tmp_path / "emb_w2v.txt"
    rng = np.random.RandomState(0)
    write_word2vec_format(
        str(emb), [f"G{i}" for i in range(8)],
        rng.randn(8, 4).astype(np.float32),
    )
    gmt = tmp_path / "sets.gmt"
    gmt.write_text(
        "SET_A\turl\tG0\tG1\tG2\nSET_B\turl\tG3\tG4\tG5\n"
    )
    out = tmp_path / "eval.json"
    r = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.evaluate",
         str(emb), str(gmt), "--json", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["schema_version"] == 1
    assert "command" in doc and "created_unix" in doc
    assert isinstance(doc["trained_target_func_ratio"], float)
    assert json.loads(out.read_text())["schema_version"] == 1
