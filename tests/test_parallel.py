"""Mesh/sharding correctness on the virtual 8-device CPU mesh (SURVEY §4).

The framework's communication layer is sharding specs + XLA collectives
(SURVEY §5 "distributed communication backend"); these tests pin down that

* data-parallel and vocab-sharded (row-parallel) training produce the same
  numbers as unsharded training — the collectives XLA inserts are exact;
* parameters actually live where the specs say (row-sharded over the model
  axis / replicated);
* the dim=512 vocab-sharded configuration (BASELINE config 5) trains.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from gene2vec_tpu.config import MeshConfig, SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.parallel.mesh import make_mesh, single_device_mesh
from gene2vec_tpu.parallel.sharding import SGNSSharding
from gene2vec_tpu.sgns.train import SGNSTrainer


def _corpus(vocab_size=64, num_pairs=512, seed=0):
    rng = np.random.RandomState(seed)
    pairs = rng.randint(0, vocab_size, (num_pairs, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(vocab_size)], counts), pairs)


def test_make_mesh_shapes():
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("data", "model")
    with pytest.raises(ValueError, match="does not cover"):
        make_mesh(MeshConfig(data=3, model=2))
    assert single_device_mesh().devices.shape == (1, 1)


@pytest.mark.parametrize("vocab_sharded", [False, True])
def test_sharded_matches_unsharded(vocab_sharded):
    """Same seed, same corpus → sharded epoch ≈ single-device epoch."""
    corpus = _corpus()
    cfg = SGNSConfig(dim=16, num_iters=1, batch_pairs=64, seed=3)

    ref_trainer = SGNSTrainer(corpus, cfg)
    ref_params = ref_trainer.init()
    key = jax.random.PRNGKey(11)
    ref_params, ref_loss = ref_trainer.train_epoch(ref_params, key)

    mesh = make_mesh(MeshConfig(data=-1, model=2))
    sharding = SGNSSharding(mesh, vocab_sharded=vocab_sharded)
    tr = SGNSTrainer(corpus, cfg, sharding=sharding)
    params = tr.init()
    params, loss = tr.train_epoch(params, key)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(params.emb), np.asarray(ref_params.emb), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(params.ctx), np.asarray(ref_params.ctx), atol=1e-5
    )


def test_vocab_sharded_placement():
    """Tables are row-sharded over the model axis exactly as declared."""
    corpus = _corpus()
    cfg = SGNSConfig(dim=16, num_iters=1, batch_pairs=64)
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    tr = SGNSTrainer(
        corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=True)
    )
    params = tr.init()
    spec = params.emb.sharding.spec
    assert spec[0] == "model"
    # each device holds V/2 rows (model axis = 2)
    shard_shapes = {s.data.shape for s in params.emb.addressable_shards}
    assert shard_shapes == {(corpus.vocab_size // 2, cfg.dim)}


def test_dim512_vocab_sharded_trains():
    """BASELINE config 5: dim=512 row-parallel table over the 8-device mesh."""
    corpus = _corpus(vocab_size=128, num_pairs=1024)
    cfg = SGNSConfig(dim=512, num_iters=1, batch_pairs=128, vocab_sharded=True)
    mesh = make_mesh(MeshConfig(data=2, model=4))
    tr = SGNSTrainer(corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=True))
    params = tr.init()
    assert params.emb.sharding.spec[0] == "model"
    params, loss = tr.train_epoch(params, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    # rows stay sharded through the epoch (constrain_params held)
    assert params.emb.sharding.spec[0] == "model"


def test_data_sharded_corpus_upload():
    """The corpus array itself is sharded over the data axis in HBM."""
    corpus = _corpus(num_pairs=512)
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    sharding = SGNSSharding(mesh)
    tr = SGNSTrainer(
        corpus, SGNSConfig(dim=8, batch_pairs=64), sharding=sharding
    )
    spec = tr.pairs.sharding.spec
    assert spec[0] == "data"


def test_mesh_with_odd_device_count():
    """dryrun-style fallback: model axis collapses to 1 on odd counts."""
    devices = jax.devices()[:5]
    mesh = Mesh(np.asarray(devices).reshape(5, 1), ("data", "model"))
    corpus = _corpus(num_pairs=500)
    tr = SGNSTrainer(
        corpus,
        SGNSConfig(dim=8, batch_pairs=50),
        sharding=SGNSSharding(mesh, vocab_sharded=False),
    )
    params = tr.init()
    _, loss = tr.train_epoch(params, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
