"""Mesh/sharding correctness on the virtual 8-device CPU mesh (SURVEY §4).

The framework's communication layer is sharding specs + XLA collectives
(SURVEY §5 "distributed communication backend"); these tests pin down that

* data-parallel and vocab-sharded (row-parallel) training produce the same
  numbers as unsharded training — the collectives XLA inserts are exact;
* parameters actually live where the specs say (row-sharded over the model
  axis / replicated);
* the dim=512 vocab-sharded configuration (BASELINE config 5) trains.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from gene2vec_tpu.config import MeshConfig, SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.parallel.mesh import make_mesh, single_device_mesh
from gene2vec_tpu.parallel.sharding import SGNSSharding
from gene2vec_tpu.sgns.train import SGNSTrainer


def _corpus(vocab_size=64, num_pairs=512, seed=0):
    rng = np.random.RandomState(seed)
    pairs = rng.randint(0, vocab_size, (num_pairs, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=vocab_size).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(vocab_size)], counts), pairs)


def test_make_mesh_shapes():
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("data", "model")
    with pytest.raises(ValueError, match="does not cover"):
        make_mesh(MeshConfig(data=3, model=2))
    assert single_device_mesh().devices.shape == (1, 1)


@pytest.mark.parametrize("vocab_sharded", [False, True])
@pytest.mark.parametrize("positive_mid", [0, 24])
def test_sharded_matches_unsharded(vocab_sharded, positive_mid):
    """Same seed, same corpus → sharded epoch ≈ single-device epoch.

    Both mesh strategies use the dense-positive path (round 5: the slabs
    of a vocab-sharded table broadcast from their owning model shards),
    whose per-device block layout changes example ORDER (not the example
    set), so the unsharded reference pins the same layout via
    pos_layout_shards.
    """
    corpus = _corpus()
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    data = mesh.shape["data"]
    # head=8 < V/2 keeps real head/mid/tail classes on the 64-token vocab
    # (the default 512 would clamp to the whole vocab and leave no tail)
    cfg = SGNSConfig(
        dim=16, num_iters=1, batch_pairs=64, seed=3, positive_head=8,
        positive_mid=positive_mid, pos_layout_shards=data,
    )

    ref_trainer = SGNSTrainer(corpus, cfg)
    ref_params = ref_trainer.init()
    key = jax.random.PRNGKey(11)
    ref_params, ref_loss = ref_trainer.train_epoch(ref_params, key)
    assert ref_trainer.pos_quotas is not None  # dense path exercised

    sharding = SGNSSharding(mesh, vocab_sharded=vocab_sharded)
    tr = SGNSTrainer(corpus, cfg, sharding=sharding)
    assert tr.pos_quotas is not None  # dense path on the mesh too
    params = tr.init()
    params, loss = tr.train_epoch(params, key)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(params.emb), np.asarray(ref_params.emb), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(params.ctx), np.asarray(ref_params.ctx), atol=1e-5
    )


def test_vocab_sharded_placement():
    """Tables are row-sharded over the model axis exactly as declared."""
    corpus = _corpus()
    cfg = SGNSConfig(dim=16, num_iters=1, batch_pairs=64)
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    tr = SGNSTrainer(
        corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=True)
    )
    params = tr.init()
    spec = params.emb.sharding.spec
    assert spec[0] == "model"
    # each device holds V/2 rows (model axis = 2)
    shard_shapes = {s.data.shape for s in params.emb.addressable_shards}
    assert shard_shapes == {(corpus.vocab_size // 2, cfg.dim)}


def test_dim512_vocab_sharded_trains():
    """BASELINE config 5: dim=512 row-parallel table over the 8-device mesh."""
    corpus = _corpus(vocab_size=128, num_pairs=1024)
    cfg = SGNSConfig(dim=512, num_iters=1, batch_pairs=128, vocab_sharded=True)
    mesh = make_mesh(MeshConfig(data=2, model=4))
    tr = SGNSTrainer(corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=True))
    params = tr.init()
    assert params.emb.sharding.spec[0] == "model"
    params, loss = tr.train_epoch(params, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    # rows stay sharded through the epoch (constrain_params held)
    assert params.emb.sharding.spec[0] == "model"


def test_data_sharded_corpus_upload():
    """The corpus array itself is sharded over the data axis in HBM — for
    the plain path (one array) and the dense-head path (class pools)."""
    corpus = _corpus(num_pairs=512)
    mesh = make_mesh(MeshConfig(data=-1, model=2))
    sharding = SGNSSharding(mesh)
    tr = SGNSTrainer(
        corpus, SGNSConfig(dim=8, batch_pairs=64, positive_head=0),
        sharding=sharding,
    )
    assert tr.pairs.sharding.spec[0] == "data"

    tr = SGNSTrainer(
        corpus, SGNSConfig(dim=8, batch_pairs=64), sharding=sharding
    )
    assert tr.pos_quotas is not None
    for pool, q in zip(tr.pairs, tr.pos_quotas):
        if q:
            assert pool.sharding.spec[0] == "data"
            assert pool.shape[0] % tr.pos_shards == 0


def test_mesh_with_odd_device_count():
    """dryrun-style fallback: model axis collapses to 1 on odd counts."""
    devices = jax.devices()[:5]
    mesh = Mesh(np.asarray(devices).reshape(5, 1), ("data", "model"))
    corpus = _corpus(num_pairs=500)
    tr = SGNSTrainer(
        corpus,
        SGNSConfig(dim=8, batch_pairs=50),
        sharding=SGNSSharding(mesh, vocab_sharded=False),
    )
    params = tr.init()
    _, loss = tr.train_epoch(params, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))


def test_distributed_single_process_noop():
    """With nothing configured on a non-TPU backend, initialize() is a
    no-op returning False and the local run is untouched."""
    from gene2vec_tpu.parallel import distributed

    assert distributed.initialize() is False
    assert distributed.process_count() == 1
    assert distributed.process_index() == 0


def test_distributed_initialize_single_process_runtime():
    """jax.distributed.initialize with an explicit 1-process coordinator:
    the runtime comes up, the global mesh covers the forced-8 CPU devices,
    and a collective executes.  Subprocess: the distributed runtime is
    process-global and must not leak into other tests."""
    import subprocess
    import sys

    code = """
import numpy as np
import jax
from gene2vec_tpu.parallel import distributed
from gene2vec_tpu.parallel.mesh import make_mesh
from gene2vec_tpu.config import MeshConfig

active = distributed.initialize(
    coordinator_address="127.0.0.1:12955", num_processes=1, process_id=0
)
assert active is False, "1 process is not a multi-process runtime"
assert jax.process_count() == 1
# the distributed CPU client ignores xla_force_host_platform_device_count,
# so build the mesh over however many devices the runtime exposes
n = len(jax.devices())
mesh = make_mesh(MeshConfig(data=n, model=1))
assert mesh.devices.shape == (n, 1)
from jax.sharding import NamedSharding, PartitionSpec as P
import jax.numpy as jnp
x = jax.device_put(np.arange(float(n)), NamedSharding(mesh, P("data")))
s = float(jnp.sum(x * 2.0))
assert s == float(n * (n - 1)), s
distributed.shutdown()
print("DISTRIBUTED_OK")
"""
    env = dict(
        __import__("os").environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=180, env=env,
    )
    assert "DISTRIBUTED_OK" in res.stdout, res.stderr[-2000:]


def test_process_shard_partitions_corpus():
    """Multi-host feeding (docs/DISTRIBUTED.md): strided shards are all
    exactly num_pairs // count rows (ADVICE r3: unequal shards let hosts
    compile different epoch step counts and deadlock collectives), are
    disjoint, share the full-corpus vocab, and the single-process default
    is the identity."""
    # all 101 rows distinct, so set inclusion below is true multiset logic
    # (disjointness across shards is detectable, not masked by duplicates)
    pairs = np.stack(
        [np.arange(101), np.arange(101) + 101], axis=1
    ).astype(np.int32)
    vocab = Vocab(
        [f"g{i}" for i in range(202)],
        np.bincount(pairs.reshape(-1), minlength=202),
    )
    corpus = PairCorpus(vocab, pairs)

    for count in (2, 3, 4, 7):
        shards = [corpus.process_shard(i, count) for i in range(count)]
        # every host agrees on shard length => same num_batches everywhere
        assert {s.num_pairs for s in shards} == {101 // count}
        kept = {
            tuple(row)
            for shard in shards
            for row in shard.pairs
        }
        # disjoint (no row appears in two shards) and drawn from the corpus,
        # with at most count-1 tail rows dropped by the equal-length trim
        assert len(kept) == (101 // count) * count
        assert kept <= {tuple(row) for row in pairs}
        for s in shards:
            assert s.vocab is vocab  # full-corpus vocab, never re-derived
    assert corpus.process_shard(0, 1) is corpus  # single-process identity
    with pytest.raises(ValueError, match="process index"):
        corpus.process_shard(4, 4)
    with pytest.raises(ValueError, match="process count"):
        corpus.process_shard(0, 0)


def test_two_process_distributed_training(tmp_path):
    """REAL multi-host SPMD: two OS processes, each with 4 forced-CPU
    devices, form one 8-device jax.distributed runtime; each feeds its
    process_shard of the same corpus; the global-mesh epoch runs over
    Gloo collectives.  Both processes must compute identical, decreasing
    losses — the strongest executable evidence for docs/DISTRIBUTED.md
    (per-host shards assembled via make_array_from_process_local_data,
    global num_batches derived from the global row count, dense-head
    auto-disabled)."""
    import subprocess
    import sys

    worker = tmp_path / "worker.py"
    worker.write_text(
        """
import sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)
pid = int(sys.argv[1])
from gene2vec_tpu.parallel import distributed
from gene2vec_tpu.config import MeshConfig, SGNSConfig
from gene2vec_tpu.parallel.mesh import make_mesh
from gene2vec_tpu.parallel.sharding import SGNSSharding
from gene2vec_tpu.sgns.train import SGNSTrainer
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab

port = sys.argv[2]
distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2
assert len(jax.devices()) == 8

rng = np.random.RandomState(0)  # same full corpus on every host
pairs = rng.randint(0, 64, (4096, 2)).astype(np.int32)
counts = np.bincount(pairs.reshape(-1), minlength=64).astype(np.int64)
corpus = PairCorpus(Vocab([f"G{i}" for i in range(64)], counts), pairs)
local = corpus.process_shard()
assert local.num_pairs == 2048

mesh = make_mesh(MeshConfig(data=8, model=1))
tr = SGNSTrainer(
    local,
    SGNSConfig(dim=16, num_iters=1, batch_pairs=256, seed=3),
    sharding=SGNSSharding(mesh, vocab_sharded=False),
)
assert tr.global_num_pairs == 4096 and tr.num_batches == 16
params = tr.init()
params, l1 = tr.train_epoch(params, jax.random.PRNGKey(7))
params, l2 = tr.train_epoch(params, jax.random.PRNGKey(8))

# dense-head positives on multi-host: quotas derive from the FULL
# corpus (identical on every host), pools assemble from per-host shards
tr2 = SGNSTrainer(
    local,
    SGNSConfig(
        dim=16, num_iters=1, batch_pairs=256, seed=3, positive_head=16,
        strat_head=8, strat_block=16,
    ),
    sharding=SGNSSharding(mesh, vocab_sharded=False),
    full_corpus=corpus,
)
assert tr2.pos_quotas is not None and tr2.config.positive_head == 16
p2 = tr2.init()
dlosses = []
for ep in range(5):  # tiny-scale epoch losses are noisy; look at the trend
    p2, dl = tr2.train_epoch(p2, jax.random.fold_in(jax.random.PRNGKey(9), ep))
    dlosses.append(float(dl))
print(
    f"RESULT {float(l1):.6f} {float(l2):.6f} "
    f"{tr2.pos_quotas} {dlosses[0]:.6f} {min(dlosses):.6f}",
    flush=True,
)

# phase 3: VOCAB-SHARDED tables on the multi-host runtime (round 5).
# 3a) trainer-level, mesh (data=2, model=4): rows sharded over the model
# axis (intra-host), while the data axis — and therefore the gradient
# reduction into the row-sharded tables and the dense-slab broadcasts —
# crosses the Gloo transport.  Dense positives stay ON (the round-5 gate
# removal).
mesh_a = make_mesh(MeshConfig(data=2, model=4))
tr3 = SGNSTrainer(
    local,
    SGNSConfig(
        dim=16, num_iters=1, batch_pairs=256, seed=3, positive_head=16,
        positive_mid=24, strat_head=8, strat_block=16,
    ),
    sharding=SGNSSharding(mesh_a, vocab_sharded=True),
    full_corpus=corpus,
)
assert tr3.pos_quotas is not None and len(tr3.pos_quotas) == 6
p3 = tr3.init()
assert p3.emb.sharding.spec[0] == "model"
vlosses = []
for ep in range(5):
    p3, vl = tr3.train_epoch(p3, jax.random.fold_in(jax.random.PRNGKey(21), ep))
    vlosses.append(float(vl))

# 3b) step-level, model axis SPANNING the two processes (devices
# interleaved (2,4).T): every sharded-table gather/scatter and slab
# broadcast crosses the transport.  The parent re-runs the identical
# construction single-process and pins numeric equality.
import functools
import jax.numpy as jnp
from jax.sharding import Mesh
from gene2vec_tpu.sgns.model import init_params
from gene2vec_tpu.sgns.step import sgns_step
from gene2vec_tpu.data.negative_sampling import (
    NegativeSampler, build_stratified_spec,
)

mesh_b = Mesh(np.asarray(jax.devices()).reshape(2, 4).T, ("data", "model"))
sh_b = SGNSSharding(mesh_b, vocab_sharded=True)
init_fn = jax.jit(
    functools.partial(init_params, vocab_size=64, dim=16, dtype=jnp.float32),
    out_shardings=sh_b.params_sharding(),
)
pb = init_fn(jax.random.PRNGKey(5))
assert pb.emb.sharding.spec[0] == "model"
spec = build_stratified_spec(counts, 8, 16, 0.75)
noise = NegativeSampler(counts, 0.75).table
step = jax.jit(
    functools.partial(
        sgns_step, negatives=5, negative_mode="stratified",
        strat_group=32,
    )
)
batch = jnp.asarray(corpus.pairs[:256])  # replicated global input
bl = None
for i in range(3):
    pb, bl = step(
        pb, batch, noise, jax.random.PRNGKey(100 + i), jnp.float32(0.025),
        stratified=spec,
    )
print(
    f"RESULT2 {vlosses[0]:.6f} {min(vlosses):.6f} {float(bl):.6f}",
    flush=True,
)
distributed.shutdown()
"""
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    # a free port per run: concurrent sessions (or a stale listener) on a
    # fixed port would hang both workers in the rendezvous until timeout
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo,
        )
        for i in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=480)
            assert p.returncode == 0, err[-2000:]
            outs.append(out)
    finally:
        # a failed/timed-out worker must not leave its peer blocked in
        # the distributed rendezvous with the coordinator port bound
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT ")
    ]
    assert len(results) == 2
    assert results[0] == results[1], results  # identical across processes
    parts = results[0].split()
    l1, l2 = float(parts[1]), float(parts[2])
    assert l2 < l1  # and the model actually learns
    d_first, d_best = float(parts[-2]), float(parts[-1])
    assert d_best < d_first - 0.5  # dense-head multi-host path learns too

    # phase-3 assertions: vocab-sharded multi-host executed and learned,
    # identically on both processes
    results2 = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT2")
    ]
    assert len(results2) == 2
    assert results2[0] == results2[1], results2
    v_first, v_best, bl = (float(x) for x in results2[0].split()[1:])
    assert v_best < v_first - 0.5  # trainer-level vocab-sharded learns

    # single-process reference for phase 3b: the identical construction on
    # this process's own 8 CPU devices must produce the same loss the two
    # workers computed over the cross-process model axis — the collectives
    # XLA lowered onto the Gloo transport are numerically exact
    import functools

    import jax.numpy as jnp

    from gene2vec_tpu.data.negative_sampling import (
        NegativeSampler, build_stratified_spec,
    )
    from gene2vec_tpu.sgns.model import init_params
    from gene2vec_tpu.sgns.step import sgns_step

    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 64, (4096, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=64).astype(np.int64)
    mesh_b = Mesh(
        np.asarray(jax.devices()).reshape(2, 4).T, ("data", "model")
    )
    sh_b = SGNSSharding(mesh_b, vocab_sharded=True)
    init_fn = jax.jit(
        functools.partial(
            init_params, vocab_size=64, dim=16, dtype=jnp.float32
        ),
        out_shardings=sh_b.params_sharding(),
    )
    pb = init_fn(jax.random.PRNGKey(5))
    spec = build_stratified_spec(counts, 8, 16, 0.75)
    noise = NegativeSampler(counts, 0.75).table
    step = jax.jit(
        functools.partial(
            sgns_step, negatives=5, negative_mode="stratified",
            strat_group=32,
        )
    )
    batch = jnp.asarray(pairs[:256])
    ref_bl = None
    for i in range(3):
        pb, ref_bl = step(
            pb, batch, noise, jax.random.PRNGKey(100 + i),
            jnp.float32(0.025), stratified=spec,
        )
    assert abs(float(ref_bl) - bl) < 1e-4, (float(ref_bl), bl)
