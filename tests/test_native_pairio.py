"""Native C++ corpus reader vs the pure-Python reader: exact parity."""

import subprocess

import numpy as np
import pytest

from gene2vec_tpu.io import native_pairio
from gene2vec_tpu.io.pair_reader import iter_pair_files, load_corpus
from gene2vec_tpu.io.vocab import Vocab


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native_pairio.available():
        pytest.skip("native library unavailable and build failed")


def _python_load(source_dir, pattern="txt", min_count=1):
    return load_corpus(source_dir, pattern, min_count=min_count, use_native=False)


def _native_load(source_dir, pattern="txt", min_count=1):
    return native_pairio.load_corpus(
        iter_pair_files(source_dir, pattern), min_count=min_count
    )


def _assert_same(a, b):
    vocab_a, pairs_a = a
    vocab_b, pairs_b = b
    assert vocab_a.id_to_token == vocab_b.id_to_token
    np.testing.assert_array_equal(vocab_a.counts, vocab_b.counts)
    np.testing.assert_array_equal(pairs_a, pairs_b)


def test_parity_on_synthetic_corpus(synthetic_corpus_dir):
    _assert_same(
        _python_load(synthetic_corpus_dir), _native_load(synthetic_corpus_dir)
    )


def test_parity_with_messy_lines(tmp_path):
    d = tmp_path / "c"
    d.mkdir()
    # blank lines, 1-token and 3-token lines (count tokens, drop as pairs),
    # tabs, repeated tokens with tie counts, windows-1252 high bytes
    (d / "a.txt").write_bytes(
        b"A B\n"
        b"\n"
        b"C\n"
        b"D E F\n"
        b"B\tA\n"
        b"G\xe9NE1 G\xe9NE2\n"   # e-acute in windows-1252
        b"  A   B  \n"
    )
    (d / "b.txt").write_bytes(b"H I\nI H\nH I\n")
    _assert_same(_python_load(str(d)), _native_load(str(d)))


def test_parity_min_count(tmp_path):
    d = tmp_path / "c"
    d.mkdir()
    (d / "a.txt").write_text("A B\nA C\nA B\nD E\n")
    _assert_same(
        _python_load(str(d), min_count=2), _native_load(str(d), min_count=2)
    )
    vocab, pairs = _native_load(str(d), min_count=2)
    assert set(vocab.id_to_token) == {"A", "B"}
    assert pairs.shape == (2, 2)  # both "A B" lines survive, "A C"/"D E" drop


def test_load_corpus_uses_native_by_default(synthetic_corpus_dir):
    v1, p1 = load_corpus(synthetic_corpus_dir, "txt", use_native=True)
    v2, p2 = load_corpus(synthetic_corpus_dir, "txt", use_native=False)
    assert v1.id_to_token == v2.id_to_token
    np.testing.assert_array_equal(p1, p2)


def test_empty_file(tmp_path):
    d = tmp_path / "c"
    d.mkdir()
    (d / "a.txt").write_text("")
    vocab, pairs = _native_load(str(d))
    assert len(vocab) == 0 and pairs.shape == (0, 2)


def test_native_speed_sanity(tmp_path):
    """Native reader should beat Python comfortably on a larger corpus."""
    import time

    rng = np.random.RandomState(0)
    d = tmp_path / "c"
    d.mkdir()
    genes = [f"GENE{i}" for i in range(5000)]
    lines = [
        f"{genes[a]} {genes[b]}"
        for a, b in rng.randint(0, 5000, (200_000, 2))
    ]
    (d / "big.txt").write_text("\n".join(lines) + "\n")

    t0 = time.perf_counter()
    _native_load(str(d))
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    _python_load(str(d))
    t_python = time.perf_counter() - t0
    assert t_native < t_python, (t_native, t_python)


def test_abi_version_mismatch_falls_back(monkeypatch):
    """ADVICE r3: a library reporting the wrong ABI version (stale .so that
    `make` could not rebuild) must make the wrapper fall back to the Python
    reader instead of calling mismatched entry points."""
    from gene2vec_tpu.io import native_pairio as np_mod

    assert np_mod.available()  # fresh build reports the expected version
    monkeypatch.setattr(np_mod, "_lib", None)
    monkeypatch.setattr(np_mod, "_ABI_VERSION", -1)
    assert not np_mod.available()
    monkeypatch.undo()
    np_mod._lib = None
    assert np_mod.available()  # cache restored for later tests


def test_native_reader_rejects_cp1252_undefined_bytes(tmp_path):
    """ADVICE r1: strict-decode parity with the Python fallback — a file
    containing a cp1252-undefined byte raises, even in skipped content."""
    import pytest

    from gene2vec_tpu.io import native_pairio

    if not native_pairio.available():
        pytest.skip("native pairio library unavailable")
    bad = tmp_path / "bad.txt"
    bad.write_bytes(b"GENE1 GENE2\nGEN\x81E3 GENE4\n")
    with pytest.raises(UnicodeDecodeError):
        native_pairio.load_corpus([str(bad)])
