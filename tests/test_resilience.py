"""Resilience tier-1: atomic visibility, CRC rejection, preemption
drain, the async writer, and registry fallback/quarantine.

The full fault-injection drill against the real CLIs (SIGKILL at a
random step → bit-exact resume, serve no-garbage-swap, the async
overhead budget) is ``scripts/chaos_drill.py``, exercised here by the
``slow``-marked test at the bottom; these tier-1 tests pin the same
invariants in-process where they are cheap.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.resilience import chaos
from gene2vec_tpu.resilience import snapshot as snap
from gene2vec_tpu.resilience.async_writer import (
    AsyncCheckpointWriter,
    CheckpointWriteError,
)
from gene2vec_tpu.resilience.preempt import EXIT_PREEMPTED, PreemptionHandler
from gene2vec_tpu.sgns.model import SGNSParams

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D = 12, 4


def _vocab():
    return Vocab([f"G{i}" for i in range(V)], np.arange(1, V + 1))


def _save(export_dir, it, fill=None, txt=True):
    fill = float(it) if fill is None else fill
    params = SGNSParams(
        emb=np.full((V, D), fill, np.float32),
        ctx=np.zeros((V, D), np.float32),
    )
    return ckpt.save_iteration(
        str(export_dir), D, it, params, _vocab(), txt_output=txt
    )


def _prefix(export_dir, it):
    return os.path.join(str(export_dir), f"gene2vec_dim_{D}_iter_{it}")


def _corpus(seed=0, vocab=24, pairs=300):
    rng = np.random.RandomState(seed)
    p = rng.randint(0, vocab, size=(pairs, 2)).astype(np.int32)
    counts = np.bincount(p.reshape(-1), minlength=vocab).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(vocab)], counts), p)


# -- atomic visibility -------------------------------------------------------


def test_atomic_savez_never_exposes_partial_file(tmp_path):
    """A concurrent reader sees the old npz or the new npz, never a
    prefix of the new one (write-to-temp + rename)."""
    path = str(tmp_path / "state.npz")
    snap.atomic_savez(path, x=np.zeros(4096, np.float32))
    errors, torn = [], []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                with np.load(path) as z:
                    x = np.asarray(z["x"])
                # every visible file is one writer's COMPLETE array
                if not (x == x[0]).all():
                    torn.append(x[0])
            except Exception as e:  # a partial file fails to parse
                errors.append(repr(e))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for i in range(1, 60):
        snap.atomic_savez(path, x=np.full(4096, float(i), np.float32))
    stop.set()
    t.join(timeout=10)
    assert errors == [] and torn == []


def test_checkpoint_rewrite_visibility_under_concurrent_reader(tmp_path):
    """save_iteration over an existing iteration never exposes a torn
    load to a concurrent load_iteration (the registry/trainer race)."""
    _save(tmp_path, 1, fill=0.0)
    errors, seen = [], set()
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                params, _, meta = ckpt.load_iteration(str(tmp_path), D, 1)
                emb = np.asarray(params.emb)
                if not (emb == emb.flat[0]).all():
                    errors.append("mixed fill")
                seen.add(float(emb.flat[0]))
            except Exception as e:
                errors.append(repr(e))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for i in range(1, 40):
        _save(tmp_path, 1, fill=float(i), txt=False)
    stop.set()
    t.join(timeout=10)
    assert errors == []
    assert seen  # the reader actually observed values


# -- manifests / CRC rejection ----------------------------------------------


def test_manifest_written_and_verifies(tmp_path):
    path = _save(tmp_path, 1)
    res = snap.verify_manifest(path[: -len(".npz")])
    assert res.ok and res.reason == "ok"
    names = set(res.manifest["files"])
    assert names == {
        f"gene2vec_dim_{D}_iter_1.npz",
        f"gene2vec_dim_{D}_iter_1.txt",
        f"gene2vec_dim_{D}_iter_1_w2v.txt",
        "vocab.tsv",
    }
    # the manifest carries the checkpoint meta (config hash / rng land
    # here from the trainer loop)
    assert res.manifest["iteration"] == 1 and res.manifest["dim"] == D


def test_crc_rejection_and_fallback(tmp_path):
    for it in (1, 2, 3):
        _save(tmp_path, it)
    assert ckpt.latest_iteration(str(tmp_path), D) == 3

    chaos.truncate_file(_prefix(tmp_path, 3) + ".npz")
    snap.clear_verify_cache()
    assert not snap.verify_manifest(_prefix(tmp_path, 3))
    assert snap.verify_manifest(_prefix(tmp_path, 3)).reason.startswith(
        ("crc:", "size:")
    )
    # torn newest falls back to the previous committed iteration
    assert ckpt.latest_iteration(str(tmp_path), D) == 2

    chaos.flip_byte(_prefix(tmp_path, 2) + "_w2v.txt", offset=10)
    snap.clear_verify_cache()
    assert snap.verify_manifest(_prefix(tmp_path, 2)).reason.startswith("crc:")
    assert ckpt.latest_iteration(str(tmp_path), D) == 1

    # unverified discovery still sees everything (inspection tools)
    assert ckpt.latest_iteration(str(tmp_path), D, verified_only=False) == 3


def test_deleting_optional_text_exports_keeps_checkpoint_committed(tmp_path):
    """The text twins are convenience artifacts: an operator reclaiming
    space by deleting them must not un-commit the npz checkpoint (their
    CORRUPTION while present is still detected — test_crc_rejection)."""
    for it in (1, 2):
        _save(tmp_path, it)
    for it in (1, 2):
        os.unlink(_prefix(tmp_path, it) + ".txt")
        os.unlink(_prefix(tmp_path, it) + "_w2v.txt")
    snap.clear_verify_cache()
    assert snap.verify_manifest(_prefix(tmp_path, 2)).ok
    assert ckpt.latest_iteration(str(tmp_path), D) == 2
    # the npz itself stays load-bearing
    os.unlink(_prefix(tmp_path, 2) + ".npz")
    snap.clear_verify_cache()
    assert ckpt.latest_iteration(str(tmp_path), D) == 1


def test_missing_manifest_treated_as_uncommitted(tmp_path):
    _save(tmp_path, 1)
    _save(tmp_path, 2)
    os.unlink(snap.manifest_path(_prefix(tmp_path, 2)))
    # iteration 2 has files but no commit record → killed mid-save
    assert ckpt.latest_iteration(str(tmp_path), D) == 1


def test_legacy_dir_without_any_manifest_accepted(tmp_path):
    """Pre-manifest export dirs (reference scripts) have nothing to
    verify against and must keep working."""
    _save(tmp_path, 1)
    _save(tmp_path, 2)
    for it in (1, 2):
        os.unlink(snap.manifest_path(_prefix(tmp_path, it)))
    assert ckpt.latest_iteration(str(tmp_path), D) == 2
    found = list(ckpt.iter_checkpoints(str(tmp_path), verified_only=True))
    assert [it for _, it, _ in found] == [1, 2]


def test_manifest_expectation_is_scoped_per_dim(tmp_path):
    """Another dim's manifests say nothing about this dim's history: a
    legacy (manifest-less) dim-D history next to a manifested dim-8 run
    stays discoverable."""
    from gene2vec_tpu.io.vocab import Vocab

    _save(tmp_path, 1)
    _save(tmp_path, 2)
    for it in (1, 2):
        os.unlink(snap.manifest_path(_prefix(tmp_path, it)))  # legacy dim-D
    params = SGNSParams(
        emb=np.ones((V, 8), np.float32), ctx=np.zeros((V, 8), np.float32)
    )
    ckpt.save_iteration(str(tmp_path), 8, 5, params, _vocab())  # manifested
    snap.clear_verify_cache()
    assert ckpt.latest_iteration(str(tmp_path), D) == 2
    assert ckpt.latest_iteration(str(tmp_path), 8) == 5


def test_mixed_legacy_and_manifested_history_falls_back(tmp_path):
    """Mid-run manifest adoption: legacy iterations stay usable as the
    fallback when the newest (manifested) export rots — pre-adoption
    history must not be orphaned by the upgrade."""
    for it in (1, 2):
        _save(tmp_path, it)
        os.unlink(snap.manifest_path(_prefix(tmp_path, it)))  # legacy
    _save(tmp_path, 3)  # manifested (post-upgrade)
    snap.clear_verify_cache()
    assert ckpt.latest_iteration(str(tmp_path), D) == 3
    chaos.truncate_file(_prefix(tmp_path, 3) + ".npz")
    snap.clear_verify_cache()
    assert ckpt.latest_iteration(str(tmp_path), D) == 2


def test_corrupt_manifest_crc_injector(tmp_path):
    _save(tmp_path, 1)
    chaos.corrupt_manifest_crc(_prefix(tmp_path, 1))
    snap.clear_verify_cache()
    assert snap.verify_manifest(_prefix(tmp_path, 1)).reason.startswith("crc:")


def test_malformed_manifest_shapes_never_raise(tmp_path):
    """Valid-JSON-wrong-shape manifests (hand-edited, corrupted) must
    yield a falsy torn-manifest verdict, not an exception — discovery
    is a never-raises contract."""
    _save(tmp_path, 1)
    mpath = snap.manifest_path(_prefix(tmp_path, 1))
    for bad in ('{"files": ["a"]}', '{"files": {"x.npz": 123}}',
                '{"files": null}', "[]", "{"):
        with open(mpath, "w") as f:
            f.write(bad)
        snap.clear_verify_cache()
        res = snap.verify_manifest(_prefix(tmp_path, 1))
        assert not res and res.reason == "torn-manifest", (bad, res)
        assert ckpt.latest_iteration(str(tmp_path), D) == 0  # skipped, no crash


def test_verify_cache_invalidates_on_change(tmp_path):
    _save(tmp_path, 1)
    assert snap.verify_manifest(_prefix(tmp_path, 1)).ok
    time.sleep(0.01)  # ensure a distinct mtime_ns on coarse filesystems
    chaos.truncate_file(_prefix(tmp_path, 1) + ".npz")
    assert not snap.verify_manifest(_prefix(tmp_path, 1))


# -- registry fallback / quarantine -----------------------------------------


def test_registry_falls_back_counts_and_quarantines(tmp_path):
    from gene2vec_tpu.obs.registry import MetricsRegistry
    from gene2vec_tpu.serve.registry import ModelRegistry

    metrics = MetricsRegistry()
    _save(tmp_path, 1)
    reg = ModelRegistry(
        str(tmp_path), metrics=metrics,
        retry_backoff_s=0.01, quarantine_after=2,
    )
    assert reg.refresh() and reg.model.iteration == 1

    # iteration 2 VERIFIES (manifest restamped over the rotten bytes)
    # but fails to load — the path CRC checking cannot catch
    _save(tmp_path, 2)
    chaos.truncate_file(_prefix(tmp_path, 2) + ".npz")
    chaos.restamp_manifest(_prefix(tmp_path, 2))
    snap.clear_verify_cache()

    assert reg.refresh() is False
    assert reg.model.iteration == 1  # last good model keeps serving
    assert metrics.counter("model_load_failures_total").value == 1

    time.sleep(0.05)  # clear the backoff window
    assert reg.refresh() is False
    assert metrics.counter("model_load_failures_total").value == 2
    assert _prefix(tmp_path, 2) + ".npz" in reg.quarantined

    time.sleep(0.05)
    assert reg.refresh() is False  # quarantined: not even attempted
    assert metrics.counter("model_load_failures_total").value == 2

    _save(tmp_path, 3)
    assert reg.refresh() and reg.model.iteration == 3


def test_registry_backoff_suppresses_immediate_retry(tmp_path):
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.obs.registry import MetricsRegistry

    metrics = MetricsRegistry()
    _save(tmp_path, 1)
    reg = ModelRegistry(
        str(tmp_path), metrics=metrics,
        retry_backoff_s=60.0, quarantine_after=99,
    )
    assert reg.refresh()
    _save(tmp_path, 2)
    chaos.truncate_file(_prefix(tmp_path, 2) + ".npz")
    chaos.restamp_manifest(_prefix(tmp_path, 2))
    snap.clear_verify_cache()
    for _ in range(5):
        assert reg.refresh() is False
    # one load attempt, four backoff skips
    assert metrics.counter("model_load_failures_total").value == 1


def test_registry_torn_export_filtered_before_load(tmp_path):
    """A checkpoint whose manifest fails verification is filtered at
    discovery — zero load attempts, zero failure counts."""
    from gene2vec_tpu.obs.registry import MetricsRegistry
    from gene2vec_tpu.serve.registry import ModelRegistry, discover_newest

    metrics = MetricsRegistry()
    _save(tmp_path, 1)
    _save(tmp_path, 2)
    chaos.truncate_file(_prefix(tmp_path, 2) + ".npz")
    snap.clear_verify_cache()
    assert discover_newest(str(tmp_path))[1] == 1
    reg = ModelRegistry(str(tmp_path), metrics=metrics)
    assert reg.refresh() and reg.model.iteration == 1
    assert metrics.counter("model_load_failures_total").value == 0


def test_registry_quarantine_cleared_when_file_rewritten(tmp_path):
    """A quarantine verdict applies to the bytes, not the filename: a
    checkpoint atomically rewritten under the same name gets a fresh
    chance."""
    from gene2vec_tpu.obs.registry import MetricsRegistry
    from gene2vec_tpu.serve.registry import ModelRegistry

    metrics = MetricsRegistry()
    _save(tmp_path, 1)
    reg = ModelRegistry(
        str(tmp_path), metrics=metrics,
        retry_backoff_s=0.001, quarantine_after=1,
    )
    assert reg.refresh()
    _save(tmp_path, 2)
    chaos.truncate_file(_prefix(tmp_path, 2) + ".npz")
    chaos.restamp_manifest(_prefix(tmp_path, 2))
    snap.clear_verify_cache()
    assert reg.refresh() is False
    assert _prefix(tmp_path, 2) + ".npz" in reg.quarantined

    time.sleep(0.01)  # distinct mtime_ns for the rewrite
    _save(tmp_path, 2, fill=7.0)  # training re-commits the iteration
    snap.clear_verify_cache()
    assert reg.refresh() is True
    assert reg.model.iteration == 2
    assert reg.quarantined == {}


def test_latest_iteration_verifies_only_the_newest(tmp_path, monkeypatch):
    """Newest-first lazy discovery: an intact newest checkpoint costs
    ONE manifest verification, not a CRC sweep of the whole history."""
    for it in (1, 2, 3):
        _save(tmp_path, it)
    calls = []
    real = snap.verify_manifest

    def counting(prefix, use_cache=True):
        calls.append(prefix)
        return real(prefix, use_cache=use_cache)

    monkeypatch.setattr(ckpt.snap, "verify_manifest", counting)
    assert ckpt.latest_iteration(str(tmp_path), D) == 3
    assert len(calls) == 1 and calls[0].endswith("iter_3")


# -- async writer ------------------------------------------------------------


def test_async_writer_runs_jobs_in_order_and_flushes():
    done = []
    w = AsyncCheckpointWriter(max_pending=1)
    for i in range(4):
        w.submit(lambda i=i: (time.sleep(0.01), done.append(i), 128)[-1])
    w.flush()
    assert done == [0, 1, 2, 3]
    w.close()
    with pytest.raises(CheckpointWriteError):
        w.submit(lambda: None)  # closed writers refuse work


def test_async_writer_double_buffer_bound():
    """At most max_pending writes outstanding: a second submit blocks
    until the in-flight write RETIRES, so with the caller's one staged
    copy no more than two snapshots are ever alive."""
    gate = threading.Event()
    w = AsyncCheckpointWriter(max_pending=1)
    t0 = time.perf_counter()
    w.submit(lambda: gate.wait(10))  # writer idle → returns instantly
    assert time.perf_counter() - t0 < 1.0
    assert w.pending == 1
    release = threading.Thread(
        target=lambda: (time.sleep(0.2), gate.set()), daemon=True
    )
    release.start()
    t0 = time.perf_counter()
    w.submit(lambda: None)  # second: must wait for the first to retire
    assert time.perf_counter() - t0 > 0.1
    w.close()
    assert w.pending == 0


def test_async_writer_error_surfaces_on_train_thread():
    w = AsyncCheckpointWriter()
    w.submit(lambda: (_ for _ in ()).throw(IOError("disk full")))
    with pytest.raises(CheckpointWriteError, match="disk full"):
        w.flush()
    w.close()


def test_async_writer_metrics():
    from gene2vec_tpu.obs.registry import MetricsRegistry

    metrics = MetricsRegistry()
    w = AsyncCheckpointWriter(metrics=metrics)
    w.submit(lambda: 4096)
    w.close()
    assert metrics.counter("ckpt_writes_total").value == 1
    assert metrics.counter("ckpt_bytes_total").value == 4096
    assert metrics.histogram("ckpt_write_seconds").count == 1
    assert metrics.gauge("ckpt_inflight").value == 0


# -- preemption drain --------------------------------------------------------


def test_preemption_handler_trigger_and_second_signal_semantics():
    h = PreemptionHandler()
    assert not h.triggered
    h.trigger(signal.SIGTERM)
    assert h.triggered and h.received == signal.SIGTERM
    h.trigger(signal.SIGINT)  # first signal wins the record
    assert h.received == signal.SIGTERM
    assert h.wait(0.01)


def test_sigterm_drain_in_process_resumes_bit_exact(tmp_path):
    """Drain after iteration 1, resume, and match the uninterrupted
    run's final embedding bit for bit on CPU — the tier-1 version of the
    chaos drill's kill/resume contract."""
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = _corpus()
    cfg = SGNSConfig(dim=8, num_iters=3, batch_pairs=64, seed=5)
    ref_dir, drain_dir = str(tmp_path / "ref"), str(tmp_path / "drain")
    SGNSTrainer(corpus, cfg).run(ref_dir, log=lambda s: None)
    ref = chaos.load_table(ref_dir, 8, 3)

    h = PreemptionHandler()

    def log(msg):
        if "iteration 1 done" in msg:
            h.trigger(signal.SIGTERM)

    SGNSTrainer(corpus, cfg).run(drain_dir, log=log, preempt=h)
    assert ckpt.latest_iteration(drain_dir, 8) == 1  # drained, committed
    with open(os.path.join(drain_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["interrupted"] is True
    assert manifest["completed_iteration"] == 1

    SGNSTrainer(corpus, cfg).run(drain_dir, log=lambda s: None)
    assert np.array_equal(ref, chaos.load_table(drain_dir, 8, 3))


def test_sigterm_drain_cli_exit_code(tmp_path):
    """The real training CLI maps a SIGTERM drain to EXIT_PREEMPTED
    and leaves a committed, resumable export dir."""
    data = tmp_path / "corpus"
    data.mkdir()
    rng = np.random.RandomState(0)
    lines = [f"G{a} G{b}" for a, b in rng.randint(0, 15, size=(120, 2))]
    (data / "pairs.txt").write_text("\n".join(lines) + "\n")
    export = str(tmp_path / "out")
    r = chaos.run_cli_kill_on(
        chaos.gene2vec_argv(
            str(data), export, dim=8, iters=3, batch_pairs=32
        ),
        r"iteration 1 done",
        sig=signal.SIGTERM,
        timeout=300,
    )
    assert r.returncode == EXIT_PREEMPTED, r.output[-2000:]
    assert ckpt.latest_iteration(export, 8) >= 1
    with open(os.path.join(export, "manifest.json")) as f:
        assert json.load(f)["interrupted"] is True


def test_async_checkpoint_run_matches_sync(tmp_path):
    from gene2vec_tpu.sgns.train import SGNSTrainer

    corpus = _corpus(seed=2)
    cfg = SGNSConfig(dim=8, num_iters=2, batch_pairs=64, seed=9)
    sync_dir, async_dir = str(tmp_path / "s"), str(tmp_path / "a")
    SGNSTrainer(corpus, cfg).run(sync_dir, log=lambda s: None)
    SGNSTrainer(
        corpus, dataclasses.replace(cfg, async_checkpoint=True)
    ).run(async_dir, log=lambda s: None)
    assert np.array_equal(
        chaos.load_table(sync_dir, 8, 2), chaos.load_table(async_dir, 8, 2)
    )
    # every async checkpoint committed with a verifying manifest
    for it in (1, 2):
        assert snap.verify_manifest(
            os.path.join(async_dir, f"gene2vec_dim_8_iter_{it}")
        ).ok


# -- budget wiring -----------------------------------------------------------


def test_async_overhead_budget_entry_is_honest():
    """The drill's overhead gate reads budgets.json; pin the contract
    values so the <2% acceptance criterion cannot drift silently."""
    from gene2vec_tpu.analysis.passes_hlo import load_budgets

    entry = load_budgets()["resilience"]["async_ckpt"]
    assert entry["max_overhead_fraction"] <= 0.02
    assert entry["reference_overhead_fraction"] <= entry["max_overhead_fraction"]
    assert entry["txt_output"] is False


# -- the full drill ----------------------------------------------------------


@pytest.mark.slow
def test_chaos_drill_smoke():
    """End-to-end fault injection against the real CLIs (SIGKILL at a
    random step → bit-exact resume; serve no-garbage-swap; async
    overhead budget)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_drill.py"),
         "--smoke", "--seed", "23"],
        capture_output=True, text=True, timeout=590,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr[-3000:]
    doc = json.loads(proc.stdout)
    assert doc["passed"] is True
    # the resilience core is a SUBSET: later PRs grew the drill
    # (fleet/alerts/autoscale/shard phases, each with its own gated
    # smoke in run_static_analysis.sh --with-chaos)
    assert {
        "training_resume", "corruption", "serve", "async_overhead"
    } <= set(doc["phases"])
