"""Serve subsystem: registry hot swap, engine parity, batcher policy,
HTTP round trip.  Everything runs on the CPU backend with tiny models
(conftest pins JAX_PLATFORMS=cpu)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from gene2vec_tpu.io.checkpoint import save_iteration
from gene2vec_tpu.io.emb_io import read_word2vec_format
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    RejectedError,
)
from gene2vec_tpu.serve.engine import SimilarityEngine, next_pow2
from gene2vec_tpu.serve.registry import (
    ModelRegistry,
    discover_newest,
    l2_normalize,
)
from gene2vec_tpu.serve.server import (
    ServeApp,
    ServeConfig,
    make_server,
)
from gene2vec_tpu.sgns.model import SGNSParams

V, D = 32, 8


def _write_iteration(export_dir, iteration, seed):
    rng = np.random.RandomState(seed)
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    emb = rng.randn(V, D).astype(np.float32)
    params = SGNSParams(
        emb=jnp.asarray(emb), ctx=jnp.asarray(np.zeros((V, D), np.float32))
    )
    save_iteration(str(export_dir), D, iteration, params, vocab)
    return emb


@pytest.fixture
def export_dir(tmp_path):
    d = tmp_path / "exports"
    _write_iteration(d, 1, seed=1)
    _write_iteration(d, 2, seed=2)
    return d


# -- registry ----------------------------------------------------------------


def test_registry_loads_newest_iteration(export_dir):
    reg = ModelRegistry(str(export_dir))
    assert reg.refresh()
    m = reg.model
    assert m.iteration == 2 and m.dim == D and len(m) == V
    assert m.meta["iteration"] == 2
    # unit rows are L2-normalized
    norms = np.linalg.norm(np.asarray(m.unit), axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)
    # stale rescan is a no-op
    assert not reg.refresh()


def test_registry_hot_swap_is_atomic_under_reader(export_dir):
    reg = ModelRegistry(str(export_dir))
    reg.refresh()
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            m = reg.model  # one snapshot; all fields must cohere
            if not (
                m.meta["iteration"] == m.iteration
                and len(m.tokens) == m.emb.shape[0]
                and m.unit.shape[0] == m.emb.shape[0]
            ):
                torn.append(m.iteration)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for it in range(3, 7):
        _write_iteration(export_dir, it, seed=it)
        assert reg.refresh()
        assert reg.model.iteration == it
    stop.set()
    t.join(timeout=5)
    assert torn == []


def test_registry_text_format_fallback(export_dir):
    # strip the npz checkpoints AND their manifests: only the
    # reference-style text exports remain (the reference scripts write
    # neither), exercising the streaming word2vec reader path
    for p in list(export_dir.glob("*.npz")) + list(
        export_dir.glob("*.MANIFEST.json")
    ):
        p.unlink()
    assert discover_newest(str(export_dir))[2].endswith("_w2v.txt")
    reg = ModelRegistry(str(export_dir))
    assert reg.refresh()
    m = reg.model
    assert m.iteration == 2 and len(m) == V
    assert m.meta.get("format") == "w2v"


def test_registry_empty_dir(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    assert not reg.refresh()
    with pytest.raises(RuntimeError):
        reg.model


def test_word2vec_streaming_reader_errors(tmp_path):
    p = tmp_path / "bad_w2v.txt"
    p.write_text("3 2\nA 1.0 2.0\nB 3.0 4.0\n")
    with pytest.raises(ValueError, match="header says 3 rows, found 2"):
        read_word2vec_format(str(p))
    p.write_text("1 2\nA 1.0 2.0\nB 3.0 4.0\n")
    with pytest.raises(ValueError, match="header says 1 rows, found 2"):
        read_word2vec_format(str(p))


# -- engine ------------------------------------------------------------------


def test_engine_topk_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    unit = jnp.asarray(l2_normalize(rng.randn(V, D).astype(np.float32)))
    queries = rng.randn(5, D).astype(np.float32)
    engine = SimilarityEngine(max_batch=8)
    scores, idx = engine.top_k(unit, queries, k=6)
    qn = l2_normalize(queries)
    oracle = qn @ np.asarray(unit).T
    expect_idx = np.argsort(-oracle, axis=1)[:, :6]
    np.testing.assert_array_equal(idx, expect_idx)
    np.testing.assert_allclose(
        scores, np.take_along_axis(oracle, expect_idx, axis=1), atol=1e-5
    )


def test_engine_valid_mask_hides_pad_rows():
    rng = np.random.RandomState(0)
    unit = np.zeros((8, D), np.float32)
    unit[:5] = l2_normalize(rng.randn(5, D).astype(np.float32))
    engine = SimilarityEngine(max_batch=4)
    _, idx = engine.top_k(jnp.asarray(unit), rng.randn(2, D), k=5, valid=5)
    assert (idx < 5).all()


def test_engine_buckets_bound_compiles():
    engine = SimilarityEngine(max_batch=8)
    assert engine.buckets == (1, 2, 4, 8)
    assert [engine.bucket(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        engine.bucket(9)
    assert next_pow2(1) == 1 and next_pow2(5) == 8
    rng = np.random.RandomState(0)
    unit = jnp.asarray(l2_normalize(rng.randn(V, D).astype(np.float32)))
    size0 = engine._cache_size()
    if size0 is None:
        pytest.skip("jit cache introspection unavailable")
    for n in (1, 2, 3, 4, 5, 8):
        engine.top_k(unit, rng.randn(n, D), k=3)
    first = engine._cache_size()
    # same shapes again: the cache must not grow
    for n in (3, 5, 8, 1):
        engine.top_k(unit, rng.randn(n, D), k=3)
    assert engine._cache_size() == first
    # n=3,4 share bucket 4 and n=5,8 share 8: at most one compile per
    # bucket (the size counter may be process-global, hence the delta)
    assert first - size0 <= len(engine.buckets)


# -- batcher -----------------------------------------------------------------


def _echo_compute(items, k_max):
    return [(item, k_max) for item in items]


def test_batcher_coalesces_within_window():
    batches = []

    def compute(items, k_max):
        batches.append(len(items))
        return _echo_compute(items, k_max)

    b = MicroBatcher(
        compute, max_batch=8, max_delay_s=0.2, max_queue=32
    ).start()
    try:
        tickets = [b.submit_async(i, 4) for i in range(5)]
        results = [t.get() for t in tickets]
        assert [r[0] for r in results] == list(range(5))
        assert max(batches) >= 2  # coalesced, not all singletons
    finally:
        b.stop()


def test_batcher_max_batch_closes_window():
    batches = []

    def compute(items, k_max):
        batches.append(len(items))
        return _echo_compute(items, k_max)

    # a huge window: only max_batch can close it
    b = MicroBatcher(
        compute, max_batch=4, max_delay_s=5.0, max_queue=32
    ).start()
    try:
        tickets = [b.submit_async(i, 1) for i in range(4)]
        t0 = time.monotonic()
        for t in tickets:
            t.get()
        assert time.monotonic() - t0 < 2.0  # did not wait out the window
        assert batches[0] == 4
    finally:
        b.stop()


def test_batcher_queue_full_rejects():
    release = threading.Event()

    def compute(items, k_max):
        release.wait(5.0)
        return _echo_compute(items, k_max)

    b = MicroBatcher(
        compute, max_batch=1, max_delay_s=0.0, max_queue=2,
        default_timeout_s=10.0,
    ).start()
    try:
        first = b.submit_async(0, 1)
        time.sleep(0.05)  # worker drains it into the blocked batch
        fillers = [b.submit_async(10 + i, 1) for i in range(2)]
        with pytest.raises(RejectedError):
            for i in range(3):
                b.submit_async(20 + i, 1)
        release.set()
        first.get()
        for t in fillers:
            t.get()
    finally:
        release.set()
        b.stop()


def test_batcher_deadline_expires():
    def compute(items, k_max):
        time.sleep(0.3)
        return _echo_compute(items, k_max)

    b = MicroBatcher(compute, max_batch=4, max_delay_s=0.0).start()
    try:
        with pytest.raises(DeadlineExceeded):
            b.submit("x", 1, timeout_s=0.05)
    finally:
        b.stop()


def test_batcher_lru_cache_hits():
    calls = []

    def compute(items, k_max):
        calls.extend(items)
        return _echo_compute(items, k_max)

    b = MicroBatcher(compute, max_batch=4, max_delay_s=0.0).start()
    try:
        r1 = b.submit("q", 3, cache_key=("m", "q", 3))
        r2 = b.submit("q", 3, cache_key=("m", "q", 3))
        assert r1 == r2
        assert calls == ["q"]  # second submit served from cache
        b.submit("q", 3, cache_key=("m2", "q", 3))
        assert calls == ["q", "q"]  # new model version misses
    finally:
        b.stop()


def test_batcher_compute_failure_propagates():
    def compute(items, k_max):
        raise RuntimeError("boom")

    b = MicroBatcher(compute, max_batch=4, max_delay_s=0.0).start()
    try:
        with pytest.raises(RuntimeError, match="boom"):
            b.submit("x", 1, timeout_s=1.0)
    finally:
        b.stop()


# -- HTTP round trip ---------------------------------------------------------


@pytest.fixture
def serving(export_dir):
    reg = ModelRegistry(str(export_dir))
    assert reg.refresh()
    app = ServeApp(
        reg, ServeConfig(max_batch=8, max_delay_ms=2.0, max_queue=16)
    ).start()
    server = make_server(app, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield url, reg, app
    server.shutdown()
    server.server_close()
    app.stop()


def _post(url, path, body, timeout=10.0):
    req = urllib.request.Request(
        f"{url}{path}",
        data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, path, timeout=10.0):
    try:
        with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_similar_round_trip(serving):
    url, reg, _ = serving
    status, doc = _post(url, "/v1/similar", {"genes": ["G0", "G3"], "k": 4})
    assert status == 200
    assert doc["model"]["iteration"] == 2
    assert len(doc["results"]) == 2
    for res in doc["results"]:
        assert len(res["neighbors"]) == 4
        # gene queries exclude the query row itself
        assert res["query"] not in [n["gene"] for n in res["neighbors"]]
    # oracle: best neighbor of G0
    m = reg.model
    scores = np.asarray(m.unit) @ np.asarray(m.unit)[0]
    order = [m.tokens[i] for i in np.argsort(-scores) if i != 0]
    got = [n["gene"] for n in doc["results"][0]["neighbors"]]
    assert got == order[:4]


def test_http_similar_get_and_errors(serving):
    url, _, _ = serving
    status, raw = _get(url, "/v1/similar?gene=G1&k=3")
    assert status == 200
    assert len(json.loads(raw)["results"][0]["neighbors"]) == 3
    assert _post(url, "/v1/similar", {"genes": ["NOPE"]})[0] == 400
    assert _post(url, "/v1/similar", {"k": 3})[0] == 400
    assert _post(url, "/v1/similar", {"genes": ["G0"], "k": 0})[0] == 400
    # malformed query ints are client errors, not route crashes
    assert _get(url, "/v1/similar?gene=G1&k=abc")[0] == 400
    assert _get(url, "/v1/genes?limit=abc")[0] == 400
    assert _get(url, "/nope")[0] == 404


def test_http_embedding_and_genes(serving):
    url, reg, _ = serving
    status, doc = _post(url, "/v1/embedding", {"genes": ["G5"]})
    assert status == 200
    np.testing.assert_allclose(
        doc["embeddings"][0]["vector"], reg.model.emb[5], atol=1e-6
    )
    status, raw = _get(url, "/v1/genes?limit=4&offset=2")
    assert status == 200
    doc = json.loads(raw)
    assert doc["total"] == V
    assert doc["genes"] == ["G2", "G3", "G4", "G5"]


def test_http_interaction(serving):
    url, _, _ = serving
    status, doc = _post(
        url, "/v1/interaction", {"pairs": [["G0", "G1"], ["G2", "G3"]]}
    )
    assert status == 200
    assert doc["trained_head"] is False  # no checkpoint supplied
    assert len(doc["scores"]) == 2
    for row in doc["scores"]:
        assert 0.0 <= row["score"] <= 1.0
    assert _post(url, "/v1/interaction", {"pairs": [["G0", "NO"]]})[0] == 400


def test_interaction_checkpoint_loads_head_not_table(export_dir, tmp_path):
    """A --ggipnn-checkpoint supplies the MLP head ONLY: its embedding
    table is row-ordered by the GGIPNN training vocab, so adopting it
    under served-vocab ids would score silently wrong pairs — and would
    also freeze scores across hot swaps."""
    from gene2vec_tpu.models.ggipnn_obs import _flatten_params
    from gene2vec_tpu.serve.interaction import InteractionScorer

    reg = ModelRegistry(str(export_dir))
    assert reg.refresh()
    base = InteractionScorer(reg.model)
    flat = _flatten_params(base.params)
    flat["embedding"] = np.zeros_like(flat["embedding"])  # poisoned table
    marked = {
        k: (v + 1.0 if k.endswith("kernel") else v)
        for k, v in flat.items()
    }
    ckpt = tmp_path / "model-100.npz"
    np.savez(str(ckpt), **marked)
    s = InteractionScorer(reg.model, checkpoint_path=str(ckpt))
    assert s.trained
    np.testing.assert_allclose(
        np.asarray(s.params["embedding"]), reg.model.emb, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(s.params["hidden1"]["kernel"]),
        np.asarray(base.params["hidden1"]["kernel"]) + 1.0,
        atol=1e-6,
    )


def test_http_healthz_and_metrics(serving):
    url, _, _ = serving
    status, raw = _get(url, "/healthz")
    doc = json.loads(raw)
    assert status == 200 and doc["status"] == "ok"
    assert doc["model"]["iteration"] == 2
    _post(url, "/v1/similar", {"genes": ["G0"], "k": 2})
    status, raw = _get(url, "/metrics")
    assert status == 200
    text = raw.decode()
    assert "serve_requests_total" in text
    assert "model_iteration" in text


def test_http_serves_new_iteration_after_swap(serving, export_dir):
    url, reg, _ = serving
    emb3 = _write_iteration(export_dir, 3, seed=33)
    assert reg.refresh()
    status, doc = _post(url, "/v1/embedding", {"genes": ["G0"]})
    assert status == 200
    assert doc["model"]["iteration"] == 3
    np.testing.assert_allclose(
        doc["embeddings"][0]["vector"], emb3[0], atol=1e-6
    )


def test_dashboard_fetch_neighbors(serving):
    from gene2vec_tpu.viz.dash_app import fetch_neighbors

    url, _, _ = serving
    hits = fetch_neighbors(url, "G0", k=3)
    assert hits is not None and len(hits) == 3
    assert all(isinstance(g, str) and isinstance(s, float) for g, s in hits)
    # every failure mode degrades to None (the figure-json fallback)
    assert fetch_neighbors(url, "NOPE", k=3) is None
    assert fetch_neighbors("http://127.0.0.1:9", "G0") is None
