"""GGIPNN model family tests: data utils, model math, training, AUC."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import GGIPNNConfig
from gene2vec_tpu.eval.metrics import roc_auc_score
from gene2vec_tpu.models import GGIPNN, GGIPNNTrainer, PairTextVocab
from gene2vec_tpu.models.ggipnn_data import batch_iter, one_hot_labels


def test_auc_matches_sklearn():
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, 500)
    s = rng.rand(500)
    s[y == 1] += 0.3  # separable-ish, with ties impossible
    assert roc_auc_score(y, s) == pytest.approx(
        sklearn_metrics.roc_auc_score(y, s), abs=1e-12
    )
    # with heavy ties
    s_t = np.round(s, 1)
    assert roc_auc_score(y, s_t) == pytest.approx(
        sklearn_metrics.roc_auc_score(y, s_t), abs=1e-12
    )


def test_pair_vocab_transductive():
    train = ["A B", "B C"]
    test = ["C D"]  # D appears only in test → transductive fit must include it
    v = PairTextVocab().fit(train, test)
    assert len(v) == 4
    enc = v.transform(test)
    assert enc.shape == (1, 2)
    assert v.id_to_token[enc[0, 1]] == "D"


def test_one_hot_and_batch_iter():
    oh = one_hot_labels(["0", "1", "1"])
    assert oh.tolist() == [[1, 0], [0, 1], [0, 1]]
    data = np.arange(10)[:, None]
    batches = list(batch_iter(data, batch_size=4, num_epochs=2, shuffle=False))
    # ragged tail kept: 4+4+2 per epoch
    assert [len(b) for b in batches] == [4, 4, 2, 4, 4, 2]


def test_ggipnn_forward_shapes():
    model = GGIPNN(vocab_size=20, embedding_dim=8, hidden_dims=(16, 16, 4))
    x = jnp.zeros((3, 2), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (3, 2)
    # dropout active only in train mode and changes outputs
    l1 = model.apply(
        {"params": params}, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    l2 = model.apply(
        {"params": params}, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)}
    )
    assert not np.allclose(np.asarray(l1), np.asarray(l2))


def _toy_problem(n=600, vocab=30, seed=3):
    """Pairs labeled by a planted rule: positive iff both ids < vocab/2."""
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (n, 2)).astype(np.int32)
    y = ((x[:, 0] < vocab // 2) & (x[:, 1] < vocab // 2)).astype(int)
    return x, y


def test_ggipnn_learns_planted_rule():
    x, y = _toy_problem()
    # last hidden layer wide enough that the reference-mandated 50% dropout
    # after it (quirk #12) doesn't wreck calibration on a 600-sample toy set
    cfg = GGIPNNConfig(
        embedding_dim=16,
        hidden_dims=(64, 64, 16),
        embed_train=True,
        use_pretrained=False,
        num_epochs=60,
        batch_size=64,
        evaluate_every=10**9,
    )
    vocab = PairTextVocab().fit([f"g{a} g{b}" for a, b in x])
    trainer = GGIPNNTrainer(cfg, vocab)
    enc = vocab.transform([f"g{a} g{b}" for a, b in x])
    yoh = one_hot_labels(y)
    params, _ = trainer.fit(enc, yoh, log=lambda s: None)
    res = trainer.evaluate(params, enc, yoh)
    assert res["accuracy"] > 0.9
    assert res["auc"] > 0.95


def test_frozen_embedding_not_updated(tmp_path):
    x, y = _toy_problem(n=200)
    lines = [f"g{a} g{b}" for a, b in x]
    vocab = PairTextVocab().fit(lines)

    # write a pretrained emb file covering half the vocab
    from gene2vec_tpu.io.emb_io import write_word2vec_format

    toks = vocab.id_to_token[: len(vocab) // 2]
    mat = np.random.RandomState(0).randn(len(toks), 8).astype(np.float32)
    emb_file = tmp_path / "emb.txt"
    write_word2vec_format(str(emb_file), toks, mat)

    cfg = GGIPNNConfig(
        embedding_dim=8,
        hidden_dims=(16, 16, 4),
        embed_train=False,
        num_epochs=3,
        batch_size=32,
        evaluate_every=10**9,
    )
    trainer = GGIPNNTrainer(cfg, vocab)
    params, opt_state = trainer.init_state(pretrained_emb_path=str(emb_file))
    # pretrained rows present; missing rows random U(-0.25, 0.25) (quirk #6)
    table0 = np.asarray(params["embedding"])
    np.testing.assert_allclose(table0[vocab.token_to_id[toks[0]]], mat[0], rtol=1e-6)
    missing = table0[len(vocab) // 2 :]
    assert np.abs(missing).max() <= 0.25

    trainer._state = (params, opt_state)
    enc = vocab.transform(lines)
    params_after, _ = trainer.fit(enc, one_hot_labels(y), log=lambda s: None)
    np.testing.assert_array_equal(np.asarray(params_after["embedding"]), table0)


def _write_split_dir(tmp_path, n=300):
    """predictionData/-shaped directory from the toy problem."""
    x, y = _toy_problem(n=n)
    d = tmp_path / "data"
    d.mkdir()
    cuts = {"train": slice(0, n - 100), "valid": slice(n - 100, n - 50),
            "test": slice(n - 50, n)}
    for split, sl in cuts.items():
        with open(d / f"{split}_text.txt", "w") as f:
            f.writelines(f"g{a} g{b}\n" for a, b in x[sl])
        with open(d / f"{split}_label.txt", "w") as f:
            f.writelines(f"{v}\n" for v in y[sl])
    return str(d)


def test_run_dir_summaries_and_checkpoints(tmp_path):
    """Reference runs/<ts>/ parity (src/GGIPNN_Classification.py:129-163,
    216-222): separate train/dev summary writers with grad sparsity, and
    step checkpoints that appear on the checkpoint_every cadence."""
    import glob
    import os

    from gene2vec_tpu.models.ggipnn_train import run_classification

    data_dir = _write_split_dir(tmp_path)
    run_dir = str(tmp_path / "run")
    cfg = GGIPNNConfig(
        embedding_dim=8, hidden_dims=(16, 16, 4), use_pretrained=False,
        num_epochs=4, batch_size=16, evaluate_every=10, checkpoint_every=20,
    )
    run_classification(data_dir, None, cfg, log=lambda s: None, run_dir=run_dir)

    # train writer: per-step rows with loss/accuracy + grad sparsity columns
    train_csv = os.path.join(run_dir, "summaries", "train", "metrics.csv")
    with open(train_csv) as f:
        header = f.readline().strip().split(",")
        rows = f.readlines()
    assert "loss" in header and "accuracy" in header
    assert any(c.endswith("/grad/sparsity") for c in header)
    # 200 train pairs / batch 16 = 13 ragged batches x 4 epochs = 52 steps
    assert len(rows) == 52
    # dev writer: one row per evaluate_every steps
    dev_csv = os.path.join(run_dir, "summaries", "dev", "metrics.csv")
    with open(dev_csv) as f:
        assert len(f.readlines()) == 1 + 52 // 10
    # tensorboardX event files when the package is installed
    try:
        import tensorboardX  # noqa: F401

        assert glob.glob(os.path.join(run_dir, "summaries", "train", "events.*"))
        assert glob.glob(os.path.join(run_dir, "summaries", "dev", "events.*"))
    except ImportError:
        pass
    # checkpoints on the every-20 cadence: steps 20 and 40
    ckpts = sorted(os.listdir(os.path.join(run_dir, "checkpoints")))
    assert ckpts == ["model-20.npz", "model-40.npz"]
    # unified obs layer rides in the same run dir (docs/OBSERVABILITY.md)
    import json

    manifest = json.load(open(os.path.join(run_dir, "manifest.json")))
    assert manifest["name"] == "ggipnn"
    assert manifest["config"]["batch_size"] == 16
    from gene2vec_tpu.obs.trace import read_events

    events = read_events(os.path.join(run_dir, "events.jsonl"))
    names = {e["name"] for e in events}
    assert {"fit", "test_eval", "checkpoint", "dev_eval"} <= names
    assert os.path.exists(os.path.join(run_dir, "metrics.prom"))


def test_run_checkpoints_keep_five(tmp_path):
    """Saver max_to_keep=5 parity: older snapshots are pruned, and a saved
    checkpoint round-trips the param pytree."""
    import os

    from gene2vec_tpu.models.ggipnn_obs import GGIPNNRun, load_checkpoint

    run = GGIPNNRun(str(tmp_path / "run"))
    params = {"dense1": {"kernel": np.ones((3, 2), np.float32)},
              "embedding": np.zeros((4, 2), np.float32)}
    for step in range(1000, 8000, 1000):
        run.checkpoint(step, params)
    run.close()
    kept = sorted(os.listdir(run.checkpoint_dir))
    assert kept == [f"model-{s}.npz" for s in range(3000, 8000, 1000)]
    loaded = load_checkpoint(os.path.join(run.checkpoint_dir, "model-7000.npz"))
    np.testing.assert_array_equal(loaded["dense1/kernel"], np.ones((3, 2)))
    np.testing.assert_array_equal(loaded["embedding"], np.zeros((4, 2)))
