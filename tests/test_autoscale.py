"""Elastic fleet & multi-tenant admission: the autoscale policy as a
pure state machine over fake aggregator snapshots, tenant token
buckets + weighted-fair dequeue, the in-flight drain contract, the
elastic supervisor over a jax-free stub replica, and the
passes_autoscale budget gate (docs/SERVING.md#elastic-fleet)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from gene2vec_tpu.serve.autoscale import (
    AutoscaleConfig,
    AutoscalePolicy,
    ElasticController,
)
from gene2vec_tpu.serve.batcher import MicroBatcher
from gene2vec_tpu.serve.client import InFlightTracker, ResilientClient, RetryPolicy
from gene2vec_tpu.serve.fleet import (
    FleetConfig,
    FleetSupervisor,
    ReplicaState,
)
from gene2vec_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    OVERFLOW_TENANT,
    FairQueue,
    RateBucket,
    TenantAdmission,
    TenantPolicy,
    TenantQuota,
    sanitize_tenant,
)
from gene2vec_tpu.obs.registry import MetricsRegistry


# -- snapshot helpers --------------------------------------------------------


def snap(queue=0.0, requests=0.0, rejected=0.0, ok=None, responses=None,
         fresh=3.0, p99=None, route="/v1/similar", quota_rejected=0.0,
         throttled=0.0):
    """One fake aggregator snapshot in the evaluator/scaler shape."""
    responses = requests if responses is None else responses
    ok = responses if ok is None else ok
    s = {
        "fleet_queue_depth": queue,
        "fleet_requests": requests,
        "fleet_rejected": rejected,
        "fleet_quota_rejected": quota_rejected,
        "fleet_ok": ok,
        "fleet_responses": responses,
        "fleet_throttled": throttled,
        "_fresh_targets": fresh,
    }
    if p99 is not None:
        s[f"fleet_route_p99_seconds{{route={route}}}"] = p99
    return s


def make_policy(**kw):
    base = dict(
        min_replicas=1, max_replicas=4,
        up_queue_per_replica=8.0, up_rejection_rate=0.02,
        up_after_ticks=2, down_after_ticks=3,
        down_queue_per_replica=1.0, cooldown_s=10.0,
    )
    base.update(kw)
    return AutoscalePolicy(AutoscaleConfig(**base))


# -- the pure policy state machine -------------------------------------------


def test_policy_breach_scales_up_exactly_at_tick_boundary():
    p = make_policy()
    # tick 0 seeds the counter baselines and can never act
    assert p.observe(snap(), now=0.0, current=1).action == "hold"
    # breach tick 1 of 2: hold
    d = p.observe(snap(queue=20), now=1.0, current=1)
    assert d.action == "hold" and d.breach_ticks == 1
    # breach tick 2 of 2: up, +1 replica
    d = p.observe(snap(queue=20), now=2.0, current=1)
    assert d.action == "up" and d.target == 2
    assert "queue" in d.reason


def test_policy_rejection_signal_is_windowed_not_lifetime():
    p = make_policy(cooldown_s=0.0, down_after_ticks=2)
    # a historic rejection burst: lifetime rate 50%...
    p.observe(snap(requests=100, rejected=50), now=0.0, current=2)
    # ...but the following windows are perfectly clean: every tick's
    # DELTA shows zero rejections, so the policy must read "clear" and
    # scale down, not stay pinned on the cumulative ratio
    p.observe(snap(requests=110, rejected=50), now=1.0, current=2)
    d = p.observe(snap(requests=120, rejected=50), now=2.0, current=2)
    assert d.action == "down" and d.target == 1


def test_policy_clear_window_scale_down_and_min_clamp():
    p = make_policy(cooldown_s=0.0)
    p.observe(snap(), now=0.0, current=2)
    p.observe(snap(), now=1.0, current=2)
    p.observe(snap(), now=2.0, current=2)
    d = p.observe(snap(), now=3.0, current=2)  # clear tick 3 of 3
    assert d.action == "down" and d.target == 1
    # at min_replicas a complete clear window holds instead
    for i in range(6):
        d = p.observe(snap(), now=10.0 + i, current=1)
    assert d.action == "hold" and "min_replicas" in d.reason


def test_policy_middle_band_resets_both_streaks():
    p = make_policy(cooldown_s=0.0, down_after_ticks=2)
    p.observe(snap(), now=0.0, current=2)
    p.observe(snap(), now=1.0, current=2)  # clear 1/2
    # queue per replica 2.0: above down (1.0), below up (8.0) — the
    # hysteresis band; the clear streak must restart
    d = p.observe(snap(queue=4), now=2.0, current=2)
    assert d.action == "hold" and d.clear_ticks == 0
    d = p.observe(snap(), now=3.0, current=2)  # clear 1/2 again
    assert d.action == "hold" and d.clear_ticks == 1
    d = p.observe(snap(), now=4.0, current=2)
    assert d.action == "down"


def test_policy_cooldown_suppresses_consecutive_actions():
    p = make_policy(cooldown_s=100.0)
    p.observe(snap(), now=0.0, current=1)
    p.observe(snap(queue=20), now=1.0, current=1)
    assert p.observe(snap(queue=20), now=2.0, current=1).action == "up"
    # the breach persists: streak re-accumulates but cooldown holds
    p.observe(snap(queue=20), now=3.0, current=2)
    d = p.observe(snap(queue=20), now=4.0, current=2)
    assert d.action == "hold" and "cooldown" in d.reason
    # past the cooldown the pent-up breach fires immediately
    d = p.observe(snap(queue=20), now=200.0, current=2)
    assert d.action == "up" and d.target == 3


def test_policy_max_clamp_holds_on_breach():
    p = make_policy(cooldown_s=0.0, max_replicas=2)
    p.observe(snap(), now=0.0, current=2)
    p.observe(snap(queue=50), now=1.0, current=2)
    d = p.observe(snap(queue=50), now=2.0, current=2)
    assert d.action == "hold" and "max_replicas" in d.reason


def test_policy_stale_snapshot_advances_neither_streak():
    p = make_policy(cooldown_s=0.0, down_after_ticks=2)
    p.observe(snap(), now=0.0, current=2)
    # a frozen snapshot (no fresh targets) that LOOKS like a breach
    # must not scale up...
    for i in range(5):
        d = p.observe(snap(queue=100, fresh=0.0), now=1.0 + i, current=2)
        assert d.action == "hold" and "stale" in d.reason
        assert d.breach_ticks == 0
    # ...and one that looks clear must not scale down
    for i in range(5):
        d = p.observe(snap(fresh=0.0), now=10.0 + i, current=2)
        assert d.action == "hold" and d.clear_ticks == 0


def test_policy_availability_burn_breach():
    p = make_policy(cooldown_s=0.0)
    p.observe(snap(requests=0, responses=0), now=0.0, current=1)
    # window: 100 responses, 50 ok -> availability 0.5 < 0.95
    p.observe(snap(responses=100, ok=50, requests=100),
              now=1.0, current=1)
    d = p.observe(snap(responses=200, ok=100, requests=200),
                  now=2.0, current=1)
    assert d.action == "up" and "availability" in d.reason


def test_policy_quota_shedding_does_not_scale_the_fleet():
    """An abusive tenant saturating its own token bucket produces
    tenant-labeled rejections and 429 responses — DELIBERATE shedding
    that must not buy the abuser more capacity by scaling up."""
    p = make_policy(cooldown_s=0.0)
    p.observe(snap(), now=0.0, current=1)
    # every tick: 500 new 429s, all of them quota rejections, all of
    # them throttled responses; the handful of real answers are fine
    for i in range(1, 6):
        d = p.observe(
            snap(
                requests=10.0 * i, rejected=500.0 * i,
                quota_rejected=500.0 * i,
                responses=510.0 * i, ok=10.0 * i,
                throttled=500.0 * i,
            ),
            now=float(i), current=1,
        )
        assert d.action != "up", d
    # the same volume of QUEUE-FULL (capacity) rejections still fires
    p2 = make_policy(cooldown_s=0.0)
    p2.observe(snap(), now=0.0, current=1)
    p2.observe(snap(requests=100, rejected=50), now=1.0, current=1)
    d = p2.observe(snap(requests=200, rejected=100), now=2.0, current=1)
    assert d.action == "up" and "rejection" in d.reason


def test_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(AutoscaleConfig(min_replicas=0))
    with pytest.raises(ValueError):
        AutoscalePolicy(AutoscaleConfig(min_replicas=3, max_replicas=2))


# -- tenant primitives -------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_rate_bucket_refill_and_burst_cap():
    clock = FakeClock()
    b = RateBucket(rate=10.0, burst=5.0, clock=clock)
    # starts full at burst
    assert all(b.take() for _ in range(5))
    assert not b.take()
    clock.t += 0.1  # +1 token
    assert b.take() and not b.take()
    clock.t += 100.0  # refill caps at burst, not rate*dt
    assert all(b.take() for _ in range(5))
    assert not b.take()


def test_tenant_policy_from_args():
    assert TenantPolicy.from_args(0.0) is None
    p = TenantPolicy.from_args(10.0)
    assert p.default == TenantQuota(10.0, 20.0, 1.0)
    p = TenantPolicy.from_args(10.0, 30.0, ["vip:100:200:4"])
    assert p.quota("vip") == TenantQuota(100.0, 200.0, 4.0)
    assert p.quota("anyone") == TenantQuota(10.0, 30.0, 1.0)
    with pytest.raises(ValueError):
        TenantPolicy.from_args(10.0, None, ["vip"])  # no rate
    with pytest.raises(ValueError):
        TenantPolicy.from_args(10.0, None, ["vip:-1"])
    with pytest.raises(ValueError):
        # named overrides with an unmetered default is a footgun
        TenantPolicy.from_args(0.0, None, ["vip:10"])
    with pytest.raises(ValueError):
        # a NEGATIVE rate is a typo, never a disable request — only
        # exactly 0 turns tenancy off
        TenantPolicy.from_args(-50.0)
    with pytest.raises(ValueError):
        TenantPolicy.from_args(10.0, -5.0)


def test_sanitize_tenant():
    assert sanitize_tenant(None) == DEFAULT_TENANT
    assert sanitize_tenant("  ") == DEFAULT_TENANT
    assert sanitize_tenant("alice") == "alice"
    assert len(sanitize_tenant("x" * 500)) == 64


def test_tenant_admission_buckets_and_labeled_rejections():
    clock = FakeClock()
    metrics = MetricsRegistry()
    adm = TenantAdmission(
        TenantPolicy.from_args(10.0, 2.0, ["vip:100:50"]),
        metrics=metrics, clock=clock,
    )
    # default tenant: burst 2 then rejected
    assert adm.admit("alice") == (True, "alice")
    assert adm.admit("alice") == (True, "alice")
    ok, label = adm.admit("alice")
    assert not ok and label == "alice"
    # the rejection is tenant-labeled in the registry
    text = metrics.prometheus_text()
    assert 'serve_rejected_total{tenant="alice"} 1' in text
    # vip has its own bigger bucket
    assert all(adm.admit("vip")[0] for _ in range(50))
    assert not adm.admit("vip")[0]
    assert adm.weight("vip") == 1.0


def test_tenant_admission_bounded_table_collapses_minted_ids():
    clock = FakeClock()
    adm = TenantAdmission(
        TenantPolicy.from_args(10.0, 1.0), clock=clock, max_tenants=3,
    )
    for t in ("a", "b", "c"):
        assert adm.admit(t) == (True, t)
    # the table is full: every further minted id shares ONE bucket
    ok1, label1 = adm.admit("minted-1")
    ok2, label2 = adm.admit("minted-2")
    assert label1 == label2 == OVERFLOW_TENANT
    assert ok1 and not ok2  # burst 1, shared
    # known tenants keep their own buckets
    assert adm.resolve("a") == "a"


def test_fair_queue_weighted_interleave_and_fifo_within_tenant():
    weights = {"a": 3.0, "b": 1.0}
    q = FairQueue(weight_of=lambda t: weights.get(t, 1.0))
    for i in range(12):
        q.push("a", f"a{i}")
    for i in range(4):
        q.push("b", f"b{i}")
    assert len(q) == 16
    order = q.pop_upto(16)
    assert len(q) == 0
    # proportional drain: among the first 8 pops, ~3:1
    first8 = order[:8]
    n_a = sum(1 for x in first8 if x.startswith("a"))
    assert n_a == 6, first8
    # FIFO within each tenant
    assert [x for x in order if x.startswith("a")] == [
        f"a{i}" for i in range(12)
    ]
    assert [x for x in order if x.startswith("b")] == [
        f"b{i}" for i in range(4)
    ]


def test_fair_queue_single_lane_is_fifo_and_credit_drops_when_empty():
    q = FairQueue()
    for i in range(5):
        q.push("only", i)
    assert q.pop_upto(5) == [0, 1, 2, 3, 4]
    assert q.pop() is None and not q
    # an idle tenant must not hoard scheduling credit: after its lane
    # empties, a fresh contest starts from zero
    q.push("a", "a0")
    q.pop()
    q.push("a", "a1")
    q.push("b", "b0")
    got = {q.pop(), q.pop()}
    assert got == {"a1", "b0"}


def test_batcher_drains_tenant_lanes_weighted_fair():
    release = threading.Event()
    batches = []

    def compute(items, k):
        if items == ["plug"]:
            release.wait(timeout=10.0)
        batches.append(list(items))
        return [{"i": i} for i in items]

    weights = {"heavy": 1.0, "light": 1.0}
    b = MicroBatcher(
        compute, max_batch=8, max_delay_s=0.01, max_queue=64,
        cache_size=0, tenant_weights=lambda t: weights.get(t, 1.0),
    ).start()
    try:
        plug = b.submit_async("plug", 1)
        time.sleep(0.1)  # the worker is now parked inside compute
        # a burst from "heavy" arrives FIRST, then a few from "light"
        heavy = [b.submit_async(f"h{i}", 1, tenant="heavy")
                 for i in range(16)]
        light = [b.submit_async(f"l{i}", 1, tenant="light")
                 for i in range(4)]
        release.set()
        for t in heavy + light:
            t.get()
        plug.get()
        # the first contended batch (8 slots, 16 heavy + 4 light
        # waiting) must interleave round-robin, not serve the heavy
        # burst's arrival order
        first = batches[1]
        n_light = sum(1 for x in first if x.startswith("l"))
        assert n_light == 4, batches
    finally:
        b.stop()


# -- in-flight tracking + client integration ---------------------------------


def test_inflight_tracker_counts():
    t = InFlightTracker()
    assert t.total() == 0
    t.enter("u1")
    t.enter("u1")
    t.enter("u2")
    assert t.count("u1") == 2 and t.count("u2") == 1 and t.total() == 3
    t.exit("u1")
    t.exit("u2")
    assert t.count("u1") == 1 and t.count("u2") == 0 and t.total() == 1


def test_client_tracks_inflight_and_passes_headers():
    tracker = InFlightTracker()
    seen = {}

    def transport(base, method, path, body, ct, rt, headers=None):
        seen["headers"] = dict(headers or {})
        seen["inflight_during"] = tracker.count(base)
        return 200, b'{"ok": true}'

    c = ResilientClient(
        ["http://replica-a"], RetryPolicy(max_attempts=1),
        transport=transport, inflight=tracker,
    )
    r = c.request("/v1/similar", {"genes": ["G0"]},
                  headers={"X-Tenant": "alice"})
    assert r.ok
    # the attempt was tracked exactly while on the wire, and released
    assert seen["inflight_during"] == 1
    assert tracker.total() == 0
    assert seen["headers"].get("X-Tenant") == "alice"


def test_client_releases_inflight_on_transport_error():
    tracker = InFlightTracker()

    def transport(base, method, path, body, ct, rt, headers=None):
        raise ConnectionRefusedError("nope")

    c = ResilientClient(
        ["http://replica-a"], RetryPolicy(max_attempts=2),
        transport=transport, inflight=tracker,
        sleep=lambda s: None,
    )
    r = c.request("/v1/genes")
    assert not r.ok
    assert tracker.total() == 0


# -- the elastic controller over fakes ---------------------------------------


class FakeSupervisor:
    def __init__(self, count=2):
        self.count = count
        self.config = FleetConfig(contract_timeout_s=5.0)
        self.calls = []
        self.victim = type(
            "R", (), {"url": "http://victim", "state": ReplicaState.UP,
                      "alive": True, "spawning": False, "index": 1},
        )()

    def active_count(self):
        return self.count

    def scale_up(self):
        self.calls.append("scale_up")
        self.count += 1
        r = type(
            "R", (), {"url": "http://new", "state": ReplicaState.UP,
                      "alive": True, "spawning": False, "index": 99},
        )()
        return r

    def pick_drain_victim(self):
        return self.victim

    def begin_drain(self, r):
        self.calls.append(("begin_drain", r.url))
        r.state = ReplicaState.DRAINING

    def finish_drain(self, r):
        self.calls.append(("finish_drain", r.url))
        self.count -= 1


class FakeProxy:
    def __init__(self):
        self.inflight = InFlightTracker()


def _wait_for(predicate, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def test_controller_scales_up_on_breach_and_counts_decision():
    sup, proxy = FakeSupervisor(count=1), FakeProxy()
    metrics = MetricsRegistry()
    ctl = ElasticController(
        sup, proxy,
        AutoscaleConfig(min_replicas=1, max_replicas=2,
                        up_after_ticks=2, cooldown_s=0.0),
        metrics=metrics,
    )
    ctl.observe(snap())                      # seed baselines
    ctl.observe(snap(queue=50))              # breach 1/2
    assert sup.calls == []
    ctl.observe(snap(queue=50))              # breach 2/2 -> act
    _wait_for(lambda: "scale_up" in sup.calls, what="scale_up call")
    _wait_for(lambda: not ctl._busy, what="action slot released")
    assert metrics.counter("fleet_scale_up_total").value == 1


def test_controller_drain_waits_for_inflight_then_terminates():
    sup, proxy = FakeSupervisor(count=2), FakeProxy()
    ctl = ElasticController(
        sup, proxy,
        AutoscaleConfig(min_replicas=1, max_replicas=2,
                        down_after_ticks=2, cooldown_s=0.0),
        metrics=MetricsRegistry(),
        drain_timeout_s=10.0, drain_poll_s=0.01,
    )
    # a request is in flight against the victim when the drain begins
    proxy.inflight.enter("http://victim")
    ctl.observe(snap())
    ctl.observe(snap())                       # clear 1/2
    ctl.observe(snap())                       # clear 2/2 -> down
    _wait_for(
        lambda: ("begin_drain", "http://victim") in sup.calls,
        what="begin_drain",
    )
    # the victim must NOT be terminated while its request is on board
    time.sleep(0.2)
    assert ("finish_drain", "http://victim") not in sup.calls
    proxy.inflight.exit("http://victim")      # the request completes
    _wait_for(
        lambda: ("finish_drain", "http://victim") in sup.calls,
        what="finish_drain after in-flight settles",
    )


def test_controller_drain_timeout_is_counted_not_wedged():
    sup, proxy = FakeSupervisor(count=2), FakeProxy()
    metrics = MetricsRegistry()
    ctl = ElasticController(
        sup, proxy,
        AutoscaleConfig(min_replicas=1, max_replicas=2,
                        down_after_ticks=1, cooldown_s=0.0),
        metrics=metrics, drain_timeout_s=0.2, drain_poll_s=0.01,
    )
    proxy.inflight.enter("http://victim")     # never settles
    ctl.observe(snap())
    ctl.observe(snap())                       # clear 1/1 -> down
    _wait_for(
        lambda: ("finish_drain", "http://victim") in sup.calls,
        what="finish_drain after timeout",
    )
    assert metrics.counter("fleet_drain_timeouts_total").value == 1


def test_controller_skips_ticks_while_an_action_is_in_flight():
    sup, proxy = FakeSupervisor(count=2), FakeProxy()
    ctl = ElasticController(
        sup, proxy,
        AutoscaleConfig(min_replicas=1, max_replicas=4,
                        down_after_ticks=1, cooldown_s=0.0),
        metrics=MetricsRegistry(),
        drain_timeout_s=5.0, drain_poll_s=0.01,
    )
    proxy.inflight.enter("http://victim")     # parks the drain
    ctl.observe(snap())
    ctl.observe(snap())                       # -> down starts
    _wait_for(lambda: ctl._busy, what="action in flight")
    # further clear ticks while busy must not queue a second action
    for _ in range(5):
        ctl.observe(snap())
    proxy.inflight.exit("http://victim")
    _wait_for(lambda: not ctl._busy, what="drain finished")
    assert sup.calls.count(("finish_drain", "http://victim")) == 1


# -- elastic supervisor over a jax-free stub replica --------------------------


STUB = r"""
import json, os, sys
from http.server import BaseHTTPRequestHandler, HTTPServer

class H(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass
    def do_GET(self):
        payload = json.dumps({"status": "ok"}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

srv = HTTPServer(("127.0.0.1", 0), H)
print(json.dumps({"url": f"http://127.0.0.1:{srv.server_address[1]}"}),
      flush=True)
srv.serve_forever()
"""


class StubSupervisor(FleetSupervisor):
    """FleetSupervisor over the always-ready stub above: elasticity
    semantics without paying a jax import per spawn."""

    def __init__(self, tmp, **kw):
        self._stub = os.path.join(tmp, "stub_replica.py")
        with open(self._stub, "w") as f:
            f.write(STUB)
        super().__init__(tmp, **kw)

    def _argv(self, index):
        return [sys.executable, self._stub]


FAST = dict(
    health_interval_s=0.05, health_timeout_s=1.0, unhealthy_after=2,
    readmit_after=1, backoff_base_s=0.05, backoff_max_s=0.2,
    contract_timeout_s=20.0,
)


def test_supervisor_scale_up_adds_replica_to_rotation(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=1, **FAST),
    )
    sup.start()
    try:
        assert sup.active_count() == 1
        r = sup.scale_up()
        assert r.index == 1  # fresh index, never reused
        assert sup.active_count() == 2
        _wait_for(
            lambda: r.state == ReplicaState.UP,
            what="scaled-up replica admitted",
        )
        assert len(sup.healthy_urls()) == 2
    finally:
        sup.stop()


def test_supervisor_drain_leaves_rotation_then_terminates(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=2, **FAST),
    )
    sup.start()
    try:
        victim = sup.pick_drain_victim()
        assert victim is not None and victim.index == 1  # newest UP
        pid = victim.pid
        sup.begin_drain(victim)
        # out of rotation IMMEDIATELY, but still alive (in-flight
        # requests are still being answered) and still scraped
        assert len(sup.healthy_urls()) == 1
        assert victim.alive
        assert victim.url in sup.live_urls()
        # the monitor must not eject/restart a draining replica
        time.sleep(0.3)
        assert victim.state == ReplicaState.DRAINING
        sup.finish_drain(victim)
        assert sup.active_count() == 1
        assert len(sup.replicas) == 1
        _wait_for(
            lambda: not _pid_alive(pid), what="victim terminated",
        )
    finally:
        sup.stop()


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def test_supervisor_draining_replica_death_is_not_restarted(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=2, **FAST),
    )
    sup.start()
    try:
        victim = sup.pick_drain_victim()
        sup.begin_drain(victim)
        restarts_before = victim.restarts
        os.kill(victim.pid, signal.SIGKILL)
        time.sleep(0.5)  # several monitor ticks
        assert victim.restarts == restarts_before
        assert victim.state == ReplicaState.DRAINING
        sup.finish_drain(victim)
    finally:
        sup.stop()


def test_pick_drain_victim_never_picks_the_last_up_replica(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=1, **FAST),
    )
    sup.start()
    try:
        assert sup.pick_drain_victim() is None
    finally:
        sup.stop()


def test_pick_drain_victim_skips_replicas_mid_spawn(tmp_path):
    """A slot whose respawn is in flight must not be drained: the
    drain's terminate would race the spawn and orphan the fresh
    child."""
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=3, **FAST),
    )
    sup.start()
    try:
        newest = max(sup.replicas, key=lambda r: r.index)
        newest.spawning = True
        victim = sup.pick_drain_victim()
        assert victim is not None and victim is not newest
        assert victim.index == 1  # newest DRAINABLE, not newest overall
        # ...and when excluding it would leave one serving replica,
        # refuse outright
        mid = victim
        mid.spawning = True
        assert sup.pick_drain_victim() is None
        mid.spawning = False
        newest.spawning = False
    finally:
        sup.stop()


# -- serve app: tenant quota end to end over HTTP ----------------------------


V, D = 32, 8


def _write_iteration(export_dir, iteration, seed):
    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(seed)
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(V, D).astype(np.float32)),
        ctx=jnp.asarray(np.zeros((V, D), np.float32)),
    )
    save_iteration(str(export_dir), D, iteration, params, vocab)


@pytest.fixture
def tenant_serving(tmp_path):
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig, make_server

    d = tmp_path / "exports"
    _write_iteration(d, 1, seed=1)
    reg = ModelRegistry(str(d))
    assert reg.refresh()
    app = ServeApp(
        reg,
        ServeConfig(
            max_batch=8, max_delay_ms=2.0, max_queue=64, cache_size=0,
            # near-zero refill: the bucket is effectively its burst of
            # 3 for the duration of the test
            tenant_rate=0.1, tenant_burst=3.0,
            tenant_overrides=("vip:1000:1000",),
        ),
    ).start()
    server = make_server(app, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield url, app
    server.shutdown()
    server.server_close()
    app.stop()


def _post_tenant(url, tenant, timeout=10.0):
    import urllib.error
    import urllib.request

    headers = {"Content-Type": "application/json"}
    if tenant is not None:
        headers["X-Tenant"] = tenant
    req = urllib.request.Request(
        f"{url}/v1/similar",
        data=json.dumps({"genes": ["G0"], "k": 4}).encode("utf-8"),
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_tenant_quota_enforced_with_labeled_429(tenant_serving):
    url, app = tenant_serving
    # burst 3 for the default-quota tenant "abuser" (refill is near
    # zero, so the 4th immediate request must shed)
    statuses = [_post_tenant(url, "abuser")[0] for _ in range(4)]
    assert statuses[:3] == [200, 200, 200]
    assert statuses[3] == 429
    status, doc = _post_tenant(url, "abuser")
    assert status == 429 and "quota" in doc["error"]
    # vip's bucket is untouched by the abuser's exhaustion
    assert _post_tenant(url, "vip")[0] == 200
    # untagged traffic is the default tenant, with its own bucket
    assert _post_tenant(url, None)[0] == 200
    text = app.metrics.prometheus_text()
    assert 'serve_rejected_total{tenant="abuser"}' in text
    assert 'serve_tenant_requests_total{tenant="vip"}' in text


def test_tenancy_off_by_default_ignores_header(tenant_serving, tmp_path):
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig

    d = tmp_path / "exports2"
    _write_iteration(d, 1, seed=1)
    reg = ModelRegistry(str(d))
    assert reg.refresh()
    app = ServeApp(reg, ServeConfig())
    assert app.tenants is None  # no bucket, no per-request cost
    app.stop()


# -- the analysis gate -------------------------------------------------------


def _autoscale_doc(**over):
    section = {
        "min_replicas": 1, "max_replicas": 2, "scrape_interval_s": 0.25,
        "scale_up_detection_ticks": 8, "dropped_answers": 0,
        "wrong_answers": 0, "mixed_iteration_answers": 0,
        "steady_state_scale_actions": 0,
        "victim_tenant_availability": 1.0,
    }
    section.update(over)
    return {"schema_version": 1, "autoscale": section}


def test_passes_autoscale_budget_gate(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_autoscale import autoscale_findings

    # missing bench = info (fresh checkout must not fail lint)
    missing = autoscale_findings(root=str(tmp_path / "absent"))
    assert [f.severity for f in missing] == ["info"]

    def run(doc):
        root = tmp_path / "root"
        root.mkdir(exist_ok=True)
        with open(root / "BENCH_AUTOSCALE_r14.json", "w") as f:
            json.dump(doc, f)
        return autoscale_findings(root=str(root))

    fs = run(_autoscale_doc())
    assert gating(fs) == [], [f.format() for f in fs]

    # each planted violation fires EXACTLY once
    for doc in (
        _autoscale_doc(scale_up_detection_ticks=500),   # slow detection
        _autoscale_doc(dropped_answers=1),              # dropped a request
        _autoscale_doc(wrong_answers=1),
        _autoscale_doc(mixed_iteration_answers=2),
        _autoscale_doc(steady_state_scale_actions=3),   # flapping
        _autoscale_doc(victim_tenant_availability=0.5),  # starved tenant
        _autoscale_doc(scale_up_detection_ticks=None),  # dropped key
        _autoscale_doc(max_replicas=8),                 # off-recipe
        {"schema_version": 1},                          # no section
    ):
        fs = run(doc)
        assert len(gating(fs)) == 1, doc

    # the newest round wins: a violating r15 beats a stale clean r14
    root = tmp_path / "root"
    with open(root / "BENCH_AUTOSCALE_r15.json", "w") as f:
        json.dump(_autoscale_doc(dropped_answers=5), f)
    with open(root / "BENCH_AUTOSCALE_r14.json", "w") as f:
        json.dump(_autoscale_doc(), f)
    fs = autoscale_findings(root=str(root))
    assert len(gating(fs)) == 1
    assert gating(fs)[0].path == "BENCH_AUTOSCALE_r15.json"


def test_cli_analyze_gates_on_planted_autoscale_violation(tmp_path):
    """The env-override path: a violating BENCH_AUTOSCALE under
    GENE2VEC_TPU_AUTOSCALE_ROOT makes the real cli.analyze exit 1 with
    exactly one autoscale-elasticity-budget finding."""
    root = tmp_path / "root"
    root.mkdir()
    with open(root / "BENCH_AUTOSCALE_r14.json", "w") as f:
        json.dump(_autoscale_doc(dropped_answers=3), f)
    env = {**os.environ, "GENE2VEC_TPU_AUTOSCALE_ROOT": str(root)}
    r = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 1, r.stderr
    doc = json.loads(r.stdout)
    mine = [f for f in doc["findings"]
            if f["pass"] == "autoscale-elasticity-budget"]
    assert len(mine) == 1
    assert mine[0]["severity"] != "info"
    assert "drain" in mine[0]["message"]


def test_ledger_adapts_autoscale_family(tmp_path):
    from gene2vec_tpu.obs import ledger

    with open(tmp_path / "BENCH_AUTOSCALE_r14.json", "w") as f:
        json.dump({
            "schema_version": 1,
            "command": "chaos_drill --only autoscale",
            "created_unix": 1000.0, "passed": True,
            "autoscale": {
                "scale_up_detection_ticks": 8,
                "victim_tenant_availability": 1.0,
                "dropped_answers": 0,
                "steady_state_scale_actions": 0,
            },
        }, f)
    records = ledger.ingest_root(str(tmp_path))
    assert len(records) == 1
    rec = records[0]
    assert rec["family"] == "autoscale"
    assert rec["headline_metric"] == "scale_up_detection_ticks"
    assert rec["metrics"]["scale_up_detection_ticks"] == 8.0
    assert rec["metrics"]["victim_tenant_availability"] == 1.0
    assert not rec["legacy_unstamped"]
