"""Event-loop serve front end: keep-alive protocol conformance, the
slow-loris read deadline, coalesced-GET correctness, hot-swap atomicity
under keep-alive connections, the pooled client transport, and the
serve capacity budget gate (passes_serve + ledger adapter).

Protocol tests drive raw sockets against a real served app so the
parser, keep-alive bookkeeping, and deadline sweeps are the actual code
under test — no mocked loop."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from gene2vec_tpu.io.checkpoint import save_iteration
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.serve.registry import ModelRegistry
from gene2vec_tpu.serve.server import (
    ServeApp,
    ServeConfig,
    make_server,
)
from gene2vec_tpu.sgns.model import SGNSParams

V, D = 32, 8

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_iteration(export_dir, iteration, seed):
    rng = np.random.RandomState(seed)
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    emb = rng.randn(V, D).astype(np.float32)
    params = SGNSParams(
        emb=jnp.asarray(emb), ctx=jnp.asarray(np.zeros((V, D), np.float32))
    )
    save_iteration(str(export_dir), D, iteration, params, vocab)
    return emb


def _serve(export_dir, config):
    reg = ModelRegistry(str(export_dir))
    assert reg.refresh()
    app = ServeApp(reg, config).start()
    server = make_server(app, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return reg, app, server


@pytest.fixture
def served(tmp_path):
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export,
        ServeConfig(max_batch=8, max_delay_ms=2.0, max_queue=16),
    )
    yield export, reg, app, server
    server.shutdown()
    server.server_close()
    app.stop()


def _connect(server, timeout=5.0):
    sock = socket.create_connection(
        ("127.0.0.1", server.server_address[1]), timeout=timeout
    )
    return sock


def _get_request(path, close=False):
    extra = "Connection: close\r\n" if close else ""
    return (
        f"GET {path} HTTP/1.1\r\nHost: x\r\n{extra}\r\n"
    ).encode("ascii")


def _read_response(sock):
    """(status, headers dict, body bytes) from one raw socket."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed before headers")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for ln in lines[1:]:
        name, _, value = ln.partition(b":")
        headers[name.strip().lower().decode()] = value.strip().decode()
    length = int(headers.get("content-length", "0"))
    while len(rest) < length:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed mid-body")
        rest += chunk
    return status, headers, rest[:length], rest[length:]


def _closed(sock, timeout=3.0):
    sock.settimeout(timeout)
    try:
        return sock.recv(1) == b""
    except socket.timeout:
        return False
    except OSError:
        return True


# -- keep-alive protocol conformance -----------------------------------------


def test_keepalive_sequential_requests_one_socket(served):
    _, _, _, server = served
    sock = _connect(server)
    try:
        for _ in range(3):
            sock.sendall(_get_request("/v1/similar?gene=G0&k=3"))
            status, headers, body, extra = _read_response(sock)
            assert status == 200
            assert headers.get("connection") != "close"
            doc = json.loads(body)
            assert len(doc["results"][0]["neighbors"]) == 3
            assert extra == b""
    finally:
        sock.close()


def test_pipelined_requests_one_socket(served):
    """Two requests written back-to-back before reading anything: both
    answers come back, in order."""
    _, _, _, server = served
    sock = _connect(server)
    try:
        sock.sendall(
            _get_request("/v1/genes?limit=2")
            + _get_request("/v1/similar?gene=G1&k=2")
        )
        status1, _, body1, extra = _read_response(sock)
        assert status1 == 200
        assert json.loads(body1)["genes"] == ["G0", "G1"]
        # any bytes already read past response 1 belong to response 2
        sock2 = _Rewound(sock, extra)
        status2, _, body2, _ = _read_response(sock2)
        assert status2 == 200
        assert json.loads(body2)["results"][0]["query"] == "G1"
    finally:
        sock.close()


class _Rewound:
    """Socket wrapper replaying bytes already read past a response."""

    def __init__(self, sock, buffered):
        self._sock = sock
        self._buf = buffered

    def recv(self, n):
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            return out
        return self._sock.recv(n)


def test_malformed_request_line_gets_400_and_close(served):
    _, _, app, server = served
    sock = _connect(server)
    try:
        sock.sendall(b"NONSENSE\r\n\r\n")
        status, headers, _, _ = _read_response(sock)
        assert status == 400
        assert headers.get("connection") == "close"
        assert _closed(sock)
    finally:
        sock.close()


def test_idle_keepalive_connection_reaped(tmp_path):
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export, ServeConfig(idle_timeout_s=0.3)
    )
    try:
        sock = _connect(server)
        sock.sendall(_get_request("/livez"))
        assert _read_response(sock)[0] == 200
        t0 = time.monotonic()
        assert _closed(sock, timeout=3.0)  # idle: silently closed
        assert time.monotonic() - t0 < 2.0
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


def test_request_cap_closes_connection(tmp_path):
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export, ServeConfig(max_conn_requests=2)
    )
    try:
        sock = _connect(server)
        sock.sendall(_get_request("/livez"))
        status, headers, _, _ = _read_response(sock)
        assert status == 200 and headers.get("connection") != "close"
        sock.sendall(_get_request("/livez"))
        status, headers, _, _ = _read_response(sock)
        assert status == 200 and headers.get("connection") == "close"
        assert _closed(sock)
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


def test_slow_loris_headers_stall_gets_408(tmp_path):
    """A request whose HEADERS never finish trips the read deadline
    too (the body-stall variant lives in test_fleet.py)."""
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export, ServeConfig(read_timeout_s=0.4)
    )
    try:
        sock = _connect(server)
        t0 = time.monotonic()
        sock.sendall(b"GET /livez HTTP/1.1\r\nHost: x\r\n")  # no blank line
        status, headers, _, _ = _read_response(sock)
        assert status == 408
        assert time.monotonic() - t0 < 2.0
        assert headers.get("connection") == "close"
        assert app.metrics.counter("serve_http_408_total").value >= 1
        assert _closed(sock)
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


def test_oversized_body_gets_413_and_close(served):
    _, _, _, server = served
    sock = _connect(server)
    try:
        sock.sendall(
            b"POST /v1/similar HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 9000000\r\n\r\n"
        )
        status, headers, body, _ = _read_response(sock)
        assert status == 413
        assert b"too large" in body
        assert headers.get("connection") == "close"
        assert _closed(sock)
    finally:
        sock.close()


def test_inflight_backpressure_bounds_read_buffer(tmp_path):
    """A client streaming garbage behind a slow in-flight request must
    not grow the server's read buffer unboundedly: the loop pauses
    reading at the pipeline cap, other connections stay responsive,
    and the garbage is rejected once the response lands."""
    from gene2vec_tpu.serve import eventloop

    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export, ServeConfig(cache_size=0, max_delay_ms=1.0)
    )
    real_compute = app._compute_batch

    def slow_compute(items, k_max):
        time.sleep(0.8)
        return real_compute(items, k_max)

    app.batcher.compute = slow_compute
    loop = server._loops[0]
    try:
        sock = _connect(server)
        sock.sendall(_get_request("/v1/similar?gene=G0&k=2"))
        time.sleep(0.1)  # request dispatched; compute sleeping
        # stream garbage well past the pipeline cap
        junk = b"x" * 65536
        sock.settimeout(0.2)
        sent = 0
        try:
            while sent < 4 * eventloop._PIPELINE_BUF_CAP:
                sent += sock.send(junk)
        except socket.timeout:
            pass  # kernel window closed: the loop stopped reading
        # the loop buffered at most ~cap + one recv worth of bytes
        bufs = [len(c.rbuf) for c in loop.conns.values()]
        assert max(bufs, default=0) <= (
            eventloop._PIPELINE_BUF_CAP + 262144
        )
        # other connections stay responsive while that one is paused
        other = _connect(server)
        other.sendall(_get_request("/livez"))
        assert _read_response(other)[0] == 200
        other.close()
        # once the slow response lands, the buffered junk parses as a
        # malformed request -> 400 + close
        sock.settimeout(5.0)
        status, _, _, extra = _read_response(sock)
        assert status == 200
        if b"400" not in extra:
            status2, _, _, _ = _read_response(_Rewound(sock, extra))
            assert status2 == 400
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


# -- coalescing + response cache ---------------------------------------------


def test_concurrent_identical_gets_coalesce_to_one_compute(tmp_path):
    """N concurrent identical GETs -> ONE batcher compute, N correct
    responses.  Caches are disabled so coalescing (not caching) is
    what's under test."""
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export,
        ServeConfig(cache_size=0, max_delay_ms=50.0, max_batch=8),
    )
    compute_calls = []
    real_compute = app._compute_batch

    def counting_compute(items, k_max):
        compute_calls.append(len(items))
        time.sleep(0.15)  # hold the window open for late joiners
        return real_compute(items, k_max)

    app.batcher.compute = counting_compute
    try:
        n = 8
        results = [None] * n

        def fire(i):
            sock = _connect(server)
            try:
                sock.sendall(_get_request("/v1/similar?gene=G5&k=4"))
                status, _, body, _ = _read_response(sock)
                results[i] = (status, json.loads(body))
            finally:
                sock.close()

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert all(r is not None for r in results)
        assert all(status == 200 for status, _ in results)
        # one engine slot for the hot gene, no matter the fan-in
        assert compute_calls == [1], compute_calls
        assert (
            app.metrics.counter("serve_coalesced_total").value == n - 1
        )
        # every response is the same correct answer
        m = reg.model
        scores = np.asarray(m.unit) @ np.asarray(m.unit)[5]
        oracle = [m.tokens[i] for i in np.argsort(-scores) if i != 5][:4]
        for _, doc in results:
            got = [
                nb["gene"] for nb in doc["results"][0]["neighbors"]
            ]
            assert got == oracle
            assert doc["model"]["iteration"] == 1
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


def test_response_cache_serves_reused_bytes(served):
    _, reg, app, server = served
    sock = _connect(server)
    try:
        sock.sendall(_get_request("/v1/similar?gene=G2&k=3"))
        status, _, body1, _ = _read_response(sock)
        assert status == 200
        hits0 = app.metrics.counter(
            "serve_response_cache_hits_total"
        ).value
        sock.sendall(_get_request("/v1/similar?gene=G2&k=3"))
        status, _, body2, _ = _read_response(sock)
        assert status == 200
        assert body2 == body1
        assert app.metrics.counter(
            "serve_response_cache_hits_total"
        ).value == hits0 + 1
        # the cached bytes ARE the stored object (zero-copy, not a
        # re-encode)
        m = reg.model
        assert app.response_cache.get((m.version, "G2", 3)) == body1
    finally:
        sock.close()


# -- hot swap under keep-alive -----------------------------------------------


def test_hot_swap_atomicity_over_keepalive_connection(served):
    """One keep-alive connection spanning a hot swap: every response is
    internally consistent (its iteration's table produced its
    neighbors), and the connection survives the swap."""
    export, reg, app, server = served
    embs = {1: _write_iteration(export, 1, seed=1)}

    def oracle(iteration, gene_row, k):
        emb = embs[iteration]
        unit = emb / np.linalg.norm(emb, axis=1, keepdims=True)
        scores = unit @ unit[gene_row]
        return [
            f"G{i}" for i in np.argsort(-scores) if i != gene_row
        ][:k]

    sock = _connect(server)
    try:
        seen_iterations = set()
        for it in (2, 3, 4):
            embs[it] = _write_iteration(export, it, seed=it * 11)
            assert reg.refresh()
            for _ in range(3):
                sock.sendall(_get_request("/v1/similar?gene=G7&k=5"))
                status, _, body, _ = _read_response(sock)
                assert status == 200
                doc = json.loads(body)
                got_iter = doc["model"]["iteration"]
                seen_iterations.add(got_iter)
                got = [
                    nb["gene"]
                    for nb in doc["results"][0]["neighbors"]
                ]
                # the answer must cohere with ITS OWN iteration — a
                # response mixing a new iteration stamp with old-table
                # neighbors (or vice versa) fails here
                assert got == oracle(got_iter, 7, 5), (
                    f"iteration {got_iter} answer does not match its "
                    "own table"
                )
        assert max(seen_iterations) == 4  # the swaps actually served
    finally:
        sock.close()


# -- pooled client transport --------------------------------------------------


def test_pooled_transport_reuses_and_recovers_stale(tmp_path):
    from gene2vec_tpu.serve.client import PooledTransport

    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(
        export, ServeConfig(idle_timeout_s=0.3)
    )
    url = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        transport = PooledTransport()
        status, _ = transport(url, "GET", "/livez", None, 2.0, 5.0)
        assert status == 200
        status, _ = transport(url, "GET", "/livez", None, 2.0, 5.0)
        assert status == 200
        assert transport.connections_opened == 1  # reused, not re-dialed
        # let the server's idle timeout reap the pooled socket, then
        # the next request must transparently re-dial
        time.sleep(0.8)
        status, _ = transport(url, "GET", "/livez", None, 2.0, 5.0)
        assert status == 200
        assert transport.connections_opened == 2
        transport.close()
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


def test_resilient_client_pools_connections_per_replica(tmp_path):
    from gene2vec_tpu.serve.client import (
        PooledTransport,
        ResilientClient,
        RetryPolicy,
    )

    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg, app, server = _serve(export, ServeConfig())
    url = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        client = ResilientClient([url], RetryPolicy(max_attempts=2))
        assert isinstance(client._transport, PooledTransport)
        for _ in range(5):
            r = client.request("/v1/similar?gene=G0&k=2", timeout_s=5.0)
            assert r.ok, r.error_class
            # zero-copy surface: raw bytes present, doc parses lazily
            assert r.raw is not None
            assert r.doc["results"][0]["query"] == "G0"
        assert client._transport.connections_opened == 1
    finally:
        server.shutdown()
        server.server_close()
        app.stop()


# -- ledger adapter: capacity fields -----------------------------------------


def test_ledger_ingests_capacity_fields(tmp_path):
    from gene2vec_tpu.obs import ledger

    new_doc = {
        "schema_version": 2,
        "bench": "serve_loadgen",
        "levels": [
            {"offered_rps": 200.0, "p50_ms": 1.0, "p99_ms": 4.0,
             "rejection_rate": 0.0, "errors": 0},
        ],
        "capacity": {"sustained_rps": 800.0, "p99_ms": 9.0},
        "fleet_capacity": {"sustained_rps": 1200.0, "p99_ms": 12.0},
    }
    legacy_doc = {
        "bench": "serve_loadgen",
        "levels": [
            {"offered_rps": 50.0, "p50_ms": 24.0, "p99_ms": 236.0,
             "rejection_rate": 0.0, "errors": 0},
        ],
    }
    (tmp_path / "BENCH_SERVE_r06.json").write_text(
        json.dumps(legacy_doc)
    )
    (tmp_path / "BENCH_SERVE_r11.json").write_text(json.dumps(new_doc))
    records = ledger.ingest_root(str(tmp_path))
    by_src = {r["source"]: r for r in records}
    assert not by_src["BENCH_SERVE_r06.json"].get("error")
    # pre-capacity legacy: visibly marked, never an ingest error
    assert (
        by_src["BENCH_SERVE_r06.json"]["metrics"][
            "serve_pre_capacity_legacy"
        ] == 1.0
    )
    m = by_src["BENCH_SERVE_r11.json"]["metrics"]
    assert m["serve_capacity_rps"] == 800.0
    assert m["serve_fleet_capacity_rps"] == 1200.0
    assert "serve_pre_capacity_legacy" not in m


# -- the capacity budget gate (passes_serve) ---------------------------------


def _capacity_doc(sustained=900.0, fleet=1200.0, wrong=0, mixed=0,
                  **overrides):
    doc = {
        "schema_version": 2,
        "bench": "serve_loadgen",
        "mode": "open",
        "method": "get",
        "k": 10,
        "duration_s": 5.0,
        "num_query_genes": 256,
        "levels": [
            {"offered_rps": 200.0, "p50_ms": 1.0, "p99_ms": 4.0},
        ],
        "capacity": {
            "sustained_rps": sustained, "p99_ms": 9.0,
            "availability": 1.0, "p99_budget_ms": 50.0,
            "min_availability": 0.99,
        },
        "fleet_capacity": {
            "sustained_rps": fleet, "p99_ms": 12.0,
            "availability": 1.0, "p99_budget_ms": 50.0,
            "min_availability": 0.99,
        },
        "fleet_levels": [
            {"offered_rps": fleet, "wrong_answers": wrong,
             "mixed_iteration_answers": mixed},
        ],
    }
    doc.update(overrides)
    return doc


def test_capacity_gate_passes_on_committed_bench():
    """The committed BENCH_SERVE_r11.json satisfies the budget (the
    analyzer's default tier depends on it)."""
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_serve import (
        serve_capacity_findings,
    )

    bad = gating(serve_capacity_findings(root=REPO))
    assert bad == [], "\n".join(f.format() for f in bad)


def test_capacity_gate_planted_violation_fires_exactly_once(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_serve import (
        serve_capacity_findings,
    )

    (tmp_path / "BENCH_SERVE_r99.json").write_text(
        json.dumps(_capacity_doc(sustained=120.0))
    )
    findings = serve_capacity_findings(root=str(tmp_path))
    bad = gating(findings)
    assert len(bad) == 1, [f.format() for f in findings]
    assert "sustained_rps 120" in bad[0].message
    assert bad[0].pass_id == "serve-capacity-budget"


def test_capacity_gate_off_recipe_and_integrity_violations(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_serve import (
        serve_capacity_findings,
    )

    # off-recipe: measured with POST instead of the pinned GET
    (tmp_path / "BENCH_SERVE_r99.json").write_text(
        json.dumps(_capacity_doc(method="post"))
    )
    (bad,) = gating(serve_capacity_findings(root=str(tmp_path)))
    assert "pins method='get'" in bad.message

    # a wrong answer in the fleet phase gates even at full capacity
    (tmp_path / "BENCH_SERVE_r99.json").write_text(
        json.dumps(_capacity_doc(wrong=1))
    )
    (bad,) = gating(serve_capacity_findings(root=str(tmp_path)))
    assert "answer integrity" in bad.message

    # a shortened window gates (a lucky 1s window must not pass)
    (tmp_path / "BENCH_SERVE_r99.json").write_text(
        json.dumps(_capacity_doc(duration_s=1.0))
    )
    (bad,) = gating(serve_capacity_findings(root=str(tmp_path)))
    assert "pins >= 5" in bad.message


def test_capacity_gate_missing_bench_is_info(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_serve import (
        serve_capacity_findings,
    )

    findings = serve_capacity_findings(root=str(tmp_path))
    assert gating(findings) == []
    assert findings[0].severity == "info"
    assert "no serve bench recorded yet" in findings[0].message


# -- concurrency regression: shutdown vs serve_forever spawn race ------------


def test_shutdown_joins_threads_spawned_concurrently(monkeypatch):
    """shutdown() racing serve_forever's spawn loop must join EVERY
    spawned thread (graftcheck lock-discipline: EventLoopHTTPServer
    _threads).  Barrier-injected FakeThreads hold the spawn window open
    while a concurrent shutdown runs; the _threads_lock forces the
    shutdown to wait for the full spawn, so no thread leaks unjoined.
    Event choreography only — no sleeps."""
    from gene2vec_tpu.serve.eventloop import EventLoopHTTPServer

    real_thread = threading.Thread

    class _FakeLoop:
        def __init__(self):
            self.stop_evt = threading.Event()

        def run(self):
            assert self.stop_evt.wait(5.0)

        def stop(self):
            self.stop_evt.set()

    spawn2_entered = threading.Event()
    release_spawn2 = threading.Event()
    started = []

    class _FakeThread:
        def __init__(self, target=None, name=None, daemon=None):
            self.joined = False
            started.append(self)
            self._nth = len(started)

        def start(self):
            if self._nth == 2:
                # hold the race window open: the second spawn is
                # mid-start while shutdown runs on another thread
                spawn2_entered.set()
                assert release_spawn2.wait(5.0)

        def join(self, timeout=None):
            self.joined = True

    class _SignalLock:
        """A Lock that reports acquisition attempts, so the test can
        observe shutdown arriving at the spawn lock deterministically."""

        def __init__(self):
            self._lk = threading.Lock()
            self.acquiring = threading.Event()

        def __enter__(self):
            self.acquiring.set()
            self._lk.acquire()
            return self

        def __exit__(self, *exc):
            self._lk.release()

    server = EventLoopHTTPServer(lambda req, peer: None, "127.0.0.1", 0)
    orig_sock = server._loops[0].lsock
    try:
        server._loops = [_FakeLoop(), _FakeLoop(), _FakeLoop()]
        lock = _SignalLock()
        server._threads_lock = lock
        monkeypatch.setattr(threading, "Thread", _FakeThread)

        t = real_thread(target=server.serve_forever, daemon=True)
        t.start()
        assert spawn2_entered.wait(5.0)  # spawn #2 holds the window open

        lock.acquiring.clear()
        s = real_thread(target=server.shutdown, daemon=True)
        s.start()
        # shutdown reached the spawn lock — it CANNOT have read the
        # (still partial) thread list, because the read is under it
        assert lock.acquiring.wait(5.0)

        release_spawn2.set()
        t.join(5.0)
        s.join(5.0)
        assert not t.is_alive() and not s.is_alive()
        assert len(started) == 2
        assert all(ft.joined for ft in started)
        assert server._threads == []
    finally:
        orig_sock.close()


def test_flight_burst_dump_deferred_off_loop_thread(tmp_path):
    """A 5xx-burst flight dump triggered on the fast path must not do
    file I/O inline (graftcheck loop-thread-blocking: _account runs on
    the event-loop thread) — it is handed to the worker pool."""
    from gene2vec_tpu.obs.flight import FLIGHT_PREFIX, FlightRecorder
    from gene2vec_tpu.obs.registry import MetricsRegistry
    from gene2vec_tpu.serve.server import ServeApp, ServeAdapter

    class _App:
        # the real route-label builder (canonical route + optional
        # bounded model label) — _account feeds it every status line
        model_name = "default"
        _mlabels = None
        _route_labels = ServeApp._route_labels

    class _Pool:
        def __init__(self):
            self.fns = []

        def submit(self, fn):
            self.fns.append(fn)
            return True

    app = _App()
    app.metrics = MetricsRegistry()
    # threshold 1: the first 5xx is a burst (fake clock, no sleeps)
    app.flight = FlightRecorder(
        capacity=8, burst_threshold=1, burst_window_s=5.0,
        clock=lambda: 100.0,
    )
    app.flight_dir = str(tmp_path)
    adapter = ServeAdapter.__new__(ServeAdapter)
    adapter.app = app
    adapter.pool = _Pool()

    adapter._account("/v1/similar", 500, 0.01)

    dumps_on_disk = [
        p for p in os.listdir(tmp_path) if p.startswith(FLIGHT_PREFIX)
    ]
    assert dumps_on_disk == []  # nothing written on the calling thread
    assert len(adapter.pool.fns) == 1  # exactly one deferred dump

    adapter.pool.fns[0]()  # the pool worker writes it
    dumps_on_disk = [
        p for p in os.listdir(tmp_path) if p.startswith(FLIGHT_PREFIX)
    ]
    assert len(dumps_on_disk) == 1
    doc = json.loads((tmp_path / dumps_on_disk[0]).read_text())
    assert doc["reason"] == "5xx-burst"
    assert doc["records"][0]["status"] == 500
