"""Stochastic-rounded bf16 table write-back (round 5).

The round-4 blocker for bf16-by-default was update absorption: an SGD
update smaller than half the weight's bf16 ulp rounds away every step
under round-to-nearest, so small-scale runs never learn.  Stochastic
rounding (``sgns/step.py:_stochastic_round_bf16``) makes the EXPECTED
write-back equal the f32 update; these tests pin the primitive's
contract and that the previously-failing smoke regime now learns.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.step import _stochastic_round_bf16
from gene2vec_tpu.sgns.train import train_epochs


def test_exact_bf16_values_pass_through():
    """Values already representable in bf16 (incl. 0, negatives, denormal
    magnitudes) must survive bit-identically — rows a step never touched
    are never perturbed."""
    vals = jnp.asarray(
        [0.0, -0.0, 1.0, -1.0, 0.5, -3.25, 65280.0, 1e-30, -1e-30],
        jnp.bfloat16,
    ).astype(jnp.float32)
    for seed in range(5):
        out = _stochastic_round_bf16(vals, jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(
            np.asarray(out, np.float32), np.asarray(vals, np.float32)
        )


def test_rounds_to_adjacent_bf16_values_only():
    """SR must land on one of the two bf16 neighbours of x, never further."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4096).astype(np.float32)) * 3.7
    lo = np.asarray(x.astype(jnp.bfloat16), np.float32)  # one neighbour
    out = np.asarray(
        _stochastic_round_bf16(x, jax.random.PRNGKey(1)), np.float32
    )
    xf = np.asarray(x)
    ulp = np.spacing(np.abs(lo).astype(np.float32)) * 2 ** (24 - 8)
    assert np.all(np.abs(out - xf) <= ulp + 1e-30)


def test_unbiased_in_expectation():
    """Mean over many keys converges to x (round-to-nearest would sit a
    half-ulp away for adversarial inputs)."""
    # x exactly halfway between bf16 neighbours: 1 + 2^-9
    x = jnp.full((2048,), np.float32(1.0 + 2.0**-9))
    acc = np.zeros(2048, np.float64)
    n = 200
    for seed in range(n):
        acc += np.asarray(
            _stochastic_round_bf16(x, jax.random.PRNGKey(seed)), np.float64
        )
    mean = acc / n
    # neighbours are 1.0 and 1.0078125; nearest-even would always pick one
    assert abs(mean.mean() - (1.0 + 2.0**-9)) < 3e-4
    assert mean.std() > 0  # it actually randomizes


def test_sub_ulp_updates_survive_in_expectation():
    """The absorption failure: w=1.0, update=-1e-5 (way below the 2^-9
    half-ulp).  Nearest rounding keeps w frozen forever; SR must advance
    w by ~n*update over n steps."""
    w = jnp.full((4096,), np.float32(1.0))
    upd = np.float32(1e-5)
    key = jax.random.PRNGKey(0)
    steps = 300
    for i in range(steps):
        key, sub = jax.random.split(key)
        w = _stochastic_round_bf16(
            w.astype(jnp.float32) - upd, sub
        ).astype(jnp.float32)
    drift = float(1.0 - np.asarray(w, np.float64).mean())
    expect = steps * float(upd)
    assert 0.5 * expect < drift < 1.5 * expect
    # nearest-rounding control: frozen at exactly 1.0
    w2 = jnp.full((16,), np.float32(1.0))
    for _ in range(50):
        w2 = (w2.astype(jnp.float32) - upd).astype(jnp.bfloat16).astype(
            jnp.float32
        )
    assert float(np.abs(np.asarray(w2) - 1.0).max()) == 0.0


def _planted_corpus(v=64, n=8192, seed=0):
    rng = np.random.RandomState(seed)
    half = v // 2
    pairs = np.concatenate(
        [
            rng.randint(0, half, size=(n // 2, 2)),
            rng.randint(half, v, size=(n // 2, 2)),
        ]
    ).astype(np.int32)
    rng.shuffle(pairs)
    counts = np.bincount(pairs.reshape(-1), minlength=v).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(v)], counts), pairs)


@pytest.mark.parametrize("negative_mode", ["stratified", "shared"])
def test_bf16_tables_learn_planted_clusters(negative_mode):
    """The round-4 documented failure regime (small scale + bf16 tables)
    must now learn with stochastic rounding on."""
    corpus = _planted_corpus()
    cfg = SGNSConfig(
        dim=16, batch_pairs=512, lr=0.05, table_dtype="bfloat16",
        negative_mode=negative_mode, positive_head=16, strat_head=8,
        strat_block=16, strat_group=32,
    )
    emb, losses = train_epochs(corpus, cfg, epochs=8)
    assert losses[-1] < losses[0] - 0.5
    emb = emb.astype(np.float32)
    unit = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    half = 32
    intra = np.mean(unit[:half] @ unit[:half].T)
    inter = np.mean(unit[:half] @ unit[half:].T)
    assert intra > inter + 0.3


def test_bf16_sr_flag_controls_dispatch(monkeypatch):
    """bf16_stochastic_round=False restores the round-4 nearest-rounding
    write-back (the documented A/B escape hatch); True routes every
    table write-back through the SR primitive — and f32 tables never
    touch it regardless of the flag."""
    import jax.numpy as jnp

    from gene2vec_tpu.data.negative_sampling import (
        NegativeSampler, build_stratified_spec,
    )
    from gene2vec_tpu.sgns import step as step_mod
    from gene2vec_tpu.sgns.model import init_params
    from gene2vec_tpu.sgns.step import sgns_step

    corpus = _planted_corpus()
    spec = build_stratified_spec(corpus.vocab.counts, 8, 16, 0.75)
    noise = NegativeSampler(corpus.vocab.counts, 0.75).table
    batch = jnp.asarray(corpus.pairs[:256])
    calls = []
    real = step_mod._stochastic_round_bf16
    monkeypatch.setattr(
        step_mod,
        "_stochastic_round_bf16",
        lambda x, k: calls.append(1) or real(x, k),
    )
    kw = dict(
        negatives=5, negative_mode="stratified", strat_group=32,
        stratified=spec,
    )
    for dtype, flag, expected_calls in [
        (jnp.bfloat16, False, 0),
        (jnp.float32, True, 0),
        (jnp.bfloat16, True, 2),  # emb + ctx write-backs
    ]:
        calls.clear()
        params = init_params(jax.random.PRNGKey(0), 64, 16, dtype)
        sgns_step(
            params, batch, noise, jax.random.PRNGKey(1),
            jnp.float32(0.025), bf16_stochastic_round=flag, **kw,
        )
        assert len(calls) == expected_calls, (dtype, flag, calls)
