"""Corpus-builder tests: correlation matmul vs pandas oracle, normalization
recipe, pair emission semantics, end-to-end on a synthetic query dir."""

import numpy as np
import pandas as pd
import pytest

from gene2vec_tpu.corpus import (
    abs_correlation,
    build_pairs,
    clean_and_normalize,
    coexpression_pairs,
    gene_annotated_data,
    half_min,
)


def test_half_min():
    x = np.array([[0.0, 4.0], [2.0, 0.0]])
    assert half_min(x) == 1.0


def test_abs_correlation_matches_pandas():
    rng = np.random.RandomState(0)
    x = rng.randn(30, 12)
    x[:, 3] = 2.0 * x[:, 1] + 0.1 * rng.randn(30)   # correlated pair
    x[:, 5] = 7.0                                    # zero variance
    df = pd.DataFrame(x)
    oracle = df.corr().abs().values
    ours = abs_correlation(x, backend="numpy")
    mask = ~np.isnan(oracle)
    np.testing.assert_allclose(ours[mask], oracle[mask], atol=1e-10)
    # zero-variance col: pandas NaN (never passes threshold) → ours 0
    assert (ours[5] == 0).all()


def test_abs_correlation_jax_backend():
    rng = np.random.RandomState(1)
    x = rng.randn(25, 8)
    np.testing.assert_allclose(
        abs_correlation(x, "jax"), abs_correlation(x, "numpy"), atol=1e-4
    )


def test_abs_correlation_mask_jax_packbits_roundtrip():
    """The device-side threshold + packbits path coexpression_pairs routes
    through must agree bit-for-bit with the host mask — including at a
    non-multiple-of-8 gene count (unpackbits count/reshape) and a planted
    above-threshold pair."""
    from gene2vec_tpu.corpus.builder import abs_correlation_mask

    rng = np.random.RandomState(2)
    x = rng.randn(40, 13)
    x[:, 7] = x[:, 2] + 0.01 * rng.randn(40)        # corr ~ 0.999
    for thr in (0.9, 1.0):
        m_np = abs_correlation_mask(x, thr, backend="numpy")
        m_jax = abs_correlation_mask(x, thr, backend="jax")
        assert m_np.shape == m_jax.shape == (13, 13)
        # at 1.0 both backends must agree (clip parity); at 0.9 the
        # planted pair must be present
        np.testing.assert_array_equal(m_np, m_jax)
    assert abs_correlation_mask(x, 0.9, backend="numpy")[2, 7]


def _toy_query(tmp_path, n_samples=25, seed=0):
    """Synthetic query dir: 2 studies, gene_id 'ENSG|SYM' with one dup
    symbol, one low-count gene, one planted correlated gene pair."""
    rng = np.random.RandomState(seed)
    samples = [f"S{i}" for i in range(2 * n_samples)]
    gene_ids = [
        "ENSG01|GA", "ENSG02|GB", "ENSG03|GC", "ENSG04|GD",
        "ENSG05|DUP", "ENSG06|DUP", "ENSG07|", "ENSG08|GLOW",
    ]
    ens = [g.split("|")[0] for g in gene_ids]
    tpm = rng.rand(len(samples), len(ens)) * 10
    tpm[:, 1] = tpm[:, 0] * 3.0 + 0.01 * rng.rand(len(samples))  # GA~GB corr
    tpm[0, 2] = 0.0  # a zero to exercise half-min replacement
    counts = (tpm * 100).round()
    counts[:, 7] = 0.0  # GLOW: low total counts → dropped

    d = tmp_path / "query" / "data"
    d.mkdir(parents=True)
    pd.DataFrame(
        {"SRA Study": ["ST1"] * n_samples + ["ST2"] * n_samples},
        index=pd.Index(samples, name="Run"),
    ).to_csv(d / "SRARunTable.csv")
    pd.DataFrame(tpm, index=pd.Index(samples, name="run"), columns=ens).to_csv(
        d / "gene_counts_TPM.csv"
    )
    cdf = pd.DataFrame(counts.T, columns=samples)
    cdf.insert(0, "gene_id", gene_ids)
    cdf.to_csv(d / "gene_counts.csv", index=False)
    return str(tmp_path / "query")


def test_clean_and_normalize_drops_low_count_genes(tmp_path):
    q = _toy_query(tmp_path)
    data = pd.read_csv(f"{q}/data/gene_counts_TPM.csv", index_col=0)
    gene_counts = pd.read_csv(f"{q}/data/gene_counts.csv")
    normed = clean_and_normalize(data, gene_counts, data.index[:25].tolist())
    assert "ENSG08" not in normed.columns        # low counts dropped
    assert "ENSG01" in normed.columns
    assert np.isfinite(normed.values).all()      # zeros half-min-replaced pre-log2


def test_gene_annotation_unique_symbols(tmp_path):
    q = _toy_query(tmp_path)
    data = pd.read_csv(f"{q}/data/gene_counts_TPM.csv", index_col=0)
    gene_counts = pd.read_csv(f"{q}/data/gene_counts.csv")
    normed = gene_annotated_data(data, gene_counts)
    assert "DUP" not in normed.columns           # duplicate symbol dropped
    assert "" not in normed.columns              # empty symbol dropped
    assert {"GA", "GB", "GC", "GD"} <= set(normed.columns)


def test_coexpression_emits_both_directions():
    rng = np.random.RandomState(2)
    base = rng.randn(40)
    df = pd.DataFrame(
        {"A": base, "B": base * 2 + 1e-3 * rng.randn(40), "C": rng.randn(40)}
    )
    pairs = coexpression_pairs(df, corr_threshold=0.9)
    assert "A B" in pairs and "B A" in pairs     # symmetric double emission
    assert not any("A A" in p.split() [0] == p.split()[1] for p in pairs)
    assert len(pairs) == 2


def test_build_pairs_end_to_end(tmp_path):
    q = _toy_query(tmp_path)
    out = tmp_path / "pairs.txt"
    pairs = build_pairs(q, str(out), log=lambda s: None)
    assert "GA GB" in pairs and "GB GA" in pairs
    assert out.read_text().count("GA GB") >= 1
    # parallel path agrees with serial
    parallel = build_pairs(q, parallel=True, num_workers=2, log=lambda s: None)
    assert sorted(parallel) == sorted(pairs)
