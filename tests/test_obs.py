"""Unified observability layer: tracer, registry, run manifest, watchdog,
report CLI, and the no-bare-prints lint (docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import sys

import pytest

from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.obs.run import Run, StallWatchdog, config_hash
from gene2vec_tpu.obs.trace import Tracer, ambient_span, read_events
from gene2vec_tpu.obs import report


# -- tracer -----------------------------------------------------------------


def test_span_nesting_and_ordering(tmp_path):
    t = Tracer(str(tmp_path / "events.jsonl"))
    with t.span("outer", phase="a"):
        with t.span("inner") as out:
            out["loss"] = 1.5
        t.event("marker", k=1)
    t.close()
    events = read_events(str(tmp_path / "events.jsonl"))
    assert [e["type"] for e in events] == [
        "span_start", "span_start", "span_end", "event", "span_end",
    ]
    outer_start, inner_start, inner_end, marker, outer_end = events
    assert inner_start["parent"] == outer_start["span"]
    assert outer_start["parent"] is None
    # the marker fired between inner and outer end, inside outer
    assert marker["span"] == outer_start["span"]
    # body-set attrs land on span_end; enter attrs on both
    assert inner_end["attrs"]["loss"] == 1.5
    assert outer_start["attrs"]["phase"] == "a"
    assert inner_end["dur"] >= 0
    # monotonic timestamps are ordered within the process
    monos = [e["mono"] for e in events]
    assert monos == sorted(monos)


def test_multi_process_event_merge(tmp_path):
    """Two processes appending to one events.jsonl merge into one
    timeline: every line parses, both pids appear, wall-ordering holds."""
    path = str(tmp_path / "events.jsonl")
    t = Tracer(path)
    with t.span("parent_phase"):
        child = (
            "from gene2vec_tpu.obs.trace import Tracer\n"
            f"t = Tracer({path!r})\n"
            "with t.span('child_phase', role='worker'):\n"
            "    t.event('child_event')\n"
            "t.close()\n"
        )
        res = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, timeout=60,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert res.returncode == 0, res.stderr.decode()
    t.close()
    events = read_events(path)
    assert len(events) == 5  # 2 parent + 3 child records
    pids = {e["pid"] for e in events}
    assert len(pids) == 2
    walls = [e["wall"] for e in events]
    assert walls == sorted(walls)
    child_names = {e["name"] for e in events if e["pid"] != os.getpid()}
    assert child_names == {"child_phase", "child_event"}


def test_ambient_span_buffers_until_run_exists(tmp_path):
    with ambient_span("pre_run_work", what="abi_check") as out:
        out["action"] = "probe"
    run = Run(str(tmp_path / "r"), name="t", probe_devices=False)
    run.close()
    events = read_events(str(tmp_path / "r" / "events.jsonl"))
    buffered = [e for e in events if e.get("buffered")]
    assert any(
        e["name"] == "pre_run_work"
        and e["attrs"]["action"] == "probe"
        for e in buffered
    )


# -- registry ---------------------------------------------------------------


def test_registry_prometheus_export(tmp_path):
    r = MetricsRegistry()
    r.counter("pairs_total").inc(100)
    r.counter("pairs_total").inc(28)
    r.gauge("loss").set(1.25)
    h = r.histogram("step_seconds")
    for v in (0.1, 0.2, 100.0):
        h.observe(v)
    text = r.prometheus_text()
    assert "# TYPE pairs_total counter" in text
    assert "pairs_total 128" in text
    assert "loss 1.25" in text
    assert "step_seconds_count 3" in text
    assert 'step_seconds_bucket{le="+Inf"} 3' in text
    assert h.max == 100.0
    path = str(tmp_path / "m" / "metrics.prom")
    r.snapshot_to(path)
    assert open(path).read() == text
    with pytest.raises(TypeError):
        r.gauge("pairs_total")  # name already a counter
    with pytest.raises(ValueError):
        r.counter("pairs_total").inc(-1)


def test_registry_csv_sink_and_gauges(tmp_path):
    r = MetricsRegistry()
    csv_path = str(tmp_path / "log.csv")
    r.attach_csv(csv_path)
    r.log_row(1, {"loss": 2.0})
    r.log_row(2, {"loss": 1.0, "auc": 0.9})
    r.close()
    assert r.gauge("auc").value == 0.9
    import csv as csv_mod

    rows = list(csv_mod.DictReader(open(csv_path)))
    # the header widened when `auc` appeared; row 1 backfilled empty
    assert rows[0]["auc"] == "" and rows[1]["auc"] == "0.9"


# -- run manifest + watchdog ------------------------------------------------


def test_manifest_determinism_and_content(tmp_path):
    from gene2vec_tpu.config import SGNSConfig

    assert config_hash(SGNSConfig(dim=16)) == config_hash(SGNSConfig(dim=16))
    assert config_hash(SGNSConfig(dim=16)) != config_hash(SGNSConfig(dim=32))
    assert config_hash({"a": 1, "b": 2}) == config_hash({"b": 2, "a": 1})

    run = Run(
        str(tmp_path / "r"), name="unit", config=SGNSConfig(dim=16),
        probe_devices=False,
    )
    run.close()
    manifest = json.load(open(tmp_path / "r" / "manifest.json"))
    assert manifest["name"] == "unit"
    assert manifest["config"]["dim"] == 16
    assert manifest["config_hash"] == config_hash(SGNSConfig(dim=16))
    assert manifest["pid"] == os.getpid()
    assert "versions" in manifest and "argv" in manifest


def test_watchdog_flags_synthetic_slow_step():
    w = StallWatchdog(window=32, factor=3.0, min_samples=5)
    assert w.budget() is None  # warming up
    for _ in range(20):
        assert not w.record(0.010)
    assert w.budget() == pytest.approx(0.030)
    assert w.record(0.050)       # 5x the p99/3 budget → stall
    assert not w.record(0.012)   # normal step after the stall is clean


def test_run_step_emits_stall_event(tmp_path):
    import time

    run = Run(str(tmp_path / "r"), name="t", probe_devices=False,
              watchdog=StallWatchdog(min_samples=3))
    for _ in range(6):
        with run.step("iteration"):
            time.sleep(0.005)
    with run.step("iteration"):   # synthetic slow step: >> 3x rolling p99
        time.sleep(0.12)
    run.close()
    events = read_events(str(tmp_path / "r" / "events.jsonl"))
    stalls = [e for e in events if e["type"] == "stall"]
    # scheduler jitter may flag a fast step too; the slow one MUST be there
    assert any(e["attrs"]["dur"] > 0.1 for e in stalls)
    assert all(
        e["attrs"]["dur"] > e["attrs"]["budget"] for e in stalls
    )
    assert run.registry.counter("stalls_total").value == len(stalls)


# -- trainer + bench integration -------------------------------------------


@pytest.fixture(scope="module")
def observed_sgns_run(tmp_path_factory):
    """A real (tiny) SGNSTrainer.run — the fixture run dir for the
    report-CLI tests."""
    import numpy as np

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.train import SGNSTrainer

    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 30, size=(256, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=30).astype(np.int64)
    corpus = PairCorpus(Vocab([f"G{i}" for i in range(30)], counts), pairs)
    out = str(tmp_path_factory.mktemp("obs_run") / "export")
    SGNSTrainer(
        corpus, SGNSConfig(dim=8, num_iters=3, batch_pairs=64)
    ).run(out, log=lambda s: None)
    return out


def test_trainer_run_writes_obs_artifacts(observed_sgns_run):
    out = observed_sgns_run
    assert os.path.exists(os.path.join(out, "manifest.json"))
    assert os.path.exists(os.path.join(out, "events.jsonl"))
    assert os.path.exists(os.path.join(out, "metrics.prom"))
    manifest = json.load(open(os.path.join(out, "manifest.json")))
    assert manifest["name"] == "sgns"
    assert manifest["config"]["dim"] == 8
    events = read_events(os.path.join(out, "events.jsonl"))
    iters = [
        e for e in events
        if e["type"] == "span_end" and e["name"] == "iteration"
    ]
    assert len(iters) == 3
    assert all("loss" in e["attrs"] for e in iters)
    prom = open(os.path.join(out, "metrics.prom")).read()
    assert "pairs_total" in prom and "step_seconds_count 3" in prom


def test_obs_report_cli(observed_sgns_run, capsys):
    from gene2vec_tpu.cli import obs as obs_cli

    assert obs_cli.main(["report", observed_sgns_run]) == 0
    out = capsys.readouterr().out
    assert "run: sgns" in out
    assert "iteration" in out
    assert "config hash:" in out
    assert "stalls: none" in out
    assert obs_cli.main(["report", "--json", observed_sgns_run]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["phases"]["iteration"]["count"] == 3
    assert summary["pairs_per_sec"] and summary["pairs_per_sec"] > 0


def test_obs_report_cli_rejects_empty_dir(tmp_path, capsys):
    from gene2vec_tpu.cli import obs as obs_cli

    assert obs_cli.main(["report", str(tmp_path)]) == 2
    capsys.readouterr()


def test_collective_stats_from_hlo():
    from gene2vec_tpu.obs.probes import collective_stats_from_hlo, shape_bytes

    assert shape_bytes("f32[8,4]") == 128
    assert shape_bytes("(f32[2], u32[2])") == 16
    hlo = (
        "  %ar = f32[100,8]{1,0} all-reduce(f32[100,8] %x), replica_groups={}\n"
        "  %ag = f32[16]{0} all-gather(f32[2] %y), dimensions={0}\n"
        "  %plain = f32[4] add(f32[4] %a, f32[4] %b)\n"
    )
    stats = collective_stats_from_hlo(hlo)
    assert stats["collectives"]["all-reduce"]["count"] == 1
    assert stats["collectives"]["all-reduce"]["output_bytes"] == 3200
    assert stats["collectives"]["all-gather"]["output_bytes"] == 64
    assert stats["total_bytes"] == 3264


def test_probe_sample_runs():
    from gene2vec_tpu.obs import probes

    r = MetricsRegistry()
    values = probes.sample(r)
    assert values["host_rss_bytes"] is None or values["host_rss_bytes"] > 0
    # jax is imported by the suite, so live-array accounting is available
    assert values["hbm_bytes"] is None or values["hbm_bytes"] >= 0


# -- lint: no bare prints in library code (tier-1 wiring) -------------------


def test_no_bare_prints_in_library_code():
    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ),
    )
    try:
        from check_no_bare_prints import bare_prints_in_source, check_tree
    finally:
        sys.path.pop(0)

    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "gene2vec_tpu",
    )
    assert check_tree(pkg) == []
    # the checker itself sees what it should
    assert bare_prints_in_source("print('x')", "<t>") != []
    assert bare_prints_in_source("import sys\nprint('x', file=sys.stderr)", "<t>") == []
    assert bare_prints_in_source("log = print", "<t>") == []
    # the shim honors the inline pragma exactly like cli.analyze does
    assert bare_prints_in_source(
        "print('x')  # graftcheck: disable=bare-print", "<t>"
    ) == []
