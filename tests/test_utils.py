"""Aux subsystems: step timer, metrics logger, trace context."""

import csv
import os

from gene2vec_tpu.utils.metrics import MetricsLogger
from gene2vec_tpu.utils.profiling import StepTimer, trace_context


def test_step_timer_skips_compile_epoch():
    t = StepTimer()
    t.record(1000, 10.0)  # compile epoch
    t.record(1000, 1.0)
    t.record(1000, 1.0)
    assert t.pairs_per_sec() == 1000.0
    assert t.pairs_per_sec(skip_first=False) < 500.0
    assert t.total_pairs == 3000


def test_metrics_logger_csv_roundtrip(tmp_path):
    path = str(tmp_path / "m" / "log.csv")
    m = MetricsLogger(path)
    m.log(1, {"loss": 4.0, "pairs_per_sec": 100.0})
    m.log(2, {"loss": 3.5, "pairs_per_sec": 120.0})
    m.close()
    # appending re-opens with the existing header
    m2 = MetricsLogger(path)
    m2.log(3, {"loss": 3.0, "pairs_per_sec": 130.0})
    m2.close()
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert [r["step"] for r in rows] == ["1", "2", "3"]
    assert float(rows[2]["loss"]) == 3.0


def test_metrics_logger_none_path_is_noop():
    m = MetricsLogger(None)
    m.log(1, {"loss": 1.0})
    m.close()


def test_trainer_writes_training_log(tmp_path, synthetic_corpus_dir):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.pair_reader import load_corpus
    from gene2vec_tpu.sgns.train import SGNSTrainer

    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    out = str(tmp_path / "emb")
    SGNSTrainer(
        PairCorpus(vocab, pairs), SGNSConfig(dim=8, num_iters=3, batch_pairs=64)
    ).run(out, log=lambda s: None)
    with open(os.path.join(out, "training_log.csv")) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 3
    assert {"loss", "pairs_per_sec", "seconds", "step", "time"} <= set(rows[0])


def test_trace_context_noop_and_real(tmp_path):
    with trace_context(None):
        pass
    import jax
    import jax.numpy as jnp

    with trace_context(str(tmp_path / "trace")):
        jnp.sum(jnp.ones(8)).block_until_ready()
    assert os.listdir(tmp_path / "trace")  # jax.profiler wrote something
