"""CBOW + hierarchical-softmax variants (BASELINE config 4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns.cbow_hs import (
    CBOWHSTrainer,
    hs_loss_and_grads,
    hs_step,
    make_trainer,
)
from gene2vec_tpu.sgns.huffman import build_huffman_tree
from gene2vec_tpu.sgns.model import SGNSParams


# -- Huffman tree ---------------------------------------------------------


def test_huffman_prefix_free_and_complete():
    counts = np.array([50, 30, 10, 5, 3, 1, 1], np.int64)
    tree = build_huffman_tree(counts)
    v = len(counts)
    assert tree.num_nodes == v - 1
    codes = []
    for i in range(v):
        n = int(tree.lengths[i])
        assert n > 0
        codes.append("".join(str(int(b)) for b in tree.codes[i, :n]))
    # prefix-free: no code is a prefix of another
    for i, a in enumerate(codes):
        for j, b in enumerate(codes):
            if i != j:
                assert not b.startswith(a)
    # Kraft equality for a full binary tree
    assert sum(2.0 ** -len(c) for c in codes) == pytest.approx(1.0)


def test_huffman_frequent_tokens_get_short_codes():
    counts = np.array([1000, 500, 100, 10, 5, 2, 1, 1], np.int64)
    tree = build_huffman_tree(counts)
    lengths = tree.lengths
    assert lengths[0] == lengths.min()
    assert lengths[-1] == lengths.max()
    # expected code length within 1 bit of entropy (Huffman optimality)
    p = counts / counts.sum()
    entropy = -(p * np.log2(p)).sum()
    expected_len = (p * lengths).sum()
    assert entropy <= expected_len <= entropy + 1.0


def test_huffman_points_in_range():
    counts = np.random.RandomState(0).randint(1, 100, 64).astype(np.int64)
    tree = build_huffman_tree(counts)
    for i in range(64):
        n = int(tree.lengths[i])
        assert (tree.points[i, :n] >= 0).all()
        assert (tree.points[i, :n] < tree.num_nodes).all()
        # root (created last) starts every path
        assert tree.points[i, 0] == tree.num_nodes - 1


# -- HS loss/grads vs numpy oracle ---------------------------------------


def _np_hs_oracle(emb, node, inputs, targets, tree):
    """Per-example sequential HS loss and summed gradients."""
    d_emb = np.zeros_like(emb)
    d_node = np.zeros_like(node)
    losses = []
    for e in range(len(inputs)):
        v = emb[inputs[e]]
        t = targets[e]
        n = int(tree.lengths[t])
        loss = 0.0
        for l in range(n):
            w = node[tree.points[t, l]]
            logit = float(v @ w)
            code = float(tree.codes[t, l])
            sign = 1.0 - 2.0 * code
            loss += np.log1p(np.exp(-sign * logit))
            g = 1.0 / (1.0 + np.exp(-logit)) - (1.0 - code)
            d_emb[inputs[e]] += g * w
            d_node[tree.points[t, l]] += g * v
        losses.append(loss)
    return np.mean(losses), d_emb, d_node


def test_hs_loss_matches_oracle():
    rng = np.random.RandomState(0)
    V, D, E = 12, 6, 20
    counts = rng.randint(1, 50, V).astype(np.int64)
    tree = build_huffman_tree(counts)
    emb = rng.randn(V, D).astype(np.float32) * 0.2
    node = rng.randn(tree.num_nodes, D).astype(np.float32) * 0.2
    inputs = rng.randint(0, V, E).astype(np.int32)
    targets = rng.randint(0, V, E).astype(np.int32)

    loss, d_in, d_nd, pts, mask = hs_loss_and_grads(
        jnp.asarray(emb), jnp.asarray(node),
        jnp.asarray(inputs), jnp.asarray(targets),
        jnp.asarray(tree.points), jnp.asarray(tree.codes),
        jnp.asarray(tree.lengths),
    )
    exp_loss, exp_demb, exp_dnode = _np_hs_oracle(emb, node, inputs, targets, tree)
    np.testing.assert_allclose(float(loss), exp_loss, rtol=1e-5)
    # scatter the per-example grads like the step would (sum semantics)
    got_demb = np.zeros_like(emb)
    np.add.at(got_demb, inputs, np.asarray(d_in))
    np.testing.assert_allclose(got_demb, exp_demb, atol=1e-5)
    got_dnode = np.zeros_like(node)
    np.add.at(
        got_dnode,
        np.asarray(pts).reshape(-1),
        np.asarray(d_nd).reshape(-1, D) * np.asarray(mask).reshape(-1, 1),
    )
    np.testing.assert_allclose(got_dnode, exp_dnode, atol=1e-5)


def test_split_shallow_layout():
    """split_shallow: shallow nodes (depth < d) are renumbered into a
    contiguous prefix; the sign row re-encodes exactly the first d path
    levels; deep remainders carry the rest under the permutation."""
    from gene2vec_tpu.sgns.huffman import split_shallow

    rng = np.random.RandomState(1)
    counts = (rng.zipf(1.5, 200) + 1).astype(np.int64)
    tree = build_huffman_tree(counts)
    d = 4
    split = split_shallow(tree, d)
    assert 1 <= split.n_shallow < 2 ** d
    inv = np.argsort(split.perm)  # new id -> old id
    for t in range(len(counts)):
        ln = int(tree.lengths[t])
        # shallow levels encoded in the sign row
        row = split.sign[t]
        on = np.flatnonzero(row)
        assert len(on) == min(ln, d)
        for l in range(min(ln, d)):
            new_id = split.perm[tree.points[t, l]]
            assert new_id < split.n_shallow
            assert row[new_id] == 1 - 2 * tree.codes[t, l]
        # deep levels preserved under the permutation
        assert int(split.lengths_deep[t]) == max(ln - d, 0)
        for l in range(d, ln):
            assert inv[split.points_deep[t, l - d]] == tree.points[t, l]
            assert split.codes_deep[t, l - d] == tree.codes[t, l]


@pytest.mark.parametrize("cbow", [False, True])
def test_hs_step_split_matches_classic(cbow):
    """The dense-shallow split (round 4) is an exact re-grouping of the
    same per-node logistic objective: one step from identical params must
    give the same loss and the same updated tables (modulo the node
    permutation and f32 matmul-vs-scatter reorder)."""
    from gene2vec_tpu.sgns.huffman import split_shallow

    rng = np.random.RandomState(0)
    V, D, B = 60, 8, 32
    counts = (rng.zipf(1.5, V) + 1).astype(np.int64)
    tree = build_huffman_tree(counts)
    split = split_shallow(tree, 4)
    emb = rng.randn(V, D).astype(np.float32) * 0.2
    node = rng.randn(tree.num_nodes, D).astype(np.float32) * 0.2
    pairs = jnp.asarray(rng.randint(0, V, (B, 2)).astype(np.int32))
    lr = jnp.float32(0.05)

    p_ref, loss_ref = hs_step(
        SGNSParams(emb=jnp.asarray(emb), ctx=jnp.asarray(node)), pairs,
        jnp.asarray(tree.points), jnp.asarray(tree.codes),
        jnp.asarray(tree.lengths), lr, cbow=cbow,
    )
    node_perm = node[np.argsort(split.perm)]  # new id -> old row
    p_new, loss_new = hs_step(
        SGNSParams(emb=jnp.asarray(emb), ctx=jnp.asarray(node_perm)), pairs,
        jnp.asarray(split.points_deep), jnp.asarray(split.codes_deep),
        jnp.asarray(split.lengths_deep), lr, cbow=cbow,
        shallow_sign=jnp.asarray(split.sign), n_shallow=split.n_shallow,
    )
    np.testing.assert_allclose(float(loss_new), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p_new.emb), np.asarray(p_ref.emb), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(p_new.ctx)[split.perm], np.asarray(p_ref.ctx), atol=1e-5
    )


def test_hs_resume_refuses_layout_mismatch(tmp_path, synthetic_corpus_dir):
    """A checkpoint saved under one hs_dense_depth must not silently
    resume under another — node-table row ids are permuted between
    layouts (round-4 split)."""
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(
        dim=8, num_iters=1, batch_pairs=64, objective="sg_hs",
        hs_dense_depth=4,
    )
    CBOWHSTrainer(corpus, cfg).run(str(tmp_path), log=lambda m: None)

    import dataclasses

    cfg2 = dataclasses.replace(cfg, num_iters=2, hs_dense_depth=0)
    with pytest.raises(ValueError, match="hs_dense_depth=4"):
        CBOWHSTrainer(corpus, cfg2).run(str(tmp_path), log=lambda m: None)
    # same depth resumes fine
    cfg3 = dataclasses.replace(cfg, num_iters=2)
    CBOWHSTrainer(corpus, cfg3).run(str(tmp_path), log=lambda m: None)


# -- training smoke -------------------------------------------------------


@pytest.mark.parametrize("objective", ["cbow", "sg_hs", "cbow_hs"])
def test_variant_learns_cluster_structure(objective, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    # ~30 epochs: the zero-initialized HS node table needs ~15 to break
    # symmetry before the loss starts dropping
    cfg = SGNSConfig(
        dim=16, num_iters=30, batch_pairs=64, objective=objective, seed=0
    )
    trainer = make_trainer(PairCorpus(vocab, pairs), cfg)
    assert isinstance(trainer, CBOWHSTrainer)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    first_loss = last_loss = None
    for it in range(cfg.num_iters):
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, it))
        loss = float(loss)
        if first_loss is None:
            first_loss = loss
        last_loss = loss
    assert np.isfinite(last_loss)
    assert last_loss < first_loss
    # cluster separation: the synthetic corpus pairs genes within 4 clusters
    from conftest import cluster_separation

    sep = cluster_separation(np.asarray(params.emb), vocab.id_to_token)
    assert sep > 0.1, (objective, sep)


def test_hs_checkpoint_roundtrip(tmp_path, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    cfg = SGNSConfig(dim=8, num_iters=2, batch_pairs=64, objective="sg_hs")
    trainer = CBOWHSTrainer(PairCorpus(vocab, pairs), cfg)
    out = str(tmp_path / "emb")
    params = trainer.run(out, log=lambda s: None)
    # node table has V-1 rows, emb has V
    assert params.emb.shape[0] == len(vocab)
    assert params.ctx.shape[0] == len(vocab) - 1
    # resume trains nothing further
    msgs = []
    trainer2 = CBOWHSTrainer(PairCorpus(vocab, pairs), cfg)
    trainer2.run(out, log=msgs.append)
    assert any("resuming from iteration 2" in m for m in msgs)


def test_cbow_hs_sharded_matches_unsharded(synthetic_corpus_dir):
    """VERDICT r1 item 5: the cbow_hs objective trains on the mesh, both
    data-parallel and vocab-sharded, matching the single-device numbers."""
    import jax

    from gene2vec_tpu.config import MeshConfig
    from gene2vec_tpu.parallel.mesh import make_mesh
    from gene2vec_tpu.parallel.sharding import SGNSSharding

    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(
        dim=16, num_iters=1, batch_pairs=64, objective="cbow_hs", seed=3
    )
    ref = CBOWHSTrainer(corpus, cfg)
    key = jax.random.PRNGKey(5)
    ref_params, ref_loss = ref.train_epoch(ref.init(), key)

    for vocab_sharded in (False, True):
        mesh = make_mesh(MeshConfig(data=-1, model=2))
        tr = CBOWHSTrainer(
            corpus, cfg, sharding=SGNSSharding(mesh, vocab_sharded=vocab_sharded)
        )
        params, loss = tr.train_epoch(tr.init(), key)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        n = ref_params.ctx.shape[0]  # sharded node table may be row-padded
        np.testing.assert_allclose(
            np.asarray(params.emb), np.asarray(ref_params.emb), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(params.ctx)[:n], np.asarray(ref_params.ctx), atol=1e-5
        )
        if vocab_sharded:
            assert params.emb.sharding.spec[0] == "model"
