"""Fleet-sharded index serving: shard loading, cross-process merge
parity vs the in-mesh two_stage_topk, scatter-gather degradation, the
shard-atomic stage/flip swap, and the BENCH_SHARD gate.  Everything on
the CPU backend with tiny tables (conftest pins JAX_PLATFORMS=cpu and
8 virtual devices)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from gene2vec_tpu.io.checkpoint import save_iteration
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.parallel.sharding import (
    merge_shard_topk,
    shard_of_row,
    shard_ranges,
)
from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy, TokenBucket
from gene2vec_tpu.serve.engine import SimilarityEngine
from gene2vec_tpu.serve.registry import ModelRegistry, l2_normalize
from gene2vec_tpu.serve.server import ApiError, ServeApp, ServeConfig, make_server
from gene2vec_tpu.serve.shardgroup import (
    RoutingTable,
    ShardGroup,
    ShardGroupConfig,
    SwapCoordinator,
)
from gene2vec_tpu.sgns.model import SGNSParams

V, D = 24, 8


def _write_iteration(export_dir, iteration, seed, vocab=V, dim=D):
    rng = np.random.RandomState(seed)
    voc = Vocab([f"G{i}" for i in range(vocab)],
                np.arange(vocab, 0, -1))
    emb = rng.randn(vocab, dim).astype(np.float32)
    params = SGNSParams(
        emb=jnp.asarray(emb),
        ctx=jnp.asarray(np.zeros((vocab, dim), np.float32)),
    )
    save_iteration(str(export_dir), dim, iteration, params, voc)
    return emb


@pytest.fixture
def export_dir(tmp_path):
    d = tmp_path / "exports"
    _write_iteration(d, 1, seed=1)
    return d


# -- shard range math --------------------------------------------------------


def test_shard_ranges_cover_and_balance():
    ranges = shard_ranges(13, 4)
    assert ranges == [(0, 4), (4, 7), (7, 10), (10, 13)]
    assert ranges[0][0] == 0 and ranges[-1][1] == 13
    sizes = [e - s for s, e in ranges]
    assert max(sizes) - min(sizes) <= 1
    # the device layout: equal padded spans, overhang past total is pad
    padded = shard_ranges(13, 8, pad_to_multiple=True)
    assert padded == [(2 * i, 2 * i + 2) for i in range(8)]
    assert shard_ranges(0, 3) == [(0, 0), (0, 0), (0, 0)]
    with pytest.raises(ValueError):
        shard_ranges(10, 0)


def test_shard_of_row():
    ranges = shard_ranges(13, 4)
    assert shard_of_row(0, ranges) == 0
    assert shard_of_row(6, ranges) == 1
    assert shard_of_row(12, ranges) == 3
    with pytest.raises(ValueError):
        shard_of_row(13, ranges)


# -- cross-process merge parity vs the in-mesh two_stage_topk ----------------


def _mesh(p):
    from gene2vec_tpu.config import MeshConfig
    from gene2vec_tpu.parallel.mesh import make_mesh

    return make_mesh(MeshConfig(data=1, model=p))


@pytest.mark.parametrize("vocab,k", [(13, 4), (13, 5), (16, 3), (9, 8)])
def test_merge_bitwise_identical_to_two_stage_topk(vocab, k):
    """The cross-process merge of per-shard local top-k must be
    BITWISE-identical to the single-host shard_map two_stage_topk on
    the same table — including pad-row masking at shard boundaries
    (vocab not a multiple of the shard count) and k larger than a
    shard's row count."""
    from gene2vec_tpu.parallel.sharding import row_sharding

    P = 8
    rng = np.random.RandomState(vocab * 100 + k)
    unit = l2_normalize(rng.randn(vocab, D).astype(np.float32))
    queries = rng.randn(3, D).astype(np.float32)
    pad = (-vocab) % P
    padded = np.concatenate(
        [unit, np.zeros((pad, D), np.float32)]
    ) if pad else unit

    mesh = _mesh(P)
    import jax

    sharded_engine = SimilarityEngine(max_batch=4, mesh=mesh)
    unit_dev = jax.device_put(jnp.asarray(padded), row_sharding(mesh))
    ref_scores, ref_idx = sharded_engine.top_k(
        unit_dev, queries, k, valid=vocab
    )

    # each "process" computes its local top-k over its padded span with
    # the SAME exact kernel a shard replica runs, then the front-door
    # merge combines the candidate sets
    local_engine = SimilarityEngine(max_batch=4)
    parts = []
    for start, end in shard_ranges(padded.shape[0], P,
                                   pad_to_multiple=True):
        local_valid = max(0, min(vocab, end) - start)
        sl = padded[start:end]
        lk = min(k, sl.shape[0])
        s, i = local_engine.top_k(
            jnp.asarray(sl), queries, lk, valid=local_valid or None
        )
        if local_valid == 0:
            # a pure-pad shard: the mesh kernel masks it to -inf but
            # still contributes candidates; emulate with -inf scores
            s = np.full_like(s, -np.inf)
        parts.append((s, i.astype(np.int64) + start))
    got_scores, got_idx = merge_shard_topk(parts, k)

    np.testing.assert_array_equal(got_scores, ref_scores)
    np.testing.assert_array_equal(got_idx, ref_idx)


def test_merge_matches_full_table_oracle_on_balanced_ranges():
    """Balanced (serving-layout) shards: the merge equals the exact
    full-table top-k, and dropping one shard equals the exact top-k
    restricted to the live shards' rows — graceful degradation IS the
    restricted oracle."""
    rng = np.random.RandomState(7)
    vocab, k, n_shards = 29, 6, 3
    unit = l2_normalize(rng.randn(vocab, D).astype(np.float32))
    queries = l2_normalize(rng.randn(4, D).astype(np.float32))
    scores_full = queries @ unit.T

    ranges = shard_ranges(vocab, n_shards)
    engine = SimilarityEngine(max_batch=4)
    parts = []
    for start, end in ranges:
        lk = min(k, end - start)
        s, i = engine.top_k(jnp.asarray(unit[start:end]), queries, lk)
        parts.append((s, i.astype(np.int64) + start))

    def oracle(cols):
        order = np.argsort(-scores_full[:, cols], axis=1,
                           kind="stable")[:, :k]
        return np.asarray(cols)[order]

    _, merged = merge_shard_topk(parts, k)
    np.testing.assert_array_equal(merged, oracle(np.arange(vocab)))

    dead = 1
    live_parts = [p for i, p in enumerate(parts) if i != dead]
    live_cols = np.concatenate([
        np.arange(s, e) for i, (s, e) in enumerate(ranges) if i != dead
    ])
    _, degraded = merge_shard_topk(live_parts, k)
    np.testing.assert_array_equal(degraded, oracle(live_cols))


def test_merge_needs_at_least_one_part():
    with pytest.raises(ValueError):
        merge_shard_topk([], 3)


# -- sharded registry loading ------------------------------------------------


def test_registry_loads_only_its_shard(export_dir):
    full = ModelRegistry(str(export_dir))
    assert full.refresh()
    whole = full.model
    reg = ModelRegistry(str(export_dir), shard=(1, 3))
    assert reg.refresh()
    m = reg.model
    start, end = shard_ranges(V, 3)[1]
    assert m.row_base == start and len(m) == end - start
    assert m.total_rows == V
    assert m.epoch == m.iteration
    assert m.tokens == whole.tokens[start:end]
    np.testing.assert_array_equal(m.emb, whole.emb[start:end])
    # index maps LOCAL rows; non-owned genes are absent
    assert m.index[whole.tokens[start]] == 0
    assert whole.tokens[0] not in m.index


def test_registry_shard_validation(export_dir):
    with pytest.raises(ValueError):
        ModelRegistry(str(export_dir), shard=(3, 3))
    with pytest.raises(ValueError):
        ModelRegistry(str(export_dir), shard=(0, 0))


def test_stage_then_flip_is_atomic(export_dir):
    reg = ModelRegistry(str(export_dir), shard=(0, 2))
    assert reg.refresh()
    assert reg.model.iteration == 1
    _write_iteration(export_dir, 2, seed=2)
    staged = reg.stage(D, 2)
    assert staged.iteration == 2
    assert reg.model.iteration == 1  # staged, not served
    # flip requires a matching staged model
    with pytest.raises(RuntimeError):
        reg.flip(3)
    m = reg.flip(2)
    assert m.iteration == 2 and m.epoch == 2
    assert reg.model.iteration == 2
    # idempotent re-flip (a coordinator retry)
    assert reg.flip(2).epoch == 2
    # stage of a missing iteration fails loudly
    with pytest.raises(FileNotFoundError):
        reg.stage(D, 9)


def test_flip_under_reader_never_shows_mixed_fields(export_dir):
    reg = ModelRegistry(str(export_dir), shard=(0, 2))
    reg.refresh()
    _write_iteration(export_dir, 2, seed=2)
    reg.stage(D, 2)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            m = reg.model  # one reference: all fields one iteration
            if m.iteration not in (1, 2) or (
                m.epoch is not None and m.epoch != m.iteration
            ):
                bad.append(m.version)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    reg.flip(2)
    stop.set()
    t.join(timeout=5)
    assert not bad


# -- shard routes on the replica ---------------------------------------------


@pytest.fixture
def shard_apps(export_dir):
    """Two shard replicas over the same export, as in-process apps."""
    apps = []
    for i in range(2):
        reg = ModelRegistry(str(export_dir), shard=(i, 2))
        assert reg.refresh()
        app = ServeApp(
            reg, config=ServeConfig(max_delay_ms=1.0)
        ).start()
        apps.append(app)
    yield apps
    for app in apps:
        app.stop()


def test_shard_topk_returns_global_rows(shard_apps, export_dir):
    full = ModelRegistry(str(export_dir))
    full.refresh()
    unit = l2_normalize(full.model.emb)
    q = unit[3]
    docs = []
    for app in shard_apps:
        doc = app.shard_topk({"vectors": [list(map(float, q))], "k": 4})
        assert doc["shard"]["num_shards"] == 2
        docs.append(doc)
    parts = [
        (np.asarray([d["results"][0]["scores"]], np.float32),
         np.asarray([d["results"][0]["rows"]]))
        for d in docs
    ]
    _, merged = merge_shard_topk(parts, 4)
    exact = np.argsort(-(unit @ q), kind="stable")[:4]
    np.testing.assert_array_equal(merged[0], exact)
    # tokens ride the candidates
    for d in docs:
        r = d["results"][0]
        for row, tok in zip(r["rows"], r["tokens"]):
            assert tok == full.model.tokens[row]


def test_shard_topk_epoch_fence(shard_apps):
    app = shard_apps[0]
    cur = app.registry.model.epoch
    with pytest.raises(ApiError) as e:
        app.shard_topk({"vectors": [[0.0] * D], "k": 2,
                        "epoch": cur + 1})
    assert e.value.status == 409


def test_shard_vectors_owned_and_not(shard_apps, export_dir):
    full = ModelRegistry(str(export_dir))
    full.refresh()
    start, end = shard_ranges(V, 2)[0]
    owned = full.model.tokens[start]
    foreign = full.model.tokens[end]
    doc = shard_apps[0].shard_vectors({"genes": [owned]})
    np.testing.assert_allclose(
        doc["vectors"][0], full.model.emb[start], rtol=1e-6
    )
    with pytest.raises(ApiError) as e:
        shard_apps[0].shard_vectors({"genes": [foreign]})
    assert e.value.status == 400


def test_shard_routes_404_on_unsharded_replica(export_dir):
    reg = ModelRegistry(str(export_dir))
    reg.refresh()
    app = ServeApp(reg)
    with pytest.raises(ApiError) as e:
        app.shard_topk({"vectors": [[0.0] * D], "k": 2})
    assert e.value.status == 404


def test_shard_healthz_reports_shard_facts(shard_apps):
    status, doc = shard_apps[1].healthz()
    assert status == 200
    start, end = shard_ranges(V, 2)[1]
    assert doc["shard"]["rows"] == [start, end]
    assert doc["shard"]["epoch"] == doc["shard"]["iteration"]


# -- routing table -----------------------------------------------------------


def test_routing_table_from_manifest(export_dir):
    rt = RoutingTable(str(export_dir), 3)
    assert rt.reload()
    assert rt.total_rows == V and rt.dim == D
    ranges = shard_ranges(V, 3)
    for row, tok in enumerate(rt.tokens):
        assert rt.owner(tok) == shard_of_row(row, ranges)
    assert rt.owner("NOPE") is None
    doc = rt.genes_doc(limit=5, offset=2)
    assert doc["total"] == V and doc["genes"] == list(rt.tokens[2:7])


# -- scatter-gather over live shard replicas ---------------------------------


@pytest.fixture
def shard_fleet(shard_apps, export_dir):
    """The two shard apps behind real HTTP, plus a ShardGroup front."""
    servers, urls = [], []
    for app in shard_apps:
        srv = make_server(app, "127.0.0.1", 0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        host, port = srv.server_address[:2]
        servers.append(srv)
        urls.append(f"http://{host}:{port}")
    alive = [True, True]

    routing = RoutingTable(str(export_dir), 2)
    assert routing.reload()
    metrics = MetricsRegistry()
    group = ShardGroup(
        ShardGroupConfig(num_shards=2, shard_deadline_s=2.0,
                         default_timeout_s=5.0),
        lambda i: urls[i] if alive[i] else None,
        metrics=metrics,
        policy=RetryPolicy(max_attempts=2, connect_timeout_s=0.5,
                           default_timeout_s=2.0),
        routing=routing,
    )
    group.current_epoch = 1
    yield group, alive, metrics, urls, shard_apps
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def _exact_reference(export_dir, gene, k):
    full = ModelRegistry(str(export_dir))
    full.refresh()
    m = full.model
    unit = l2_normalize(m.emb)
    q = unit[m.index[gene]]
    order = np.argsort(-(unit @ q), kind="stable")
    toks = [m.tokens[i] for i in order if m.tokens[i] != gene]
    return toks[:k]


def test_scatter_full_answer_matches_oracle(shard_fleet, export_dir):
    group, _alive, metrics, _urls, _apps = shard_fleet
    status, doc = group.similar({"genes": ["G3"], "k": 4})
    assert status == 200
    assert doc["degraded"] is False
    assert doc["shards"] == {
        "total": 2, "answered": 2, "indexes": [0, 1], "epoch": 1,
    }
    got = [n["gene"] for n in doc["results"][0]["neighbors"]]
    assert got == _exact_reference(export_dir, "G3", 4)
    assert metrics.counter("fleet_degraded_responses_total").value == 0


def test_scatter_vector_queries(shard_fleet, export_dir):
    group, *_ = shard_fleet
    full = ModelRegistry(str(export_dir))
    full.refresh()
    q = list(map(float, full.model.emb[5]))
    status, doc = group.similar({"vectors": [q], "k": 3})
    assert status == 200 and not doc["degraded"]
    assert len(doc["results"][0]["neighbors"]) == 3


def test_scatter_validation_errors(shard_fleet):
    group, *_ = shard_fleet
    assert group.similar({"k": 2})[0] == 400
    assert group.similar({"genes": [], "k": 2})[0] == 400
    assert group.similar({"genes": ["G1"], "k": 0})[0] == 400
    assert group.similar({"genes": ["NOPE"], "k": 2})[0] == 400
    assert group.similar(
        {"genes": ["G1"], "vectors": [[0.0]], "k": 2}
    )[0] == 400


def test_dead_shard_degrades_instead_of_failing(shard_fleet, export_dir):
    group, alive, metrics, _urls, _apps = shard_fleet
    alive[1] = False  # shard 1 leaves rotation (dead / ejected)
    full = ModelRegistry(str(export_dir))
    full.refresh()
    q = list(map(float, full.model.emb[2]))
    status, doc = group.similar({"vectors": [q], "k": 4})
    assert status == 200, "a dead shard must never 5xx the query"
    assert doc["degraded"] is True
    assert doc["shards"]["answered"] == 1
    assert doc["shards"]["indexes"] == [0]
    # every returned row belongs to the LIVE shard's range — and the
    # answer is the exact oracle restricted to those rows
    start, end = shard_ranges(V, 2)[0]
    unit = l2_normalize(full.model.emb)
    restricted = np.argsort(
        -(unit[start:end] @ l2_normalize(np.asarray([q]))[0]),
        kind="stable",
    )[:4] + start
    got = [n["gene"] for n in doc["results"][0]["neighbors"]]
    assert got == [full.model.tokens[i] for i in restricted]
    assert metrics.counter("fleet_degraded_responses_total").value == 1


def test_gene_owned_by_dead_shard_answers_from_cache(shard_fleet):
    group, alive, _metrics, _urls, _apps = shard_fleet
    # G20 lives on shard 1; warm the qvec cache, then kill the owner
    start, _ = shard_ranges(V, 2)[1]
    gene = f"G{start}"
    status, doc = group.similar({"genes": [gene], "k": 3})
    assert status == 200 and not doc["degraded"]
    alive[1] = False
    status, doc = group.similar({"genes": [gene], "k": 3})
    assert status == 200
    assert doc["degraded"] is True  # shard 1's rows are missing
    assert doc["results"][0]["neighbors"], (
        "warmed gene must still answer from the live shards"
    )


def test_cold_gene_on_dead_owner_is_degraded_not_5xx(shard_fleet):
    group, alive, metrics, _urls, _apps = shard_fleet
    alive[0] = False
    start, _ = shard_ranges(V, 2)[0]
    status, doc = group.similar({"genes": [f"G{start}"], "k": 3})
    assert status == 200
    assert doc["degraded"] is True
    assert doc["results"][0]["neighbors"] == []
    assert metrics.counter("fleet_qvec_unresolved_total").value == 1


def test_all_shards_dead_is_503(shard_fleet, export_dir):
    group, alive, *_ = shard_fleet
    alive[0] = alive[1] = False
    full = ModelRegistry(str(export_dir))
    full.refresh()
    q = list(map(float, full.model.emb[0]))
    status, doc = group.similar({"vectors": [q], "k": 2})
    assert status == 503
    assert doc["shards"]["answered"] == 0


def test_mixed_epoch_gather_rescatters_once_and_fences(
    shard_fleet, export_dir
):
    group, _alive, metrics, _urls, apps = shard_fleet
    # shard 0 flips to iteration 2, shard 1 lags (mid-swap window)
    _write_iteration(export_dir, 2, seed=2)
    apps[0].registry.stage(D, 2)
    apps[0].registry.flip(2)
    full = ModelRegistry(str(export_dir))
    full.refresh()
    q = list(map(float, full.model.emb[1]))
    status, doc = group.similar({"vectors": [q], "k": 3})
    assert status == 200
    # merged ONLY from the newest epoch; the laggard is fenced out
    assert doc["shards"]["epoch"] == 2
    assert doc["shards"]["indexes"] == [0]
    assert doc["degraded"] is True
    assert metrics.counter(
        "fleet_mixed_epoch_rescatter_total"
    ).value == 1
    start, end = shard_ranges(V, 2)[0]
    for n in doc["results"][0]["neighbors"]:
        row = full.model.index[n["gene"]]
        assert start <= row < end


def test_embedding_routes_to_owner(shard_fleet, export_dir):
    group, alive, *_ = shard_fleet
    full = ModelRegistry(str(export_dir))
    full.refresh()
    status, doc = group.embedding({"genes": ["G1", "G20"]})
    assert status == 200
    np.testing.assert_allclose(
        doc["embeddings"][0]["vector"], full.model.emb[1], rtol=1e-6
    )
    alive[1] = False
    status, doc = group.embedding({"genes": ["G20"]})
    assert status == 503  # point lookups have no partial semantics
    assert group.embedding({"genes": ["NOPE"]})[0] == 400


def test_scatter_shares_one_retry_budget(export_dir):
    """A dead shard's retries draw down the SAME token bucket as every
    other shard's — the fan-out cannot multiply attempts fleet-wide."""
    routing = RoutingTable(str(export_dir), 2)
    routing.reload()
    group = ShardGroup(
        ShardGroupConfig(num_shards=2, shard_deadline_s=0.2,
                         default_timeout_s=0.5),
        lambda i: "http://127.0.0.1:9",  # discard port: refused fast
        policy=RetryPolicy(max_attempts=3, connect_timeout_s=0.2,
                           default_timeout_s=0.2,
                           retry_budget_ratio=0.0,
                           retry_budget_burst=1.0),
        routing=routing,
    )
    assert group.client(0).budget is group.client(1).budget
    q = [0.0] * D
    group.similar({"vectors": [q], "k": 2})
    group.similar({"vectors": [q], "k": 2})
    total_retries = sum(
        group.client(i).stats["retries"] for i in range(2)
    )
    # one burst token across the WHOLE fan-out: at most 1 retry total,
    # not max_attempts-1 per shard per request
    assert total_retries <= 1


def test_swap_coordinator_stages_then_flips_all(shard_fleet, export_dir):
    group, _alive, metrics, _urls, apps = shard_fleet
    coord = SwapCoordinator(
        str(export_dir), group, interval_s=0.1, metrics=metrics
    )
    coord.tick()  # adopts the boot epoch
    assert group.current_epoch == 1
    _write_iteration(export_dir, 2, seed=2)
    coord.tick()
    assert group.current_epoch == 2
    for app in apps:
        assert app.registry.model.epoch == 2
    assert metrics.counter("fleet_swap_flips_total").value == 1
    # answers now come from the new iteration, complete again
    status, doc = group.similar({"genes": ["G0"], "k": 2})
    assert status == 200 and not doc["degraded"]
    assert doc["model"]["iteration"] == 2


def test_swap_deferred_while_a_shard_is_down(shard_fleet, export_dir):
    group, alive, metrics, _urls, apps = shard_fleet
    coord = SwapCoordinator(
        str(export_dir), group, interval_s=0.1, metrics=metrics
    )
    coord.tick()
    alive[1] = False
    _write_iteration(export_dir, 2, seed=2)
    coord.tick()
    # half a fleet can never flip atomically: swap deferred, old epoch
    # keeps serving as one logical version
    assert group.current_epoch == 1
    assert apps[0].registry.model.iteration == 1
    assert metrics.counter("fleet_swap_deferred_total").value == 1
    alive[1] = True
    coord.tick()
    assert group.current_epoch == 2


def test_shard_states_for_healthz(shard_fleet):
    group, alive, *_ = shard_fleet
    alive[1] = False
    states = group.shard_states()
    assert [s["up"] for s in states] == [True, False]
    assert states[0]["rows"] == list(shard_ranges(V, 2)[0])


# -- the BENCH_SHARD gate ----------------------------------------------------


def _good_shard_doc():
    return {
        "schema": "gene2vec-tpu/bench-shard/v1",
        "passed": True,
        "shard": {
            "bench": {
                "rows": 10000000, "dim": 64, "shards": 4, "k": 10,
                "queries": 512, "index": "ivf", "nprobe": 32,
                "rescore_mult": 4, "clusters": 4096,
                "recall_at_10": 0.999, "degraded_recall_at_10": 0.76,
                "dead_shard_row_fraction": 0.25,
                "p50_ms": 20.0, "p99_ms": 60.0,
            },
            "drill": {
                "shards": 2, "availability": 1.0, "server_5xx": 0,
                "wrong_answers": 0, "mixed_iteration_answers": 0,
                "retry_amplification": 1.05,
                "failover": {
                    "replicas_per_shard": 2,
                    "availability": 1.0,
                    "degraded_responses": 0,
                    "p99_ms": 40.0,
                    "server_5xx": 0,
                    "both_dead": {
                        "degraded_responses": 30,
                        "server_5xx": 0,
                    },
                },
            },
        },
    }


def _findings(tmp_path, doc, name="BENCH_SHARD_r15.json"):
    from gene2vec_tpu.analysis.passes_shard import shard_findings

    (tmp_path / name).write_text(json.dumps(doc))
    return shard_findings(root=str(tmp_path))


def _gating(findings):
    return [f for f in findings if f.severity in ("error", "warning")]


def test_passes_shard_good_record_is_info(tmp_path):
    fs = _findings(tmp_path, _good_shard_doc())
    assert len(fs) == 1 and not _gating(fs)


def test_passes_shard_missing_bench_is_info(tmp_path):
    from gene2vec_tpu.analysis.passes_shard import shard_findings

    fs = shard_findings(root=str(tmp_path))
    assert len(fs) == 1 and fs[0].severity == "info"
    assert "chaos_drill" in fs[0].message


def test_passes_shard_low_recall_fires_once(tmp_path):
    doc = _good_shard_doc()
    doc["shard"]["bench"]["recall_at_10"] = 0.9
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "recall@10" in fs[0].message


def test_passes_shard_off_recipe_fires(tmp_path):
    doc = _good_shard_doc()
    doc["shard"]["bench"]["rows"] = 64000  # a smoke run, not the gate
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "rows=64000" in fs[0].message


def test_passes_shard_dropped_key_gates(tmp_path):
    doc = _good_shard_doc()
    del doc["shard"]["drill"]["mixed_iteration_answers"]
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "mixed_iteration_answers" in fs[0].message


def test_passes_shard_5xx_and_mixed_gate(tmp_path):
    doc = _good_shard_doc()
    doc["shard"]["drill"]["server_5xx"] = 3
    doc["shard"]["drill"]["mixed_iteration_answers"] = 1
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1
    assert "5xx" in fs[0].message and "mixed" in fs[0].message


def test_passes_shard_ungraceful_degradation_gates(tmp_path):
    doc = _good_shard_doc()
    # one dead shard of four costing 60% recall is NOT graceful
    doc["shard"]["bench"]["degraded_recall_at_10"] = 0.4
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "row fraction" in fs[0].message


def test_passes_shard_newest_round_wins(tmp_path):
    bad = _good_shard_doc()
    bad["shard"]["bench"]["recall_at_10"] = 0.5
    _findings(tmp_path, _good_shard_doc(), name="BENCH_SHARD_r15.json")
    fs = _findings(tmp_path, bad, name="BENCH_SHARD_r16.json")
    assert len(_gating(fs)) == 1  # the violating r16 wins over r15


def test_ledger_adapts_shard_family(tmp_path):
    from gene2vec_tpu.obs import ledger

    path = tmp_path / "BENCH_SHARD_r15.json"
    path.write_text(json.dumps(_good_shard_doc()))
    rec = ledger.adapt_file(str(path))
    assert rec["family"] == "shard"
    assert rec["metrics"]["shard_recall_at_10"] == 0.999
    assert rec["metrics"]["shard_p99_ms_10m"] == 60.0
    assert rec["metrics"]["failover_degraded_responses"] == 0.0
    assert rec["metrics"]["failover_p99_ms"] == 40.0
    assert rec["headline_metric"] == "shard_recall_at_10"


# -- loadgen degraded-answer verification ------------------------------------


def _loadgen():
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "serve_loadgen.py",
    )
    spec = importlib.util.spec_from_file_location("serve_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def loadgen():
    return _loadgen()


def _shard_ctx():
    # 8 rows over 2 shards; gene Gi lives at row i
    return {
        "ranges": {0: (0, 4), 1: (4, 8)},
        "row": {f"G{i}": i for i in range(8)},
    }


def test_degraded_consistent_prefix_rule(loadgen):
    ctx = _shard_ctx()
    ref = ("G5", "G1", "G6", "G2")  # the full-fleet reference
    # shard 1 dead: survivors on shard 0 are G1, G2 — a correct
    # restricted answer leads with them in order, then fill-ins
    assert loadgen._degraded_consistent(
        ("G1", "G2", "G0", "G3"), ref, ctx, answered=[0]
    )
    # a row from the DEAD shard in the answer is a merge bug
    assert not loadgen._degraded_consistent(
        ("G1", "G5", "G0", "G3"), ref, ctx, answered=[0]
    )
    # surviving reference members out of order is a merge bug
    assert not loadgen._degraded_consistent(
        ("G2", "G1", "G0", "G3"), ref, ctx, answered=[0]
    )
    # all shards answered: the full reference must lead verbatim
    assert loadgen._degraded_consistent(ref, ref, ctx, answered=[0, 1])


def test_check_answer_scores_degraded_against_restricted(loadgen):
    verify_ref = {
        "G0": (1, ("G5", "G1", "G6", "G2")),
        loadgen.SHARD_CTX_KEY: _shard_ctx(),
    }
    stats = loadgen._Stats()
    raw = json.dumps({
        "model": {"dim": 8, "iteration": 1},
        "degraded": True,
        "shards": {"total": 2, "answered": 1, "indexes": [0]},
        "results": [{"query": "G0", "neighbors": [
            {"gene": "G1", "score": 0.9}, {"gene": "G2", "score": 0.8},
            {"gene": "G3", "score": 0.1}, {"gene": "G0", "score": 0.0},
        ]}],
    }).encode()
    loadgen._check_answer(raw, verify_ref, stats)
    assert stats.degraded == 1
    assert stats.degraded_wrong == 0
    assert stats.wrong_answers == 0  # degraded is NEVER counted wrong


def test_check_answer_degraded_wrong_and_unresolved(loadgen):
    verify_ref = {
        "G0": (1, ("G5", "G1", "G6", "G2")),
        loadgen.SHARD_CTX_KEY: _shard_ctx(),
    }
    stats = loadgen._Stats()
    # degraded answer containing a dead shard's row => degraded_wrong
    raw = json.dumps({
        "model": {"dim": 8, "iteration": 1},
        "degraded": True,
        "shards": {"total": 2, "answered": 1, "indexes": [0]},
        "results": [{"query": "G0", "neighbors": [
            {"gene": "G5", "score": 0.9},
        ]}],
    }).encode()
    loadgen._check_answer(raw, verify_ref, stats)
    assert stats.degraded == 1 and stats.degraded_wrong == 1
    # honest empty partial (unresolved gene): degraded, not wrong
    raw = json.dumps({
        "model": {"dim": 8, "iteration": 1},
        "degraded": True,
        "shards": {"total": 2, "answered": 1, "indexes": [0]},
        "results": [{"query": "G0", "neighbors": [],
                     "degraded": True}],
    }).encode()
    loadgen._check_answer(raw, verify_ref, stats)
    assert stats.degraded == 2 and stats.degraded_wrong == 1
    # mixed-iteration degraded answers still count as mixed
    raw = json.dumps({
        "model": {"dim": 8, "iteration": 7},
        "degraded": True,
        "shards": {"total": 2, "answered": 1, "indexes": [0]},
        "results": [{"query": "G0", "neighbors": []}],
    }).encode()
    loadgen._check_answer(raw, verify_ref, stats)
    assert stats.mixed_iteration_answers == 1


def test_check_answer_full_answers_unchanged(loadgen):
    verify_ref = {"G0": (1, ("G5", "G1"))}
    stats = loadgen._Stats()
    good = json.dumps({
        "model": {"dim": 8, "iteration": 1},
        "results": [{"query": "G0", "neighbors": [
            {"gene": "G5", "score": 0.9}, {"gene": "G1", "score": 0.8},
        ]}],
    }).encode()
    loadgen._check_answer(good, verify_ref, stats)
    assert stats.wrong_answers == 0 and stats.degraded == 0
    bad = json.dumps({
        "model": {"dim": 8, "iteration": 1},
        "results": [{"query": "G0", "neighbors": [
            {"gene": "G2", "score": 0.9},
        ]}],
    }).encode()
    loadgen._check_answer(bad, verify_ref, stats)
    assert stats.wrong_answers == 1


# -- review-hardening regressions --------------------------------------------


def test_read_npz_rows_partial_matches_full(export_dir):
    from gene2vec_tpu.io.checkpoint import read_npz_rows
    from gene2vec_tpu.serve.registry import discover_newest

    _, _, path = discover_newest(str(export_dir))
    with np.load(path) as z:
        full = np.asarray(z["emb"])
    probe, total = read_npz_rows(path, "emb", 0, 0)
    assert total == V and probe.shape == (0, D)
    rows, _ = read_npz_rows(path, "emb", 5, 17)
    np.testing.assert_array_equal(rows, full[5:17])
    # out-of-range clamps instead of over-reading
    rows, _ = read_npz_rows(path, "emb", V - 2, V + 10)
    np.testing.assert_array_equal(rows, full[V - 2:])
    with pytest.raises(ValueError):
        read_npz_rows(path, "nope", 0, 1)
    # a compressed npz cannot be row-seeked: ValueError, so the
    # registry falls back to the full load
    comp = export_dir / "comp.npz"
    np.savez_compressed(comp, emb=full)
    with pytest.raises(ValueError):
        read_npz_rows(str(comp), "emb", 0, 2)


def test_shard_topk_accepts_front_door_k_headroom(shard_apps):
    app = shard_apps[0]
    max_k = app.config.max_k
    q = [[0.0] * D]
    # k = max_k + 1 is the front door's self-drop fetch for k=max_k —
    # it must not 400 (the k is capped to the shard's rows internally)
    doc = app.shard_topk({"vectors": q, "k": max_k + 1})
    assert doc["results"][0]["rows"]
    with pytest.raises(ApiError) as e:
        app.shard_topk({"vectors": q, "k": max_k + 2})
    assert e.value.status == 400


def test_scatter_gene_query_at_max_k(shard_fleet):
    group, *_ = shard_fleet
    status, doc = group.similar(
        {"genes": ["G3"], "k": group.config.max_k}
    )
    assert status == 200 and not doc["degraded"]
    # vocab-capped, self-dropped: every other gene comes back
    assert len(doc["results"][0]["neighbors"]) == V - 1


def test_drop_malformed_legs_degrades_visibly(shard_fleet):
    group, _alive, metrics, *_ = shard_fleet
    good = {
        "shard": {"epoch": 1},
        "results": [{"rows": [1, 2], "scores": [0.9, 0.8],
                     "tokens": ["G1", "G2"]}],
    }
    short = {"shard": {"epoch": 1}, "results": []}
    ragged = {
        "shard": {"epoch": 1},
        "results": [{"rows": [1, 2], "scores": [0.9]}],
    }
    out = group._drop_malformed({0: good, 1: short}, 1)
    assert list(out) == [0]
    out = group._drop_malformed({0: ragged}, 1)
    assert out == {}
    assert metrics.counter("fleet_shard_malformed_total").value == 2


def test_mixed_epoch_majority_wins_over_lone_upgraded_shard(
    export_dir, tmp_path
):
    """Three shards, ONE restarts into a newer self-loaded iteration:
    the gather must merge the two-shard OLD-epoch majority (degraded
    by 1/3), not collapse every answer to the lone new shard."""
    apps, servers, urls = [], [], []
    for i in range(3):
        reg = ModelRegistry(str(export_dir), shard=(i, 3))
        assert reg.refresh()
        app = ServeApp(reg, config=ServeConfig(max_delay_ms=1.0)).start()
        srv = make_server(app, "127.0.0.1", 0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        host, port = srv.server_address[:2]
        apps.append(app)
        servers.append(srv)
        urls.append(f"http://{host}:{port}")
    try:
        routing = RoutingTable(str(export_dir), 3)
        assert routing.reload()
        metrics = MetricsRegistry()
        group = ShardGroup(
            ShardGroupConfig(num_shards=3, shard_deadline_s=2.0),
            lambda i: urls[i],
            metrics=metrics,
            policy=RetryPolicy(max_attempts=2, connect_timeout_s=0.5,
                               default_timeout_s=2.0),
            routing=routing,
        )
        group.current_epoch = 1
        # shard 2 "restarted into" iteration 2 on its own
        _write_iteration(export_dir, 2, seed=2)
        apps[2].registry.stage(D, 2)
        apps[2].registry.flip(2)
        full = ModelRegistry(str(export_dir))
        full.refresh()
        q = list(map(float, full.model.emb[0]))
        status, doc = group.similar({"vectors": [q], "k": 3})
        assert status == 200
        assert doc["shards"]["epoch"] == 1, (
            "the lone upgraded shard must not win the epoch vote"
        )
        assert doc["shards"]["indexes"] == [0, 1]
        assert doc["degraded"] is True
    finally:
        for app in apps:
            app.stop()
        for srv in servers:
            srv.shutdown()
            srv.server_close()


def test_routing_table_snapshot_is_atomic(export_dir):
    rt = RoutingTable(str(export_dir), 2)
    assert rt.reload()
    snap = rt._snap
    # owner() reads ONE snapshot: index and ranges always agree
    assert snap.index is rt.index and snap.ranges is rt.ranges


# -- PR 15: replicated shards — (shard, replica) grid ------------------------


def _grid_supervisor(export_dir, n_shards=2, rps=2):
    """An UNSTARTED FleetSupervisor over a 2x2 grid — slot accounting
    is pure state, no child processes needed."""
    from gene2vec_tpu.serve.fleet import FleetConfig, FleetSupervisor

    total = n_shards * rps
    sup = FleetSupervisor(
        str(export_dir),
        config=FleetConfig(replicas=total),
        serve_args=["--cache-size", "0"],
        replica_args={3: ["--faults"]},
        shard_of={i: i // rps for i in range(total)},
        shard_args={
            s: ["--shard-index", str(s), "--num-shards", str(n_shards)]
            for s in range(n_shards)
        },
    )
    return sup


def test_grid_slot_accounting(export_dir):
    from gene2vec_tpu.serve.fleet import ReplicaState

    sup = _grid_supervisor(export_dir)
    assert [r.shard for r in sup.replicas] == [0, 0, 1, 1]
    # every slot of a shard spawns with ITS shard's flags; per-slot
    # args ride after them
    argv3 = sup._argv(3)
    i = argv3.index("--shard-index")
    assert argv3[i:i + 4] == ["--shard-index", "1",
                              "--num-shards", "2"]
    assert argv3[-1] == "--faults"
    assert "--shard-index" in sup._argv(0)
    # rotation/redundancy accounting over fabricated states
    for r, (url, state) in zip(sup.replicas, [
        ("http://a", ReplicaState.UP),
        ("http://b", ReplicaState.UP),
        ("http://c", ReplicaState.UP),
        ("http://d", ReplicaState.BACKOFF),
    ]):
        r.url, r.state = url, state
    assert sup.shard_urls(0) == ["http://a", "http://b"]
    assert sup.shard_urls(1) == ["http://c"]
    assert sup.shard_up_counts() == {0: 2, 1: 1}
    assert sup.active_count() == 4         # backoff still provisioned
    assert sup.active_count(shard=1) == 2
    states = sup.states()
    assert [s["shard"] for s in states] == [0, 0, 1, 1]


def test_shard_redundancy_facts_track_current_promise(export_dir):
    """desired is supervisor-truth, not the boot-time R: a DRAINING
    slot (deliberate scale-down) leaves the promise so the page never
    fires on policy, while backoff/ejected/FAILED slots keep counting
    — and a brand-new scale-up spawn (STARTING, restarts == 0) is not
    yet a promise, so growing a pool cannot fire the page either."""
    from gene2vec_tpu.serve.fleet import Replica, ReplicaState

    sup = _grid_supervisor(export_dir)
    for r, state in zip(sup.replicas, [
        ReplicaState.UP, ReplicaState.UP,
        ReplicaState.UP, ReplicaState.BACKOFF,
    ]):
        r.state = state
    # involuntary loss: dead sibling in backoff stays desired -> lost
    facts = sup.shard_redundancy_facts()
    assert facts == {0: {"up": 2, "desired": 2},
                     1: {"up": 1, "desired": 2}}
    # deliberate scale-down: the drained slot leaves the promise
    sup.replicas[1].state = ReplicaState.DRAINING
    facts = sup.shard_redundancy_facts()
    assert facts[0] == {"up": 1, "desired": 1}
    # storm-abandoned slot is a PERMANENT involuntary loss: keep paging
    sup.replicas[3].state = ReplicaState.FAILED
    assert sup.shard_redundancy_facts()[1] == {"up": 1, "desired": 2}
    # a scale-up spawn in its boot window is not yet part of the
    # promise (it has never served) ...
    new = Replica(4, shard=1)
    sup.replicas.append(new)
    assert sup.shard_redundancy_facts()[1] == {"up": 1, "desired": 2}
    # ... but a RESPAWNING slot (restarts > 0) holds the page until
    # its sibling is truly back
    new.restarts = 1
    assert sup.shard_redundancy_facts()[1] == {"up": 1, "desired": 3}


def test_grid_drain_victim_is_shard_scoped(export_dir):
    from gene2vec_tpu.serve.fleet import ReplicaState

    sup = _grid_supervisor(export_dir)
    for r in sup.replicas:
        r.url, r.state = f"http://r{r.index}", ReplicaState.UP
    # newest UP sibling of the requested shard — never another shard's
    v = sup.pick_drain_victim(shard=0)
    assert v is not None and v.index == 1 and v.shard == 0
    # the LAST up replica of a shard is never a victim: its rows must
    # stay served even if the whole fleet has spare capacity elsewhere
    sup.replicas[1].state = ReplicaState.DRAINING
    assert sup.pick_drain_victim(shard=0) is None
    # a dead sibling is the preferred (trivially zero-drop) victim
    sup.replicas[3].state = ReplicaState.BACKOFF
    v = sup.pick_drain_victim(shard=1)
    assert v is not None and v.index == 3


def test_grid_scale_up_joins_shard_pool(export_dir):
    """scale_up(shard=) registers the new slot in the shard's pool
    (spawn intercepted — slot accounting is the contract here)."""
    from gene2vec_tpu.serve.fleet import ReplicaState

    sup = _grid_supervisor(export_dir)
    for r in sup.replicas:
        r.url, r.state = f"http://r{r.index}", ReplicaState.UP

    spawned = []

    def fake_spawn(replica):
        spawned.append(replica.index)
        replica.url = f"http://new{replica.index}"
        replica.state = ReplicaState.STARTING

    sup._spawn = fake_spawn
    replica = sup.scale_up(shard=1)
    assert replica.shard == 1 and replica.index == 4
    assert spawned == [4]
    # the new slot inherits shard 1's flags for any future respawn
    assert sup._argv(4)[sup._argv(4).index("--shard-index") + 1] == "1"
    assert sup.active_count(shard=1) == 3
    replica.state = ReplicaState.UP
    assert sup.shard_urls(1) == ["http://r2", "http://r3",
                                 "http://new4"]


# -- within-deadline failover on a scatter leg (fake transport) --------------


def _topk_doc(epoch, rows, scores, tokens):
    return {
        "shard": {"index": 0, "num_shards": 1, "epoch": epoch,
                  "iteration": epoch},
        "results": [{"rows": rows, "scores": scores,
                     "tokens": tokens}],
    }


def test_scatter_leg_fails_over_to_sibling_within_deadline(export_dir):
    """A dead replica with a live SIBLING: the leg's client retries
    retry-safely onto the sibling inside the same leg deadline — the
    answer is complete, never degraded."""
    calls = []

    def transport(base_url, method, path, body, ct, rt, headers=None):
        calls.append((base_url, path))
        if "dead" in base_url:
            raise ConnectionRefusedError("sibling died")
        return 200, json.dumps(_topk_doc(
            1, [1, 2], [0.9, 0.8], ["G1", "G2"]
        )).encode()

    routing = RoutingTable(str(export_dir), 1)
    assert routing.reload()
    metrics = MetricsRegistry()
    group = ShardGroup(
        ShardGroupConfig(num_shards=1, shard_deadline_s=2.0,
                         default_timeout_s=5.0),
        lambda i: ["http://dead:1", "http://live:1"],
        metrics=metrics,
        policy=RetryPolicy(max_attempts=2, connect_timeout_s=0.5,
                           default_timeout_s=2.0, backoff_base_s=0.0),
        routing=routing,
        transport=transport,
    )
    group.current_epoch = 1
    status, doc = group.similar({"vectors": [[0.0] * D], "k": 2})
    assert status == 200
    assert doc["degraded"] is False, (
        "a single replica death with a live sibling must cost nothing"
    )
    assert doc["shards"]["answered"] == 1
    assert [c[0] for c in calls] == ["http://dead:1", "http://live:1"]
    assert metrics.counter("fleet_degraded_responses_total").value == 0


def test_scatter_all_siblings_dead_still_degrades(export_dir):
    """The whole replica group down: the PR-13 degraded contract is
    unchanged — the shard counts as unanswered, never a 5xx."""
    def transport(base_url, method, path, body, ct, rt, headers=None):
        if path == "/v1/shard/topk" and "s1" in base_url:
            raise ConnectionRefusedError("group fully down")
        return 200, json.dumps(_topk_doc(
            1, [1, 2], [0.9, 0.8], ["G1", "G2"]
        )).encode()

    routing = RoutingTable(str(export_dir), 2)
    assert routing.reload()
    metrics = MetricsRegistry()
    group = ShardGroup(
        ShardGroupConfig(num_shards=2, shard_deadline_s=1.0,
                         default_timeout_s=3.0),
        lambda i: [f"http://s{i}a:1", f"http://s{i}b:1"],
        metrics=metrics,
        policy=RetryPolicy(max_attempts=2, connect_timeout_s=0.2,
                           default_timeout_s=1.0, backoff_base_s=0.0),
        routing=routing,
        transport=transport,
    )
    group.current_epoch = 1
    status, doc = group.similar({"vectors": [[0.0] * D], "k": 2})
    assert status == 200
    assert doc["degraded"] is True
    assert doc["shards"]["answered"] == 1
    assert doc["shards"]["indexes"] == [0]


# -- the replicated fleet over real HTTP -------------------------------------


@pytest.fixture
def replicated_fleet(export_dir):
    """2 shards x 2 replicas as in-process HTTP apps + a ShardGroup
    whose per-shard target list is the live sibling set."""
    apps, servers, urls = [], [], {}
    for shard in range(2):
        for rep in range(2):
            reg = ModelRegistry(str(export_dir), shard=(shard, 2))
            assert reg.refresh()
            app = ServeApp(
                reg, config=ServeConfig(max_delay_ms=1.0)
            ).start()
            srv = make_server(app, "127.0.0.1", 0)
            threading.Thread(target=srv.serve_forever,
                             daemon=True).start()
            host, port = srv.server_address[:2]
            apps.append(app)
            servers.append(srv)
            urls.setdefault(shard, []).append(f"http://{host}:{port}")
    alive = {(s, r): True for s in range(2) for r in range(2)}

    routing = RoutingTable(str(export_dir), 2)
    assert routing.reload()
    metrics = MetricsRegistry()
    group = ShardGroup(
        ShardGroupConfig(num_shards=2, shard_deadline_s=2.0,
                         default_timeout_s=5.0),
        lambda i: [
            urls[i][r] for r in range(2) if alive[(i, r)]
        ],
        metrics=metrics,
        policy=RetryPolicy(max_attempts=2, connect_timeout_s=0.5,
                           default_timeout_s=2.0, backoff_base_s=0.0),
        routing=routing,
    )
    group.current_epoch = 1
    yield group, alive, metrics, urls, apps
    for app in apps:
        app.stop()
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def test_replicated_fleet_sibling_death_is_invisible(
    replicated_fleet, export_dir
):
    group, alive, metrics, _urls, _apps = replicated_fleet
    alive[(1, 0)] = False  # one sibling of shard 1 dies
    status, doc = group.similar({"genes": ["G3"], "k": 4})
    assert status == 200
    assert doc["degraded"] is False
    assert doc["shards"]["answered"] == 2
    got = [n["gene"] for n in doc["results"][0]["neighbors"]]
    assert got == _exact_reference(export_dir, "G3", 4)
    assert metrics.counter("fleet_degraded_responses_total").value == 0


def test_replicated_fleet_group_death_degrades(replicated_fleet,
                                               export_dir):
    group, alive, metrics, _urls, _apps = replicated_fleet
    alive[(1, 0)] = alive[(1, 1)] = False
    full = ModelRegistry(str(export_dir))
    full.refresh()
    q = list(map(float, full.model.emb[2]))
    status, doc = group.similar({"vectors": [q], "k": 4})
    assert status == 200
    assert doc["degraded"] is True
    assert doc["shards"]["indexes"] == [0]
    assert metrics.counter("fleet_degraded_responses_total").value == 1


def test_failover_leg_renders_as_siblings_under_proxy_scatter(
    replicated_fleet, tmp_path
):
    """The trace satellite: a failover scatter leg = TWO sibling
    client_attempt hops under ONE proxy_scatter span, and cli.obs
    trace's renderer shows both."""
    from gene2vec_tpu.obs import tracecontext as tc
    from gene2vec_tpu.obs.flight import collect_trace, format_trace
    from gene2vec_tpu.obs.trace import Tracer, set_tracer

    group, alive, _metrics, urls, _apps = replicated_fleet
    # shard 0's group: a refused-port "sibling" FIRST, so the leg's
    # round-robin pick hits it and fails over to the live one
    dead_first = dict(urls)
    dead_first[0] = ["http://127.0.0.1:9", urls[0][0]]
    group.url_for = lambda i: dead_first[i]
    run_dir = tmp_path / "trace_run"
    tracer = Tracer(str(run_dir / "events.jsonl"))
    set_tracer(tracer)
    try:
        full = ModelRegistry(str(group.routing.export_dir))
        full.refresh()
        q = [float(x) for x in full.model.emb[3]]
        ctx = tc.new_trace(sampled=True)
        with tc.use(ctx):
            # a VECTOR query: the whole request is one topk scatter, so
            # the shard-0 failover pair lands under proxy_scatter (a
            # gene query's resolution round would advance the client's
            # round-robin past the dead sibling first)
            status, doc = group.similar({"vectors": [q], "k": 3})
        assert status == 200 and not doc["degraded"]
    finally:
        set_tracer(None)
        tracer.close()
    trace = collect_trace(str(tmp_path), ctx.trace_id)
    assert trace["roots"], "trace did not reassemble"

    def scatter_attempts(node):
        found = []

        def walk(n, under_scatter):
            name = n.get("name")
            if name == "client_attempt" and under_scatter:
                found.append(n)
            nxt = under_scatter or name == "proxy_scatter"
            for s in n.get("process_spans", []):
                walk(s, nxt)
            for c in n.get("children", []):
                walk(c, nxt)

        walk(node, False)
        return found

    attempts = [
        a for root in trace["roots"] for a in scatter_attempts(root)
    ]
    # >= 2 on the failed-over shard 0 leg + 1 on shard 1's leg; the
    # failover pair shares the scatter ancestor, i.e. siblings
    assert len(attempts) >= 3, (
        f"expected the failover pair + shard 1's leg, got "
        f"{len(attempts)} client_attempts"
    )
    statuses = sorted(
        (a.get("attrs") or {}).get("status", -1) for a in attempts
    )
    assert 0 in statuses and 200 in statuses, (
        "the dead-pick attempt (status 0) and the sibling's success "
        f"must BOTH render (got {statuses})"
    )
    rendered = format_trace(trace)
    assert "proxy_scatter" in rendered
    assert rendered.count("client_attempt") >= 3


def test_swap_stages_and_flips_every_grid_cell(replicated_fleet,
                                               export_dir):
    group, _alive, metrics, _urls, apps = replicated_fleet
    coord = SwapCoordinator(
        str(export_dir), group, interval_s=0.1, metrics=metrics
    )
    coord.tick()
    assert group.current_epoch == 1
    _write_iteration(export_dir, 2, seed=2)
    coord.tick()
    assert group.current_epoch == 2
    for app in apps:  # all FOUR cells flipped under the one token
        assert app.registry.model.epoch == 2
    assert metrics.counter("fleet_swap_flips_total").value == 1


def test_swap_proceeds_with_dead_sibling_then_repairs(
    replicated_fleet, export_dir
):
    """One replica down with a live sibling does NOT defer the swap
    (the sibling flips with the fleet); the dead cell is repaired —
    staged + flipped to the fleet epoch — once it returns."""
    group, alive, metrics, _urls, apps = replicated_fleet
    coord = SwapCoordinator(
        str(export_dir), group, interval_s=0.1, metrics=metrics
    )
    coord.tick()
    alive[(0, 1)] = False  # one sibling of shard 0 is down
    _write_iteration(export_dir, 2, seed=2)
    coord.tick()
    assert group.current_epoch == 2, (
        "a dead REPLICA with a live sibling must not defer the swap"
    )
    assert metrics.counter("fleet_swap_deferred_total").value == 0
    # cells: shard0-rep0, shard1-rep0, shard1-rep1 flipped; the dead
    # sibling still serves the old epoch
    assert apps[0].registry.model.epoch == 2
    assert apps[2].registry.model.epoch == 2
    assert apps[3].registry.model.epoch == 2
    assert apps[1].registry.model.epoch == 1
    # it returns: the repair pass converges it without a new swap
    alive[(0, 1)] = True
    coord.tick()
    assert apps[1].registry.model.epoch == 2
    assert metrics.counter("fleet_swap_repairs_total").value == 1


def test_swap_deferred_while_whole_group_down(replicated_fleet,
                                              export_dir):
    group, alive, metrics, _urls, apps = replicated_fleet
    coord = SwapCoordinator(
        str(export_dir), group, interval_s=0.1, metrics=metrics
    )
    coord.tick()
    alive[(1, 0)] = alive[(1, 1)] = False
    _write_iteration(export_dir, 2, seed=2)
    coord.tick()
    assert group.current_epoch == 1
    assert metrics.counter("fleet_swap_deferred_total").value == 1
    for app in apps:
        assert app.registry.model.epoch == 1


def test_shard_states_carry_replica_groups(replicated_fleet):
    group, alive, *_ = replicated_fleet
    alive[(1, 1)] = False
    states = group.shard_states(
        replicas_for=lambda i: [
            {"index": 2 * i + r, "up": alive[(i, r)], "epoch": 1}
            for r in range(2)
        ],
    )
    assert [s["up"] for s in states] == [True, True]
    assert [r["up"] for r in states[1]["replicas"]] == [True, False]
    assert states[0]["rows"] == list(shard_ranges(V, 2)[0])


# -- cross-shard /v1/interaction ---------------------------------------------


def _save_head_checkpoint(tmp_path, export_dir, batch_size=8):
    """A ggipnn_obs-format checkpoint whose head weights are real
    (trainer-initialized) values — the parity tests load it on BOTH
    scorers so the heads are identical by construction."""
    from gene2vec_tpu.config import GGIPNNConfig
    from gene2vec_tpu.models.ggipnn_data import PairTextVocab
    from gene2vec_tpu.models.ggipnn_obs import _flatten_params
    from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer

    full = ModelRegistry(str(export_dir))
    full.refresh()
    m = full.model
    vocab = PairTextVocab()
    vocab.token_to_id = dict(m.index)
    vocab.id_to_token = list(m.tokens)
    trainer = GGIPNNTrainer(
        GGIPNNConfig(embedding_dim=D, batch_size=batch_size, seed=7),
        vocab,
    )
    params, _ = trainer.init_state()
    flat = _flatten_params(dict(params))
    path = tmp_path / "ggipnn_head.npz"
    np.savez(str(path), **{k: np.asarray(v) for k, v in flat.items()})
    return str(path), m


def test_cross_shard_scorer_parity_with_unsharded(tmp_path, export_dir):
    """The acceptance bar: CrossShardScorer over shard-resolved
    vectors == InteractionScorer over the full served table, same
    head checkpoint, same pairs."""
    from gene2vec_tpu.serve.interaction import (
        CrossShardScorer,
        InteractionScorer,
    )

    ckpt, m = _save_head_checkpoint(tmp_path, export_dir)
    ref = InteractionScorer(m, checkpoint_path=ckpt)
    assert ref.trained
    pairs = [("G0", "G23"), ("G5", "G12"), ("G7", "G7")]
    want = ref.score(pairs)

    xs = CrossShardScorer(D, checkpoint_path=ckpt, max_pairs=8,
                          batch_size=8)
    assert xs.trained
    got = xs.score_vectors([
        (m.emb[m.index[a]], m.emb[m.index[b]]) for a, b in pairs
    ])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_cross_shard_scorer_rejects_wrong_dim(tmp_path, export_dir):
    from gene2vec_tpu.serve.interaction import CrossShardScorer

    ckpt, _ = _save_head_checkpoint(tmp_path, export_dir)
    with pytest.raises(ValueError):
        CrossShardScorer(D + 1, checkpoint_path=ckpt)


def test_group_interaction_parity_over_live_shards(
    replicated_fleet, tmp_path, export_dir
):
    """End to end over real HTTP: the front door resolves each gene's
    vector from its owner group and scores — equal to the unsharded
    replica's answer for pairs that SPAN shards."""
    from gene2vec_tpu.serve.interaction import InteractionScorer

    group, _alive, metrics, _urls, _apps = replicated_fleet
    ckpt, m = _save_head_checkpoint(tmp_path, export_dir)
    group.ggipnn_checkpoint = ckpt
    # G1 owns shard 0, G20 shard 1: the pair spans the partition
    pairs = [["G1", "G20"], ["G0", "G3"], ["G22", "G23"]]
    status, doc = group.interaction({"pairs": pairs})
    assert status == 200
    assert doc["trained_head"] is True
    assert doc.get("degraded") is False
    assert doc["model"]["iteration"] == 1
    ref = InteractionScorer(m, checkpoint_path=ckpt)
    want = ref.score([tuple(p) for p in pairs])
    got = [s["score"] for s in doc["scores"]]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert metrics.counter(
        "fleet_interaction_pairs_total"
    ).value == len(pairs)


def test_group_interaction_degrades_when_owner_group_down(
    replicated_fleet, tmp_path, export_dir
):
    group, alive, metrics, _urls, _apps = replicated_fleet
    ckpt, _ = _save_head_checkpoint(tmp_path, export_dir)
    group.ggipnn_checkpoint = ckpt
    alive[(1, 0)] = alive[(1, 1)] = False  # shard 1's group fully down
    status, doc = group.interaction(
        {"pairs": [["G1", "G20"], ["G0", "G3"]]}
    )
    assert status == 200, "an owner-group outage must not 5xx"
    assert doc["degraded"] is True
    # the cross-partition pair is honestly unscored; the shard-0 pair
    # still answers
    assert doc["scores"][0]["score"] is None
    assert doc["scores"][0]["degraded"] is True
    assert isinstance(doc["scores"][1]["score"], float)
    assert metrics.counter(
        "fleet_degraded_responses_total"
    ).value == 1


def test_group_interaction_validation_and_unknown_gene(
    replicated_fleet, tmp_path, export_dir
):
    group, _alive, *_ = replicated_fleet
    ckpt, _ = _save_head_checkpoint(tmp_path, export_dir)
    group.ggipnn_checkpoint = ckpt
    assert group.interaction({})[0] == 400
    assert group.interaction({"pairs": []})[0] == 400
    assert group.interaction({"pairs": [["G1"]]})[0] == 400
    # non-string pair elements are a CLIENT error: without the
    # string check they'd TypeError in the dedup set and surface as
    # a 500 server-error signal
    assert group.interaction({"pairs": [[["G1"], "G2"]]})[0] == 400
    assert group.interaction({"pairs": [["G1", 7]]})[0] == 400
    status, doc = group.interaction({"pairs": [["G1", "NOPE"]]})
    assert status == 400 and "NOPE" in doc["error"]


def test_group_interaction_all_owners_dead_is_503(
    replicated_fleet, tmp_path, export_dir
):
    group, alive, *_ = replicated_fleet
    ckpt, _ = _save_head_checkpoint(tmp_path, export_dir)
    group.ggipnn_checkpoint = ckpt
    for key in alive:
        alive[key] = False
    status, doc = group.interaction({"pairs": [["G1", "G20"]]})
    assert status == 503
    assert doc["shards"]["answered"] == 0


# -- per-shard autoscaling ---------------------------------------------------


def _shard_snap(q0=0.0, q1=0.0, fresh=4.0, p99=None):
    snap = {
        "fleet_shard_queue_depth{shard=0}": q0,
        "fleet_shard_queue_depth{shard=1}": q1,
        "_fresh_targets": fresh,
    }
    if p99 is not None:
        snap["fleet_shard_p99_seconds{shard=0}"] = p99
    return snap


def test_shard_policy_scales_the_hot_shard_only():
    from gene2vec_tpu.serve.autoscale import (
        AutoscaleConfig,
        ShardAutoscalePolicy,
    )

    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_queue_per_replica=8.0,
        up_after_ticks=2, down_after_ticks=5, cooldown_s=0.0,
    )
    pol = ShardAutoscalePolicy(cfg, num_shards=2)
    current = {0: 2, 1: 2}
    # tick 1 seeds baselines, tick 2-3 breach shard 1 only
    for t in range(3):
        d = pol.observe(
            _shard_snap(q0=1.0 * 2, q1=40.0), now=float(t),
            current_of=current,
        )
    assert d.action == "up" and d.shard == 1 and d.target == 3
    # shard 0 never breached: its policy holds
    d0 = pol.policies[0].observe(
        {"fleet_queue_depth": 2.0, "_fresh_targets": 4.0},
        now=4.0, current=2,
    )
    assert d0.action == "hold"


def test_shard_policy_scale_down_never_below_min():
    from gene2vec_tpu.serve.autoscale import (
        AutoscaleConfig,
        ShardAutoscalePolicy,
    )

    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_queue_per_replica=8.0,
        down_queue_per_replica=1.0, up_after_ticks=2,
        down_after_ticks=3, cooldown_s=0.0,
    )
    pol = ShardAutoscalePolicy(cfg, num_shards=2)
    # shard 0 idle with 2 replicas, shard 1 idle with 1 (at min)
    d = None
    for t in range(5):
        d = pol.observe(
            _shard_snap(q0=0.0, q1=0.0), now=float(t),
            current_of={0: 2, 1: 1},
        )
        if d.action != "hold":
            break
    assert d.action == "down" and d.shard == 0 and d.target == 1
    # with every pool at min, clear windows decide nothing
    pol2 = ShardAutoscalePolicy(cfg, num_shards=2)
    for t in range(6):
        d = pol2.observe(
            _shard_snap(), now=float(t), current_of={0: 1, 1: 1}
        )
    assert d.action == "hold"


def test_shard_policy_dark_shard_holds_not_drains():
    """A shard whose replicas all stop reporting (its queue key is
    ABSENT from the snapshot, not 0.0) must HOLD: the fleet-wide
    freshness guard can't see one dark shard among fresh ones, and
    reading absence as 'idle' would drain exactly the pool the
    controller is blind to."""
    from gene2vec_tpu.serve.autoscale import (
        AutoscaleConfig,
        ShardAutoscalePolicy,
        shard_snapshot,
    )

    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_queue_per_replica=8.0,
        down_queue_per_replica=1.0, up_after_ticks=2,
        down_after_ticks=3, cooldown_s=0.0,
    )
    # shard 1 dark: only shard 0's key exists; fleet freshness is high
    snap = {"fleet_shard_queue_depth{shard=0}": 0.0,
            "_fresh_targets": 4.0}
    sub = shard_snapshot(snap, 1, cfg.p99_route)
    assert sub["_fresh_targets"] == 0.0
    pol = ShardAutoscalePolicy(cfg, num_shards=2)
    d = None
    for t in range(6):
        d = pol.observe(snap, now=float(t), current_of={0: 2, 1: 2})
        if d.action != "hold":
            break
    # the observable idle pool scales down; the dark one never does
    assert d.action == "down" and d.shard == 0
    assert pol.policies[1].observe(
        sub, now=99.0, current=2
    ).action == "hold"


def test_shard_policy_stale_snapshot_holds():
    from gene2vec_tpu.serve.autoscale import (
        AutoscaleConfig,
        ShardAutoscalePolicy,
    )

    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_after_ticks=2,
        cooldown_s=0.0,
    )
    pol = ShardAutoscalePolicy(cfg, num_shards=2)
    for t in range(4):
        d = pol.observe(
            _shard_snap(q1=100.0, fresh=0.0), now=float(t),
            current_of={0: 1, 1: 1},
        )
        assert d.action == "hold"
        assert "stale" in d.reason


def test_shard_controller_applies_shard_scoped_actions():
    from gene2vec_tpu.obs.registry import MetricsRegistry as MR
    from gene2vec_tpu.serve.autoscale import (
        AutoscaleConfig,
        ShardElasticController,
    )
    from gene2vec_tpu.serve.client import InFlightTracker
    from gene2vec_tpu.serve.fleet import ReplicaState

    class GridFake:
        def __init__(self):
            self.counts = {0: 1, 1: 1}
            self.calls = []
            from gene2vec_tpu.serve.fleet import FleetConfig
            self.config = FleetConfig(contract_timeout_s=2.0)

        def active_count(self, shard=None):
            if shard is None:
                return sum(self.counts.values())
            return self.counts[shard]

        def scale_up(self, shard=None):
            self.calls.append(("up", shard))
            self.counts[shard] += 1
            return type("R", (), {
                "url": "http://new", "state": ReplicaState.UP,
                "alive": True, "spawning": False, "index": 9,
                "shard": shard,
            })()

        def pick_drain_victim(self, shard=None):
            self.calls.append(("victim", shard))
            return None

    class P:
        inflight = InFlightTracker()

    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=2, up_queue_per_replica=8.0,
        up_after_ticks=2, cooldown_s=0.0,
    )
    sup = GridFake()
    mr = MR()
    ctrl = ShardElasticController(
        sup, P(), cfg, num_shards=2, metrics=mr,
    )
    import time as _t
    for _ in range(3):
        ctrl.observe(_shard_snap(q1=50.0))
    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline and ("up", 1) not in sup.calls:
        _t.sleep(0.01)
    assert ("up", 1) in sup.calls, (
        "the hot shard's pool never got its sibling"
    )
    assert sup.counts == {0: 1, 1: 2}
    # the gauge pair stays fleet-wide comparable: shard 1's pool
    # target 1 -> 2 publishes as fleet 2 -> 3, never active=2/target=2
    # of one pool masquerading as the fleet
    assert mr.gauge("fleet_replicas_target").value == 3
    # every pool's active gauge refreshes on the next tick — not just
    # the deciding shard's, and not frozen at the pre-action size
    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline:
        ctrl.observe(_shard_snap())
        if mr.gauge("fleet_shard_replicas_active",
                    labels={"shard": "1"}).value == 2:
            break
        _t.sleep(0.01)
    assert mr.gauge("fleet_shard_replicas_active",
                    labels={"shard": "0"}).value == 1
    assert mr.gauge("fleet_shard_replicas_active",
                    labels={"shard": "1"}).value == 2
    ctrl.stop()


# -- aggregator per-shard signals + the redundancy alert ---------------------


def test_aggregator_exports_per_shard_signals():
    from gene2vec_tpu.obs.aggregate import FleetAggregator

    expos = {
        "http://s0a": (
            "serve_queue_depth 3\n"
            'serve_route_seconds_bucket{route="/v1/shard/topk",'
            'le="0.1"} 90\n'
            'serve_route_seconds_bucket{route="/v1/shard/topk",'
            'le="+Inf"} 100\n'
        ),
        "http://s0b": "serve_queue_depth 2\n",
        "http://s1a": (
            "serve_queue_depth 10\n"
            'serve_route_seconds_bucket{route="/v1/shard/topk",'
            'le="0.1"} 5\n'
            'serve_route_seconds_bucket{route="/v1/shard/topk",'
            'le="0.5"} 20\n'
            'serve_route_seconds_bucket{route="/v1/shard/topk",'
            'le="+Inf"} 20\n'
        ),
    }
    agg = FleetAggregator(
        list(expos), interval_s=0, fetch=lambda u, t: expos[u],
    )
    agg.shard_of = lambda u: 0 if "s0" in u else 1
    agg.shard_facts = lambda: {
        0: {"up": 2, "desired": 2}, 1: {"up": 1, "desired": 2},
    }
    snaps = []
    agg.observers.append(lambda snap, wall=None: snaps.append(snap))
    agg.scrape_once()
    snap = snaps[-1]
    assert snap["fleet_shard_queue_depth{shard=0}"] == 5.0
    assert snap["fleet_shard_queue_depth{shard=1}"] == 10.0
    # shard 0's p99 lands in the first bucket; shard 1's in the second
    assert snap["fleet_shard_p99_seconds{shard=0}"] == 0.1
    assert snap["fleet_shard_p99_seconds{shard=1}"] == 0.5
    assert snap["fleet_shard_replicas_up{shard=0}"] == 2.0
    assert snap["fleet_shard_replicas_up{shard=1}"] == 1.0
    # shard 1 is one failure from recall loss: redundancy lost
    assert snap["fleet_shards_redundancy_lost"] == 1.0
    text = agg.fleet_text()
    assert 'fleet_shard_replicas_up{shard="1"} 1' in text
    assert "fleet_shards_redundancy_lost 1" in text
    # shard 1 stops reporting (its only target dies): the queue gauge
    # retires on the first missed round, the p99 gauge once the target
    # goes stale — a dead shard must not freeze its last values on
    # /metrics/fleet (supervisor-truth replicas_up stays)
    expos.pop("http://s1a")
    for _ in range(4):
        agg.scrape_once()
    text = agg.fleet_text()
    assert 'fleet_shard_queue_depth{shard="1"}' not in text
    assert 'fleet_shard_p99_seconds{shard="1"}' not in text
    assert 'fleet_shard_queue_depth{shard="0"}' in text
    assert 'fleet_shard_replicas_up{shard="1"}' in text


def test_aggregator_without_shard_hooks_emits_no_shard_keys():
    from gene2vec_tpu.obs.aggregate import FleetAggregator

    agg = FleetAggregator(
        ["http://a"], interval_s=0,
        fetch=lambda u, t: "serve_queue_depth 1\n",
    )
    snaps = []
    agg.observers.append(lambda snap, wall=None: snaps.append(snap))
    agg.scrape_once()
    assert not any("shard" in k for k in snaps[-1])


def test_shard_redundancy_lost_rule_fires_and_clears():
    from gene2vec_tpu.obs.alerts import AlertEvaluator, default_rules

    rules = [
        r for r in default_rules()
        if r.name == "shard-redundancy-lost"
    ]
    assert rules, "default rules lost the shard-redundancy-lost rule"
    clock = {"t": 0.0}
    ev = AlertEvaluator(rules, clock=lambda: clock["t"])

    def tick(value, dt=1.0):
        clock["t"] += dt
        snap = {"_fresh_targets": 2.0}
        if value is not None:
            snap["fleet_shards_redundancy_lost"] = value
        return ev.observe(snap, now=clock["t"])

    # unsharded fleet: the selector is absent — holds forever
    assert tick(None) == []
    assert ev.states()["shard-redundancy-lost"] == "inactive"
    # a sibling dies: fires immediately (for_s = 0)
    recs = tick(1.0)
    assert any(r["to"] == "firing" for r in recs)
    # still down during the full-group outage: keeps firing
    assert tick(2.0) == []
    assert ev.states()["shard-redundancy-lost"] == "firing"
    # re-admit: clears after the clear window
    tick(0.0)
    recs = tick(0.0, dt=15.0)
    assert any(r["to"] == "inactive" for r in recs)


# -- the failover gate -------------------------------------------------------


def test_passes_shard_failover_degraded_with_live_replica_gates(
    tmp_path,
):
    doc = _good_shard_doc()
    doc["shard"]["drill"]["failover"]["degraded_responses"] = 3
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "LIVE" in fs[0].message


def test_passes_shard_missing_failover_section_gates(tmp_path):
    doc = _good_shard_doc()
    del doc["shard"]["drill"]["failover"]
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "failover" in fs[0].message


def test_passes_shard_failover_p99_and_both_dead_gate(tmp_path):
    doc = _good_shard_doc()
    doc["shard"]["drill"]["failover"]["p99_ms"] = 9000.0
    doc["shard"]["drill"]["failover"]["both_dead"][
        "degraded_responses"] = 0
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1
    assert "p99" in fs[0].message and "both-dead" in fs[0].message


def test_passes_shard_failover_off_recipe_gates(tmp_path):
    doc = _good_shard_doc()
    doc["shard"]["drill"]["failover"]["replicas_per_shard"] = 1
    fs = _gating(_findings(tmp_path, doc))
    assert len(fs) == 1 and "replicas per shard" in fs[0].message


# -- loadgen grid parsing ----------------------------------------------------


def test_parse_shard_grid_learns_replica_groups(loadgen):
    health = {
        "shards": [
            {"index": 0, "rows": [0, 12], "up": True,
             "replicas": [{"index": 0, "up": True, "epoch": 1},
                          {"index": 1, "up": False, "epoch": 1}]},
            {"index": 1, "rows": [12, 24], "up": True,
             "replicas": [{"index": 2, "up": True, "epoch": 1},
                          {"index": 3, "up": True, "epoch": 1}]},
        ],
    }
    ranges, replicas = loadgen.parse_shard_grid(health)
    assert ranges == {0: (0, 12), 1: (12, 24)}
    assert replicas == {0: 2, 1: 2}
    # pre-grid healthz (no replicas key): one replica per shard
    for s in health["shards"]:
        del s["replicas"]
    _, replicas = loadgen.parse_shard_grid(health)
    assert replicas == {0: 1, 1: 1}
    assert loadgen.parse_shard_grid({"status": "ok"}) is None


def test_fleet_cli_validates_grid_flags(tmp_path, capsys):
    from gene2vec_tpu.cli import fleet as fleet_cli

    base = ["--export-dir", str(tmp_path)]
    # replicas-per-shard needs shard mode
    assert fleet_cli.main(base + ["--replicas-per-shard", "2"]) == 2
    assert fleet_cli.main(
        base + ["--shard-by-rows", "2", "--replicas-per-shard", "0"]
    ) == 2
    # sharded autoscale bounds apply PER SHARD POOL
    assert fleet_cli.main(
        base + ["--shard-by-rows", "2", "--replicas-per-shard", "3",
                "--max-replicas", "2"]
    ) == 2
    # a missing head checkpoint fails in milliseconds, not after spawns
    assert fleet_cli.main(
        base + ["--shard-by-rows", "2",
                "--ggipnn-checkpoint", str(tmp_path / "nope.npz")]
    ) == 2
    capsys.readouterr()
