"""CLI front-end parity tests (reference invocation shapes, SURVEY §5)."""

import numpy as np
import pytest

from gene2vec_tpu.cli import evaluate as evaluate_cli
from gene2vec_tpu.cli import gene2vec as gene2vec_cli
from gene2vec_tpu.cli import ggipnn as ggipnn_cli
from gene2vec_tpu.io.emb_io import write_word2vec_format


def test_gene2vec_cli_positional_shape(tmp_path, synthetic_corpus_dir, capsys):
    """Reference invocation: gene2vec <data_dir> <out_dir> txt."""
    out = tmp_path / "emb"
    rc = gene2vec_cli.main(
        [
            synthetic_corpus_dir,
            str(out),
            "txt",
            "--dim=8",
            "--iters=2",
            "--batch-pairs=64",
        ]
    )
    assert rc == 0
    assert (out / "gene2vec_dim_8_iter_2.txt").exists()
    assert (out / "gene2vec_dim_8_iter_2_w2v.txt").exists()
    assert (out / "vocab.tsv").exists()


def test_gene2vec_cli_numpy_backend(tmp_path, synthetic_corpus_dir):
    out = tmp_path / "emb_np"
    rc = gene2vec_cli.main(
        [
            synthetic_corpus_dir,
            str(out),
            "txt",
            "--backend=numpy",
            "--dim=8",
            "--iters=1",
        ]
    )
    assert rc == 0
    assert (out / "gene2vec_dim_8_iter_1.npz").exists()


def test_gene2vec_cli_gensim_backend_gated(tmp_path, synthetic_corpus_dir):
    try:
        import gensim  # noqa: F401

        pytest.skip("gensim installed; gating not exercised")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="gensim"):
        gene2vec_cli.main(
            [synthetic_corpus_dir, str(tmp_path / "x"), "txt", "--backend=gensim"]
        )


def test_gene2vec_cli_vocab_sharded_mesh(tmp_path, synthetic_corpus_dir):
    """BASELINE config 5 path through the CLI on the 8-device CPU mesh."""
    out = tmp_path / "emb_sharded"
    rc = gene2vec_cli.main(
        [
            synthetic_corpus_dir,
            str(out),
            "txt",
            "--dim=16",
            "--iters=1",
            "--batch-pairs=64",
            "--vocab-sharded",
            "--mesh-model=2",
        ]
    )
    assert rc == 0
    assert (out / "gene2vec_dim_16_iter_1.npz").exists()


def test_evaluate_cli(tmp_path, capsys):
    """Pathway genes trained similar → score > 1."""
    rng = np.random.RandomState(0)
    toks = [f"G{i}" for i in range(50)]
    mat = rng.randn(50, 8).astype(np.float32)
    mat[:10] = rng.randn(1, 8) + 0.05 * rng.randn(10, 8)  # pathway cluster
    emb = tmp_path / "emb_w2v.txt"
    write_word2vec_format(str(emb), toks, mat)
    gmt = tmp_path / "p.gmt"
    gmt.write_text(
        "PATH1\thttp://x\t" + "\t".join(toks[:10]) + "\n"
        "TOOBIG\thttp://x\t" + "\t".join(f"G{i}" for i in range(60)) + "\n"
    )
    rc = evaluate_cli.main([str(emb), str(gmt)])
    assert rc == 0
    score = float(capsys.readouterr().out.strip())
    assert score > 1.0


def test_ggipnn_cli_end_to_end(tmp_path, capsys):
    """predictionData/-shaped splits → printed AUC line."""
    rng = np.random.RandomState(0)
    d = tmp_path / "pred"
    d.mkdir()
    genes = [f"g{i}" for i in range(30)]

    def write_split(name, n):
        xs, ys = [], []
        for _ in range(n):
            a, b = rng.randint(0, 30, 2)
            xs.append(f"{genes[a]} {genes[b]}")
            ys.append(str(int(a < 15 and b < 15)))
        (d / f"{name}_text.txt").write_text("\n".join(xs) + "\n")
        (d / f"{name}_label.txt").write_text("\n".join(ys) + "\n")

    write_split("train", 300)
    write_split("valid", 60)
    write_split("test", 60)

    emb = tmp_path / "emb.txt"
    mat = rng.randn(30, 8).astype(np.float32)
    write_word2vec_format(str(emb), genes, mat)

    rc = ggipnn_cli.main(
        [
            "--data-dir", str(d),
            "--emb", str(emb),
            "--embedding-dim=8",
            "--num-epochs=2",
            "--batch-size=32",
            "--evaluate-every=1000000",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "The AUC score is" in out
