"""Batch plane (gene2vec_tpu/batch/): the chunk-commit artifact
protocol, SIGKILL/interrupt-resume bit-identity for every job type, the
job manager + /v1/jobs dispatch, the background-priority machinery
(FairQueue weights, Pacer yield guard, tenant-tagged scatter legs), the
precomputed-graph intrinsic eval, and the passes_batch budget gate
(docs/BATCH.md)."""

import base64
import json
import os
import threading
import time
import types
import zlib

import numpy as np
import pytest

import jax.numpy as jnp

from gene2vec_tpu.batch.artifact import (
    CURSOR_NAME,
    CURSOR_PREV_NAME,
    DATA_NAME,
    MANIFEST_NAME,
    TOKENS_NAME,
    ChunkedArtifact,
    load_graph,
    pack_graph_rows,
    unpack_graph,
    write_fetched_artifact,
)
from gene2vec_tpu.batch.jobs import JobManager, JobSpec, dispatch_jobs
from gene2vec_tpu.batch.runner import (
    ChunkFailed,
    EngineBackend,
    JobCancelled,
    Pacer,
    ShardGroupBackend,
    run_job,
)
from gene2vec_tpu.serve.engine import SimilarityEngine
from gene2vec_tpu.serve.registry import LoadedModel, l2_normalize
from gene2vec_tpu.serve.tenancy import (
    BATCH_TENANT,
    DEFAULT_BATCH_WEIGHT,
    FairQueue,
)

V, D, K = 24, 6, 4


def _model(v=V, d=D, iteration=1, seed=0):
    emb = np.random.RandomState(seed).randn(v, d).astype(np.float32)
    tokens = tuple(f"G{i}" for i in range(v))
    return LoadedModel(
        dim=d, iteration=iteration, tokens=tokens,
        index={t: i for i, t in enumerate(tokens)},
        emb=emb, unit=jnp.asarray(l2_normalize(emb)),
        source="synthetic", meta={},
    )


def _backend(model=None):
    return EngineBackend(
        model if model is not None else _model(),
        SimilarityEngine(max_batch=8),
    )


def _spec(kind="knn_graph", **kw):
    body = {"type": kind, "k": K, "chunk_rows": 4}
    body.update(kw)
    return JobSpec.from_body(body)


def _read(path):
    with open(path, "rb") as f:
        return f.read()


# -- artifact commit protocol -------------------------------------------------


def test_pack_unpack_graph_roundtrip():
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 100, size=(5, K)).astype(np.int32)
    scores = rng.rand(5, K).astype(np.float32)
    out_ids, out_scores = unpack_graph(pack_graph_rows(ids, scores), K)
    np.testing.assert_array_equal(out_ids, ids)
    np.testing.assert_array_equal(out_scores, scores)
    with pytest.raises(ValueError, match="matching"):
        pack_graph_rows(ids, scores[:3])


def test_artifact_truncates_torn_tail(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"aaaa", 1)
    art.append_chunk(b"bbbb", 1)
    committed = _read(art.data_path)
    # the writer died mid-append: bytes on disk past the committed
    # cursor offset, no cursor commit
    with open(art.data_path, "ab") as f:
        f.write(b"torn!")
    art2 = ChunkedArtifact(d)
    assert art2.records_done == 2 and art2.data_bytes == 8
    assert _read(art2.data_path) == committed


def test_artifact_rotted_cursor_falls_back_one_commit(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"aaaa", 1)
    art.append_chunk(b"bbbb", 1)
    # CURSOR.json rots after the second commit; CURSOR.prev.json still
    # holds the first — recovery truncates back one chunk, never zero
    with open(os.path.join(d, CURSOR_NAME), "w") as f:
        f.write("{not json")
    art2 = ChunkedArtifact(d)
    assert art2.records_done == 1 and art2.data_bytes == 4
    assert _read(art2.data_path) == b"aaaa"


def test_artifact_both_cursors_lost_refuses(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"aaaa", 1)
    os.unlink(os.path.join(d, CURSOR_NAME))
    assert not os.path.exists(os.path.join(d, CURSOR_PREV_NAME))
    with pytest.raises(IOError, match="refusing to truncate"):
        ChunkedArtifact(d)


def test_artifact_post_commit_rot_detected(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"aaaabbbb", 2)
    with open(art.data_path, "r+b") as f:
        f.seek(2)
        f.write(b"X")  # flip a committed byte: CRC must catch it
    with pytest.raises(IOError, match="CRC mismatch"):
        ChunkedArtifact(d)


def test_artifact_data_truncated_below_commit_refuses(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"aaaabbbb", 2)
    with open(art.data_path, "r+b") as f:
        f.truncate(4)
    with pytest.raises(IOError, match="truncated after commit"):
        ChunkedArtifact(d)


def test_artifact_finalize_idempotent_and_verify(tmp_path):
    d = str(tmp_path / "job")
    art = ChunkedArtifact(d)
    art.append_chunk(b"abcd", 1)
    p1 = art.finalize({"type": "export"})
    p2 = art.finalize({"type": "export"})
    assert p1 == p2 and art.verify()
    with pytest.raises(IOError, match="already finalized"):
        art.append_chunk(b"more", 1)
    with open(art.data_path, "r+b") as f:
        f.seek(0)
        f.write(b"Z")
    # a reader must not trust rotted bytes (the open handle re-reads
    # the file; a fresh open refuses at the cursor-CRC layer already)
    assert not art.verify()


def test_write_fetched_artifact_rejects_bad_crc(tmp_path):
    with pytest.raises(IOError, match="CRC"):
        write_fetched_artifact(
            str(tmp_path / "f"), b"data", {}, 1, 1, data_crc32=12345,
        )
    assert not os.path.exists(str(tmp_path / "f" / DATA_NAME))
    good = zlib.crc32(b"data") & 0xFFFFFFFF
    write_fetched_artifact(
        str(tmp_path / "g"), b"data", {"type": "export"}, 1, 1,
        data_crc32=good, tokens_bytes=b"G0\n",
    )
    art = ChunkedArtifact(str(tmp_path / "g"))
    assert art.verify() and art.records_done == 1


# -- interrupt-resume bit-identity, every job type ---------------------------


def _interrupt_then_resume(tmp_path, spec, make_backend, stop_after=2):
    """Run the job until ``stop_after`` chunks committed, cancel, tear
    the tail (the SIGKILL-mid-append shape), resume in a fresh
    artifact handle, and return (resumed DATA.bin, control DATA.bin,
    resume result)."""
    d = str(tmp_path / "interrupted")
    art = ChunkedArtifact(d)

    with pytest.raises(JobCancelled):
        run_job(
            spec, make_backend(), art,
            should_stop=lambda: art.chunks_done >= stop_after,
        )
    assert 0 < art.records_done
    with open(art.data_path, "ab") as f:
        f.write(b"\x00\x01torn")  # died mid-append after the cancel point
    art2 = ChunkedArtifact(d)
    assert art2.chunks_done == stop_after
    result = run_job(spec, make_backend(), art2)
    assert result["resumed_records"] == art.records_done
    control = ChunkedArtifact(str(tmp_path / "control"))
    run_job(spec, make_backend(), control)
    return _read(art2.data_path), _read(control.data_path), result


def test_knn_graph_resume_bit_identical(tmp_path):
    resumed, control, result = _interrupt_then_resume(
        tmp_path, _spec("knn_graph"), _backend,
    )
    assert resumed == control
    assert result["records"] == V and result["chunks"] == -(-V // 4)
    # and the tokens sidecar written before chunk 0 survived the resume
    tokens, ids, scores, meta = load_graph(str(tmp_path / "interrupted"))
    assert tokens == [f"G{i}" for i in range(V)]
    assert ids.shape == (V, K) and meta["type"] == "knn_graph"
    assert not (ids == np.arange(V)[:, None]).any()  # self excluded


def test_pair_scores_resume_bit_identical(tmp_path):
    pairs = [[f"G{i}", f"G{(i * 7 + 3) % V}"] for i in range(17)]
    resumed, control, result = _interrupt_then_resume(
        tmp_path, _spec("pair_scores", pairs=pairs), _backend,
    )
    assert resumed == control and result["records"] == len(pairs)
    lines = resumed.decode("utf-8").splitlines()
    assert len(lines) == len(pairs)
    a, b, s = lines[0].split("\t")
    assert [a, b] == pairs[0] and 0.0 <= float(s) <= 1.0


def test_export_resume_bit_identical_and_w2v_parity(tmp_path):
    model = _model(seed=5)
    resumed, control, result = _interrupt_then_resume(
        tmp_path, _spec("export"), lambda: _backend(model),
    )
    assert resumed == control and result["records"] == V
    # byte parity with the online writer: the artifact IS a word2vec
    # text export
    from gene2vec_tpu.io.emb_io import write_word2vec_format

    ref = str(tmp_path / "ref_w2v.txt")
    write_word2vec_format(ref, list(model.tokens), model.emb)
    assert resumed == _read(ref)


def test_resume_is_noop_past_completion(tmp_path):
    art = ChunkedArtifact(str(tmp_path / "job"))
    spec = _spec("knn_graph")
    first = run_job(spec, _backend(), art)
    again = run_job(spec, _backend(), ChunkedArtifact(str(tmp_path / "job")))
    assert again["records"] == first["records"]
    assert again["resumed_records"] == first["records"]


# -- the job manager + /v1/jobs dispatch --------------------------------------


def _manager(tmp_path, model=None, **kw):
    return JobManager(
        str(tmp_path / "jobs"),
        backend_factory=lambda: _backend(model),
        **kw,
    )


def _wait_state(mgr, job_id, states=("done", "failed", "cancelled"),
                timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        status, doc = mgr.status(job_id)
        if status == 200 and doc["state"] in states:
            return doc
        time.sleep(0.02)
    raise AssertionError(f"job {job_id} never reached {states}: {doc}")


def test_manager_runs_job_to_done(tmp_path):
    mgr = _manager(tmp_path).start()
    try:
        doc = mgr.submit(_spec("knn_graph", job_id="g1"))
        assert doc["state"] in ("pending", "running")
        doc = _wait_state(mgr, "g1")
        assert doc["state"] == "done" and doc["records_done"] == V
        assert doc["result"]["chunks"] == -(-V // 4)
        assert doc["iteration"] == 1
        # resubmitting a done job is idempotent status, not a re-run
        assert mgr.submit(_spec("knn_graph", job_id="g1"))["state"] == "done"
        assert [j["job_id"] for j in mgr.list_jobs()["jobs"]] == ["g1"]
    finally:
        mgr.stop()


def test_manager_shutdown_midjob_resumes_running_first(tmp_path):
    # a worker stopped mid-job leaves the journal "running"; the next
    # start() must pick it up BEFORE pending jobs and extend its
    # committed cursor to the bit-identical artifact
    model = _model(seed=9)
    slow = threading.Event()

    class SlowBackend(EngineBackend):
        def knn_rows(self, start, n, k):
            if start >= 8 and not slow.is_set():
                time.sleep(0.05)
            return super().knn_rows(start, n, k)

    def factory():
        return SlowBackend(model, SimilarityEngine(max_batch=8))

    mgr = JobManager(str(tmp_path / "jobs"), backend_factory=factory)
    mgr.start()
    mgr.submit(_spec("knn_graph", job_id="resume-me"))
    # poll the journal, never a second ChunkedArtifact: the commit
    # protocol is single-writer (a concurrent open would "recover" the
    # live writer's in-flight append out from under it)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 30.0:
        if (mgr.status("resume-me")[1].get("records_done") or 0) >= 8:
            break
        time.sleep(0.01)
    assert (mgr.status("resume-me")[1].get("records_done") or 0) >= 8
    mgr.stop()  # shutdown, not cancel: journal must stay "running"
    doc = mgr._read_journal("resume-me")
    if doc["state"] == "done":
        pytest.skip("job finished before shutdown on this host")
    assert doc["state"] == "running"
    slow.set()
    mgr2 = JobManager(str(tmp_path / "jobs"), backend_factory=factory)
    mgr2.start()
    try:
        done = _wait_state(mgr2, "resume-me")
        assert done["state"] == "done"
        assert done["result"]["resumed_records"] > 0
    finally:
        mgr2.stop()
    control = ChunkedArtifact(str(tmp_path / "control"))
    run_job(_spec("knn_graph"), factory(), control)
    assert _read(
        os.path.join(mgr2.job_dir("resume-me"), DATA_NAME)
    ) == _read(control.data_path)


def test_manager_pins_iteration_across_swap(tmp_path):
    # journal says iteration 1, the serving model swapped to 2: the
    # resume must fail loudly, never mix iterations in one artifact
    mgr = _manager(tmp_path, model=_model(iteration=2))
    os.makedirs(mgr.job_dir("stale"), exist_ok=True)
    mgr._write_journal("stale", {
        "spec": _spec("knn_graph", job_id="stale").to_doc(),
        "state": "running", "created_unix": 0,
        "records_done": 0, "records_total": None,
        "error": None, "iteration": 1,
    })
    mgr.start()
    try:
        doc = _wait_state(mgr, "stale")
        assert doc["state"] == "failed"
        assert "swapped" in doc["error"]
    finally:
        mgr.stop()


def test_manager_cancel_pending_job(tmp_path):
    mgr = _manager(tmp_path)  # worker NOT started: jobs stay pending
    mgr.submit(_spec("knn_graph", job_id="p1"))
    status, doc = mgr.cancel("p1")
    assert status == 200 and doc["state"] == "cancelled"
    status, doc = mgr.cancel("p1")
    assert status == 409
    assert mgr.cancel("ghost")[0] == 404


def test_dispatch_jobs_routes(tmp_path):
    assert dispatch_jobs(None, "GET", "/v1/jobs", {}, None)[0] == 404
    mgr = _manager(tmp_path).start()
    try:
        status, doc = dispatch_jobs(
            mgr, "POST", "/v1/jobs", {}, {"type": "nope"},
        )
        assert status == 400 and "type" in doc["error"]
        status, doc = dispatch_jobs(
            mgr, "POST", "/v1/jobs", {},
            {"type": "knn_graph", "k": K, "chunk_rows": 4,
             "job_id": "via-http"},
        )
        assert status == 200
        _wait_state(mgr, "via-http")
        status, doc = dispatch_jobs(mgr, "GET", "/v1/jobs/via-http", {}, None)
        assert status == 200 and doc["state"] == "done"
        # artifact paging: reassemble in 64-byte pages, verify CRC
        blob, offset = b"", 0
        while True:
            status, page = dispatch_jobs(
                mgr, "GET", "/v1/jobs/via-http/artifact",
                {"offset": [str(offset)], "limit": ["64"]}, None,
            )
            assert status == 200
            blob += base64.b64decode(page["data_b64"])
            offset = page["offset"] + 64
            if page["eof"]:
                break
        assert (zlib.crc32(blob) & 0xFFFFFFFF) == page["data_crc32"]
        status, tok = dispatch_jobs(
            mgr, "GET", "/v1/jobs/via-http/artifact",
            {"part": ["tokens"]}, None,
        )
        assert status == 200
        assert dispatch_jobs(
            mgr, "GET", "/v1/jobs/../etc", {}, None,
        )[0] == 404
        assert dispatch_jobs(
            mgr, "GET", "/v1/jobs/via-http/artifact",
            {"offset": ["x"]}, None,
        )[0] == 400
    finally:
        mgr.stop()


def test_jobspec_validation():
    with pytest.raises(ValueError, match="'type'"):
        JobSpec.from_body({"type": "mine_bitcoin"})
    with pytest.raises(ValueError, match="'k'"):
        JobSpec.from_body({"type": "knn_graph", "k": 0})
    with pytest.raises(ValueError, match="'pairs'"):
        JobSpec.from_body({"type": "pair_scores", "pairs": []})
    with pytest.raises(ValueError, match="job_id"):
        JobSpec.from_body({"type": "export", "job_id": "../escape"})
    # pairs are dropped for non-pair jobs (journal stays bounded)
    assert JobSpec.from_body(
        {"type": "export", "pairs": [["A", "B"]]}
    ).pairs is None


# -- background priority: FairQueue weights + Pacer ---------------------------


def _weights(t):
    return DEFAULT_BATCH_WEIGHT if t == BATCH_TENANT else 1.0


def test_fairqueue_batch_lane_cannot_starve_interactive():
    q = FairQueue(weight_of=_weights)
    for i in range(200):
        q.push(BATCH_TENANT, ("b", i))
    for i in range(100):
        q.push("default", ("d", i))
    # drain a contended window: the batch lane's share must track its
    # weight (0.05 / 1.05 ≈ 4.8%), so interactive work is never stuck
    # behind the 200 batch items that arrived first
    window = [q.pop() for _ in range(100)]
    batch_served = sum(1 for t, _ in window if t == "b")
    assert batch_served <= 10  # ~5 expected; generous ceiling
    # and interactive stays FIFO within its own lane
    d_order = [i for t, i in window if t == "d"]
    assert d_order == sorted(d_order)


def test_fairqueue_batch_lane_never_fully_starves():
    q = FairQueue(weight_of=_weights)
    for i in range(50):
        q.push(BATCH_TENANT, ("b", i))
        q.push("default", ("d", i))
        q.push("default", ("d2", i))
    served = [q.pop() for _ in range(60)]
    assert any(t == "b" for t, _ in served)  # weighted, not locked out


def test_fairqueue_idle_lane_cannot_hoard_credit():
    q = FairQueue(weight_of=_weights)
    q.push(BATCH_TENANT, "b0")
    assert q.pop() == "b0"  # lane empties: its credit is dropped
    for i in range(40):
        q.push(BATCH_TENANT, ("b", i))
        q.push("default", ("d", i))
    window = [q.pop() for _ in range(20)]
    assert sum(1 for t, _ in window if t == "b") <= 2


def test_pacer_yields_under_pressure_and_stops():
    clock = {"t": 0.0}
    slept = []

    def sleep(s):
        slept.append(s)
        clock["t"] += s

    pressure = {"v": 1.0}
    p = Pacer(
        guard=lambda: pressure["v"], guard_max=0.5,
        clock=lambda: clock["t"], sleep=sleep,
    )

    def stop():
        if clock["t"] > 1.0:
            pressure["v"] = 0.0  # interactive pressure drains
        return False

    p.wait(0.0, stop)
    assert p.yielded_s > 1.0 and slept  # it actually backed off
    assert max(slept) <= 1.0  # backoff is capped
    # should_stop breaks the yield loop even under sustained pressure
    pressure["v"] = 1.0
    p2 = Pacer(
        guard=lambda: 1.0, guard_max=0.5,
        clock=lambda: clock["t"], sleep=sleep,
    )
    p2.wait(0.0, lambda: True)
    assert p2.yielded_s <= 0.1


def test_pacer_duty_cycle_sleeps_proportionally():
    slept = []
    p = Pacer(duty=0.5, clock=lambda: 0.0, sleep=slept.append)
    p.wait(2.0, None)
    assert slept == [2.0]  # 50% duty: idle as long as the chunk took


# -- ShardGroupBackend: tenant tagging, sub-request cap, pressure -------------


class _FakeRouting:
    def __init__(self, tokens, dim=D):
        self.tokens = list(tokens)
        self.dim = dim
        self.iteration = 1
        self.index = {t: i for i, t in enumerate(tokens)}


class _FakeGroup:
    """Captures what a scatter leg would see: the ambient scatter
    headers at call time and each sub-request's query count."""

    def __init__(self, tokens, max_queries=64):
        self.config = types.SimpleNamespace(
            max_queries_per_request=max_queries
        )
        self.routing = _FakeRouting(tokens)
        self.calls = []

    def _ambient(self):
        from gene2vec_tpu.serve.shardgroup import _SCATTER_HEADERS

        return getattr(_SCATTER_HEADERS, "value", None)

    def similar(self, body):
        self.calls.append((len(body["genes"]), self._ambient()))
        k = body["k"]
        return 200, {"results": [
            {"neighbors": [
                {"gene": self.routing.tokens[(j + 1) % len(
                    self.routing.tokens)], "score": 0.5}
                for j in range(k)
            ]}
            for _ in body["genes"]
        ]}

    def interaction(self, body):
        self.calls.append((len(body["pairs"]), self._ambient()))
        return 200, {"scores": [
            {"pair": p, "score": 0.25} for p in body["pairs"]
        ]}

    def embedding(self, body):
        self.calls.append((len(body["genes"]), self._ambient()))
        return 200, {"embeddings": [
            {"gene": g, "vector": [0.0] * self.routing.dim}
            for g in body["genes"]
        ]}


def test_shardgroup_backend_tags_every_leg_with_batch_tenant(tmp_path):
    group = _FakeGroup([f"G{i}" for i in range(40)])
    be = ShardGroupBackend(group, sub_queries=16)
    be.knn_rows(0, 40, 2)
    be.pair_scores([("G0", "G1")])
    be.vector_rows(0, 5)
    assert group.calls  # similar x3 + interaction + embedding
    for n, headers in group.calls:
        assert headers == {"X-Tenant": BATCH_TENANT}
    # ...and the ambient header is scoped to the call, not left set
    assert group._ambient() is None
    # sub-request cap: 40 queries at sub=16 -> 16, 16, 8
    assert [n for n, _ in group.calls[:3]] == [16, 16, 8]


def test_shardgroup_backend_sub_respects_front_door_cap():
    be = ShardGroupBackend(
        _FakeGroup(["G0", "G1"], max_queries=8), sub_queries=64
    )
    assert be._sub == 8  # never larger than the replicas' cap


def test_shardgroup_backend_pressure_wiring():
    group = _FakeGroup(["G0", "G1"])
    assert ShardGroupBackend(group).pressure() == 0.0
    assert ShardGroupBackend(
        group, pressure_fn=lambda: 0.75
    ).pressure() == 0.75

    def broken():
        raise RuntimeError("aggregator gone")

    # a broken signal must read as pressure (yield), never as idle
    assert ShardGroupBackend(group, pressure_fn=broken).pressure() == 1.0


def test_shardgroup_backend_degraded_answer_is_retryable_not_recorded():
    group = _FakeGroup([f"G{i}" for i in range(8)])
    real = group.similar

    def degraded(body):
        status, doc = real(body)
        doc["results"][0]["degraded"] = True
        return status, doc

    group.similar = degraded
    be = ShardGroupBackend(group, sub_queries=4)
    with pytest.raises(ChunkFailed, match="degraded"):
        be.knn_rows(0, 4, 2)


def test_scatter_headers_nesting_restores():
    from gene2vec_tpu.serve.shardgroup import (
        _SCATTER_HEADERS,
        scatter_headers,
    )

    with scatter_headers({"X-Tenant": "a"}):
        with scatter_headers({"X-Tenant": "b"}):
            assert _SCATTER_HEADERS.value == {"X-Tenant": "b"}
        assert _SCATTER_HEADERS.value == {"X-Tenant": "a"}
    assert _SCATTER_HEADERS.value is None


# -- the precomputed-graph intrinsic eval -------------------------------------


def _clustered_model(v=40, d=8, clusters=4, seed=11):
    rng = np.random.RandomState(seed)
    cent = rng.randn(clusters, d).astype(np.float32) * 3
    emb = np.vstack([
        cent[i % clusters] + 0.2 * rng.randn(d).astype(np.float32)
        for i in range(v)
    ])
    tokens = tuple(f"G{i}" for i in range(v))
    return LoadedModel(
        dim=d, iteration=1, tokens=tokens,
        index={t: i for i, t in enumerate(tokens)},
        emb=emb, unit=jnp.asarray(l2_normalize(emb)),
        source="synthetic", meta={},
    ), clusters


def test_graph_neighborhood_ratio_on_batch_artifact(tmp_path):
    from gene2vec_tpu.eval.target_function import graph_neighborhood_ratio

    model, clusters = _clustered_model()
    d = str(tmp_path / "graph")
    run_job(_spec("knn_graph"), _backend(model), ChunkedArtifact(d))
    gmt = tmp_path / "planted.gmt"
    gmt.write_text("".join(
        f"CLUSTER{c}\turl\t" + "\t".join(
            f"G{i}" for i in range(40) if i % clusters == c
        ) + "\n"
        for c in range(clusters)
    ))
    out = graph_neighborhood_ratio(d, str(gmt))
    assert out["genes_scored"] == 40 and out["k"] == K
    # planted clusters: graph neighbors share a pathway far more often
    # than degree-matched random picks
    assert out["ratio"] > 1.5
    assert out["neighbor_hit_rate"] > out["random_hit_rate"]
    bad = tmp_path / "mismatch.gmt"
    bad.write_text("P\turl\tNOT_A_GENE\tALSO_NOT\n")
    with pytest.raises(ValueError, match="no graph gene"):
        graph_neighborhood_ratio(d, str(bad))


# -- the passes_batch budget gate ---------------------------------------------
#
# The pass id "batch-graph-budget" gates cli.analyze's default tier;
# these planted fixtures pin its shape (the test_shard convention).


def _good_batch_doc():
    return {
        "schema": "gene2vec-tpu/bench-batch/v1",
        "passed": True,
        "batch": {
            "recipe": {
                "rows_24k": 24447, "dim_24k": 200, "k": 10,
                "shards": 2, "chunk_rows": 512, "rows_1m": 1000000,
                "dim_1m": 64, "queries_1m": 512, "batch_weight": 0.05,
            },
            "graph_24k": {
                "rows_per_sec": 800.0, "recall_at_10": 0.999,
                "resume_bit_exact": True, "killed_at_records": 6144,
                "resumed_records": 6144,
            },
            "graph_1m": {
                "rows_per_sec": 900.0, "recall_at_10": 0.97,
            },
            "mixed": {
                "p99_delta_frac": 0.3, "p99_delta_ms": 6.0,
            },
        },
    }


def _batch_findings(tmp_path, doc=None, name="BENCH_BATCH_r19.json"):
    from gene2vec_tpu.analysis.passes_batch import batch_findings

    if doc is not None:
        (tmp_path / name).write_text(json.dumps(doc))
    return batch_findings(root=str(tmp_path))


def _gating(findings):
    return [f for f in findings if f.severity in ("error", "warning")]


def test_passes_batch_good_record_is_info(tmp_path):
    fs = _batch_findings(tmp_path, _good_batch_doc())
    assert len(fs) == 1 and not _gating(fs)
    assert fs[0].pass_id == "batch-graph-budget"


def test_passes_batch_missing_record_is_info(tmp_path):
    fs = _batch_findings(tmp_path)
    assert len(fs) == 1 and fs[0].severity == "info"
    assert "chaos_drill" in fs[0].message


def test_passes_batch_low_recall_gates(tmp_path):
    doc = _good_batch_doc()
    doc["batch"]["graph_24k"]["recall_at_10"] = 0.9
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "recall_at_10" in fs[0].message


def test_passes_batch_off_recipe_gates(tmp_path):
    doc = _good_batch_doc()
    doc["batch"]["recipe"]["rows_24k"] = 4096  # a smoke run
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "rows_24k" in fs[0].message


def test_passes_batch_resume_divergence_gates(tmp_path):
    doc = _good_batch_doc()
    doc["batch"]["graph_24k"]["resume_bit_exact"] = False
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "resume_bit_exact" in fs[0].message


def test_passes_batch_dropped_key_gates_like_violation(tmp_path):
    doc = _good_batch_doc()
    del doc["batch"]["graph_24k"]["recall_at_10"]
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "recall_at_10 missing" in fs[0].message


def test_passes_batch_p99_either_bound_suffices(tmp_path):
    doc = _good_batch_doc()
    # frac blows past the budget but the absolute delta is tiny: a
    # fast baseline must not turn scheduler noise into a gate
    doc["batch"]["mixed"] = {"p99_delta_frac": 2.5, "p99_delta_ms": 3.0}
    assert not _gating(_batch_findings(tmp_path, doc))
    doc["batch"]["mixed"] = {"p99_delta_frac": 2.5, "p99_delta_ms": 80.0}
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "interactive p99" in fs[0].message


def test_passes_batch_drill_verdict_gates(tmp_path):
    doc = _good_batch_doc()
    doc["passed"] = False
    fs = _gating(_batch_findings(tmp_path, doc))
    assert len(fs) == 1 and "passed=false" in fs[0].message


def test_passes_batch_newest_round_wins(tmp_path):
    bad = _good_batch_doc()
    bad["batch"]["graph_24k"]["recall_at_10"] = 0.5
    (tmp_path / "BENCH_BATCH_r18.json").write_text(json.dumps(bad))
    fs = _batch_findings(tmp_path, _good_batch_doc(),
                         name="BENCH_BATCH_r19.json")
    assert len(fs) == 1 and not _gating(fs)
