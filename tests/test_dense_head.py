"""Dense-head positive path (round 4): head-token emb/ctx rows move via
one-hot MXU matmuls over the contiguous table[:H] slab; tail rows keep the
per-row gather/scatter.  The split must be an exact re-grouping of the same
per-example updates — pinned here against the plain-scatter stratified step
on identical batches — and the segmented corpus machinery must preserve the
corpus (same multiset of pairs per class, quotas summing to the batch).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NoiseTable, build_stratified_spec
from gene2vec_tpu.data.pipeline import (
    PairCorpus,
    segment_corpus_by_head,
    segmented_epoch_shuffle,
)
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns import step as step_mod
from gene2vec_tpu.sgns.model import init_params
from gene2vec_tpu.sgns.step import sgns_step
from gene2vec_tpu.sgns.train import SGNSTrainer, train_epochs


def _zipf_corpus(v, n, seed=0):
    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, v + 1)
    p /= p.sum()
    pairs = rng.choice(v, size=(n, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=v).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(v)], counts), pairs)


def _segmented_batch(v, b, head, seed=0):
    """One class-segmented batch + its per-pool quotas (``head`` may be a
    boundary tuple for the 3-class head/mid/tail layout)."""
    corpus = _zipf_corpus(v, b, seed)
    pools, quotas = segment_corpus_by_head(corpus.pairs, head, b)
    batch = np.concatenate([p[:q] for p, q in zip(pools, quotas)], axis=0)
    return corpus, jnp.asarray(batch), quotas


@pytest.mark.parametrize("head", [8, 64])
def test_dense_head_step_matches_scatter(head, monkeypatch):
    """positive_head>0 on a segmented batch must equal the plain path on
    the same batch (HIGHEST matmul precision isolates the re-grouping
    from bf16 input truncation)."""
    monkeypatch.setattr(
        step_mod, "_DENSE_HEAD_PRECISION", jax.lax.Precision.HIGHEST
    )
    v, d, b = 257, 16, 128
    corpus, batch, quotas = _segmented_batch(v, b, head)
    spec = build_stratified_spec(corpus.vocab.counts, 32, 64, 0.75)
    noise = NoiseTable(
        prob=jnp.ones((v,)) / v,
        alias=jnp.arange(v, dtype=jnp.int32),
    )
    params = init_params(jax.random.PRNGKey(0), v, d, jnp.float32)
    key = jax.random.PRNGKey(7)
    lr = jnp.asarray(0.05, jnp.float32)

    kw = dict(
        negatives=5, combiner="capped", negative_mode="stratified",
        strat_group=32, stratified=spec,
    )
    p_ref, loss_ref = sgns_step(params, batch, noise, key, lr, **kw)
    p_dense, loss_dense = sgns_step(
        params, batch, noise, key, lr,
        positive_head=head, pos_quotas=quotas, **kw,
    )
    np.testing.assert_allclose(
        float(loss_dense), float(loss_ref), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(p_dense.emb), np.asarray(p_ref.emb), atol=2e-6
    )
    np.testing.assert_allclose(
        np.asarray(p_dense.ctx), np.asarray(p_ref.ctx), atol=2e-6
    )


@pytest.mark.parametrize("bounds", [(8, 24), (16, 64), (8, 200)])
def test_dense_mid_step_matches_scatter(bounds, monkeypatch):
    """The 3-class head/mid/tail layout (positive_mid > 0, round 5) must
    equal the plain path on the same 6-class-segmented batch — the mid
    slab is the same per-example update re-grouped through a second
    one-hot contraction."""
    monkeypatch.setattr(
        step_mod, "_DENSE_HEAD_PRECISION", jax.lax.Precision.HIGHEST
    )
    v, d, b = 257, 16, 128
    corpus, batch, quotas = _segmented_batch(v, b, bounds)
    assert len(quotas) == 6 and sum(quotas) == b
    spec = build_stratified_spec(corpus.vocab.counts, 32, 64, 0.75)
    noise = NoiseTable(
        prob=jnp.ones((v,)) / v, alias=jnp.arange(v, dtype=jnp.int32)
    )
    params = init_params(jax.random.PRNGKey(0), v, d, jnp.float32)
    key = jax.random.PRNGKey(7)
    lr = jnp.asarray(0.05, jnp.float32)
    kw = dict(
        negatives=5, combiner="capped", negative_mode="stratified",
        strat_group=32, stratified=spec,
    )
    p_ref, loss_ref = sgns_step(params, batch, noise, key, lr, **kw)
    p_dense, loss_dense = sgns_step(
        params, batch, noise, key, lr,
        positive_head=bounds[0], positive_mid=bounds[1] - bounds[0],
        pos_quotas=quotas, **kw,
    )
    np.testing.assert_allclose(float(loss_dense), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p_dense.emb), np.asarray(p_ref.emb), atol=2e-6
    )
    np.testing.assert_allclose(
        np.asarray(p_dense.ctx), np.asarray(p_ref.ctx), atol=2e-6
    )


@pytest.mark.parametrize("shards", [1, 4])
def test_dense_slab_gather_scatter_roundtrip(shards, monkeypatch):
    """Unit test of the multi-slab primitives (order-independent): the
    gather must equal table[idx] and the scatter-accumulator must equal a
    plain per-row scatter, for a 3-class per-shard segment layout."""
    monkeypatch.setattr(
        step_mod, "_DENSE_HEAD_PRECISION", jax.lax.Precision.HIGHEST
    )
    rng = np.random.RandomState(0)
    v, d = 300, 8
    h1, h2 = 16, 80
    slabs = [(0, h1), (h1, h2)]
    quotas = [4, 6, 2, 8, 4, 8]  # per-pool PAIR counts per shard
    b = sum(quotas)
    c_segs, x_segs = step_mod._dense_segments(quotas, b, 3)
    # build an index array honoring the center-class layout
    bands = [(0, h1), (h1, h2), (h2, v)]

    def fill(seg_lists):
        idx = np.zeros((shards, 2 * b), dtype=np.int32)
        for c, segs in enumerate(seg_lists):
            lo, hi = bands[c]
            for s, l in segs:
                idx[:, s : s + l] = rng.randint(lo, hi, size=(shards, l))
        return idx

    idx = fill(c_segs)
    table = jnp.asarray(rng.randn(v, d).astype(np.float32))
    rows, onehots, idx_tail = step_mod._dense_slab_gather(
        table, jnp.asarray(idx), slabs, c_segs, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(rows), np.asarray(table)[idx], atol=1e-6
    )
    grads = jnp.asarray(rng.randn(shards, 2 * b, d).astype(np.float32))
    weights = jnp.ones((shards, 2 * b), jnp.float32)
    acc = step_mod._dense_slab_scatter_acc(
        v, grads, weights, onehots, idx_tail, slabs, c_segs, jnp.float32
    )
    ref = step_mod._scatter_accumulator(
        v,
        jnp.asarray(idx.reshape(-1)),
        grads.reshape(-1, d),
        weights.reshape(-1),
        jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(acc), np.asarray(ref), atol=1e-4)


def test_dense_head_default_precision_close():
    """Under the default (bf16-input) matmul policy the dense path is the
    same update within bf16 rounding — no precision override."""
    v, d, b, head = 257, 16, 128, 64
    corpus, batch, quotas = _segmented_batch(v, b, head)
    spec = build_stratified_spec(corpus.vocab.counts, 32, 64, 0.75)
    noise = NoiseTable(
        prob=jnp.ones((v,)) / v, alias=jnp.arange(v, dtype=jnp.int32)
    )
    params = init_params(jax.random.PRNGKey(0), v, d, jnp.float32)
    key = jax.random.PRNGKey(7)
    kw = dict(
        negatives=5, combiner="capped", negative_mode="stratified",
        strat_group=32, stratified=spec,
    )
    p_ref, loss_ref = sgns_step(
        params, batch, noise, key, jnp.float32(0.05), **kw
    )
    p_dense, loss_dense = sgns_step(
        params, batch, noise, key, jnp.float32(0.05),
        positive_head=head, pos_quotas=quotas, **kw,
    )
    assert abs(float(loss_dense) - float(loss_ref)) < 2e-2
    np.testing.assert_allclose(
        np.asarray(p_dense.ctx), np.asarray(p_ref.ctx), atol=2e-3
    )


def test_segment_corpus_by_head_partitions_exactly():
    v, n, b, head = 500, 4096 + 37, 512, 32
    corpus = _zipf_corpus(v, n)
    pools, quotas = segment_corpus_by_head(corpus.pairs, head, b)
    assert sum(quotas) == b
    nb = n // b
    hh, ht, tt = pools
    assert np.all((hh < head).all(axis=1))
    assert np.all((tt >= head).all(axis=1))
    assert np.all(ht[:, 0] < head) and np.all(ht[:, 1] >= head)
    for pool, q in zip(pools, quotas):
        assert len(pool) >= q * nb
    # the pools together are the corpus (up to HT direction canonicalization
    # and the deterministic < nb wrap-padding rows)
    canon = np.sort(corpus.pairs, axis=1)
    got = np.concatenate(
        [np.sort(p, axis=1) for p in pools], axis=0
    )
    base = {tuple(r) for r in canon.tolist()}
    assert base == {tuple(r) for r in got.tolist()}
    assert len(got) - len(canon) < nb * 3


def test_segmented_epoch_shuffle_preserves_classes():
    v, n, b, head = 300, 2048, 256, 16
    corpus = _zipf_corpus(v, n)
    pools, quotas = segment_corpus_by_head(corpus.pairs, head, b)
    nb = n // b
    out = segmented_epoch_shuffle(
        tuple(jnp.asarray(p) for p in pools),
        jax.random.PRNGKey(3), quotas, nb, "offset",
    )
    for arr, q, pool in zip(out, quotas, pools):
        arr = np.asarray(arr)
        assert arr.shape == (q * nb, 2)
        pool_set = {tuple(r) for r in pool.tolist()}
        assert {tuple(r) for r in arr.tolist()} <= pool_set


def test_segment_tiny_pool_tiles_to_quota():
    """A class pool far smaller than its forced quota must wrap-pad by
    tiling (one concatenation pass is not enough when the pool has fewer
    than half the needed rows)."""
    rng = np.random.RandomState(0)
    head, b = 4, 8
    # 4000 pairs -> 500 batches; make TT almost empty but non-zero
    hh = rng.randint(0, head, size=(3000, 2))
    ht = np.stack(
        [rng.randint(0, head, 2995), rng.randint(head, 50, 2995)], axis=1
    )
    tt = rng.randint(head, 50, size=(5, 2))
    pairs = np.concatenate([hh, ht, tt]).astype(np.int32)
    rng.shuffle(pairs)
    pools, quotas = segment_corpus_by_head(pairs, head, b)
    nb = len(pairs) // b
    assert sum(quotas) == b
    for pool, q in zip(pools, quotas):
        assert len(pool) >= q * nb
        # non-empty classes must never round to quota 0 (a permanent
        # training-set drop); the 5-row TT pool gets q=1 and is tiled
        assert q >= 1
    out = segmented_epoch_shuffle(
        tuple(jnp.asarray(p) for p in pools),
        jax.random.PRNGKey(0), quotas, nb, "full",
    )
    for arr, q in zip(out, quotas):
        assert np.asarray(arr).shape[0] >= q * nb


def test_all_head_vocab_trains():
    """positive_head >= vocab_size: every pair is HH, HT/TT quotas are 0,
    'full' shuffle mode must not divide by zero."""
    corpus = _zipf_corpus(40, 2048)
    cfg = SGNSConfig(
        dim=8, batch_pairs=256, positive_head=4096, strat_head=8,
        strat_block=8, shuffle_mode="full",
    )
    tr = SGNSTrainer(corpus, cfg)
    assert tr.config.positive_head == 40
    params, loss = tr.train_epoch(tr.init(), jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_trainer_dense_head_learns_planted_clusters():
    """Integrated trainer with positive_head: loss decreases and the
    planted two-cluster structure is recovered (same check the plain
    path's quality tests use)."""
    rng = np.random.RandomState(0)
    v, n = 64, 8192
    half = v // 2
    pairs = np.concatenate(
        [
            rng.randint(0, half, size=(n // 2, 2)),
            rng.randint(half, v, size=(n // 2, 2)),
        ]
    ).astype(np.int32)
    rng.shuffle(pairs)
    counts = np.bincount(pairs.reshape(-1), minlength=v).astype(np.int64)
    corpus = PairCorpus(Vocab([f"G{i}" for i in range(v)], counts), pairs)
    cfg = SGNSConfig(
        dim=16, batch_pairs=512, positive_head=16, strat_head=8,
        strat_block=16, strat_group=32, lr=0.05,
    )
    emb, losses = train_epochs(corpus, cfg, epochs=8)
    assert losses[-1] < losses[0] - 0.5
    unit = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    intra = np.mean(unit[:half] @ unit[:half].T)
    inter = np.mean(unit[:half] @ unit[half:].T)
    assert intra > inter + 0.3


try:
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=60, deadline=None)
    @given(
        n_hh=st.integers(0, 3000),
        n_ht=st.integers(0, 3000),
        n_tt=st.integers(0, 3000),
        batch=st.sampled_from([8, 16, 64, 128]),
        multiple=st.sampled_from([1, 2, 4, 8]),
        seed=st.integers(0, 10),
    )
    def test_segment_quota_invariants_fuzz(
        n_hh, n_ht, n_tt, batch, multiple, seed
    ):
        """Property test over random class mixes: quotas sum to the batch,
        are multiples of `multiple`, non-empty classes never drop to 0,
        and every pool covers quota*num_batches rows at a length divisible
        by `multiple`."""
        head = 8
        rng = np.random.RandomState(seed)
        parts = []
        if n_hh:
            parts.append(rng.randint(0, head, size=(n_hh, 2)))
        if n_ht:
            parts.append(
                np.stack(
                    [
                        rng.randint(0, head, n_ht),
                        rng.randint(head, 60, n_ht),
                    ],
                    axis=1,
                )
            )
        if n_tt:
            parts.append(rng.randint(head, 60, size=(n_tt, 2)))
        if not parts:
            return
        pairs = np.concatenate(parts).astype(np.int32)
        rng.shuffle(pairs)
        if len(pairs) < batch or batch % multiple or batch < 3 * multiple:
            return
        pools, quotas = segment_corpus_by_head(
            pairs, head, batch, multiple=multiple
        )
        nb = len(pairs) // batch
        assert sum(quotas) == batch
        for pool, q, n_orig in zip(pools, quotas, (n_hh, n_ht, n_tt)):
            assert q % multiple == 0
            assert len(pool) >= q * nb
            assert len(pool) % multiple == 0 or len(pool) == 0
            if n_orig:
                assert q >= multiple  # non-empty class always trains

except ImportError:  # pragma: no cover - hypothesis is in the base image
    pass


def test_trainer_falls_back_on_multihost(monkeypatch):
    """Multi-host runs must not use dense-head positives: per-host corpus
    shards derive mismatched static quotas, so hosts would compile
    different batch layouts and deadlock the collectives.  The trainer
    warns and falls back to plain gathers."""
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    corpus = _zipf_corpus(100, 2048)
    cfg = SGNSConfig(dim=8, batch_pairs=256, positive_head=16)
    with pytest.warns(UserWarning, match="multi-host"):
        tr = SGNSTrainer(corpus, cfg)
    assert tr.pos_quotas is None and tr.config.positive_head == 0
    params, loss = tr.train_epoch(tr.init(), jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_trainer_falls_back_without_stratified():
    corpus = _zipf_corpus(100, 2048)
    cfg = SGNSConfig(
        dim=8, batch_pairs=256, positive_head=16, negative_mode="shared"
    )
    tr = SGNSTrainer(corpus, cfg)
    assert tr.pos_quotas is None and tr.config.positive_head == 0
    params, loss = tr.train_epoch(tr.init(), jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
