"""Fleet tier: resilient client state machines, fault injection, server
read deadlines, and replica supervision.

Everything here is tier-1 fast: the client/breaker/budget tests drive
the state machines with injected clocks and transports (no sleeps), the
supervisor tests run against a jax-free stub replica executable (spawn
cost ~100 ms), and the only real sleeps are a few-ms drips in the
slow-loris test."""

import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.resilience.faults import (
    Decision,
    FaultInjector,
    FaultSpec,
    slow_loris,
)
from gene2vec_tpu.serve.client import (
    BreakerState,
    CircuitBreaker,
    ClientResponse,
    ResilientClient,
    RetryPolicy,
    TokenBucket,
    _classify,
)
from gene2vec_tpu.serve.fleet import (
    FleetConfig,
    FleetProxy,
    FleetSupervisor,
    ReplicaState,
    read_contract_line,
)


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0
        self.sleeps = []

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.sleeps.append(s)
        self.t += s


# -- circuit breaker ---------------------------------------------------------


def test_breaker_opens_after_consecutive_failures():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                       clock=clock)
    assert b.state == BreakerState.CLOSED
    b.record_failure()
    b.record_failure()
    assert b.state == BreakerState.CLOSED  # not yet
    b.record_success()  # CONSECUTIVE failures only
    b.record_failure()
    b.record_failure()
    b.record_failure()
    assert b.state == BreakerState.OPEN
    assert not b.allow()


def test_breaker_half_open_single_probe_and_close():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                       half_open_successes=2, clock=clock)
    b.record_failure()
    assert b.state == BreakerState.OPEN
    clock.t += 5.0
    assert b.state == BreakerState.HALF_OPEN
    assert b.allow()
    assert not b.allow()  # one probe in flight at a time
    b.record_success()
    assert b.state == BreakerState.HALF_OPEN  # needs 2 successes
    assert b.allow()
    b.record_success()
    assert b.state == BreakerState.CLOSED


def test_breaker_probe_failure_reopens_with_fresh_window():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                       clock=clock)
    b.record_failure()
    clock.t += 5.0
    assert b.allow()  # the half-open probe
    b.record_failure()
    assert b.state == BreakerState.OPEN
    clock.t += 4.9  # window restarts at the probe failure
    assert b.state == BreakerState.OPEN
    clock.t += 0.2
    assert b.state == BreakerState.HALF_OPEN


def test_breaker_cancel_releases_probe_slot():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                       clock=clock)
    b.record_failure()
    clock.t += 1.0
    assert b.allow()
    b.cancel()  # abandoned before I/O — the slot must come back
    assert b.allow()


# -- token bucket ------------------------------------------------------------


def test_token_bucket_exhausts_and_earns():
    tb = TokenBucket(ratio=0.5, burst=2.0)
    assert tb.spend() and tb.spend()
    assert not tb.spend()  # empty
    tb.earn()  # +0.5
    assert not tb.spend()
    tb.earn()  # 1.0
    assert tb.spend()
    for _ in range(100):
        tb.earn()
    assert tb.tokens == pytest.approx(2.0)  # capped at burst


# -- retry classification ----------------------------------------------------


@pytest.mark.parametrize("status,doc,cls,safe", [
    (200, None, "ok", False),
    (429, None, "http_429", False),
    (400, None, "http_4xx", False),
    (503, None, "http_503", True),
    (504, {"error": "expired in queue"}, "http_504", True),
    (504, {"error": "no result within 1.0s"}, "http_504", False),
    (500, None, "http_500", True),
])
def test_classify(status, doc, cls, safe):
    assert _classify(status, doc) == (cls, safe)


# -- resilient client --------------------------------------------------------


def _client(transport, clock, targets=("http://a", "http://b"), **kw):
    policy = RetryPolicy(**kw)
    return ResilientClient(
        list(targets), policy, transport=transport, clock=clock,
        sleep=clock.sleep,
    )


def test_client_fails_over_and_propagates_shrinking_deadline():
    clock = FakeClock()
    seen = []

    def transport(base, method, path, body, ct, rt, headers=None):
        at = clock.t
        clock.t += 0.2
        seen.append((base, json.loads(body)["timeout_ms"], at))
        if base == "http://a":
            raise ConnectionRefusedError()
        return 200, json.dumps({"hello": 1}).encode()

    c = _client(transport, clock, max_attempts=3, backoff_base_s=0.0)
    r = c.request("/v1/similar", {"genes": ["G0"]}, timeout_s=1.0)
    assert r.ok and r.retries == 1 and r.target == "http://b"
    # every attempt's propagated budget == what was left at launch, so
    # it shrinks monotonically and never exceeds the caller's deadline
    assert seen[0][1] == pytest.approx(1000.0)
    assert seen[1][1] == pytest.approx(800.0)
    for _, timeout_ms, at in seen:
        assert timeout_ms / 1000.0 <= (1.0 - at) + 1e-9


def test_client_never_launches_attempt_past_deadline():
    clock = FakeClock()
    launches = []

    def transport(base, method, path, body, ct, rt, headers=None):
        launches.append(clock.t)
        clock.t += 0.6  # each attempt eats most of the budget
        raise ConnectionRefusedError()

    c = _client(transport, clock, max_attempts=10, backoff_base_s=0.0)
    r = c.request("/v1/similar", {"genes": ["G0"]}, timeout_s=1.0)
    assert not r.ok
    assert all(t < 1.0 for t in launches)
    assert clock.t <= 1.0 + 0.6  # the in-flight attempt may finish late


@pytest.mark.parametrize("status,retriable", [
    (400, False), (429, False), (503, True),
])
def test_client_retries_only_retry_safe_statuses(status, retriable):
    clock = FakeClock()
    calls = []

    def transport(base, *a, **kw):
        calls.append(base)
        clock.t += 0.01
        return status, json.dumps({"error": "x"}).encode()

    c = _client(transport, clock, max_attempts=3, backoff_base_s=0.0)
    r = c.request("/v1/similar", {"genes": ["G0"]}, timeout_s=5.0)
    assert r.status == status
    assert len(calls) == (3 if retriable else 1)


def test_client_retries_queue_expired_504_but_not_compute_504():
    clock = FakeClock()
    calls = []

    def queue_504(base, *a, **kw):
        calls.append(base)
        clock.t += 0.01
        return 504, json.dumps({"error": "expired in queue"}).encode()

    c = _client(queue_504, clock, max_attempts=2, backoff_base_s=0.0)
    assert c.request("/x", {"a": 1}, timeout_s=5.0).status == 504
    assert len(calls) == 2

    calls.clear()

    def compute_504(base, *a, **kw):
        calls.append(base)
        clock.t += 0.01
        return 504, json.dumps({"error": "no result within 2.0s"}).encode()

    c2 = _client(compute_504, clock, max_attempts=2, backoff_base_s=0.0)
    assert c2.request("/x", {"a": 1}, timeout_s=5.0).status == 504
    assert len(calls) == 1  # the work may have completed: don't retry


def test_client_retry_budget_bounds_amplification():
    clock = FakeClock()

    def refuse(base, *a, **kw):
        clock.t += 0.001
        raise ConnectionRefusedError()

    c = _client(
        refuse, clock, targets=("http://a",), max_attempts=5,
        retry_budget_ratio=0.0, retry_budget_burst=3.0,
        backoff_base_s=0.0, breaker_failure_threshold=10_000,
    )
    attempts = sum(
        c.request("/x", {"a": 1}, timeout_s=5.0).attempts
        for _ in range(10)
    )
    # 10 primaries + exactly burst=3 retries, ever — outage amplification
    # is bounded by the budget, not by max_attempts
    assert attempts == 13
    assert c.stats["budget_exhausted"] >= 1


def test_client_backoff_jitter_within_bounds():
    clock = FakeClock()

    def refuse(base, *a, **kw):
        clock.t += 0.001
        raise ConnectionRefusedError()

    c = _client(
        refuse, clock, targets=("http://a",), max_attempts=4,
        backoff_base_s=0.1, backoff_max_s=10.0, jitter_frac=0.5,
        breaker_failure_threshold=10_000,
    )
    c.request("/x", {"a": 1}, timeout_s=100.0)
    assert len(clock.sleeps) == 3
    for i, s in enumerate(clock.sleeps):
        base = 0.1 * (2 ** i)
        assert base * 0.5 <= s <= base * 1.5  # jitter never leaves ±50%


def test_client_all_breakers_open_fails_fast_as_503():
    clock = FakeClock()

    def refuse(base, *a, **kw):
        clock.t += 0.001
        raise ConnectionRefusedError()

    c = _client(
        refuse, clock, targets=("http://a",), max_attempts=1,
        breaker_failure_threshold=2, breaker_reset_timeout_s=60.0,
    )
    c.request("/x", {"a": 1}, timeout_s=1.0)
    c.request("/x", {"a": 1}, timeout_s=1.0)
    r = c.request("/x", {"a": 1}, timeout_s=1.0)
    assert r.status == 503 and not r.ok
    assert c.stats["breaker_rejections"] == 1
    assert c.breaker("http://a").state == BreakerState.OPEN


def test_client_hedges_at_p95_and_first_answer_wins():
    # real (few-ms) sleeps: hedging genuinely races two threads
    slow, fast = "http://slow", "http://fast"

    def transport(base, method, path, body, ct, rt, headers=None):
        time.sleep(0.25 if base == slow else 0.005)
        return 200, json.dumps({"from": base}).encode()

    c = ResilientClient(
        [slow, fast],
        RetryPolicy(hedge=True, hedge_min_samples=4, max_attempts=2),
        transport=transport,
    )
    for _ in range(6):  # seed the p95 estimate
        c._record_latency(0.01)
    r = c.request("/x", {"a": 1}, timeout_s=5.0)
    assert r.ok and r.hedged
    assert r.doc["from"] == fast
    assert r.latency_s < 0.2  # did NOT wait for the slow primary
    assert c.stats["hedges"] == 1


# -- fault injection ---------------------------------------------------------


def test_fault_spec_json_round_trip_and_unknown_field():
    spec = FaultSpec(seed=3, latency_p=0.5, latency_ms=10.0)
    assert FaultSpec.from_json(spec.to_json()) == spec
    with pytest.raises(ValueError, match="unknown fault spec"):
        FaultSpec.from_json('{"nope": 1}')


def test_fault_injector_is_deterministic_and_route_scoped():
    spec = FaultSpec(seed=11, latency_p=0.3, latency_ms=5.0,
                     error_p=0.2, reset_p=0.1, blackhole_p=0.05)
    a, b = FaultInjector(spec), FaultInjector(spec)
    for _ in range(50):
        assert a.decide("/healthz") is None  # outside route_prefix
    seq_a = [a.decide("/v1/similar") for _ in range(200)]
    seq_b = [b.decide("/v1/similar") for _ in range(200)]
    assert seq_a == seq_b  # same seed, same request order -> same faults
    kinds = {d.kind for d in seq_a if d is not None}
    assert {"error", "reset", "blackhole"} <= kinds
    assert a.decisions == b.decisions
    assert sum(a.decisions.values()) >= 200


def test_fault_injector_disabled_without_env(monkeypatch):
    monkeypatch.delenv("GENE2VEC_TPU_FAULTS", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("GENE2VEC_TPU_FAULTS", '{"seed": 5, "error_p": 1.0}')
    inj = FaultInjector.from_env()
    assert inj is not None
    d = inj.decide("/v1/x")
    assert d == Decision(delay_s=0.0, kind="error", arg=503.0)


# -- server read deadline + readiness (needs a real served app) --------------


@pytest.fixture
def tiny_app(tmp_path):
    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import (
        ServeApp,
        ServeConfig,
        make_server,
    )
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(0)
    vocab = Vocab([f"G{i}" for i in range(8)], np.arange(8, 0, -1))
    save_iteration(
        str(tmp_path), 4, 1,
        SGNSParams(emb=rng.randn(8, 4).astype(np.float32),
                   ctx=np.zeros((8, 4), np.float32)),
        vocab,
    )
    reg = ModelRegistry(str(tmp_path))
    app = ServeApp(reg, ServeConfig(read_timeout_s=0.5))
    server = make_server(app, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield app, reg, server
    server.shutdown()
    server.server_close()
    app.stop()


def test_healthz_not_ready_until_loaded_and_livez_always(tiny_app):
    app, reg, _ = tiny_app
    status, doc = app.handle("GET", "/healthz", None)
    assert status == 503 and doc["status"] == "not_ready"
    assert app.handle("GET", "/livez", None)[0] == 200
    assert reg.refresh()
    status, doc = app.handle("GET", "/healthz", None)
    assert status == 200 and doc["status"] == "ok"
    assert doc["model"]["iteration"] == 1


def test_slow_loris_gets_408_and_thread_is_unpinned(tiny_app):
    app, reg, server = tiny_app
    reg.refresh()
    app.batcher.start()
    host, port = server.server_address[:2]
    status, held = slow_loris(
        host, port, drip_bytes=1, drip_interval_s=0.05, duration_s=5.0,
    )
    assert status == 408
    assert held < 2.0  # ~read_timeout_s (0.5), NOT the loris duration
    assert app.metrics.counter("serve_http_408_total").value >= 1
    # the handler thread is free again: a normal request still answers
    url = f"http://{host}:{port}"
    with urllib.request.urlopen(f"{url}/healthz", timeout=5.0) as r:
        assert r.status == 200


def test_injected_reset_surfaces_as_transport_error(tiny_app):
    app, reg, server = tiny_app
    reg.refresh()
    app.batcher.start()
    app.faults = FaultInjector(FaultSpec(seed=0, reset_p=1.0))
    host, port = server.server_address[:2]
    clockless = ResilientClient(
        [f"http://{host}:{port}"], RetryPolicy(max_attempts=1),
    )
    r = clockless.request("/v1/genes?limit=2")
    assert r.error_class == "transport"
    app.faults = None


# -- supervisor over a stub replica (jax-free, ~100ms spawns) ----------------


STUB = r"""
import json, os, sys, threading
from http.server import BaseHTTPRequestHandler, HTTPServer

unready_flag = sys.argv[1]
die_flag = sys.argv[2]

class H(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass
    def do_GET(self):
        if os.path.exists(die_flag):
            os._exit(9)
        ready = not os.path.exists(unready_flag)
        payload = json.dumps(
            {"status": "ok" if ready else "not_ready"}
        ).encode()
        self.send_response(200 if ready else 503)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        self.rfile.read(n)
        payload = json.dumps({"pid": os.getpid()}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

srv = HTTPServer(("127.0.0.1", 0), H)
print(json.dumps({"url": f"http://127.0.0.1:{srv.server_address[1]}"}),
      flush=True)
srv.serve_forever()
"""


class StubSupervisor(FleetSupervisor):
    """FleetSupervisor whose replicas are the stub above — supervision
    semantics (restart, backoff, ejection, storm cap) without paying a
    jax import per spawn."""

    def __init__(self, tmp, **kw):
        self._stub = os.path.join(tmp, "stub_replica.py")
        with open(self._stub, "w") as f:
            f.write(STUB)
        self.unready_flag = os.path.join(tmp, "unready")
        self.die_flag = os.path.join(tmp, "die")
        super().__init__(tmp, **kw)

    def _argv(self, index):
        return [sys.executable, self._stub, self.unready_flag,
                self.die_flag]


FAST = dict(
    health_interval_s=0.05, health_timeout_s=1.0, unhealthy_after=2,
    readmit_after=2, backoff_base_s=0.05, backoff_max_s=0.2,
    contract_timeout_s=20.0,
)


def _wait(predicate, timeout_s=15.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def test_supervisor_restarts_sigkilled_replica(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=1, **FAST),
    )
    sup.start()
    try:
        assert len(sup.healthy_urls()) == 1
        old_pid = sup.replicas[0].pid
        os.kill(old_pid, signal.SIGKILL)
        _wait(
            lambda: sup.replicas[0].restarts >= 1
            and sup.replicas[0].state == ReplicaState.UP,
            what="restart after SIGKILL",
        )
        assert sup.replicas[0].pid != old_pid
        assert sup.healthy_urls()  # back in rotation
    finally:
        sup.stop()


def test_supervisor_ejects_unready_and_readmits(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=1, **FAST),
    )
    sup.start()
    try:
        open(sup.unready_flag, "w").close()
        _wait(
            lambda: sup.replicas[0].state == ReplicaState.EJECTED,
            what="ejection on failing readiness",
        )
        assert sup.healthy_urls() == []
        assert sup.replicas[0].alive  # ejected, NOT restarted
        os.unlink(sup.unready_flag)
        _wait(
            lambda: sup.replicas[0].state == ReplicaState.UP,
            what="re-admission after consecutive passes",
        )
        assert len(sup.healthy_urls()) == 1
    finally:
        sup.stop()


def test_supervisor_storm_cap_gives_up(tmp_path):
    sup = StubSupervisor(
        str(tmp_path),
        config=FleetConfig(
            replicas=1, storm_max_restarts=2, storm_window_s=60.0, **FAST
        ),
    )
    sup.start()
    try:
        # every probe now kills the stub: a crash loop
        open(sup.die_flag, "w").close()
        _wait(
            lambda: sup.replicas[0].state == ReplicaState.FAILED,
            what="storm cap abandoning the slot",
        )
        assert sup.replicas[0].restarts <= 3
        assert "storm" in sup.replicas[0].last_error
        assert sup.healthy_urls() == []
    finally:
        sup.stop()


def test_supervisor_storm_cap_covers_precontract_crashes(tmp_path):
    """A replica whose respawns die BEFORE printing a contract line
    (bad flag, import error) must still trip the storm cap — the
    attempt, not the successful spawn, feeds the window."""
    sup = StubSupervisor(
        str(tmp_path),
        config=FleetConfig(
            replicas=1, storm_max_restarts=2, storm_window_s=60.0,
            **{**FAST, "contract_timeout_s": 5.0},
        ),
    )
    sup.start()
    try:
        # swap the stub for an instant-exit script, then kill the live
        # replica: every respawn from here dies pre-contract
        with open(sup._stub, "w") as f:
            f.write("import sys; sys.exit(3)\n")
        os.kill(sup.replicas[0].pid, signal.SIGKILL)
        _wait(
            lambda: sup.replicas[0].state == ReplicaState.FAILED,
            what="storm cap on pre-contract crash loop",
        )
        assert sup.replicas[0].restarts == 0  # none ever succeeded
        assert "storm" in sup.replicas[0].last_error
    finally:
        sup.stop()


def test_proxy_reaps_slow_loris_with_408(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=1, **FAST),
    )
    sup.start()
    proxy = FleetProxy(sup, metrics=MetricsRegistry(), read_timeout_s=0.5)
    url = proxy.serve("127.0.0.1", 0)
    try:
        host, port = url.split("//")[1].split(":")
        status, held = slow_loris(
            host, int(port), drip_bytes=1, drip_interval_s=0.05,
            duration_s=5.0,
        )
        assert status == 408
        assert held < 2.0
        assert proxy.metrics.counter("fleet_http_408_total").value >= 1
    finally:
        proxy.stop()
        sup.stop()


def test_supervisor_jittered_backoff_bounds(tmp_path):
    import random

    sup = StubSupervisor(
        str(tmp_path),
        config=FleetConfig(
            replicas=1, backoff_base_s=1.0, backoff_max_s=64.0,
            jitter_frac=0.5, **{k: v for k, v in FAST.items()
                                if "backoff" not in k},
        ),
        rng=random.Random(0),
    )
    r = sup.replicas[0]
    now = 100.0
    delays = []
    for n in range(4):
        r.restart_times.clear()
        r.restart_times.extend([now] * n)  # n recent restarts
        sup._schedule_restart(r, now)
        delays.append(r.next_restart_at - now)
    for n, d in enumerate(delays):
        base = 1.0 * (2 ** n)
        assert base * 0.5 <= d <= base * 1.5


def test_read_contract_line_times_out_on_silent_child(tmp_path):
    import subprocess

    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        with pytest.raises(TimeoutError, match="contract line"):
            read_contract_line(proc, timeout_s=0.3)
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_proxy_routes_and_reports_fleet_health(tmp_path):
    sup = StubSupervisor(
        str(tmp_path), config=FleetConfig(replicas=2, **FAST),
    )
    sup.start()
    proxy = FleetProxy(sup, metrics=MetricsRegistry())
    url = proxy.serve("127.0.0.1", 0)
    try:
        with urllib.request.urlopen(f"{url}/healthz", timeout=5.0) as r:
            doc = json.loads(r.read())
        assert r.status == 200 and doc["replicas_up"] == 2
        req = urllib.request.Request(
            f"{url}/v1/similar", data=b'{"genes": ["G0"]}',
            headers={"Content-Type": "application/json"},
        )
        pids = set()
        for _ in range(4):  # round-robin spreads over both stubs
            with urllib.request.urlopen(req, timeout=5.0) as r:
                pids.add(json.loads(r.read())["pid"])
        assert len(pids) == 2
    finally:
        proxy.stop()
        sup.stop()
