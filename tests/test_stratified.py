"""negative_mode="stratified": spec geometry, estimator unbiasedness, and
training sanity (the round-3 noise-term redesign, sgns/step.py
_step_stratified)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import (
    build_stratified_spec,
    noise_distribution,
)
from gene2vec_tpu.sgns.model import SGNSParams
from gene2vec_tpu.sgns.step import sgns_step


@pytest.mark.parametrize("v", [7, 50, 200, 3711, 24447])
def test_spec_geometry_and_unbiasedness(v):
    counts = np.arange(v, 0, -1) ** 2  # skewed, frequency-sorted
    spec = build_stratified_spec(counts)
    q = np.asarray(spec.q)
    tail_w = np.asarray(spec.tail_w)
    assert 1 <= spec.head <= v // 2 or v < 2
    assert spec.block <= v - spec.head
    # every tail row is covered by at least one block
    assert (tail_w[spec.head:] > 0).all()
    # head rows are never tail-sampled
    assert (tail_w[: spec.head] == 0).all()
    # unbiasedness identity: averaging the per-block weighted sums over a
    # uniform block draw recovers the tail q-mass exactly
    starts = np.minimum(
        spec.head + np.arange(spec.nb) * spec.block, v - spec.block
    )
    total = sum(tail_w[s : s + spec.block].sum() for s in starts) / spec.nb
    np.testing.assert_allclose(total, q[spec.head :].sum(), rtol=1e-5)


def test_stratified_loss_unbiased_vs_exact_expectation():
    """The stratified loss, averaged over block draws, must equal the exact
    SGNS objective (positives + K * E_q[masked softplus]) computed densely.
    This pins both the head term's exactness and the tail importance
    weights in one identity."""
    v_size, d, b = 64, 16, 32
    rng = np.random.RandomState(0)
    counts = (np.arange(v_size, 0, -1) ** 1.5).astype(np.int64)
    spec = build_stratified_spec(counts, head=8, block=8)
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
        ctx=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
    )
    pairs = jnp.asarray(rng.randint(0, v_size, (b, 2)).astype(np.int32))

    def loss_of(key):
        _, loss = sgns_step(
            params, pairs, None, key, 0.0,
            negative_mode="stratified", stratified=spec, shared_groups=8,
        )
        return loss

    losses = jax.vmap(loss_of)(
        jax.random.split(jax.random.PRNGKey(1), 512)
    )
    est = float(jnp.mean(losses))

    # exact objective, dense over the whole vocab
    q = np.asarray(spec.q)
    emb, ctx = np.asarray(params.emb), np.asarray(params.ctx)
    centers = np.concatenate([pairs[:, 0], pairs[:, 1]])
    contexts = np.concatenate([pairs[:, 1], pairs[:, 0]])
    v = emb[centers]
    pos = np.log1p(np.exp(-np.sum(v * ctx[contexts], axis=1)))
    logits = v @ ctx.T                                   # (E, V)
    mask = np.arange(v_size)[None, :] != contexts[:, None]
    neg = 5.0 * np.sum(q[None, :] * mask * np.log1p(np.exp(logits)), axis=1)
    exact = float(np.mean(pos + neg))
    # 512 draws of the tail estimator: sampling error ~1%
    assert est == pytest.approx(exact, rel=0.02), (est, exact)


def test_stratified_trains_and_separates(synthetic_corpus_dir):
    from conftest import cluster_separation
    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.pair_reader import load_corpus
    from gene2vec_tpu.sgns.train import train_epochs

    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    emb, losses = train_epochs(
        PairCorpus(vocab, pairs),
        SGNSConfig(dim=16, batch_pairs=64, negative_mode="stratified"),
        60,
    )
    assert losses[-1] < losses[0] - 1.0, losses[:3] + losses[-3:]
    assert cluster_separation(emb, vocab.id_to_token) > 0.3


def test_stratified_requires_spec():
    params = SGNSParams(
        emb=jnp.zeros((8, 4)), ctx=jnp.zeros((8, 4))
    )
    with pytest.raises(ValueError, match="StratifiedSpec"):
        sgns_step(
            params, jnp.zeros((4, 2), jnp.int32), None,
            jax.random.PRNGKey(0), 0.1, negative_mode="stratified",
        )


def test_aggregate_tail_blocks_matches_scatter():
    """The one-hot MXU aggregation (round 4) must compute the same sums
    as the block-indexed scatter-add it replaced — duplicate draws add,
    undrawn blocks are zero, and the (clamped) last block slot works.
    On CPU (this suite) matmuls are exact f32, so equality is tight."""
    from gene2vec_tpu.sgns.step import _aggregate_tail_blocks

    rng = np.random.RandomState(0)
    g, s, d1, nb = 64, 8, 5, 7
    blocks = jnp.asarray(rng.randint(0, nb, (g,)).astype(np.int32))
    payload = jnp.asarray(rng.randn(g, s, d1).astype(np.float32))

    got = _aggregate_tail_blocks(blocks, payload, nb)
    want = jnp.zeros((nb, s, d1), jnp.float32).at[blocks].add(payload)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )

    # a block nobody drew stays exactly zero
    blocks1 = jnp.full((g,), 3, jnp.int32)
    got1 = _aggregate_tail_blocks(blocks1, payload, nb)
    assert np.all(np.asarray(got1[0]) == 0) and np.all(np.asarray(got1[6]) == 0)
    # f32 reduction order differs (matmul tree vs sequential), so compare
    # with an absolute floor for near-cancelling sums
    np.testing.assert_allclose(
        np.asarray(got1[3]), np.asarray(payload.sum(axis=0)),
        rtol=1e-5, atol=1e-5,
    )


def test_stratified_warns_on_degenerate_grouping():
    """ADVICE r3: awkward example counts that collapse the divisor search
    (e.g. E = 2*supergroup) must warn about the raised estimator variance,
    like the shared-mode fallback does."""
    import warnings

    rng = np.random.RandomState(0)
    v_size, d = 64, 16
    counts = (np.arange(v_size, 0, -1) ** 1.5).astype(np.int64)
    spec = build_stratified_spec(counts, head=8, block=8)
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
        ctx=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
    )
    # E = 2*307 (307 prime, default group size 32): the divisor search
    # collapses to g=2 -> groups of 307 examples >> 8*32 -> warn
    pairs = jnp.asarray(rng.randint(0, v_size, (307, 2)).astype(np.int32))
    with pytest.warns(UserWarning, match="tail-block group"):
        sgns_step(
            params, pairs, None, jax.random.PRNGKey(0), 0.05,
            negative_mode="stratified", stratified=spec,
        )
    # a well-shaped batch must not warn
    pairs = jnp.asarray(rng.randint(0, v_size, (32, 2)).astype(np.int32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sgns_step(
            params, pairs, None, jax.random.PRNGKey(0), 0.05,
            negative_mode="stratified", stratified=spec,
        )


@pytest.mark.parametrize("combiner", ["capped", "sum", "mean"])
@pytest.mark.parametrize("both_directions", [True, False])
def test_stratified_edge_configs(combiner, both_directions):
    """Single-direction mode, every combiner, and sub-group/odd batch
    sizes all produce finite losses and finite updated tables."""
    rng = np.random.RandomState(0)
    v_size, d = 64, 16
    counts = (np.arange(v_size, 0, -1) ** 1.5).astype(np.int64)
    spec = build_stratified_spec(counts, head=8, block=8)
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
        ctx=jnp.asarray(rng.randn(v_size, d).astype(np.float32) * 0.3),
    )
    for n_pairs in (20, 13):  # E < group size; E odd and indivisible
        pairs = jnp.asarray(rng.randint(0, v_size, (n_pairs, 2), ).astype(np.int32))
        p2, loss = sgns_step(
            params, pairs, None, jax.random.PRNGKey(0), 0.05,
            negative_mode="stratified", stratified=spec,
            combiner=combiner, both_directions=both_directions,
        )
        assert np.isfinite(float(loss))
        assert np.isfinite(np.asarray(p2.emb)).all()
        assert np.isfinite(np.asarray(p2.ctx)).all()
