"""Intrinsic eval tests: GMT parsing, matmul-form pairwise cosine vs a
naive pair-loop oracle, end-to-end score behavior on structured embeddings."""

import itertools

import numpy as np
import pytest

from gene2vec_tpu.eval.target_function import (
    load_gmt,
    mean_pairwise_cosine,
    pathway_similarities,
    random_pair_similarity,
    target_function,
    target_function_arrays,
)
from gene2vec_tpu.io.emb_io import write_word2vec_format


def _naive_mean_cosine(mat):
    sims = []
    for i, j in itertools.combinations(range(len(mat)), 2):
        a, b = mat[i], mat[j]
        sims.append(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
    return float(np.mean(sims))


def test_mean_pairwise_cosine_matches_pair_loop():
    rng = np.random.RandomState(0)
    mat = rng.randn(17, 9)
    unit = mat / np.linalg.norm(mat, axis=1, keepdims=True)
    assert mean_pairwise_cosine(unit) == pytest.approx(_naive_mean_cosine(mat), abs=1e-10)


def test_load_gmt_size_filter(tmp_path):
    p = tmp_path / "x.gmt"
    small = "PATH_A\turl\t" + "\t".join(f"G{i}" for i in range(3))
    big = "PATH_B\turl\t" + "\t".join(f"G{i}" for i in range(51))
    exact = "PATH_C\turl\t" + "\t".join(f"G{i}" for i in range(50))
    p.write_text(small + "\n" + big + "\n" + exact + "\n")
    pw = load_gmt(str(p))
    assert set(pw) == {"PATH_A", "PATH_C"}  # >50-gene pathway skipped
    assert len(pw["PATH_C"]) == 50


def test_target_function_rewards_pathway_structure(tmp_path):
    """Genes in the same pathway given similar vectors must score > an
    unstructured random embedding."""
    rng = np.random.RandomState(1)
    n_pathways, genes_per, dim = 8, 6, 16
    tokens, rows = [], []
    pathways = {}
    for p in range(n_pathways):
        center = rng.randn(dim) * 3
        members = []
        for g in range(genes_per):
            name = f"P{p}G{g}"
            tokens.append(name)
            rows.append(center + rng.randn(dim) * 0.3)
            members.append(name)
        pathways[f"PW{p}"] = members
    # background genes (in emb, not in pathways)
    for i in range(1500):
        tokens.append(f"BG{i}")
        rows.append(rng.randn(dim))
    matrix = np.asarray(rows)

    structured = target_function_arrays(tokens, matrix, pathways)
    shuffled = matrix[rng.permutation(len(matrix))]
    unstructured = target_function_arrays(tokens, shuffled, pathways)
    assert structured > 2.0 * abs(unstructured)
    assert structured > 1.5


def test_target_function_end_to_end_file(tmp_path):
    rng = np.random.RandomState(2)
    tokens = [f"G{i}" for i in range(40)]
    mat = rng.randn(40, 8).astype(np.float32)
    emb = tmp_path / "emb_w2v.txt"
    write_word2vec_format(str(emb), tokens, mat)
    gmt = tmp_path / "p.gmt"
    gmt.write_text("PW1\turl\tG0\tG1\tG2\nPW2\turl\tG3\tG4\n")
    score = target_function(str(emb), str(gmt), num_random_genes=30)
    assert np.isfinite(score)


def test_random_pair_denominator_deterministic():
    rng = np.random.RandomState(3)
    tokens = [f"G{i}" for i in range(200)]
    mat = rng.randn(200, 8)
    a = random_pair_similarity(tokens, mat, num_genes=100, seed=35)
    b = random_pair_similarity(tokens, mat, num_genes=100, seed=35)
    c = random_pair_similarity(tokens, mat, num_genes=100, seed=36)
    assert a == b
    assert a != c


def test_pathway_similarities_skips_sparse_pathways():
    tokens = ["A", "B", "C"]
    mat = np.eye(3)
    pathways = {"ok": ["A", "B"], "missing": ["X", "Y"], "single": ["C", "Z"]}
    mean, per = pathway_similarities(tokens, mat, pathways)
    assert set(per) == {"ok"}
