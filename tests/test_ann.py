"""Approximate + quantized retrieval (serve/ann.py + engine index modes):
numpy-oracle recall harness, exact-mode bitwise parity, hot-swap
atomicity of the table+index pair, centroid-cache CRC invalidation,
per-mode jit-cache bucketing, and the BENCH_ANN analysis gate."""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gene2vec_tpu.serve import ann
from gene2vec_tpu.serve.engine import (
    BucketedTopKEngine,
    SimilarityEngine,
    _topk_cosine,
)
from gene2vec_tpu.serve.registry import ModelRegistry, l2_normalize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def clustered_table(rows, dim, clusters, seed=0, spread=0.35):
    rng = np.random.RandomState(seed)
    cent = rng.randn(clusters, dim).astype(np.float32)
    x = cent[rng.randint(0, clusters, rows)]
    return l2_normalize(x + spread * rng.randn(rows, dim).astype(np.float32))


def random_table(rows, dim, seed=0):
    return l2_normalize(
        np.random.RandomState(seed).randn(rows, dim).astype(np.float32)
    )


# -- quantization ------------------------------------------------------------


def test_quantize_rows_roundtrip():
    x = random_table(64, 16, seed=1)
    q, scale = quantized = ann.quantize_rows(x)
    assert q.dtype == np.int8 and scale.dtype == np.float32
    # symmetric per-row scale: dequantized error under half a step
    np.testing.assert_allclose(
        q.astype(np.float32) * scale[:, None], x,
        atol=float(scale.max()) * 0.51,
    )
    del quantized


def test_quantize_rows_zero_row_stays_zero():
    x = np.zeros((3, 8), np.float32)
    x[0, 0] = 1.0
    q, scale = ann.quantize_rows(x)
    assert (q[1:] == 0).all()
    assert np.isfinite(scale).all()


# -- recall harness vs the numpy oracle --------------------------------------


def test_quant_recall_on_seeded_random_tables():
    """Quantization noise must be fully absorbed by the exact-rescore
    tail: recall@10 >= 0.99 on pure-random tables (the adversarial,
    structureless case) across seeds."""
    engine = BucketedTopKEngine(max_batch=32, index="quant")
    for seed in (0, 1, 2):
        x = random_table(2048, 32, seed=seed)
        q = x[np.random.RandomState(seed + 10).choice(2048, 32, False)]
        oracle = ann.exact_oracle(x, q, 10)
        index = ann.build_index(x, "quant")
        _, idx = engine.top_k_ann(index, jnp.asarray(x), q, 10)
        assert ann.recall_at_k(idx, oracle) >= 0.99, f"seed {seed}"


def test_ivf_recall_on_clustered_table():
    x = clustered_table(4096, 32, clusters=64, seed=3)
    q = x[np.random.RandomState(7).choice(4096, 48, False)]
    oracle = ann.exact_oracle(x, q, 10)
    engine = BucketedTopKEngine(max_batch=64, index="ivf", nprobe=8)
    index = ann.build_index(x, "ivf", clusters=64)
    _, idx = engine.top_k_ann(index, jnp.asarray(x), q, 10)
    assert ann.recall_at_k(idx, oracle) >= 0.99


def test_ivf_recall_at_real_vocab_geometry():
    """The real serving geometry: a clustered table at the paper's
    24,447-gene vocab must hold recall@10 >= 0.99 for quant AND ivf
    (the bench gates the same floor at 1M rows)."""
    V = 24447
    x = clustered_table(V, 64, clusters=256, seed=5)
    q = x[np.random.RandomState(11).choice(V, 64, False)]
    oracle = ann.exact_oracle(x, q, 10)
    engine = BucketedTopKEngine(max_batch=64, index="ivf", nprobe=32)
    unit = jnp.asarray(x)
    for mode, kw in (("quant", {}), ("ivf", {"clusters": 256})):
        index = ann.build_index(x, mode, **kw)
        _, idx = engine.top_k_ann(index, unit, q, 10)
        assert ann.recall_at_k(idx, oracle) >= 0.99, mode


def test_ivf_nprobe_sweep_monotone_to_exhaustive():
    """On a RANDOM table (no cluster structure — IVF's worst case)
    recall must improve with nprobe and reach 1.0 when every list is
    probed (nprobe=C makes the index an exhaustive scan + rescore)."""
    x = random_table(1024, 16, seed=4)
    q = x[np.random.RandomState(9).choice(1024, 24, False)]
    oracle = ann.exact_oracle(x, q, 10)
    index = ann.build_index(x, "ivf", clusters=16)
    unit = jnp.asarray(x)
    recalls = []
    for nprobe in (1, 4, 16):
        engine = BucketedTopKEngine(
            max_batch=32, index="ivf", nprobe=nprobe, rescore_mult=8
        )
        _, idx = engine.top_k_ann(index, unit, q, 10)
        recalls.append(ann.recall_at_k(idx, oracle))
    assert recalls == sorted(recalls), recalls
    assert recalls[-1] == 1.0  # exhaustive probe == exact


def test_bf16_quant_variant():
    x = random_table(512, 16, seed=6)
    q = x[:16]
    oracle = ann.exact_oracle(x, q, 5)
    index = ann.build_index(x, "quant", quant_dtype="bf16")
    assert str(index.table_q.dtype) == "bfloat16"
    engine = BucketedTopKEngine(max_batch=16, index="quant")
    _, idx = engine.top_k_ann(index, jnp.asarray(x), q, 5)
    assert ann.recall_at_k(idx, oracle) >= 0.99


def test_rescore_tail_returns_exact_scores():
    """Whatever the approximate stage surfaces, returned SCORES are the
    exact f32 cosine (the rescore contract: approximation can cost
    recall, never wrong numbers)."""
    x = clustered_table(1024, 16, clusters=16, seed=8)
    q = x[:8]
    engine = BucketedTopKEngine(max_batch=8, index="ivf", nprobe=16)
    index = ann.build_index(x, "ivf", clusters=16)
    scores, idx = engine.top_k_ann(index, jnp.asarray(x), q, 5)
    qn = l2_normalize(q)
    for b in range(8):
        expect = qn[b] @ x[idx[b]].T
        np.testing.assert_allclose(scores[b], expect, atol=1e-5)


def test_quant_valid_mask_hides_pad_rows():
    x = random_table(20, 8, seed=2)
    padded = np.concatenate([x, np.zeros((12, 8), np.float32)])
    index = ann.build_index(x, "quant", pad_rows=12)
    engine = BucketedTopKEngine(max_batch=8, index="quant")
    _, idx = engine.top_k_ann(
        index, jnp.asarray(padded), x[:4], 10, valid=20
    )
    assert (idx < 20).all()


def test_ivf_honors_caller_valid_prefix():
    """The top_k contract lets a caller restrict to a row prefix; the
    IVF kernel must honor it like the exact/quant kernels even though
    registry-built lists never reference pad rows."""
    x = random_table(64, 8, seed=2)
    index = ann.build_index(x, "ivf", clusters=4)
    engine = BucketedTopKEngine(max_batch=8, index="ivf", nprobe=4)
    _, idx = engine.top_k_ann(index, jnp.asarray(x), x[:4], 10, valid=30)
    assert (idx < 30).all()


# -- exact-mode parity -------------------------------------------------------


def test_index_exact_bitwise_parity_with_plain_kernel():
    """--index exact must be BITWISE identical to the pre-ANN engine:
    same kernel, same buckets, same bytes out."""
    x = random_table(256, 16, seed=0)
    unit = jnp.asarray(x)
    q = np.random.RandomState(1).randn(5, 16).astype(np.float32)
    engine = BucketedTopKEngine(max_batch=8, index="exact")
    scores, idx = engine.top_k(unit, q, 7)
    # reference: the raw kernel at the same padded shapes
    ref_fn = jax.jit(_topk_cosine, static_argnums=(2, 3))
    qp = np.concatenate([q, np.zeros((3, 16), np.float32)])
    ref_s, ref_i = ref_fn(unit, jnp.asarray(qp), 8, None)
    assert np.array_equal(scores, np.asarray(ref_s)[:5, :7])
    assert np.array_equal(idx, np.asarray(ref_i)[:5, :7])
    # the legacy name keeps constructing the same engine
    assert SimilarityEngine is BucketedTopKEngine


def test_approximate_engine_without_index_falls_back_exact():
    """An approximate-mode engine given a snapshot with no AnnIndex
    serves the exact path (mixed-rollout safety)."""
    x = random_table(64, 8, seed=3)

    class Snapshot:
        unit = jnp.asarray(x)
        tokens = tuple(f"G{i}" for i in range(64))
        ann = None

        def __len__(self):
            return 64

    model = Snapshot()
    engine_ivf = BucketedTopKEngine(max_batch=8, index="ivf")
    engine_exact = BucketedTopKEngine(max_batch=8, index="exact")
    q = [x[1], x[2]]
    out_a = engine_ivf.similar_batch(model, q, 5)
    out_b = engine_exact.similar_batch(model, q, 5)
    assert out_a == out_b


# -- per-mode jit-cache bucketing --------------------------------------------


def test_per_mode_jit_cache_is_bucket_stable():
    x = random_table(512, 16, seed=0)
    unit = jnp.asarray(x)
    engine = BucketedTopKEngine(max_batch=8, index="ivf", nprobe=4)
    quant = ann.build_index(x, "quant")
    ivf = ann.build_index(x, "ivf", clusters=16)
    rng = np.random.RandomState(0)

    def cycle():
        for n in engine.buckets:
            q = rng.randn(n, 16).astype(np.float32)
            engine.top_k(unit, q, 3)
            engine.top_k_ann(quant, unit, q, 3)
            engine.top_k_ann(ivf, unit, q, 3)

    cycle()
    warm = engine.cache_sizes()
    if all(v is None for v in warm.values()):
        pytest.skip("jit cache introspection unavailable")
    cycle()
    cycle()
    assert engine.cache_sizes() == warm
    assert set(warm) == {"exact", "quant", "ivf"}
    # the public accessor /metrics exports
    assert engine.cache_size("quant") == warm["quant"]
    assert engine.cache_size() == sum(v for v in warm.values())


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs a multi-device mesh")
def test_sharded_ann_kernels_match_unsharded():
    """Row-sharded quant/IVF kernels (two_stage_topk merge) return the
    same neighbors as their unsharded twins."""
    from gene2vec_tpu.config import MeshConfig
    from gene2vec_tpu.parallel.mesh import make_mesh
    from gene2vec_tpu.parallel.sharding import row_sharding

    P = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=1, model=P))
    sharding = row_sharding(mesh)
    V, D = 256, 16
    x = clustered_table(V, D, clusters=16, seed=1)
    pad = (-V) % P
    padded = np.concatenate([x, np.zeros((pad, D), np.float32)])
    unit_sh = jax.device_put(jnp.asarray(padded), sharding)
    q = x[np.random.RandomState(3).choice(V, 8, False)]

    plain = BucketedTopKEngine(max_batch=8, index="ivf", nprobe=16)
    shard = BucketedTopKEngine(
        max_batch=8, mesh=mesh, index="ivf", nprobe=16
    )
    for mode in ("quant", "ivf"):
        kw = {"clusters": 16} if mode == "ivf" else {}
        idx_plain = ann.build_index(x, mode, **kw)
        idx_shard = ann.build_index(
            x, mode, sharding=sharding, pad_rows=pad, **kw
        )
        _, i_plain = plain.top_k_ann(
            idx_plain, jnp.asarray(x), q, 10, valid=V
        )
        _, i_shard = shard.top_k_ann(idx_shard, unit_sh, q, 10, valid=V)
        assert set(map(tuple, i_plain)) == set(map(tuple, i_shard)), mode


# -- registry: build, cache, hot swap ----------------------------------------

V, D = 48, 8


def _write_iteration(export_dir, iteration, seed):
    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(seed)
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    emb = rng.randn(V, D).astype(np.float32)
    params = SGNSParams(
        emb=jnp.asarray(emb), ctx=jnp.asarray(np.zeros((V, D), np.float32))
    )
    save_iteration(str(export_dir), D, iteration, params, vocab)
    return emb


def test_registry_builds_and_caches_ivf_index(tmp_path):
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg.refresh()
    m = reg.model
    assert m.ann is not None and m.ann.mode == "ivf"
    assert m.ann.version == m.version
    assert not m.ann.built_from_cache
    cache_dir = export / "ann_cache"
    assert list(cache_dir.glob("ivf_*_crc*.npz")), "centroids not cached"
    # a fresh registry over the same export loads the cache
    reg2 = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg2.refresh()
    assert reg2.model.ann.built_from_cache
    assert reg2.model.ann.crc == m.ann.crc


def test_centroid_cache_invalidated_when_table_crc_changes(tmp_path):
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg.refresh()
    old_crc = reg.model.ann.crc
    # same iteration re-exported with DIFFERENT bytes: the old cache
    # file may still sit in ann_cache, but its CRC key no longer
    # matches the table — the index must rebuild, not reuse
    for f in export.glob("gene2vec_dim_*"):
        f.unlink()
    _write_iteration(export, 1, seed=99)
    reg2 = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg2.refresh()
    m2 = reg2.model
    assert m2.ann.crc != old_crc
    assert not m2.ann.built_from_cache


def test_forged_cache_file_is_ignored(tmp_path):
    x = random_table(32, 8, seed=0)
    cache_dir = tmp_path / "ann_cache"
    index = ann.build_index(
        x, "ivf", clusters=4, cache_dir=str(cache_dir), tag="t"
    )
    (path,) = cache_dir.glob("*.npz")
    # restamp the cached meta with a wrong CRC: loader must reject it
    with np.load(path) as z:
        cent, lists = z["centroids"], z["lists"]
    meta = json.dumps({"crc": (index.crc + 1) & 0xFFFFFFFF})
    np.savez(path, centroids=cent, lists=lists, meta=meta)
    assert ann._load_centroid_cache(str(path), index.crc) is None
    rebuilt = ann.build_index(
        x, "ivf", clusters=4, cache_dir=str(cache_dir), tag="t"
    )
    assert not rebuilt.built_from_cache
    # a TRUNCATED cache (valid zip magic, broken structure) must also
    # mean rebuild — a bad cache file can never block loading a good
    # checkpoint into quarantine
    path.write_bytes(path.read_bytes()[: path.stat().st_size // 2])
    assert ann._load_centroid_cache(str(path), index.crc) is None
    again = ann.build_index(
        x, "ivf", clusters=4, cache_dir=str(cache_dir), tag="t"
    )
    assert not again.built_from_cache


def test_hot_swap_atomicity_of_table_and_index(tmp_path):
    """Under a concurrent reader, every observed snapshot must carry an
    index built for EXACTLY its table — never a (new table, old index)
    or (old table, new index) pair."""
    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg.refresh()
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            m = reg.model  # one snapshot
            a = m.ann
            if a is None or a.version != m.version or (
                a.table_q.shape[0] != m.unit.shape[0]
            ) or a.crc != ann.table_crc(l2_normalize(m.emb)):
                torn.append(m.iteration)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for it in range(2, 6):
        _write_iteration(export, it, seed=it)
        assert reg.refresh()
        assert reg.model.iteration == it
    stop.set()
    t.join(timeout=5)
    assert torn == []


# -- serve app integration ---------------------------------------------------


def test_serve_app_ivf_mode_end_to_end(tmp_path):
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig

    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg = ModelRegistry(str(export), index_mode="ivf", ann_clusters=8)
    assert reg.refresh()
    app = ServeApp(
        reg,
        ServeConfig(max_batch=8, max_delay_ms=1.0, index="ivf",
                    nprobe=8, rescore_mult=4),
    ).start()
    try:
        status, doc = app.handle(
            "POST", "/v1/similar", {"genes": ["G0"], "k": 5}
        )
        assert status == 200
        got = [n["gene"] for n in doc["results"][0]["neighbors"]]
        # nprobe=8 over 8 lists == exhaustive: must match the oracle
        m = reg.model
        scores = np.asarray(m.unit) @ np.asarray(m.unit)[0]
        want = [m.tokens[i] for i in np.argsort(-scores) if i != 0][:5]
        assert got == want
        status, health = app.healthz()
        assert status == 200 and health["index"] == "ivf"
        assert health["ann"]["mode"] == "ivf"
        app.publish_engine_metrics()
        text = app.metrics.prometheus_text()
        assert "engine_jit_cache_entries" in text
        assert 'mode="ivf"' in text
    finally:
        app.stop()


def test_serve_app_exact_mode_counts_no_fallback(tmp_path):
    """index=exact never touches the fallback counter; an approximate
    config over an index-less registry counts it (visibly exact)."""
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig

    export = tmp_path / "exports"
    _write_iteration(export, 1, seed=1)
    reg = ModelRegistry(str(export))  # no index built
    assert reg.refresh()
    app = ServeApp(
        reg, ServeConfig(max_batch=8, max_delay_ms=1.0, index="quant")
    ).start()
    try:
        status, _ = app.handle(
            "POST", "/v1/similar", {"genes": ["G1"], "k": 3}
        )
        assert status == 200
        assert (
            app.metrics.counter("engine_index_fallback_total").value >= 1
        )
    finally:
        app.stop()


# -- ledger + analysis gate --------------------------------------------------


def _ann_doc(ivf_recall=0.999, quant_recall=1.0, real_ivf=0.999,
             real_quant=1.0, speedup=8.0, bytes_factor=30.0, **over):
    doc = {
        "schema_version": 1,
        "bench": "ann",
        "recipe": {
            "rows": 1000000, "dim": 64, "k": 10, "queries": 512,
            "clusters": 1024, "nprobe": 32, "rescore_mult": 4,
            "seed": 0,
        },
        "modes": {
            "exact": {"recall_at_10": 1.0, "p50_ms": 90.0, "p99_ms": 120.0,
                      "bytes_per_query": 256e6},
            "quant": {"recall_at_10": quant_recall, "p50_ms": 30.0,
                      "p99_ms": 40.0, "bytes_per_query": 68e6},
            "ivf": {"recall_at_10": ivf_recall, "p50_ms": 5.0,
                    "p99_ms": 12.0, "bytes_per_query": 8e6,
                    "p99_speedup_vs_exact": speedup,
                    "bytes_reduction_vs_exact": bytes_factor},
        },
        "real_table": {
            "rows": 24447, "dim": 200,
            "recall_at_10_ivf": real_ivf,
            "recall_at_10_quant": real_quant,
        },
    }
    doc.update(over)
    return doc


def test_ledger_adapts_ann_family(tmp_path):
    from gene2vec_tpu.obs import ledger

    p = tmp_path / "BENCH_ANN_r12.json"
    p.write_text(json.dumps(_ann_doc()))
    (rec,) = ledger.ingest_root(str(tmp_path))
    assert rec["family"] == "ann" and rec["round"] == 12
    assert rec["headline_metric"] == "ann_recall_at_10"
    assert rec["metrics"]["ann_recall_at_10"] == 0.999
    assert rec["metrics"]["ann_p99_ms_1m"] == 12.0
    assert rec["metrics"]["ann_real_recall_at_10_ivf"] == 0.999
    assert not rec["legacy_unstamped"]


def test_ann_gate_passes_on_committed_bench():
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_ann import ann_recall_findings

    bad = gating(ann_recall_findings(root=REPO))
    assert bad == [], "\n".join(f.format() for f in bad)


def test_ann_gate_planted_low_recall_fires_exactly_once(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_ann import ann_recall_findings

    (tmp_path / "BENCH_ANN_r99.json").write_text(
        json.dumps(_ann_doc(ivf_recall=0.9))
    )
    findings = ann_recall_findings(root=str(tmp_path))
    bad = gating(findings)
    assert len(bad) == 1, [f.format() for f in findings]
    assert "recall_at_10 0.9 < budget" in bad[0].message
    assert bad[0].pass_id == "ann-recall-budget"


def test_ann_gate_off_recipe_and_missing_keys(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_ann import ann_recall_findings

    # looser probe knob than the budget pins
    doc = _ann_doc()
    doc["recipe"]["nprobe"] = 256
    (tmp_path / "BENCH_ANN_r99.json").write_text(json.dumps(doc))
    (bad,) = gating(ann_recall_findings(root=str(tmp_path)))
    assert "pins nprobe=32" in bad.message

    # dropping the real-table recall must gate, not pass
    doc = _ann_doc()
    del doc["real_table"]["recall_at_10_ivf"]
    (tmp_path / "BENCH_ANN_r99.json").write_text(json.dumps(doc))
    (bad,) = gating(ann_recall_findings(root=str(tmp_path)))
    assert "real_table.recall_at_10_ivf missing" in bad.message

    # the scaling claim must be measured: both gain fields gone gates
    doc = _ann_doc()
    del doc["modes"]["ivf"]["p99_speedup_vs_exact"]
    del doc["modes"]["ivf"]["bytes_reduction_vs_exact"]
    (tmp_path / "BENCH_ANN_r99.json").write_text(json.dumps(doc))
    (bad,) = gating(ann_recall_findings(root=str(tmp_path)))
    assert "scaling claim is unmeasured" in bad.message

    # a gain below the floor gates
    doc = _ann_doc(speedup=1.5, bytes_factor=2.0)
    (tmp_path / "BENCH_ANN_r99.json").write_text(json.dumps(doc))
    (bad,) = gating(ann_recall_findings(root=str(tmp_path)))
    assert "below the budget's 5x" in bad.message


def test_ann_gate_missing_bench_is_info(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_ann import ann_recall_findings

    findings = ann_recall_findings(root=str(tmp_path))
    assert gating(findings) == []
    assert findings[0].severity == "info"
    assert "no ANN bench recorded yet" in findings[0].message


def test_analyze_cli_exits_1_on_planted_recall_collapse(tmp_path):
    """Acceptance: a planted recall collapse fails the DEFAULT
    cli.analyze tier through GENE2VEC_TPU_PERF_ROOT, firing the ANN
    gate exactly once."""
    import subprocess
    import sys

    (tmp_path / "BENCH_ANN_r99.json").write_text(
        json.dumps(_ann_doc(real_ivf=0.5))
    )
    env = {**os.environ, "GENE2VEC_TPU_PERF_ROOT": str(tmp_path)}
    proc = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    fired = [
        f for f in json.loads(proc.stdout)["findings"]
        if f["pass"] == "ann-recall-budget" and f["severity"] != "info"
    ]
    assert len(fired) == 1
    assert "real_table.recall_at_10_ivf 0.5 < budget" in fired[0]["message"]


def test_bytes_per_query_accounting():
    # exact touches the full f32 table; ivf touches centroids + probed
    # int8 lists + the rescore tail — the 1M-row geometry must clear
    # the budget's 5x floor analytically
    exact = ann.bytes_per_query("exact", 1_000_000, 64)
    ivf = ann.bytes_per_query(
        "ivf", 1_000_000, 64, r=64, clusters=1024, list_len=2048,
        nprobe=32,
    )
    assert exact == 256e6
    assert exact / ivf >= 5.0
    with pytest.raises(ValueError):
        ann.bytes_per_query("nope", 1, 1)
