"""Training performance plane: step-phase timeline ring + Perfetto
export, goodput bucket arithmetic, the unified bench ledger adapters,
and the perf regression gate (docs/OBSERVABILITY.md "Training timeline
& goodput", docs/BENCHMARKS.md)."""

import glob
import json
import os
import shutil
import subprocess
import sys

import pytest

from gene2vec_tpu.obs import goodput, ledger
from gene2vec_tpu.obs.timeline import (
    TIMELINE_NAME,
    PhaseTimeline,
    chrome_trace,
    collect_run,
    read_timeline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- timeline ring ----------------------------------------------------------


def test_ring_bounds_and_drop_accounting():
    tl = PhaseTimeline(capacity=8)
    for i in range(20):
        tl.add("dispatch", 0.01, step=i)
    assert len(tl) == 8
    assert tl.dropped == 12
    # newest kept, oldest evicted
    steps = [r["step"] for r in tl.records()]
    assert steps == list(range(12, 20))


def test_disabled_timeline_is_a_noop(tmp_path):
    tl = PhaseTimeline(enabled=False)
    with tl.phase("dispatch", step=0):
        pass
    tl.add("compute", 0.5)
    assert len(tl) == 0
    assert tl.flush(str(tmp_path / TIMELINE_NAME)) == 0
    assert not (tmp_path / TIMELINE_NAME).exists()


def test_phase_context_records_duration_and_attrs():
    tl = PhaseTimeline()
    with tl.phase("compute", step=3, mode="sync"):
        pass
    (rec,) = tl.records()
    assert rec["name"] == "compute"
    assert rec["step"] == 3
    assert rec["mode"] == "sync"
    assert rec["dur"] >= 0
    assert rec["pid"] == os.getpid()


def test_flush_and_read_round_trip(tmp_path):
    tl = PhaseTimeline(capacity=4)
    for i in range(6):
        tl.add("dispatch", 0.01, step=i, wall=100.0 + i)
    path = str(tmp_path / TIMELINE_NAME)
    assert tl.flush(path) == 4
    records = read_timeline(path)
    assert [r["step"] for r in records] == [2, 3, 4, 5]
    # the meta header records the truncation
    with open(path) as f:
        meta = json.loads(f.readline())
    assert meta["type"] == "timeline_meta"
    assert meta["dropped"] == 2


def test_invalid_capacity_rejected():
    with pytest.raises(ValueError):
        PhaseTimeline(capacity=0)


# -- Chrome trace export ----------------------------------------------------


def test_chrome_trace_valid_and_phase_tracks():
    tl = PhaseTimeline()
    for step in range(3):
        for name in ("host_ingest", "dispatch", "compute"):
            tl.add(name, 0.01, step=step, wall=1000.0 + step)
    spans = [
        {"type": "span_end", "name": "iteration", "wall": 1003.0,
         "dur": 1.0, "pid": 42, "tid": 7, "attrs": {"loss": 1.0}},
        {"type": "span_end", "name": "batch_item", "wall": 1003.5,
         "dur": 0.1, "pid": 43, "tid": 8, "hop": True, "trace": "ab" * 16},
        {"type": "event", "name": "probe", "wall": 1004.0, "pid": 42,
         "tid": 7, "attrs": {"rss": 1}},
    ]
    doc = chrome_trace(tl.records(), spans)
    # loads↔dumps round trip: Perfetto parses standard JSON
    doc2 = json.loads(json.dumps(doc))
    events = doc2["traceEvents"]
    assert events, "no events emitted"
    for e in events:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    # >= 3 distinct phase tracks, each with thread_name metadata
    assert set(doc2["otherData"]["phase_tracks"]) == {
        "host_ingest", "dispatch", "compute",
    }
    thread_names = {
        e["args"]["name"] for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert {"phase:host_ingest", "phase:dispatch",
            "phase:compute"} <= thread_names
    # the hop record kept its category and trace id
    hop = [e for e in events if e.get("cat") == "hop"]
    assert hop and hop[0]["args"]["trace"] == "ab" * 16
    # instant event for the probe
    assert any(e["ph"] == "i" and e["name"] == "probe" for e in events)


def test_collect_run_merges_timeline_and_events(tmp_path):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    tl = PhaseTimeline()
    tl.add("dispatch", 0.5, step=1, wall=100.0)
    tl.flush(str(run_dir / TIMELINE_NAME))
    (run_dir / "events.jsonl").write_text(json.dumps({
        "type": "span_end", "name": "iteration", "wall": 101.0,
        "dur": 0.9, "pid": 1, "tid": 1,
    }) + "\n")
    (run_dir / "manifest.json").write_text(json.dumps({
        "name": "sgns", "pid": 1,
    }))
    doc = collect_run(str(run_dir))
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"dispatch", "iteration"} <= names
    # manifest-derived process label
    labels = [
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    ]
    assert any("sgns" in v for v in labels)


def test_timeline_cli_round_trip(tmp_path):
    from gene2vec_tpu.cli.obs import main as obs_main

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    tl = PhaseTimeline()
    for name in ("host_ingest", "dispatch", "compute"):
        tl.add(name, 0.01, step=0)
    tl.flush(str(run_dir / TIMELINE_NAME))
    out = tmp_path / "trace.json"
    assert obs_main(["timeline", str(run_dir), "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert len(doc["otherData"]["phase_tracks"]) >= 3
    # empty dir exits 1 (nothing to export), bad dir exits 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obs_main(["timeline", str(empty)]) == 1
    assert obs_main(["timeline", str(tmp_path / "absent")]) == 2


# -- goodput ----------------------------------------------------------------


def test_goodput_buckets_sum_to_wall():
    records = [
        {"name": "dispatch", "dur": 2.0},
        {"name": "compute", "dur": 5.0},
        {"name": "host_ingest", "dur": 1.0},
        {"name": "ckpt_stage", "dur": 0.5},
        {"name": "unknown_phase", "dur": 99.0},  # unattributed
    ]
    buckets = goodput.classify(records, wall_s=10.0, preempted_s=0.5)
    assert buckets["compute"] == 7.0
    assert buckets["input_stall"] == 1.0
    assert buckets["checkpoint"] == 0.5
    assert buckets["preempted"] == 0.5
    assert abs(sum(buckets.values()) - 10.0) < 1e-9
    assert buckets["other"] == pytest.approx(1.0)


def test_goodput_overlapping_phases_scale_down():
    # instrumented time exceeding the wall clock must not report a sum
    # that disagrees with the clock
    records = [{"name": "compute", "dur": 8.0},
               {"name": "host_ingest", "dur": 4.0}]
    buckets = goodput.classify(records, wall_s=6.0)
    assert abs(sum(buckets.values()) - 6.0) < 1e-9
    assert buckets["compute"] == pytest.approx(4.0)
    assert buckets["input_stall"] == pytest.approx(2.0)
    assert buckets["other"] == pytest.approx(0.0)


def test_goodput_summary_and_utilization():
    records = [{"name": "compute", "dur": 8.0}]
    s = goodput.summarize(
        records, wall_s=10.0, pairs_total=1000.0, peak_pairs_per_sec=200.0,
    )
    assert s["achieved_pairs_per_sec"] == 100.0
    assert s["utilization"] == pytest.approx(0.5)
    assert abs(sum(s["buckets_s"].values()) - 10.0) < 1e-6
    # peak falls back to pairs-over-compute-seconds when not supplied
    s2 = goodput.summarize(records, wall_s=10.0, pairs_total=1000.0)
    assert s2["peak_pairs_per_sec"] == pytest.approx(125.0)


def test_goodput_stamp_into_manifest_and_metrics(tmp_path):
    from gene2vec_tpu.obs.run import Run

    run = Run(str(tmp_path), name="t", probe_devices=False)
    s = goodput.summarize(
        [{"name": "compute", "dur": 1.0}], wall_s=2.0, pairs_total=10.0,
    )
    goodput.stamp(run, s)
    run.close()
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["goodput"]["fractions"]["compute"] == pytest.approx(0.5)
    prom = (tmp_path / "metrics.prom").read_text()
    assert "goodput_compute_fraction" in prom
    assert "achieved_pairs_per_sec" in prom
    # the report surfaces it (text and --json)
    from gene2vec_tpu.obs import report

    assert report.summarize(str(tmp_path))["goodput"] == manifest["goodput"]
    assert "goodput:" in report.format_report(str(tmp_path))


# -- ledger adapters over the real root artifacts ---------------------------

_ARTIFACT_GLOBS = (
    "BENCH_*.json", "MULTICHIP_*.json", "MESH_SANITY_*.json",
    "INTRINSIC_*.json", "REAL_AUC.json",
)


def _real_artifacts():
    out = []
    for pattern in _ARTIFACT_GLOBS:
        out.extend(sorted(glob.glob(os.path.join(REPO, pattern))))
    return out


def test_ledger_ingests_every_real_root_artifact(tmp_path):
    """Acceptance: cli.obs ledger ingests every existing root bench
    artifact without error (copies, so the test never depends on cwd)."""
    sources = _real_artifacts()
    assert len(sources) >= 10, "root artifact set shrank unexpectedly"
    for p in sources:
        shutil.copy(p, tmp_path / os.path.basename(p))
    records = ledger.ingest_root(str(tmp_path))
    ingested = {r["source"] for r in records}
    expected = {
        os.path.basename(p) for p in sources
        if ledger.match_family(os.path.basename(p))
    }
    # every family-matched artifact produced a record, none errored
    assert ingested == expected
    errors = [(r["source"], r["error"]) for r in records if r.get("error")]
    assert errors == []
    # each record carries a resolvable headline metric
    for r in records:
        assert r["headline_metric"], r["source"]
        assert r["headline_metric"] in r["metrics"], r["source"]
    # pre-stamp artifacts are marked legacy — visibly, never silently
    legacy = {r["source"] for r in records if r["legacy_unstamped"]}
    assert "BENCH_r01.json" in legacy
    # the sgns headline series is complete r01..r05
    series = ledger.series(records, "sgns_pairs_per_sec")
    assert [s for s, _ in series][:5] == [
        f"BENCH_r0{i}.json" for i in range(1, 6)
    ]


def test_ledger_unreadable_artifact_yields_error_record(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    (rec,) = ledger.ingest_root(str(tmp_path))
    assert rec["error"]
    assert rec["metrics"] == {}


def test_ledger_jsonl_and_csv_outputs(tmp_path):
    for p in _real_artifacts():
        shutil.copy(p, tmp_path / os.path.basename(p))
    records = ledger.ingest_root(str(tmp_path))
    jl = tmp_path / "ledger.jsonl"
    cv = tmp_path / "ledger.csv"
    ledger.write_jsonl(records, str(jl))
    ledger.write_csv(records, str(cv))
    lines = jl.read_text().strip().splitlines()
    assert len(lines) == len(records)
    assert all(json.loads(ln)["schema"] == ledger.SCHEMA for ln in lines)
    header = cv.read_text().splitlines()[0].split(",")
    assert {"family", "source", "round", "headline_metric"} <= set(header)
    assert "sgns_pairs_per_sec" in header


# -- regression detection ----------------------------------------------------


def _fake_bench(value, rc=0):
    return {
        "n": 1, "cmd": "python bench.py", "rc": rc, "tail": "",
        "parsed": {"metric": "sgns_pairs_per_sec", "value": value,
                   "unit": "pairs/s"},
    }


_RULES = {
    "window": 4, "min_points": 3,
    "metrics": {"sgns_pairs_per_sec": {
        "direction": "higher", "max_regression_frac": 0.3,
    }},
}


def _plant(tmp_path, values):
    for i, v in enumerate(values, start=1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_fake_bench(v))
        )
    return ledger.ingest_root(str(tmp_path))


def test_regression_detection_median_of_band(tmp_path):
    # healthy trajectory: no regression
    records = _plant(tmp_path, [4e6, 5e6, 6e6, 6.5e6])
    (ev,) = ledger.detect_regressions(records, _RULES)
    assert not ev["regressed"]
    # planted collapse: newest far below the trailing-band median
    records = _plant(tmp_path, [4e6, 5e6, 6e6, 6.5e6, 2e6])
    (ev,) = ledger.detect_regressions(records, _RULES)
    assert ev["regressed"]
    assert ev["newest_source"] == "BENCH_r05.json"
    assert ev["band_median"] == pytest.approx(5.5e6)
    # one outlier round in the BAND cannot fake a regression (median,
    # not mean): same healthy newest, one garbage point in history
    records = _plant(tmp_path, [4e6, 0.1e6, 6e6, 6.5e6, 6.2e6])
    (ev,) = ledger.detect_regressions(records, _RULES)
    assert not ev["regressed"]


def test_regression_short_series_skipped(tmp_path):
    records = _plant(tmp_path, [4e6, 5e6])
    (ev,) = ledger.detect_regressions(records, _RULES)
    assert not ev["regressed"]
    assert "skipped" in ev


def test_lower_is_better_direction(tmp_path):
    rules = {
        "window": 4, "min_points": 3,
        "metrics": {"serve_p50_ms_min_load": {
            "direction": "lower", "max_regression_frac": 0.5,
        }},
    }

    def serve_doc(p50):
        return {"bench": "serve_loadgen", "levels": [
            {"offered_rps": 50.0, "p50_ms": p50, "p99_ms": p50 * 3,
             "rejection_rate": 0.0, "errors": 0},
        ]}

    for i, p50 in enumerate([20.0, 22.0, 21.0, 80.0], start=1):
        (tmp_path / f"BENCH_SERVE_r{i:02d}.json").write_text(
            json.dumps(serve_doc(p50))
        )
    records = ledger.ingest_root(str(tmp_path))
    (ev,) = ledger.detect_regressions(records, rules)
    assert ev["regressed"]  # 80ms vs median 21ms: latency exploded


# -- the perf gate (passes_perf + cli.analyze) -------------------------------


def _stage_perf_root(tmp_path, values, with_perf_bench=True):
    root = tmp_path / "perf_root"
    root.mkdir(exist_ok=True)
    for i, v in enumerate(values, start=1):
        (root / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_fake_bench(v))
        )
    if with_perf_bench:
        shutil.copy(
            os.path.join(REPO, "BENCH_PERF_r10.json"),
            root / "BENCH_PERF_r10.json",
        )
    return str(root)


def test_passes_perf_planted_regression_fires_exactly_once(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_perf import perf_findings

    # clean trajectory: nothing gates
    clean = _stage_perf_root(tmp_path, [4e6, 5e6, 6e6, 6.5e6])
    assert gating(perf_findings(root=clean)) == []
    # planted collapse: exactly ONE gating finding, from the regression
    # pass, naming the regressed artifact
    bad = _stage_perf_root(tmp_path, [4e6, 5e6, 6e6, 6.5e6, 2e6])
    gate = gating(perf_findings(root=bad))
    assert len(gate) == 1, [f.format() for f in gate]
    assert gate[0].pass_id == "perf-ledger-regression"
    assert gate[0].path == "BENCH_r05.json"
    assert "sgns_pairs_per_sec" in gate[0].message


def test_passes_perf_timeline_overhead_gate(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_hlo import load_budgets
    from gene2vec_tpu.analysis.passes_perf import (
        BENCH_PERF_NAME,
        perf_findings,
    )

    budget = load_budgets()["perf"]["timeline_overhead"]
    recipe = {k: budget[k] for k in
              ("dim", "vocab", "num_pairs", "batch_pairs", "rounds",
               "epochs_per_window")}
    root = tmp_path / "root"
    root.mkdir()
    # missing bench: info only
    fs = perf_findings(root=str(root))
    assert gating(fs) == []
    assert any(f.pass_id == "perf-timeline-overhead-budget"
               and f.severity == "info" for f in fs)
    ok = {
        "bench": "timeline_overhead", "recipe": recipe,
        "rate_timeline_off": 100.0, "rate_timeline_on": 99.5,
        "regression_frac": 0.005,
    }
    (root / BENCH_PERF_NAME).write_text(json.dumps(ok))
    assert gating(perf_findings(root=str(root))) == []
    for doc in (
        {**ok, "regression_frac": 0.10},              # over budget
        {**ok, "recipe": {**recipe, "rounds": 1}},    # shrunken recipe
        {**ok, "recipe": {**recipe,                   # half-length windows
                          "epochs_per_window": 1}},
        {k: v for k, v in ok.items()
         if k != "regression_frac"},                  # dropped key
        {**ok, "recipe": {}},                         # recipe gone
    ):
        (root / BENCH_PERF_NAME).write_text(json.dumps(doc))
        gate = gating(perf_findings(root=str(root)))
        assert len(gate) == 1, doc
        assert gate[0].pass_id == "perf-timeline-overhead-budget"
    # the gate follows the round convention: a NEWER violating record
    # (r11) must win over the stale clean r10
    (root / BENCH_PERF_NAME).write_text(json.dumps(ok))
    (root / "BENCH_PERF_r11.json").write_text(json.dumps(
        {**ok, "regression_frac": 0.10}
    ))
    gate = gating(perf_findings(root=str(root)))
    assert len(gate) == 1
    assert gate[0].path == "BENCH_PERF_r11.json"


def test_analyze_cli_exits_1_on_planted_regression(tmp_path):
    """Acceptance: a planted throughput regression fails the DEFAULT
    cli.analyze tier (and the clean staged root passes it)."""
    bad = _stage_perf_root(tmp_path, [4e6, 5e6, 6e6, 6.5e6, 2e6])
    env = {**os.environ, "GENE2VEC_TPU_PERF_ROOT": bad}
    proc = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    fired = [f for f in doc["findings"]
             if f["pass"] == "perf-ledger-regression"
             and f["severity"] != "info"]
    assert len(fired) == 1


def test_obs_ledger_cli_check(tmp_path):
    from gene2vec_tpu.cli.obs import main as obs_main

    root = _stage_perf_root(tmp_path, [4e6, 5e6, 6e6, 6.5e6])
    out = tmp_path / "ledger.jsonl"
    assert obs_main(
        ["ledger", root, "--check", "--out", str(out)]
    ) == 0
    assert out.exists()
    bad = _stage_perf_root(tmp_path, [4e6, 5e6, 6e6, 6.5e6, 2e6])
    assert obs_main(["ledger", bad, "--check"]) == 1


# -- provenance stamps -------------------------------------------------------


def test_bench_stamp_and_adapter_provenance(tmp_path):
    sys.path.insert(0, REPO)
    try:
        from bench import bench_stamp
    finally:
        sys.path.pop(0)
    doc = bench_stamp({"metric": "sgns_pairs_per_sec", "value": 1.0})
    assert doc["schema_version"] == 1
    assert "command" in doc and "created_unix" in doc
    # a stamped artifact is NOT legacy, and its producer is recorded
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        **_fake_bench(5e6), "schema_version": 1,
        "command": "python bench.py",
    }))
    (rec,) = ledger.ingest_root(str(tmp_path))
    assert rec["legacy_unstamped"] is False
    assert rec["producer"] == "python bench.py"
    # the BENCH_r* driver wrapper stores bench's stdout doc under
    # "parsed" — stamps must survive the wrapping
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 1, "cmd": "driver", "rc": 0, "tail": "",
        "parsed": {**_fake_bench(6e6)["parsed"],
                   **bench_stamp({})},
    }))
    rec2 = [r for r in ledger.ingest_root(str(tmp_path))
            if r["source"] == "BENCH_r02.json"][0]
    assert rec2["legacy_unstamped"] is False
    assert rec2["producer"]
    assert rec2["created_unix"] == pytest.approx(
        json.loads((tmp_path / "BENCH_r02.json").read_text())
        ["parsed"]["created_unix"]
    )


# -- trainer integration -----------------------------------------------------


@pytest.fixture(scope="module")
def tiny_corpus():
    import numpy as np

    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 64, (800, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=64).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(64)], counts), pairs)


def test_sgns_run_writes_timeline_and_goodput(tmp_path, tiny_corpus):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.train import SGNSTrainer

    export = tmp_path / "export"
    trainer = SGNSTrainer(tiny_corpus, SGNSConfig(
        dim=8, batch_pairs=256, num_iters=2, txt_output=False,
    ))
    trainer.run(str(export), log=lambda m: None)
    records = read_timeline(str(export / TIMELINE_NAME))
    phases = {r["name"] for r in records}
    assert {"host_ingest", "dispatch", "compute", "ckpt_stage"} <= phases
    manifest = json.loads((export / "manifest.json").read_text())
    g = manifest["goodput"]
    assert abs(sum(g["buckets_s"].values()) - g["wall_s"]) < 1e-3
    assert g["pairs_total"] > 0
    prom = (export / "metrics.prom").read_text()
    assert "goodput_compute_fraction" in prom


def test_sgns_run_timeline_off_writes_nothing(tmp_path, tiny_corpus):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.train import SGNSTrainer

    export = tmp_path / "export_off"
    trainer = SGNSTrainer(tiny_corpus, SGNSConfig(
        dim=8, batch_pairs=256, num_iters=1, txt_output=False,
        timeline=False,
    ))
    trainer.run(str(export), log=lambda m: None)
    assert not (export / TIMELINE_NAME).exists()
    # goodput still stamps (wall + pairs are timeline-independent)
    manifest = json.loads((export / "manifest.json").read_text())
    assert manifest["goodput"]["pairs_total"] > 0


def test_cbow_hs_run_writes_timeline(tmp_path, tiny_corpus):
    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.sgns.cbow_hs import CBOWHSTrainer

    export = tmp_path / "export_hs"
    trainer = CBOWHSTrainer(tiny_corpus, SGNSConfig(
        dim=8, batch_pairs=256, num_iters=1, objective="cbow_hs",
        txt_output=False,
    ))
    trainer.run(str(export), log=lambda m: None)
    records = read_timeline(str(export / TIMELINE_NAME))
    assert {"dispatch", "compute"} <= {r["name"] for r in records}
    assert "goodput" in json.loads((export / "manifest.json").read_text())
