"""Test env: 8 virtual CPU devices so mesh/sharding paths run without TPUs.

Must set flags before jax initializes its backends (standard JAX practice,
SURVEY §4).
"""

import os
import sys

# Force-override: the session env pins JAX_PLATFORMS to the real TPU tunnel
# (axon), which would make every test compile against (and contend for) the
# single chip. Tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The interpreter's sitecustomize imports jax at startup, so jax.config
# latched the env *before* the overrides above.  Re-pin via the config API
# (valid any time before backend initialization).
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_deselected(items):
    """Track deselected items so tests/test_analysis.py can reconstruct
    the FULL collected count (selected + deselected) of this session and
    cross-check round-summary test-count claims against it without a
    second (expensive) collection pass."""
    if items:
        config = items[0].session.config
        config._gene2vec_deselected = (
            getattr(config, "_gene2vec_deselected", 0) + len(items)
        )


def pytest_collection_finish(session):
    session.config._gene2vec_collected = len(session.items) + getattr(
        session.config, "_gene2vec_deselected", 0
    )


@pytest.fixture(scope="session")
def synthetic_corpus_dir(tmp_path_factory):
    """A small gene-pair corpus directory shaped like the reference's
    ``data/test.txt`` smoke fixture (2 tokens per line, txt suffix)."""
    rng = np.random.RandomState(7)
    d = tmp_path_factory.mktemp("corpus")
    genes = [f"GENE{i}" for i in range(40)]
    # pairs drawn within 4 clusters of 10 genes → planted co-expression
    # structure that SGNS can actually learn (loss must decrease)
    lines = []
    for _ in range(300):
        c = rng.randint(4)
        a, b = rng.choice(10, 2, replace=False) + 10 * c
        lines.append(f"{genes[a]} {genes[b]}")
    (d / "pairs_a.txt").write_text("\n".join(lines[:150]) + "\n")
    (d / "pairs_b.txt").write_text("\n".join(lines[150:]) + "\n")
    (d / "ignored.csv").write_text("not,a,pair,file\n")
    return str(d)


def cluster_separation(emb, tokens, prefix="GENE", cluster_size=10):
    """Mean intra-cluster minus inter-cluster cosine for the synthetic
    corpus's planted clusters (shared by backend/variant quality tests)."""
    emb = np.asarray(emb, dtype=np.float64)
    emb = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    cluster = np.array([int(t[len(prefix):]) // cluster_size for t in tokens])
    sims = emb @ emb.T
    intra = sims[cluster[:, None] == cluster[None, :]].mean()
    inter = sims[cluster[:, None] != cluster[None, :]].mean()
    return float(intra - inter)
