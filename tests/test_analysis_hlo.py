"""graftcheck tier-2 (slow): jaxpr/HLO invariant checks on the hot paths.

Compiles the SGNS epoch, CBOW-HS epoch, GGIPNN train step, and the
serve top-k engine on the virtual 8-device CPU backend and enforces: no
host callbacks, dtype discipline, jit cache stability (including across
the serve engine's bucketed batch shapes), and the per-mesh
collective-bytes budgets in gene2vec_tpu/analysis/budgets.json.  Driven
standalone by ``scripts/run_static_analysis.sh`` (or ``cli.analyze
--hlo all``).
"""

import pytest

from gene2vec_tpu.analysis import gating
from gene2vec_tpu.analysis.passes_hlo import (
    budget_findings,
    build_sgns,
    cache_stability_findings,
    dtype_census,
    dtype_findings,
    host_callback_findings,
    hot_path_findings,
    load_budgets,
)

pytestmark = pytest.mark.slow


# -- unit tests on the check primitives (cheap, but grouped here with
# their tier) ---------------------------------------------------------------


def test_dtype_census_and_findings():
    hlo = "x = f32[8,2] add(f32[8,2] a, f32[8,2] b)\ny = f64[4] c(bf16[4] d)"
    assert dtype_census(hlo) == {"f32": 3, "f64": 1, "bf16": 1}
    fs = dtype_findings(hlo, "hlo:unit", compute_dtype="float32")
    msgs = [f.message for f in gating(fs)]
    assert any("f64" in m for m in msgs)
    assert any("bf16" in m for m in msgs)
    clean = "x = f32[8] add(f32[8] a, f32[8] b)"
    assert gating(dtype_findings(clean, "hlo:unit")) == []


def test_host_callback_detection():
    hlo = (
        'cc = f32[2] custom-call(f32[2] a), '
        'custom_call_target="xla_python_cpu_callback"'
    )
    assert len(host_callback_findings(hlo, "hlo:unit")) == 1
    benign = (
        'cc = f32[2] custom-call(f32[2] a), custom_call_target="TopK"'
    )
    assert host_callback_findings(benign, "hlo:unit") == []


# -- the real gates ---------------------------------------------------------


def test_hot_paths_clean():
    """SGNS + CBOW-HS + GGIPNN + serve top-k compiled steps: no host
    callbacks, no dtype violations, stable jit caches under fresh
    same-shape inputs (and across the serve engine's batch buckets)."""
    findings = hot_path_findings()
    bad = gating(findings)
    assert bad == [], "\n".join(f.format() for f in bad)
    labels = {f.path for f in findings}
    assert "hlo:serve" in labels
    # bucket stability is asserted PER INDEX MODE: the quant/IVF
    # kernels must compile once per bucket exactly like the exact one
    for mode in ("exact", "quant", "ivf"):
        assert f"hlo:serve/buckets/{mode}" in labels
    # the cache checks must actually have RUN — the introspection-
    # unavailable skip also emits this pass_id, so assert on the
    # structured checked flag, not mere presence
    assert any(
        f.pass_id == "hlo-cache-stability" and (f.data or {}).get("checked")
        for f in findings
    ), "cache-stability checks were silently skipped:\n" + "\n".join(
        f.format() for f in findings if f.pass_id == "hlo-cache-stability"
    )


def test_sharded_sgns_no_host_callbacks():
    """The 8-way sharded program (collectives present) stays free of
    host callbacks too — the collective path must not smuggle one in."""
    _, _, lowered, _ = build_sgns(
        dim=16, vocab=64, batch_pairs=32, num_pairs=256, mesh=(8, 1),
    )
    text = lowered.compile().as_text()
    assert host_callback_findings(text, "hlo:sgns/8way") == []
    assert gating(dtype_findings(text, "hlo:sgns/8way")) == []


def test_collective_budgets_hold():
    """The enforced version of scripts/hlo_comm_audit.py: every budgeted
    mesh config stays within its recorded per-pair collective bytes.
    The data-parallel config is the acceptance gate; config 5
    (vocab_sharded_8way_dense) records the round-5 22.7 KB/pair value as
    its documented budget."""
    findings = budget_findings()
    bad = gating(findings)
    assert bad == [], "\n".join(f.format() for f in bad)
    labels = {f.path for f in findings}
    assert "hlo:sgns/data_parallel_8way" in labels
    assert "hlo:sgns/vocab_sharded_8way_dense" in labels
    assert "hlo:serve/row_sharded_8way" in labels


def test_budget_file_documented():
    budgets = load_budgets()
    units = {"sgns": "bytes_per_pair", "serve": "bytes_per_query"}
    for section, unit in units.items():
        assert budgets[section], section
        for key, entry in budgets[section].items():
            if "mesh" not in entry:
                continue  # non-kernel budget (capacity_rps: passes_serve)
            ref, cap = entry[f"reference_{unit}"], entry[f"max_{unit}"]
            assert cap >= ref, key
            # headroom stays a budget, not a blank check (< 10%)
            assert cap < ref * 1.10, key


def test_cache_stability_catches_recompiles():
    """Negative control: a function that recompiles every call (fresh
    wrapper) must trip the check."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    calls = []

    def args_maker():
        return (jnp.asarray(np.ones(4, np.float32)),)

    class FreshEveryCall:
        def __call__(self, x):
            calls.append(1)
            return jax.jit(lambda y: y + 1)(x)  # planted hazard

        def _cache_size(self):
            return len(calls)

    fs = cache_stability_findings(FreshEveryCall(), args_maker, "hlo:unit")
    assert gating(fs), [f.format() for f in fs]
