"""Native Hogwild SGNS oracle: learns, checkpoints, and registers as a
backend."""

import numpy as np
import pytest

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns import native_backend
from gene2vec_tpu.sgns.backends import make_backend_trainer

from conftest import cluster_separation


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native_backend.available():
        pytest.skip("native hogwild library unavailable and build failed")


def test_hogwild_learns_cluster_structure(tmp_path, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    cfg = SGNSConfig(dim=16, num_iters=60, seed=0)
    trainer = make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    )
    params = trainer.run(str(tmp_path / "emb"), log=lambda s: None)
    sep = cluster_separation(np.asarray(params.emb), vocab.id_to_token)
    assert sep > 0.3, sep
    assert np.isfinite(np.asarray(params.emb)).all()


def test_hogwild_loss_decreases(synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    trainer = make_backend_trainer(
        PairCorpus(vocab, pairs), SGNSConfig(dim=16, seed=0), backend="hogwild"
    )
    params = trainer.init()
    rng = np.random.RandomState(0)
    first = last = None
    for it in range(30):
        params, loss = trainer.train_epoch(params, seed=it, rng=rng)
        first = loss if first is None else first
        last = loss
    assert last < first


def test_hogwild_checkpoint_resume(tmp_path, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    cfg = SGNSConfig(dim=8, num_iters=2)
    out = str(tmp_path / "emb")
    make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    ).run(out, log=lambda s: None)
    msgs = []
    make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    ).run(out, log=msgs.append)
    assert any("resuming from iteration 2" in m for m in msgs)


def test_hogwild_hs_learns_and_matches_tpu_objective(synthetic_corpus_dir):
    """The native HS oracle (BASELINE config 4's CPU denominator) must
    optimize the SAME objective as the jitted cbow_hs path: same Huffman
    tree, comparable loss trajectory, and planted clusters recovered."""
    import jax

    from gene2vec_tpu.sgns.cbow_hs import CBOWHSTrainer
    from gene2vec_tpu.sgns.native_backend import HogwildHSTrainer

    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(dim=16, seed=0, objective="cbow_hs", batch_pairs=64)

    native = HogwildHSTrainer(corpus, cfg, n_threads=1)
    p_nat = native.init()
    rng = np.random.RandomState(0)
    nat_losses = []
    for it in range(40):
        p_nat, loss = native.train_epoch(p_nat, rng=rng)
        nat_losses.append(loss)

    tpu = CBOWHSTrainer(corpus, cfg)
    p_tpu = tpu.init()
    tpu_losses = []
    for it in range(40):
        p_tpu, loss = tpu.train_epoch(
            p_tpu, jax.random.fold_in(jax.random.PRNGKey(0), it)
        )
        tpu_losses.append(float(loss))

    # same objective: both start at the same tree-determined plateau and
    # both minimize it (sequential Hogwild descends faster per epoch than
    # the batched step at tiny scale — only the objective must agree)
    assert abs(nat_losses[0] - tpu_losses[0]) < 0.6, (
        nat_losses[0], tpu_losses[0],
    )
    assert nat_losses[-1] < nat_losses[0] - 0.5
    assert tpu_losses[-1] < tpu_losses[0] - 0.5

    sep = cluster_separation(np.asarray(p_nat.emb), vocab.id_to_token)
    assert sep > 0.2, sep


def test_hogwild_hs_sg_variant_and_validation():
    from gene2vec_tpu.sgns.native_backend import HogwildHSTrainer

    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 50, (2048, 2)).astype(np.int32)
    from gene2vec_tpu.io.vocab import Vocab

    counts = np.bincount(pairs.reshape(-1), minlength=50).astype(np.int64)
    corpus = PairCorpus(Vocab([f"G{i}" for i in range(50)], counts), pairs)
    tr = HogwildHSTrainer(
        corpus, SGNSConfig(dim=8, objective="sg_hs"), n_threads=2
    )
    params = tr.init()
    params, l0 = tr.train_epoch(params)
    for _ in range(10):
        params, l1 = tr.train_epoch(params)
    assert np.isfinite(l1) and l1 < l0
    with pytest.raises(ValueError, match="hs objectives"):
        HogwildHSTrainer(corpus, SGNSConfig(objective="sgns"))


def test_abi_stamp_sidecar(tmp_path):
    """The .abi sidecar replaces the per-process subprocess probe: a
    stamp written for this exact .so passes; missing/mismatched ones, or
    stamps describing a different build, do not."""
    so = tmp_path / "lib.so"
    so.write_bytes(b"\x7fELF fake")
    assert not native_backend._stamp_ok(str(so))  # no stamp yet
    native_backend._write_stamp(str(so))
    assert native_backend._stamp_ok(str(so))
    digest = native_backend._so_digest(str(so))
    (tmp_path / "lib.so.abi").write_text(
        f"{native_backend._ABI_VERSION + 1}\n{digest}\n"
    )
    assert not native_backend._stamp_ok(str(so))  # version mismatch
    (tmp_path / "lib.so.abi").write_text("garbage\n")
    assert not native_backend._stamp_ok(str(so))  # unparseable
    (tmp_path / "lib.so.abi").write_text(f"{native_backend._ABI_VERSION}\n")
    assert not native_backend._stamp_ok(str(so))  # legacy stamp: no hash
    # a stamp is bound to the .so's content: after the library changes
    # (stale build + stamp restored by a git checkout, say) it must fail
    # _stamp_ok no matter the mtimes, forcing the probe-and-rebuild path
    native_backend._write_stamp(str(so))
    so.write_bytes(b"\x7fELF a different build")
    assert not native_backend._stamp_ok(str(so))


def test_loaded_lib_wrote_stamp():
    """After available() the real library carries a matching stamp, so
    future processes skip the subprocess ABI probe."""
    assert native_backend._stamp_ok(native_backend._LIB_PATH)
