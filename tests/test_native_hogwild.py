"""Native Hogwild SGNS oracle: learns, checkpoints, and registers as a
backend."""

import numpy as np
import pytest

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns import native_backend
from gene2vec_tpu.sgns.backends import make_backend_trainer

from conftest import cluster_separation


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native_backend.available():
        pytest.skip("native hogwild library unavailable and build failed")


def test_hogwild_learns_cluster_structure(tmp_path, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    cfg = SGNSConfig(dim=16, num_iters=60, seed=0)
    trainer = make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    )
    params = trainer.run(str(tmp_path / "emb"), log=lambda s: None)
    sep = cluster_separation(np.asarray(params.emb), vocab.id_to_token)
    assert sep > 0.3, sep
    assert np.isfinite(np.asarray(params.emb)).all()


def test_hogwild_loss_decreases(synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    trainer = make_backend_trainer(
        PairCorpus(vocab, pairs), SGNSConfig(dim=16, seed=0), backend="hogwild"
    )
    params = trainer.init()
    rng = np.random.RandomState(0)
    first = last = None
    for it in range(30):
        params, loss = trainer.train_epoch(params, seed=it, rng=rng)
        first = loss if first is None else first
        last = loss
    assert last < first


def test_hogwild_checkpoint_resume(tmp_path, synthetic_corpus_dir):
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    cfg = SGNSConfig(dim=8, num_iters=2)
    out = str(tmp_path / "emb")
    make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    ).run(out, log=lambda s: None)
    msgs = []
    make_backend_trainer(
        PairCorpus(vocab, pairs), cfg, backend="hogwild"
    ).run(out, log=msgs.append)
    assert any("resuming from iteration 2" in m for m in msgs)
