"""End-to-end smoke: train on the synthetic fixture, check loss decrease,
checkpoint cadence, export formats, and resume (SURVEY §4 implications)."""

import os

import numpy as np
import pytest

import jax

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.io.emb_io import read_matrix_txt, read_word2vec_format
from gene2vec_tpu.io.pair_reader import load_corpus
from gene2vec_tpu.sgns.train import SGNSTrainer


@pytest.fixture(scope="module")
def trained(tmp_path_factory, synthetic_corpus_dir):
    out = str(tmp_path_factory.mktemp("emb"))
    vocab, pairs = load_corpus(synthetic_corpus_dir, "txt")
    corpus = PairCorpus(vocab, pairs)
    cfg = SGNSConfig(dim=16, num_iters=3, batch_pairs=50, negatives=5, seed=0)
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.run(out, log=lambda s: None)
    return out, corpus, cfg, trainer, params


def test_loss_decreases(trained):
    out, corpus, cfg, trainer, params = trained
    losses = []
    for it in range(1, cfg.num_iters + 1):
        _, _, meta = ckpt.load_iteration(out, cfg.dim, it)
        losses.append(meta["loss"])
    assert losses[-1] < losses[0]


def test_checkpoint_files_and_formats(trained):
    out, corpus, cfg, _, params = trained
    for it in range(1, cfg.num_iters + 1):
        prefix = ckpt.ckpt_prefix(out, cfg.dim, it)
        assert os.path.exists(prefix + ".npz")
        assert os.path.exists(prefix + ".txt")
        assert os.path.exists(prefix + "_w2v.txt")
    toks, m = read_matrix_txt(ckpt.ckpt_prefix(out, cfg.dim, cfg.num_iters) + ".txt")
    assert toks == corpus.vocab.id_to_token
    np.testing.assert_allclose(m, np.asarray(params.emb), rtol=1e-6)
    toks2, m2 = read_word2vec_format(
        ckpt.ckpt_prefix(out, cfg.dim, cfg.num_iters) + "_w2v.txt"
    )
    assert toks2 == toks
    np.testing.assert_allclose(m2, m, rtol=1e-6)


def test_resume_continues_from_latest(trained, tmp_path):
    out, corpus, cfg, _, _ = trained
    assert ckpt.latest_iteration(out, cfg.dim) == cfg.num_iters
    # extend num_iters and resume: iterations 1..3 must not be retrained
    cfg5 = SGNSConfig(
        dim=cfg.dim, num_iters=5, batch_pairs=50, negatives=5, seed=0
    )
    trainer = SGNSTrainer(corpus, cfg5)
    logs = []
    trainer.run(out, log=logs.append)
    assert any("resuming from iteration 3" in s for s in logs)
    assert ckpt.latest_iteration(out, cfg.dim) == 5
    started = [s for s in logs if s.endswith("start")]
    assert len(started) == 2  # only iterations 4 and 5


def test_embedding_quality_sanity(trained):
    """Pairs seen in the corpus should, on average, be more similar than
    random pairs — the de-facto correctness oracle the reference relies
    on (target-function shape, src/evaluation_target_function.py:54-60)."""
    out, corpus, cfg, _, params = trained
    emb = np.asarray(params.emb)
    unit = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    pair_sims = np.einsum(
        "nd,nd->n", unit[corpus.pairs[:, 0]], unit[corpus.pairs[:, 1]]
    )
    rng = np.random.RandomState(0)
    ra = rng.randint(0, len(corpus.vocab), 2000)
    rb = rng.randint(0, len(corpus.vocab), 2000)
    keep = ra != rb
    rand_sims = np.einsum("nd,nd->n", unit[ra[keep]], unit[rb[keep]])
    assert pair_sims.mean() > rand_sims.mean()


def test_epoch_shuffle_preserves_pair_multiset():
    """Offset/block shuffle must reorder, never alter, the pair stream."""
    import jax
    import jax.numpy as jnp

    from gene2vec_tpu.data.pipeline import epoch_shuffle

    rng = np.random.RandomState(0)
    pairs = jnp.asarray(rng.randint(0, 50, (2048, 2)).astype(np.int32))
    for mode in ("offset", "full"):
        out = jax.jit(
            lambda p, k: epoch_shuffle(p, k, 2048, 4, 512, mode)
        )(pairs, jax.random.PRNGKey(3))
        got = np.asarray(out)
        assert got.shape == (2048, 2)
        want = np.asarray(pairs)
        key = lambda a: sorted(map(tuple, a.tolist()))
        assert key(got) == key(want), mode
        assert not np.array_equal(got, want)  # it actually shuffled


REFERENCE_SMOKE = "/root/reference/data"


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE_SMOKE}/test.txt"),
    reason="reference smoke corpus not mounted",
)
def test_reference_smoke_corpus_end_to_end(tmp_path):
    """BASELINE required config 1's data: the reference's own 39-pair
    ``data/test.txt`` through the reference-shaped CLI invocation
    (``python gene2vec.py data_dir out_dir txt``, src/gene2vec.py:8-15).
    The trainer must shrink its batch to the tiny corpus, run all
    iterations, and leave the per-iteration artifact set."""
    import shutil

    from gene2vec_tpu.cli.gene2vec import main as gene2vec_main

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    shutil.copy(f"{REFERENCE_SMOKE}/test.txt", data_dir / "test.txt")
    out = tmp_path / "out"
    rc = gene2vec_main(
        [str(data_dir), str(out), "txt", "--dim", "16", "--iters", "2"]
    )
    assert rc == 0
    # per-iteration artifact set: every iteration keeps all three formats
    for it in (1, 2):
        for suffix in (".npz", ".txt", "_w2v.txt"):
            assert (out / f"gene2vec_dim_16_iter_{it}{suffix}").exists(), (
                it, suffix,
            )
    toks, mat = read_word2vec_format(str(out / "gene2vec_dim_16_iter_2_w2v.txt"))
    assert mat.shape == (len(toks), 16)
    assert np.isfinite(mat).all()
    # every gene of the 39-pair corpus is in vocab (min_count=1 parity)
    with open(f"{REFERENCE_SMOKE}/test.txt", encoding="windows-1252") as f:
        genes = {g for line in f for g in line.split()}
    assert set(toks) == genes


def test_bfloat16_tables_checkpoint_and_export(tmp_path):
    """table_dtype="bfloat16" (the measured +7% opt-in) must checkpoint,
    export, and resume: npz has no bf16 dtype, so the file stores f32 (a
    lossless upcast) and load restores the recorded training width."""
    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 50, (2048, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=50).astype(np.int64)
    from gene2vec_tpu.io.vocab import Vocab

    corpus = PairCorpus(Vocab([f"G{i}" for i in range(50)], counts), pairs)
    cfg = SGNSConfig(
        dim=8, num_iters=2, batch_pairs=256, table_dtype="bfloat16"
    )
    tr = SGNSTrainer(corpus, cfg)
    tr.run(str(tmp_path), log=lambda m: None)

    params, vocab, meta = ckpt.load_iteration(str(tmp_path), 8, 2)
    assert str(params.emb.dtype) == "bfloat16"
    assert meta["table_dtype"] == "bfloat16"
    toks, mat = read_word2vec_format(
        str(tmp_path / "gene2vec_dim_8_iter_2_w2v.txt")
    )
    assert mat.shape == (50, 8) and np.isfinite(mat).all()
    # resume picks up from the saved iteration without retraining
    tr2 = SGNSTrainer(corpus, cfg)
    msgs = []
    tr2.run(str(tmp_path), log=msgs.append)
    assert any("resuming from iteration 2" in m for m in msgs)


def test_resume_honors_configured_table_dtype(tmp_path):
    """Resuming a bf16 checkpoint with table_dtype=float32 configured must
    warn and continue at the CONFIGURED width (and vice versa) — not
    silently undo the config change."""
    rng = np.random.RandomState(0)
    pairs = rng.randint(0, 50, (2048, 2)).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=50).astype(np.int64)
    from gene2vec_tpu.io.vocab import Vocab

    corpus = PairCorpus(Vocab([f"G{i}" for i in range(50)], counts), pairs)
    cfg16 = SGNSConfig(
        dim=8, num_iters=1, batch_pairs=256, table_dtype="bfloat16"
    )
    SGNSTrainer(corpus, cfg16).run(str(tmp_path), log=lambda m: None)

    cfg32 = SGNSConfig(
        dim=8, num_iters=2, batch_pairs=256, table_dtype="float32"
    )
    tr = SGNSTrainer(corpus, cfg32)
    with pytest.warns(UserWarning, match="resuming at the configured"):
        params = tr.run(str(tmp_path), log=lambda m: None)
    assert str(params.emb.dtype) == "float32"
