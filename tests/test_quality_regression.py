"""Quality regression guards for the DEFAULT training configuration.

Round 2 shipped a default whose loss froze at init (ln2·(1+K) ≈ 4.157)
and whose geometry was never checked; round 3's diagnosis (see
docs/QUALITY_NOTES.md) found two distinct failure modes that plain
loss-decrease tests miss:

* FREEZE — asymmetric capping crushes the negative term; loss plateaus
  at its init value while positive-only dynamics still move cosine
  metrics.
* COLLAPSE — low-rank/weakened noise lets every vector drift onto one
  ray: the loss keeps falling, intra-cluster cosine looks perfect, but
  inter-cluster cosine climbs toward 1 and the embedding stops ranking.

Both are pinned here with the *default* SGNSConfig on a planted-cluster
corpus (the verify recipe's shape: 10 clusters × 20 genes).
"""

import itertools

import numpy as np
import pytest

import jax

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.train import SGNSTrainer

N_CLUSTERS, N_GENES, N_PAIRS_PER = 10, 20, 1500
EPOCHS = 12


@pytest.fixture(scope="module")
def planted():
    rng = np.random.RandomState(0)
    lines = []
    for c in range(N_CLUSTERS):
        genes = [f"C{c}G{i}" for i in range(N_GENES)]
        for _ in range(N_PAIRS_PER):
            a, b = rng.choice(N_GENES, 2, replace=False)
            lines.append((genes[a], genes[b]))
    vocab = Vocab.from_pairs(lines)
    return vocab, PairCorpus(vocab, vocab.encode_pairs(lines))


def _train_default(corpus, epochs=EPOCHS, dim=32, batch_pairs=1024):
    cfg = SGNSConfig(dim=dim, num_iters=epochs, batch_pairs=batch_pairs)
    tr = SGNSTrainer(corpus, cfg)
    params = tr.init()
    losses = []
    for it in range(1, epochs + 1):
        params, loss = tr.train_epoch(
            params, jax.random.fold_in(jax.random.PRNGKey(cfg.seed), it)
        )
        losses.append(float(loss))
    return np.asarray(params.emb), losses


def _cluster_cosines(vocab, emb):
    m = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9)
    idx = vocab.token_to_id
    rng = np.random.RandomState(1)
    intra, inter = [], []
    for c in range(N_CLUSTERS):
        rows = [idx[f"C{c}G{i}"] for i in range(8)]
        for a, b in itertools.combinations(rows, 2):
            intra.append(m[a] @ m[b])
    for _ in range(400):
        c1, c2 = rng.choice(N_CLUSTERS, 2, replace=False)
        inter.append(
            m[idx[f"C{c1}G{rng.randint(N_GENES)}"]]
            @ m[idx[f"C{c2}G{rng.randint(N_GENES)}"]]
        )
    return float(np.mean(intra)), float(np.mean(inter))


def test_default_config_loss_decreases_not_frozen(planted):
    """FREEZE guard: the loss must fall well below its init plateau
    ln2·(1+K) — round 2's default sat within 0.02 of it forever."""
    _, losses = _train_default(planted[1])
    init_plateau = float(np.log(2.0) * (1 + SGNSConfig().negatives))
    assert losses[0] == pytest.approx(init_plateau, abs=0.1)
    assert losses[-1] < init_plateau - 1.0, losses


def test_default_config_geometry_not_collapsed(planted):
    """COLLAPSE guard: intra-cluster cosine high AND inter-cluster cosine
    bounded.  The collapsing designs in QUALITY_NOTES §2 pass any
    intra-only check while inter drifts to 0.97."""
    vocab, corpus = planted
    emb, _ = _train_default(corpus)
    intra, inter = _cluster_cosines(vocab, emb)
    assert intra > 0.95, (intra, inter)
    assert inter < 0.6, (intra, inter)
    assert intra - inter > 0.35, (intra, inter)
