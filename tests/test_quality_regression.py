"""Quality regression guards for the DEFAULT training configuration.

Round 2 shipped a default whose loss froze at init (ln2·(1+K) ≈ 4.157)
and whose geometry was never checked; round 3's diagnosis (see
docs/QUALITY_NOTES.md) found two distinct failure modes that plain
loss-decrease tests miss:

* FREEZE — asymmetric capping crushes the negative term; loss plateaus
  at its init value while positive-only dynamics still move cosine
  metrics.
* COLLAPSE — low-rank/weakened noise lets every vector drift onto one
  ray: the loss keeps falling, intra-cluster cosine looks perfect, but
  inter-cluster cosine climbs toward 1 and the embedding stops ranking.

Both are pinned here with the *default* SGNSConfig on a planted-cluster
corpus (the verify recipe's shape: 10 clusters × 20 genes).
"""

import numpy as np
import pytest

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.eval.planted import (
    INTER_MAX,
    INTRA_MIN,
    cluster_cosines,
    planted_corpus,
)
from gene2vec_tpu.sgns.train import train_epochs

EPOCHS = 12


@pytest.fixture(scope="module")
def planted():
    # smaller than the bench gate's corpus (pairs_per) to keep the CPU-mesh
    # test suite fast; same cliques, same metric, same thresholds
    return planted_corpus(pairs_per=1500)


def _train_default(corpus, epochs=EPOCHS, dim=32, batch_pairs=1024):
    """The canonical shared loop — identical seeding to the bench gate."""
    cfg = SGNSConfig(dim=dim, num_iters=epochs, batch_pairs=batch_pairs)
    return train_epochs(corpus, cfg, epochs)


def test_default_config_loss_decreases_not_frozen(planted):
    """FREEZE guard: the loss must fall well below its init plateau
    ln2·(1+K) — round 2's default sat within 0.02 of it forever."""
    _, losses = _train_default(planted[1])
    init_plateau = float(np.log(2.0) * (1 + SGNSConfig().negatives))
    assert losses[0] == pytest.approx(init_plateau, abs=0.1)
    assert losses[-1] < init_plateau - 1.0, losses


def test_auc_gate_band_rejects_too_good():
    """DEGENERATION guard (VERDICT r3 item 7): the gate must reject AUC
    far above the oracle — the broken P=64 config scores 0.9613 on this
    metric while its loss never moves (QUALITY_NOTES §8), so "too good"
    is a failure signature, not a success."""
    from gene2vec_tpu.eval.holdout import (
        GATE_MAX_AUC,
        GATE_MIN_AUC,
        ORACLE_COS_AUC,
        auc_in_gate_band,
    )

    assert GATE_MIN_AUC < ORACLE_COS_AUC < GATE_MAX_AUC
    assert auc_in_gate_band(ORACLE_COS_AUC)
    assert auc_in_gate_band(0.8965)          # round-3 recorded default
    assert not auc_in_gate_band(0.9613)      # broken P=64 degenerate
    assert not auc_in_gate_band(0.5)         # chance
    assert not auc_in_gate_band(float("nan"))  # diverged


def test_default_config_geometry_not_collapsed(planted):
    """COLLAPSE guard: intra-cluster cosine high AND inter-cluster cosine
    bounded.  The collapsing designs in QUALITY_NOTES §2 pass any
    intra-only check while inter drifts to 0.97."""
    vocab, corpus = planted
    emb, _ = _train_default(corpus)
    intra, inter = cluster_cosines(vocab, emb)
    assert intra > INTRA_MIN, (intra, inter)
    assert inter < INTER_MAX, (intra, inter)
    assert intra - inter > 0.35, (intra, inter)
