"""Multi-model serving plane (serve/catalog.py + routes.py +
autoscale.py pool keys).

Tier-1 fast: spec validation and route splitting are pure functions,
the admission/policy tests run on injected clocks and synthetic
snapshots, and the one live piece — a two-model ModelCatalog — serves
in-process through ``ServeApp.handle`` (no HTTP, no subprocesses)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from gene2vec_tpu.io.checkpoint import save_iteration
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.serve.autoscale import (
    AutoscaleConfig,
    PoolAutoscalePolicy,
    ShardAutoscalePolicy,
)
from gene2vec_tpu.serve.catalog import (
    ModelAdmission,
    ModelCatalog,
    load_catalog_spec,
    parse_catalog_spec,
)
from gene2vec_tpu.serve.routes import model_label, split_model_route
from gene2vec_tpu.serve.server import ServeConfig
from gene2vec_tpu.sgns.model import SGNSParams


def _write_export(export_dir, dim, iteration=1, vocab_size=16, seed=0):
    rng = np.random.RandomState(seed + iteration)
    vocab = Vocab(
        [f"G{i}" for i in range(vocab_size)],
        np.arange(vocab_size, 0, -1),
    )
    emb = rng.randn(vocab_size, dim).astype(np.float32)
    params = SGNSParams(
        emb=jnp.asarray(emb),
        ctx=jnp.asarray(np.zeros((vocab_size, dim), np.float32)),
    )
    save_iteration(str(export_dir), dim, iteration, params, vocab)


# -- spec parsing ------------------------------------------------------------


def _spec_doc(tmp_path, **overrides):
    doc = {
        "schema": "gene2vec-tpu/catalog/v1",
        "default": "alpha",
        "models": {
            "alpha": {"export_dir": str(tmp_path / "a"), "dim": 4},
            "beta": {"export_dir": str(tmp_path / "b"), "dim": 8},
        },
    }
    doc.update(overrides)
    return doc


def test_parse_catalog_spec_round_trip(tmp_path):
    spec = parse_catalog_spec(_spec_doc(tmp_path))
    assert spec.names == ("alpha", "beta")
    assert spec.default == "alpha"
    assert spec.default_entry.dim == 4
    assert spec.entry("beta").export_dir == str(tmp_path / "b")
    with pytest.raises(KeyError):
        spec.entry("gamma")


def test_parse_catalog_spec_default_falls_back_to_first(tmp_path):
    doc = _spec_doc(tmp_path)
    del doc["default"]
    assert parse_catalog_spec(doc).default == "alpha"


def test_parse_catalog_spec_rejects_bad_docs(tmp_path):
    with pytest.raises(ValueError):
        parse_catalog_spec({"models": {}})
    with pytest.raises(ValueError):
        parse_catalog_spec(_spec_doc(tmp_path, default="nope"))
    # reserved names collide with the /v1 route grammar
    with pytest.raises(ValueError, match="reserved"):
        parse_catalog_spec({
            "models": {"similar": {"export_dir": "/x"}},
        })
    # names become URL segments and metric labels
    with pytest.raises(ValueError, match="must match"):
        parse_catalog_spec({
            "models": {"bad name!": {"export_dir": "/x"}},
        })
    with pytest.raises(ValueError, match="export_dir"):
        parse_catalog_spec({"models": {"alpha": {}}})
    with pytest.raises(ValueError, match="replicas"):
        parse_catalog_spec({
            "models": {"alpha": {"export_dir": "/x", "replicas": 0}},
        })
    with pytest.raises(ValueError, match="rate/burst"):
        parse_catalog_spec({
            "models": {"alpha": {"export_dir": "/x", "rate": -1}},
        })


def test_parse_catalog_spec_model_cap(tmp_path):
    models = {
        f"m{i}": {"export_dir": f"/x/{i}"} for i in range(17)
    }
    with pytest.raises(ValueError, match="cap"):
        parse_catalog_spec({"models": models})


def test_load_catalog_spec_resolves_relative_paths(tmp_path):
    p = tmp_path / "catalog.json"
    p.write_text(json.dumps({
        "default": "alpha",
        "models": {"alpha": {"export_dir": "exports/a"}},
    }))
    spec = load_catalog_spec(str(p))
    assert spec.entry("alpha").export_dir == str(
        tmp_path / "exports" / "a"
    )


# -- route grammar -----------------------------------------------------------


def test_split_model_route():
    assert split_model_route("/v1/alpha/similar") == (
        "alpha", "/v1/similar"
    )
    assert split_model_route("/v1/alpha/genes") == ("alpha", "/v1/genes")
    # unprefixed routes pass through untouched (the default model's
    # backward-compat surface)
    assert split_model_route("/v1/similar") == (None, "/v1/similar")
    assert split_model_route("/healthz") == (None, "/healthz")
    # verbs and job ids are NOT model names
    assert split_model_route("/v1/shard/topk") == (None, "/v1/shard/topk")
    assert split_model_route("/v1/jobs/j123/artifact") == (
        None, "/v1/jobs/j123/artifact"
    )
    # a model prefix on the jobs plane is recognized
    assert split_model_route("/v1/alpha/jobs") == ("alpha", "/v1/jobs")
    # garbage tails are not model routes
    assert split_model_route("/v1/alpha/doesnotexist") == (
        None, "/v1/alpha/doesnotexist"
    )


def test_model_label_is_bounded():
    known = ("alpha", "beta")
    assert model_label("alpha", known) == "alpha"
    assert model_label(None, known) != "alpha"
    overflow = model_label("not-in-catalog", known)
    assert overflow == model_label("x" * 500, known)
    assert len(overflow) <= 64


# -- per-model admission -----------------------------------------------------


def test_model_admission_buckets_per_model(tmp_path):
    doc = _spec_doc(tmp_path)
    doc["models"]["alpha"]["rate"] = 1.0
    doc["models"]["alpha"]["burst"] = 2
    spec = parse_catalog_spec(doc)
    now = [100.0]
    adm = ModelAdmission(spec, clock=lambda: now[0])
    # alpha's burst of 2, then 429 territory
    assert adm.admit("alpha")
    assert adm.admit("alpha")
    assert not adm.admit("alpha")
    # beta is unlimited; unknown names admit (they 404 later — the
    # quota gate is not a validity gate)
    for _ in range(10):
        assert adm.admit("beta")
    assert adm.admit("gamma")
    assert adm.admit(None)
    # tokens refill on the injected clock
    now[0] += 1.0
    assert adm.admit("alpha")


# -- (model, shard) autoscale pools ------------------------------------------


def _tick(policy, snapshot, now, current_of):
    snapshot = dict(snapshot)
    snapshot.setdefault("_fresh_targets", 2.0)
    return policy.observe(snapshot, now=now, current_of=current_of)


def test_pool_policy_scales_only_the_hot_model():
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_queue_per_replica=4.0,
        up_after_ticks=2, cooldown_s=0.0,
    )
    policy = PoolAutoscalePolicy(
        cfg, [("alpha", None), ("beta", None)]
    )
    current = {("alpha", None): 1, ("beta", None): 1}
    hot = {
        "fleet_model_queue_depth{model=alpha}": 40.0,
        "fleet_model_queue_depth{model=beta}": 0.0,
    }
    d = _tick(policy, hot, 1.0, current)
    assert d.action == "hold"          # first tick seeds baselines
    d = _tick(policy, hot, 2.0, current)
    assert d.action == "hold"          # breach window still filling
    d = _tick(policy, hot, 3.0, current)
    assert d.action == "up"
    assert d.model == "alpha"
    assert d.shard is None
    assert d.target == 2
    assert "model alpha" in d.reason


def test_pool_policy_hottest_queue_wins_tie():
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_queue_per_replica=4.0,
        up_after_ticks=1, cooldown_s=0.0,
    )
    policy = PoolAutoscalePolicy(
        cfg, [("alpha", None), ("beta", None)]
    )
    current = {("alpha", None): 1, ("beta", None): 1}
    both_hot = {
        "fleet_model_queue_depth{model=alpha}": 10.0,
        "fleet_model_queue_depth{model=beta}": 50.0,
    }
    _tick(policy, both_hot, 1.0, current)  # seeds baselines
    d = _tick(policy, both_hot, 2.0, current)
    assert (d.action, d.model) == ("up", "beta")


def test_pool_policy_rejects_degenerate_pools():
    cfg = AutoscaleConfig()
    with pytest.raises(ValueError):
        PoolAutoscalePolicy(cfg, [])
    with pytest.raises(ValueError):
        PoolAutoscalePolicy(
            cfg, [("alpha", None), ("alpha", None)]
        )


def test_shard_policy_is_a_pool_policy_view():
    """The pre-catalog shard API is a re-keyed view over the SAME
    policy instances — not a parallel implementation."""
    policy = ShardAutoscalePolicy(AutoscaleConfig(), num_shards=2)
    assert policy.policies[0] is policy.pool_policies[(None, 0)]
    assert policy.policies[1] is policy.pool_policies[(None, 1)]
    d = policy.observe(
        {"_fresh_targets": 2.0,
         "fleet_shard_queue_depth{shard=1}": 1.0},
        now=1.0, current_of={0: 1, 1: 1},
    )
    assert d.shard in (0, 1) and d.model is None


# -- the live two-model catalog ----------------------------------------------


@pytest.fixture
def two_model_catalog(tmp_path):
    _write_export(tmp_path / "a", dim=4, seed=1)
    _write_export(tmp_path / "b", dim=8, seed=2)
    spec = parse_catalog_spec(_spec_doc(tmp_path))
    catalog = ModelCatalog(
        spec,
        config=ServeConfig(max_delay_ms=1.0, cache_size=0),
    ).build().start()
    yield catalog
    catalog.stop()


def test_catalog_serves_each_model_by_name(two_model_catalog):
    app = two_model_catalog.default_app
    body = {"genes": ["G0"], "k": 3}
    status, alpha = app.handle("POST", "/v1/alpha/similar", body)
    assert status == 200
    assert alpha["model"]["name"] == "alpha"
    assert alpha["model"]["dim"] == 4
    status, beta = app.handle("POST", "/v1/beta/similar", body)
    assert status == 200
    assert beta["model"]["name"] == "beta"
    assert beta["model"]["dim"] == 8
    # different tables answer differently
    assert (
        [n["gene"] for n in alpha["results"][0]["neighbors"]]
        != [n["gene"] for n in beta["results"][0]["neighbors"]]
    )


def test_catalog_unprefixed_routes_serve_the_default(two_model_catalog):
    app = two_model_catalog.default_app
    body = {"genes": ["G0"], "k": 3}
    _, plain = app.handle("POST", "/v1/similar", body)
    _, named = app.handle("POST", "/v1/alpha/similar", body)
    assert (
        plain["results"][0]["neighbors"]
        == named["results"][0]["neighbors"]
    )


def test_catalog_unknown_model_404s_before_labels(two_model_catalog):
    app = two_model_catalog.default_app
    status, doc = app.handle(
        "POST", "/v1/gamma/similar", {"genes": ["G0"], "k": 3}
    )
    assert status == 404
    assert "unknown model" in doc["error"]


def test_catalog_sibling_dispatch_works_from_any_app(two_model_catalog):
    """The shared catalog table is symmetric: the NON-default app can
    address its sibling by name too (the fleet front door may land a
    prefixed request on any replica)."""
    beta_app = two_model_catalog.apps["beta"]
    status, doc = beta_app.handle(
        "POST", "/v1/alpha/similar", {"genes": ["G0"], "k": 3}
    )
    assert status == 200
    assert doc["model"]["name"] == "alpha"


def test_catalog_default_must_load(tmp_path):
    (tmp_path / "a").mkdir()          # empty: no checkpoint at all
    _write_export(tmp_path / "b", dim=8, seed=2)
    spec = parse_catalog_spec(_spec_doc(tmp_path))
    with pytest.raises(RuntimeError, match="default model"):
        ModelCatalog(spec, config=ServeConfig()).build()


def test_catalog_non_default_may_start_empty(tmp_path):
    _write_export(tmp_path / "a", dim=4, seed=1)
    (tmp_path / "b").mkdir()
    spec = parse_catalog_spec(_spec_doc(tmp_path))
    catalog = ModelCatalog(
        spec, config=ServeConfig(max_delay_ms=1.0)
    ).build().start()
    try:
        app = catalog.default_app
        status, _ = app.handle(
            "POST", "/v1/alpha/similar", {"genes": ["G0"], "k": 3}
        )
        assert status == 200
        # beta exists in the route table but has nothing to serve yet
        status, _ = app.handle(
            "POST", "/v1/beta/similar", {"genes": ["G0"], "k": 3}
        )
        assert status == 503
    finally:
        catalog.stop()
