"""Kernel cost-attribution plane (obs/profiler.py) tier-1: static XLA
cost extraction, the per-backend peak table, kernels.jsonl round trips,
serve-engine per-bucket publication, the recompile-storm counter +
alert rule, the ``cli.obs kernels`` exit-code contract, the
passes_kernels gate fixtures, and the BENCH_KERNELS ledger adapter
over a copy of the committed artifact.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from gene2vec_tpu.obs import profiler  # noqa: E402
from gene2vec_tpu.obs.registry import MetricsRegistry  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_KERNELS = os.path.join(REPO, "BENCH_KERNELS_r18.json")

V, D = 32, 8


def _toy_fn(a, b):
    return (a @ b).sum(axis=1)


def _toy_args():
    rng = np.random.RandomState(0)
    return (
        jnp.asarray(rng.randn(16, D).astype(np.float32)),
        jnp.asarray(rng.randn(D, D).astype(np.float32)),
    )


# -- static cost extraction --------------------------------------------------


def test_extract_costs_on_toy_jitted_fn():
    args = _toy_args()
    compiled = jax.jit(_toy_fn).lower(*args).compile()
    costs = profiler.extract_costs(compiled)
    assert costs is not None
    assert costs["flops"] and costs["flops"] > 0
    assert costs["bytes_accessed"] and costs["bytes_accessed"] > 0


def test_attribute_records_costs_and_compile_walls():
    p = profiler.KernelProfiler()
    rec = p.attribute("toy", jax.jit(_toy_fn), _toy_args())
    assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
    assert rec["lower_s"] > 0 and rec["compile_s"] > 0
    # attribute alone -> no dynamic observations yet
    (merged,) = p.records()
    assert merged["name"] == "toy"
    assert merged["calls"] == 0 and merged["best_wall_s"] is None
    assert merged["utilization"] is None
    # measure feeds the roofline: utilization lands in (0, 1]-ish and
    # the binding resource is named
    best = p.measure("toy", jax.jit(_toy_fn), _toy_args())
    assert best is not None and best > 0
    (merged,) = p.records()
    assert merged["best_wall_s"] == pytest.approx(best)
    assert merged["utilization"] is not None and merged["utilization"] > 0
    assert merged["bound"] in ("compute", "memory")


def test_attribute_never_raises_on_unjittable():
    p = profiler.KernelProfiler()
    # a plain callable has no .lower: attribute degrades to a record
    # with lowering wall only, and records() still carries the name
    rec = p.attribute("broken", lambda x: x.nonsense(), (object(),))
    assert rec.get("flops") is None and "compile_s" not in rec
    (merged,) = p.records()
    assert merged["name"] == "broken" and merged["flops"] is None


# -- peak table --------------------------------------------------------------


def test_peak_table_cpu_and_unknown_fallbacks():
    cpu = profiler.peak_table("cpu", "cpu")
    assert cpu["provenance"] == "cpu-conservative"
    assert cpu["peak_flops_per_sec"] == profiler.CPU_PEAK_FLOPS
    # unknown platform/device: a conservative table, never a KeyError
    unk = profiler.peak_table("rocm", "gizmo9000")
    assert unk["provenance"] == "unknown-conservative"
    assert unk["peak_flops_per_sec"] > 0


def test_peak_table_tpu_device_facts_longest_match():
    v4 = profiler.peak_table("tpu", "TPU v4")
    assert v4["provenance"] == "tpu-device-facts"
    assert v4["peak_flops_per_sec"] == pytest.approx(275e12)
    # longest substring wins: "v5e" must not resolve via "v5p"
    v5e = profiler.peak_table("tpu", "TPU v5e")
    assert v5e["peak_flops_per_sec"] == pytest.approx(197e12)
    # an unknown TPU generation still degrades, not crashes
    future = profiler.peak_table("tpu", "TPU v99")
    assert future["provenance"] == "unknown-conservative"


def test_utilization_roofline_bound():
    peaks = {"peak_flops_per_sec": 100.0, "peak_bytes_per_sec": 100.0}
    u = profiler.utilization(50.0, 10.0, 1.0, peaks)
    assert u["utilization"] == pytest.approx(0.5)
    assert u["bound"] == "compute"
    u = profiler.utilization(10.0, 50.0, 1.0, peaks)
    assert u["bound"] == "memory"
    assert profiler.utilization(None, 10.0, 1.0, peaks)["flops_util"] is None
    assert profiler.utilization(10.0, 10.0, None, peaks)["utilization"] is None


# -- kernels.jsonl round trip ------------------------------------------------


def test_kernels_jsonl_round_trip_and_gauges(tmp_path):
    reg = MetricsRegistry()
    p = profiler.KernelProfiler(run_dir=str(tmp_path), registry=reg)
    p.attribute("toy", jax.jit(_toy_fn), _toy_args())
    p.measure("toy", jax.jit(_toy_fn), _toy_args())
    written = p.flush()
    assert (tmp_path / profiler.KERNELS_LOG_NAME).exists()
    back = profiler.read_kernels(str(tmp_path))
    assert [r["name"] for r in back] == ["toy"]
    assert back[0]["flops"] == written[0]["flops"]
    assert back[0]["backend"]["provenance"]
    text = reg.prometheus_text()
    assert 'kernel_flops{kernel="toy"}' in text
    assert 'kernel_utilization{kernel="toy"}' in text
    assert 'kernel_compile_seconds{kernel="toy"}' in text
    # the renderers consume the same records
    table = profiler.format_kernels(back)
    assert "toy" in table and "peaks:" in table
    summary = profiler.kernel_summary(back)
    assert summary["kernels"] == 1
    assert summary["top"][0]["name"] == "toy"
    assert summary["top"][0]["wall_share"] == pytest.approx(1.0)


def test_read_kernels_nested_and_malformed(tmp_path):
    sub = tmp_path / "run"
    sub.mkdir()
    (sub / profiler.KERNELS_LOG_NAME).write_text(
        json.dumps({"name": "a", "flops": 1.0}) + "\n"
        + "{not json\n"
        + json.dumps({"name": "b"}) + "\n"
    )
    # one level down is found; malformed lines are skipped, not fatal
    recs = profiler.read_kernels(str(tmp_path))
    assert [r["name"] for r in recs] == ["a", "b"]
    assert profiler.read_kernels(str(tmp_path / "nothing-here")) == []


# -- goodput per-kernel breakdown --------------------------------------------


def test_goodput_kernel_breakdown_sums_to_compute_bucket():
    from gene2vec_tpu.obs import goodput

    records = [{"name": "compute", "dur": 8.0}]
    s = goodput.summarize(
        records, wall_s=10.0, pairs_total=100.0,
        kernel_seconds={"sgns_train_step": 6.0},
    )
    ks = s["compute_kernels_s"]
    # under-attribution leaves an explicit residual; the kernel seconds
    # sum to the compute bucket EXACTLY
    assert ks["_unattributed"] == pytest.approx(2.0)
    assert sum(ks.values()) == pytest.approx(s["buckets_s"]["compute"])
    assert s["compute_kernels"]["sgns_train_step"] == pytest.approx(0.6)
    # over-attribution scales DOWN to fit the bucket, same discipline
    # as the buckets themselves vs the wall clock
    s2 = goodput.summarize(
        records, wall_s=10.0,
        kernel_seconds={"a": 6.0, "b": 10.0},
    )
    ks2 = s2["compute_kernels_s"]
    assert "_unattributed" not in ks2
    assert sum(ks2.values()) == pytest.approx(s2["buckets_s"]["compute"])


# -- serve engine per-bucket publication -------------------------------------


def _write_export(export_dir, iteration=1, seed=0):
    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.sgns.model import SGNSParams

    rng = np.random.RandomState(seed)
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(V, D).astype(np.float32)),
        ctx=jnp.asarray(np.zeros((V, D), np.float32)),
    )
    save_iteration(str(export_dir), D, iteration, params, vocab)


def test_engine_profile_buckets_and_serve_publication(tmp_path):
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import ServeApp, ServeConfig

    export = tmp_path / "exports"
    _write_export(export)
    reg = ModelRegistry(str(export))
    assert reg.refresh()
    app = ServeApp(
        reg, ServeConfig(max_batch=4, max_delay_ms=1.0)
    ).start()
    try:
        # the exact-mode jit cache is process-global (other tests may
        # have warmed it): assert no GROWTH from AOT attribution
        before = app.engine.cache_sizes().get("exact", 0)
        costs = app.profile_kernels(k=4)
        assert costs, "exact-mode profiling must attribute buckets"
        # one record per batch bucket, keyed serve_topk_<mode>/b<n>
        assert set(costs) == {
            f"serve_topk_exact/b{b}" for b in app.engine.buckets
        }
        for rec in costs.values():
            assert rec["flops"] > 0 and rec["compile_s"] > 0
            assert rec["mode"] == "exact"
        text = app.metrics.prometheus_text()
        assert 'kernel_flops{kernel="serve_topk_exact/b1"}' in text
        assert (
            'kernel_compile_seconds{kernel="serve_topk_exact/b1"}' in text
        )
        # AOT attribution must not populate the request-path jit cache
        assert app.engine.cache_sizes().get("exact", 0) == before
    finally:
        app.stop()


def test_engine_profile_buckets_needs_index_for_ann_modes():
    from gene2vec_tpu.serve.engine import BucketedTopKEngine

    eng = BucketedTopKEngine(max_batch=2, index="ivf")
    unit = jnp.asarray(np.eye(4, dtype=np.float32))
    with pytest.raises(ValueError, match="AnnIndex"):
        eng.profile_buckets(unit, k=2)


# -- recompile-storm counter + alert rule ------------------------------------


def _replica_text(compiles):
    r = MetricsRegistry()
    r.counter("serve_requests_total").inc(10)
    if compiles:
        r.counter("jit_compile_events_total").inc(compiles)
    return r.prometheus_text()


def test_aggregator_compile_delta_seeds_then_tracks():
    from gene2vec_tpu.obs.aggregate import FleetAggregator

    texts = {"http://r0": _replica_text(5)}
    agg = FleetAggregator(
        lambda: list(texts), fetch=lambda url, t: texts[url],
    )
    # the full snapshot (what the alert evaluator sees) flows to
    # observers; scrape_once() returns only the small headline dict
    seen = []
    agg.observers.append(lambda snap, wall=None: seen.append(dict(snap)))
    # first scrape SEEDS the baseline: a warm fleet joining mid-life
    # must not read as a storm
    agg.scrape_once()
    assert seen[-1]["fleet_jit_compiles"] == 5.0
    assert seen[-1]["fleet_jit_compile_delta"] == 0.0
    agg.scrape_once()
    assert seen[-1]["fleet_jit_compile_delta"] == 0.0
    texts["http://r0"] = _replica_text(9)
    agg.scrape_once()
    assert seen[-1]["fleet_jit_compiles"] == 9.0
    assert seen[-1]["fleet_jit_compile_delta"] == 4.0


def test_recompile_storm_rule_fires_and_clears():
    from gene2vec_tpu.obs.alerts import AlertEvaluator, default_rules

    (rule,) = [
        r for r in default_rules() if r.name == "jit-recompile-storm"
    ]

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    ev = AlertEvaluator([rule], clock=clk)
    quiet = {"fleet_jit_compile_delta": 0.0, "_fresh_targets": 1}
    storm = {"fleet_jit_compile_delta": 3.0, "_fresh_targets": 1}
    ev.observe(quiet)
    assert ev.firing() == []
    # sustained compiling past for_s fires; a single cold-start burst
    # shorter than the debounce must NOT
    for _ in range(3):
        clk.t += 10.0
        ev.observe(storm)
    assert ev.firing() == []  # 30s not yet exceeded-and-held from 10s
    clk.t += rule.for_s
    ev.observe(storm)
    assert ev.firing() == ["jit-recompile-storm"]
    # back to zero for clear_for_s clears
    clk.t += 1.0
    ev.observe(quiet)
    clk.t += rule.clear_for_s + 1.0
    ev.observe(quiet)
    assert ev.firing() == []


# -- cli.obs kernels exit codes ----------------------------------------------


def test_cli_obs_kernels_exit_codes(tmp_path, capsys):
    from gene2vec_tpu.cli.obs import main as obs_main

    assert obs_main(["kernels", str(tmp_path / "nope")]) == 2
    assert obs_main(["kernels", str(tmp_path)]) == 1
    p = profiler.KernelProfiler(run_dir=str(tmp_path))
    p.attribute("toy", jax.jit(_toy_fn), _toy_args())
    p.flush()
    capsys.readouterr()
    assert obs_main(["kernels", str(tmp_path)]) == 0
    assert "toy" in capsys.readouterr().out
    assert obs_main(["kernels", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc[0]["name"] == "toy"


def test_obs_report_carries_kernel_block(tmp_path):
    from gene2vec_tpu.obs import report

    p = profiler.KernelProfiler(run_dir=str(tmp_path))
    p.attribute("toy", jax.jit(_toy_fn), _toy_args())
    p.measure("toy", jax.jit(_toy_fn), _toy_args())
    p.flush()
    s = report.summarize(str(tmp_path))
    assert s["kernels"]["kernels"] == 1
    assert s["kernels"]["top"][0]["name"] == "toy"
    text = report.format_report(str(tmp_path))
    assert "kernels: 1 attributed" in text


# -- passes_kernels gate -----------------------------------------------------


def _budget():
    from gene2vec_tpu.analysis.passes_hlo import load_budgets

    return load_budgets()["kernels"]["profile"]


def _kernels_doc(**over):
    b = _budget()
    kernel = {
        "flops": 1e9, "bytes_accessed": 1e8, "peak_memory_bytes": 1e7,
        "lower_s": 0.1, "compile_s": 0.5, "calls": 3, "wall_s": 0.05,
        "utilization": 0.02, "bound": "compute",
    }
    doc = {
        "schema_version": 1,
        "bench": "kernels",
        "recipe": {
            k: b[k] for k in (
                "dim", "vocab", "num_pairs", "batch_pairs", "serve_rows",
                "serve_dim", "serve_batch", "serve_k", "serve_clusters",
                "rounds", "epochs_per_window",
            )
        },
        "backend": {"platform": "cpu", "device_kind": "cpu",
                    "provenance": "cpu-conservative"},
        "kernels": {
            name: dict(kernel) for name in b["require_kernels"]
        },
        "overhead": {"regression_frac": 0.001},
    }
    doc.update(over)
    return doc


def test_kernels_gate_passes_on_committed_bench():
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_kernels import kernels_findings

    bad = gating(kernels_findings(root=REPO))
    assert bad == [], "\n".join(f.format() for f in bad)


def test_kernels_gate_missing_bench_is_info(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_kernels import kernels_findings

    findings = kernels_findings(root=str(tmp_path))
    assert gating(findings) == []
    assert findings[0].severity == "info"
    assert "bench.py --kernel-profile" in findings[0].message


def test_kernels_gate_planted_violations_fire_exactly_once(tmp_path):
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_kernels import kernels_findings

    ok = _kernels_doc()
    path = tmp_path / "BENCH_KERNELS_r99.json"
    path.write_text(json.dumps(ok))
    assert gating(kernels_findings(root=str(tmp_path))) == []

    # overhead past the ceiling
    doc = _kernels_doc(overhead={"regression_frac": 0.5})
    path.write_text(json.dumps(doc))
    (bad,) = gating(kernels_findings(root=str(tmp_path)))
    assert "0.5000 > budget" in bad.message

    # a silently dropped required kernel gates
    doc = _kernels_doc()
    del doc["kernels"]["serve_topk_ivf"]
    path.write_text(json.dumps(doc))
    (bad,) = gating(kernels_findings(root=str(tmp_path)))
    assert "'serve_topk_ivf' missing" in bad.message

    # a dropped required field gates
    doc = _kernels_doc()
    del doc["kernels"]["sgns_train_step"]["utilization"]
    path.write_text(json.dumps(doc))
    (bad,) = gating(kernels_findings(root=str(tmp_path)))
    assert "missing required field 'utilization'" in bad.message

    # off-recipe gates
    doc = _kernels_doc()
    doc["recipe"]["batch_pairs"] = 64
    path.write_text(json.dumps(doc))
    (bad,) = gating(kernels_findings(root=str(tmp_path)))
    assert "pins batch_pairs" in bad.message

    # unreadable gates
    path.write_text("{torn")
    (bad,) = gating(kernels_findings(root=str(tmp_path)))
    assert "unreadable" in bad.message


def test_analyze_cli_exits_1_via_kernels_env_root(tmp_path):
    doc = _kernels_doc(overhead={"regression_frac": 0.5})
    (tmp_path / "BENCH_KERNELS_r99.json").write_text(json.dumps(doc))
    env = {**os.environ, "GENE2VEC_TPU_KERNELS_ROOT": str(tmp_path)}
    proc = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    fired = [f for f in out["findings"]
             if f["pass"] == "kernels-attribution-budget"
             and f["severity"] != "info"]
    assert len(fired) == 1


# -- ledger adapter ----------------------------------------------------------


def test_ledger_adapts_kernels_family_from_committed_artifact(tmp_path):
    from gene2vec_tpu.obs import ledger

    assert os.path.exists(BENCH_KERNELS), (
        "committed BENCH_KERNELS_r18.json is part of the contract"
    )
    shutil.copy(BENCH_KERNELS, tmp_path / "BENCH_KERNELS_r18.json")
    (rec,) = ledger.ingest_root(str(tmp_path))
    assert rec["family"] == "kernels" and rec["round"] == 18
    assert rec["headline_metric"] == "kernel_profile_overhead_frac"
    assert not rec["legacy_unstamped"]
    m = rec["metrics"]
    assert m["kernel_profile_overhead_frac"] is not None
    for name in _budget()["require_kernels"]:
        assert m[f"kernel_{name}_flops"] > 0
        assert m[f"kernel_{name}_wall_s"] > 0
        assert m[f"kernel_{name}_utilization"] > 0
    assert m["kernel_sgns_utilization"] == (
        m["kernel_sgns_train_step_utilization"]
    )
