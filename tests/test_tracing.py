"""Distributed tracing, fleet telemetry aggregation, and the flight
recorder (docs/OBSERVABILITY.md#distributed-tracing).

Covers the cross-process trace plane end to end at tier-1 scale:
traceparent parsing/propagation, tracer stamping, retry/hedge span
lineage through the resilient client (fake clock + fake transport),
batcher ticket hops, HTTP round trip into a real in-process ServeApp,
reassembly + ``cli.obs trace``, Prometheus label escaping round trips,
the registry cardinality cap, and the aggregator's merged fleet view.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from gene2vec_tpu.obs import flight as flight_mod
from gene2vec_tpu.obs import tracecontext as tc
from gene2vec_tpu.obs.aggregate import (
    FleetAggregator,
    histogram_quantile,
    merge_samples,
    parse_prometheus,
)
from gene2vec_tpu.obs.flight import FlightRecorder, collect_trace
from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.obs.trace import (
    Tracer,
    hop_span,
    read_events,
    set_tracer,
)
from gene2vec_tpu.serve.client import ResilientClient, RetryPolicy


# -- trace context -----------------------------------------------------------


def test_traceparent_header_round_trip():
    ctx = tc.new_trace()
    back = tc.TraceContext.from_header(ctx.to_header())
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled
    off = tc.TraceContext(ctx.trace_id, ctx.span_id, sampled=False)
    assert tc.TraceContext.from_header(off.to_header()).sampled is False


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-abc-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",     # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",     # all-zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",     # invalid version
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",     # non-hex
])
def test_traceparent_rejects_malformed(bad):
    assert tc.TraceContext.from_header(bad) is None


def test_child_lineage_and_thread_local_use():
    root = tc.new_trace()
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    assert tc.current() is None
    with tc.use(root):
        assert tc.current() is root
        with tc.use(child):
            assert tc.current() is child
        assert tc.current() is root
    assert tc.current() is None
    with tc.use(None):
        assert tc.current() is None


def test_sampler_rates():
    assert tc.Sampler(0.0).maybe_new_trace() is None
    ctx = tc.Sampler(1.0).maybe_new_trace()
    assert ctx is not None and ctx.sampled


# -- tracer stamping ---------------------------------------------------------


def test_tracer_stamps_sampled_context(tmp_path):
    t = Tracer(str(tmp_path / "events.jsonl"))
    ctx = tc.new_trace()
    with tc.use(ctx):
        with t.span("serve_request", route="/x"):
            t.event("inner")
    unsampled = tc.TraceContext("a" * 32, "b" * 16, sampled=False)
    with tc.use(unsampled):
        t.event("dark")
    t.close()
    events = read_events(str(tmp_path / "events.jsonl"))
    spans = [e for e in events if e["name"] == "serve_request"]
    assert spans and all(e["trace"] == ctx.trace_id for e in spans)
    assert all(e["tsid"] == ctx.span_id for e in spans)
    inner = next(e for e in events if e["name"] == "inner")
    assert inner["trace"] == ctx.trace_id
    dark = next(e for e in events if e["name"] == "dark")
    assert "trace" not in dark


def test_hop_span_links_process_local_parent(tmp_path):
    t = Tracer(str(tmp_path / "events.jsonl"))
    set_tracer(t)
    try:
        root = tc.new_trace()
        hop = root.child()
        with t.span("serve_batch"):
            hop_span("batch_item", hop, dur=0.01, queue_wait_s=0.002)
    finally:
        set_tracer(None)
        t.close()
    events = read_events(str(tmp_path / "events.jsonl"))
    batch_start = next(
        e for e in events
        if e["name"] == "serve_batch" and e["type"] == "span_start"
    )
    item = next(e for e in events if e["name"] == "batch_item")
    assert item["trace"] == root.trace_id
    assert item["tsid"] == hop.span_id
    assert item["tpid"] == root.span_id
    assert item["span"] == batch_start["span"]  # process-local link
    # no tracer installed -> silently free
    hop_span("batch_item", hop, dur=0.01)


# -- resilient client propagation (retries / hedges) -------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _client(transport, clock, policy=None, targets=("http://a", "http://b")):
    return ResilientClient(
        list(targets),
        policy or RetryPolicy(
            max_attempts=3, default_timeout_s=5.0, backoff_base_s=0.0,
            trace_sample=1.0,
        ),
        transport=transport,
        clock=clock,
        sleep=lambda s: None,
    )


def test_every_attempt_shares_trace_with_distinct_child_spans():
    clock = FakeClock()
    seen = []

    def transport(base, method, path, body, ct, rt, headers=None):
        seen.append((base, dict(headers or {})))
        if len(seen) < 3:
            raise ConnectionRefusedError("down")
        return 200, json.dumps({"ok": True}).encode()

    c = _client(transport, clock)
    r = c.request("/v1/similar", {"genes": ["G1"]})
    assert r.ok and r.attempts == 3 and r.retries == 2
    assert r.trace_id is not None
    parsed = [
        tc.TraceContext.from_header(h["traceparent"]) for _, h in seen
    ]
    assert all(p is not None for p in parsed)
    # one trace id across every attempt of the logical request...
    assert {p.trace_id for p in parsed} == {r.trace_id}
    # ...but each attempt is its own span
    assert len({p.span_id for p in parsed}) == 3
    assert all(p.sampled for p in parsed)


def test_ambient_context_wins_over_client_sampling():
    clock = FakeClock()
    seen = []

    def transport(base, method, path, body, ct, rt, headers=None):
        seen.append(dict(headers or {}))
        return 200, b"{}"

    c = _client(transport, clock)
    root = tc.new_trace()
    with tc.use(root):
        r = c.request("/v1/similar", {"genes": ["G1"]})
    assert r.trace_id == root.trace_id
    p = tc.TraceContext.from_header(seen[0]["traceparent"])
    assert p.trace_id == root.trace_id
    assert p.span_id != root.span_id  # the attempt is a CHILD span


def test_trace_sample_zero_sends_no_header():
    clock = FakeClock()
    seen = []

    def transport(base, method, path, body, ct, rt, headers=None):
        seen.append(headers)
        return 200, b"{}"

    c = _client(
        transport, clock,
        policy=RetryPolicy(max_attempts=2, default_timeout_s=5.0),
    )
    r = c.request("/v1/similar", {"genes": ["G1"]})
    assert r.ok and r.trace_id is None
    assert seen == [None]
    # an UNSELECTED request under partial sampling also gets no
    # context at all — no header, so the replica's own sampler stays
    # free to act (an unsampled header would suppress it)
    import random as random_mod

    class FixedRng(random_mod.Random):
        def random(self):
            return 0.9  # above the 0.5 rate -> not selected

    c2 = ResilientClient(
        ["http://a"],
        RetryPolicy(max_attempts=2, default_timeout_s=5.0,
                    trace_sample=0.5),
        transport=transport, clock=clock, sleep=lambda s: None,
        rng=FixedRng(),
    )
    r2 = c2.request("/v1/similar", {"genes": ["G1"]})
    assert r2.ok and r2.trace_id is None
    assert seen[-1] is None


def test_hedged_attempt_parents_to_same_request():
    """The hedge fires on a different replica while the primary stalls;
    both attempts must be sibling child spans of one request root."""
    headers_by_target = {}
    release = threading.Event()

    def transport(base, method, path, body, ct, rt, headers=None):
        headers_by_target.setdefault(base, []).append(
            dict(headers or {})
        )
        if base == "http://a":
            release.wait(5.0)  # the slow primary
        return 200, json.dumps({"from": base}).encode()

    c = ResilientClient(
        ["http://a", "http://b"],
        RetryPolicy(
            max_attempts=3, default_timeout_s=5.0, hedge=True,
            hedge_min_samples=4, trace_sample=1.0,
        ),
        transport=transport,
    )
    c._latencies = [0.01] * 8  # warm the p95 estimate
    try:
        r = c.request("/v1/similar", {"genes": ["G1"]}, timeout_s=5.0)
    finally:
        release.set()
    assert r.ok and r.hedged
    assert set(headers_by_target) == {"http://a", "http://b"}
    primary = tc.TraceContext.from_header(
        headers_by_target["http://a"][0]["traceparent"]
    )
    hedge = tc.TraceContext.from_header(
        headers_by_target["http://b"][0]["traceparent"]
    )
    assert primary.trace_id == hedge.trace_id == r.trace_id
    assert primary.span_id != hedge.span_id


# -- batcher ticket hops -----------------------------------------------------


def test_batcher_emits_batch_item_hops(tmp_path):
    from gene2vec_tpu.serve.batcher import MicroBatcher

    t = Tracer(str(tmp_path / "events.jsonl"))
    set_tracer(t)
    try:
        b = MicroBatcher(
            lambda items, k: [i * 2 for i in items],
            max_batch=4, max_delay_s=0.01, max_queue=16,
        ).start()
        ctx = tc.new_trace()
        with tc.use(ctx), flight_mod.collect_hops() as hops:
            assert b.submit(21, 1) == 42
        b.stop()
    finally:
        set_tracer(None)
        t.close()
    # the ticket deposited its timings into the request's hop sink
    assert "queue_wait_s" in hops and "compute_s" in hops
    events = read_events(str(tmp_path / "events.jsonl"))
    item = next(e for e in events if e["name"] == "batch_item")
    assert item["trace"] == ctx.trace_id
    assert item["tpid"] == ctx.span_id
    assert item["attrs"]["batch"] == 1
    assert item["attrs"]["queue_wait_s"] >= 0
    batch = next(
        e for e in events
        if e["name"] == "serve_batch" and e["type"] == "span_end"
    )
    assert batch["attrs"]["traces"] == [ctx.trace_id]
    assert item["span"] == batch["span"]


# -- HTTP round trip + reassembly -------------------------------------------


@pytest.fixture
def traced_serving(tmp_path):
    import jax.numpy as jnp

    from gene2vec_tpu.io.checkpoint import save_iteration
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import (
        ServeApp,
        ServeConfig,
        make_server,
    )
    from gene2vec_tpu.sgns.model import SGNSParams

    V, D = 12, 4
    rng = np.random.RandomState(0)
    export = tmp_path / "exports"
    vocab = Vocab([f"G{i}" for i in range(V)], np.arange(V, 0, -1))
    params = SGNSParams(
        emb=jnp.asarray(rng.randn(V, D).astype(np.float32)),
        ctx=jnp.asarray(np.zeros((V, D), np.float32)),
    )
    save_iteration(str(export), D, 1, params, vocab)

    run_dir = tmp_path / "run"
    tracer = Tracer(str(run_dir / "events.jsonl"))
    set_tracer(tracer)
    reg = ModelRegistry(str(export))
    assert reg.refresh()
    app = ServeApp(
        reg, ServeConfig(max_batch=8, max_delay_ms=2.0, max_queue=16)
    ).start()
    app.flight_dir = str(run_dir)
    server = make_server(app, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield url, app, str(run_dir)
    server.shutdown()
    server.server_close()
    app.stop()
    set_tracer(None)
    tracer.close()


def test_http_request_joins_propagated_trace(traced_serving):
    url, app, run_dir = traced_serving
    sender = tc.new_trace()          # pretend we are a proxy attempt
    req = urllib.request.Request(
        f"{url}/v1/similar",
        data=json.dumps({"genes": ["G1"], "k": 3}).encode(),
        headers={
            "Content-Type": "application/json",
            "traceparent": sender.to_header(),
        },
    )
    with urllib.request.urlopen(req, timeout=10.0) as r:
        assert r.status == 200
    events = read_events(os.path.join(run_dir, "events.jsonl"))
    sreq = next(
        e for e in events
        if e["name"] == "serve_request" and e["type"] == "span_end"
        and e.get("trace") == sender.trace_id
    )
    assert sreq["tpid"] == sender.span_id     # child of the sender hop
    item = next(
        e for e in events
        if e["name"] == "batch_item"
        and e.get("trace") == sender.trace_id
    )
    assert item["tpid"] == sreq["tsid"]       # child of the replica hop
    # reassembly: serve_request -> batch_item -> compute subtree
    doc = collect_trace(run_dir, sender.trace_id)
    assert doc["roots"] and doc["roots"][0]["name"] == "serve_request"
    children = doc["roots"][0]["children"]
    assert children and children[0]["name"] == "batch_item"
    sub_names = set()

    def walk(n):
        sub_names.add(n["name"])
        for s in n.get("process_spans", []) + n.get("children", []):
            walk(s)

    walk(doc["roots"][0])
    assert {"serve_request", "batch_item", "serve_batch",
            "engine_topk"} <= sub_names
    # flight recorder saw the request with its hop timings
    rec = next(
        r for r in app.flight.snapshot()
        if r.get("trace") == sender.trace_id
    )
    assert rec["route"] == "/v1/similar" and rec["status"] == 200
    assert "queue_wait_s" in rec["hops"]


def test_untraced_request_stays_dark(traced_serving):
    url, app, run_dir = traced_serving
    req = urllib.request.Request(
        f"{url}/v1/similar",
        data=json.dumps({"genes": ["G2"], "k": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10.0) as r:
        assert r.status == 200
    events = read_events(os.path.join(run_dir, "events.jsonl"))
    g2 = [
        e for e in events
        if e.get("type") == "span_end" and e.get("name") == "serve_request"
    ]
    assert all("trace" not in e for e in g2)


def test_obs_trace_cli(traced_serving, capsys):
    from gene2vec_tpu.cli import obs as obs_cli

    url, app, run_dir = traced_serving
    sender = tc.new_trace()
    req = urllib.request.Request(
        f"{url}/v1/similar",
        data=json.dumps({"genes": ["G3"], "k": 2}).encode(),
        headers={
            "Content-Type": "application/json",
            "traceparent": sender.to_header(),
        },
    )
    with urllib.request.urlopen(req, timeout=10.0):
        pass
    assert obs_cli.main(["trace", run_dir, sender.trace_id]) == 0
    out = capsys.readouterr().out
    assert "serve_request" in out and "batch_item" in out
    assert "engine_topk" in out
    # JSON mode parses; unknown trace exits 1
    assert obs_cli.main(
        ["trace", "--json", run_dir, sender.trace_id]
    ) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["trace_id"] == sender.trace_id
    assert obs_cli.main(["trace", run_dir, "f" * 32]) == 1
    capsys.readouterr()


# -- flight recorder ---------------------------------------------------------


def test_flight_recorder_ring_and_burst(tmp_path):
    clk = FakeClock()
    fr = FlightRecorder(
        capacity=4, burst_threshold=3, burst_window_s=5.0, clock=clk
    )
    for i in range(6):
        assert fr.record(f"/r{i}", 200, 0.01) is False
    assert len(fr.snapshot()) == 4  # bounded
    assert fr.record("/x", 500, 0.01) is False
    clk.t += 1
    assert fr.record("/x", 503, 0.01) is False
    clk.t += 1
    assert fr.record("/x", 500, 0.01) is True     # 3 in window -> dump
    assert fr.record("/x", 500, 0.01) is False    # rate-limited
    clk.t += 6.0
    fr.record("/x", 500, 0.01)
    fr.record("/x", 500, 0.01)
    assert fr.record("/x", 500, 0.01) is True     # new window
    path = fr.dump(str(tmp_path), "test")
    doc = json.load(open(path))
    assert doc["reason"] == "test" and len(doc["records"]) == 4
    # dumps feed reassembly
    fr2 = FlightRecorder()
    fr2.record("/v1/similar", 200, 0.02, trace_id="ab" * 16,
               hops={"queue_wait_s": 0.001})
    fr2.dump(str(tmp_path), "test2")
    out = collect_trace(str(tmp_path), "ab" * 16)
    assert out["flight"] and out["flight"][0]["route"] == "/v1/similar"


# -- registry escaping / cardinality (satellites) ----------------------------


def test_label_escaping_round_trips_through_parser():
    r = MetricsRegistry()
    nasty = 'back\\slash "quoted"\nnewline'
    r.counter("esc_total", labels={"route": nasty}).inc(5)
    text = r.prometheus_text()
    assert "\n\n" not in text.strip()  # the newline was escaped
    samples = parse_prometheus(text)
    s = next(s for s in samples if s.name == "esc_total")
    assert dict(s.labels)["route"] == nasty
    assert s.value == 5.0


def test_labeled_series_share_one_type_line():
    r = MetricsRegistry()
    r.counter("routes_total", labels={"route": "/a"}).inc(1)
    r.counter("routes_total", labels={"route": "/b"}).inc(2)
    r.counter("routes_total").inc(4)
    text = r.prometheus_text()
    assert text.count("# TYPE routes_total counter") == 1
    assert 'routes_total{route="/a"} 1' in text
    assert 'routes_total{route="/b"} 2' in text
    assert "routes_total 4" in text.splitlines()
    with pytest.raises(TypeError):
        r.gauge("routes_total", labels={"route": "/c"})


def test_label_cardinality_cap_warns_then_drops(capsys):
    r = MetricsRegistry(max_label_sets=4)
    for i in range(10):
        r.counter("per_gene_total", labels={"gene": f"G{i}"}).inc()
    text = r.prometheus_text()
    assert text.count("per_gene_total{") == 4
    dropped = r.counter("metrics_dropped_labels_total").value
    assert dropped == 6
    assert "cardinality cap" in capsys.readouterr().err
    # dropped updates keep working against the shared overflow series
    inst = r.counter("per_gene_total", labels={"gene": "G99"})
    inst.inc(5)
    assert "G99" not in r.prometheus_text()
    # histograms capped the same way
    r2 = MetricsRegistry(max_label_sets=2)
    for i in range(5):
        r2.histogram("lat_seconds", labels={"t": str(i)}).observe(0.1)
    assert r2.prometheus_text().count("lat_seconds_count{") == 2


# -- aggregator --------------------------------------------------------------


def _replica_text(requests, rejected, depth, route_ms):
    r = MetricsRegistry()
    r.counter("serve_requests_total").inc(requests)
    r.counter("serve_rejected_total").inc(rejected)
    r.gauge("serve_queue_depth").set(depth)
    h = r.histogram(
        "serve_route_seconds",
        buckets=tuple(0.0005 * (2 ** e) for e in range(15)),
        labels={"route": "/v1/similar"},
    )
    for ms in route_ms:
        h.observe(ms / 1000.0)
    return r.prometheus_text()


def test_aggregator_merges_replicas_and_derives_slos(tmp_path):
    texts = {
        "http://r0": _replica_text(100, 5, 3, [2.0] * 90 + [40.0] * 10),
        "http://r1": _replica_text(50, 0, 1, [2.0] * 50),
    }
    proxy = MetricsRegistry()
    proxy.counter("fleet_proxy_responses_total").inc(140)
    proxy.counter("fleet_proxy_ok_total").inc(133)
    csv_path = str(tmp_path / "telemetry.csv")
    agg = FleetAggregator(
        lambda: list(texts) + ["http://dead"],
        proxy_registry=proxy,
        csv_path=csv_path,
        fetch=lambda url, t: texts[url],  # KeyError for dead -> error
    )
    headline = agg.scrape_once()
    assert headline["fleet_replicas_scraped"] == 2
    assert headline["fleet_queue_depth"] == 4
    assert headline["fleet_requests"] == 150
    assert headline["fleet_rejected"] == 5
    assert headline["fleet_rejection_rate"] == pytest.approx(5 / 150)
    assert headline["fleet_availability"] == pytest.approx(133 / 140)
    text = agg.fleet_text()
    samples = {(s.name, s.labels): s.value for s in parse_prometheus(text)}
    assert samples[("fleet_scrape_errors_total", ())] == 1
    p50 = samples[
        ("fleet_route_p50_seconds", (("route", "/v1/similar"),))
    ]
    p99 = samples[
        ("fleet_route_p99_seconds", (("route", "/v1/similar"),))
    ]
    # 140/150 observations at 2ms, tail at 40ms: p50 lands in a small
    # bucket, p99 in a large one (bucket edges, so conservative)
    assert p50 <= 0.01 < p99 <= 0.128
    agg.view.close()
    rows = open(csv_path).read().splitlines()
    assert len(rows) == 2 and "fleet_availability" in rows[0]


def test_aggregator_retains_counters_across_death_and_restart(tmp_path):
    """Monotone series never go backward: a SIGKILLed replica keeps its
    accumulated contribution, and a restarted one (counters reset to 0)
    resumes accumulating instead of subtracting."""
    texts = {"http://r0": _replica_text(100, 5, 3, [2.0])}
    targets = ["http://r0"]
    agg = FleetAggregator(
        lambda: list(targets),
        fetch=lambda url, t: texts[url],
    )
    h = agg.scrape_once()
    assert h["fleet_requests"] == 100
    # replica dies: no scrape target, but its history stays
    targets.clear()
    h = agg.scrape_once()
    assert h["fleet_requests"] == 100
    assert h["fleet_queue_depth"] == 0  # gauges are live-only
    # replica restarts with zeroed counters: 10 NEW requests accumulate
    texts["http://r0"] = _replica_text(10, 0, 1, [2.0])
    targets.append("http://r0")
    h = agg.scrape_once()
    assert h["fleet_requests"] == 110
    assert h["fleet_rejected"] == 5
    assert h["fleet_queue_depth"] == 1
    agg.view.close()


def test_histogram_quantile_and_parser_edges():
    merged = merge_samples([parse_prometheus(
        'h_bucket{le="0.1"} 50\nh_bucket{le="1"} 99\n'
        'h_bucket{le="+Inf"} 100\nh_sum 12\nh_count 100\n'
    )])
    assert histogram_quantile(merged, "h", (), 0.50) == 0.1
    assert histogram_quantile(merged, "h", (), 0.99) == 1.0
    # a quantile landing in +Inf saturates to the top FINITE bound —
    # the gauge keeps moving during overload instead of freezing stale
    assert histogram_quantile(merged, "h", (), 0.999) == 1.0
    assert histogram_quantile(merged, "missing", (), 0.5) is None
    # malformed lines are skipped, not fatal
    assert parse_prometheus('broken{le="x" 1\n# comment\nok 2\n') == [
        parse_prometheus("ok 2")[0]
    ]


def test_obs_trace_overhead_budget_gate(tmp_path):
    """analysis/passes_obs.py: missing bench = info, a record that
    violates — or omits — a budgeted field gates, a clean record is
    info (the passes_fleet contract, for the obs budget)."""
    from gene2vec_tpu.analysis.findings import gating
    from gene2vec_tpu.analysis.passes_obs import obs_budget_findings

    missing = obs_budget_findings(
        bench_path=str(tmp_path / "absent.json")
    )
    assert [f.severity for f in missing] == ["info"]

    ok_section = {
        "rps": 50, "duration_s": 4, "rounds": 5,
        "p50_untraced_ms": 10.0, "p50_traced_ms": 10.1,
        "regression_frac": 0.01,
    }
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"trace_overhead": ok_section}))
    fs = obs_budget_findings(bench_path=str(good))
    assert gating(fs) == [], [f.format() for f in fs]

    for doc in (
        {"trace_overhead": {**ok_section,  # over budget
                            "regression_frac": 0.10}},
        {"trace_overhead": {**ok_section, "rps": 5}},  # wrong load
        {"trace_overhead": {**ok_section,  # shrunken recipe
                            "duration_s": 0.5, "rounds": 1}},
        {"trace_overhead": {  # dropped the budgeted key
            k: v for k, v in ok_section.items()
            if k != "regression_frac"
        }},
        {},  # no section at all
    ):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(doc))
        assert gating(obs_budget_findings(bench_path=str(bad))), doc


def test_aggregator_background_loop(tmp_path):
    texts = {"http://r0": _replica_text(10, 0, 0, [1.0])}
    agg = FleetAggregator(
        ["http://r0"],
        interval_s=0.05,
        fetch=lambda url, t: texts[url],
    )
    agg.start()
    deadline = time.monotonic() + 5.0
    try:
        while time.monotonic() < deadline:
            if ("fleet_requests 10"
                    in agg.fleet_text()):
                break
            time.sleep(0.02)
        assert "fleet_requests 10" in agg.fleet_text()
    finally:
        agg.stop()
