"""Declarative checkpoint->device placement (parallel/partition_rules).

Tier-1 fast: everything runs on the CPU backend's single device (the
shard/gather closures are jit identities — placement semantics, not
multi-chip layout, are under test here; the multi-chip layouts ride
the mesh-sanity harness)."""

import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as PS

from gene2vec_tpu.parallel.partition_rules import (
    DEFAULT_SERVE_RULES,
    REPLICATED_RULES,
    gather_params,
    match_partition_rules,
    parse_rules,
    shard_params,
    spec_for_name,
    tree_path_name,
)


def test_first_matching_rule_wins():
    """Ordering is the API: a specific pattern listed first must beat a
    catch-all listed after it, and vice versa."""
    specific_first = (
        (r"(^|/)emb$", PS("model", None)),
        (r".*", PS()),
    )
    assert spec_for_name(specific_first, "emb", (8, 4)) == PS(
        "model", None
    )
    assert spec_for_name(specific_first, "kernel", (8, 4)) == PS()
    # a catch-all FIRST shadows everything — first match wins, the
    # rules are not best-match
    catchall_first = (
        (r".*", PS()),
        (r"(^|/)emb$", PS("model", None)),
    )
    assert spec_for_name(catchall_first, "emb", (8, 4)) == PS()


def test_scalar_and_size1_leaves_never_partition():
    """Scalars and size-1 leaves get PS() regardless of what the rules
    say — partitioning a scalar is always a bug."""
    rules = ((r".*", PS("model", None)),)
    assert spec_for_name(rules, "emb", ()) == PS()
    assert spec_for_name(rules, "emb", (1,)) == PS()
    assert spec_for_name(rules, "emb", (1, 1)) == PS()
    # ...but a real 2-D table does take the rule
    assert spec_for_name(rules, "emb", (8, 4)) == PS("model", None)


def test_no_match_replicates_with_warning():
    """A leaf no rule matches degrades to replicated with a
    RuntimeWarning naming the leaf — it must not crash the serve
    loop."""
    rules = ((r"(^|/)emb$", PS("model", None)),)
    with pytest.warns(RuntimeWarning, match="new_head/kernel"):
        spec = spec_for_name(rules, "new_head/kernel", (8, 4))
    assert spec == PS()


def test_match_partition_rules_flax_style_nested_dict():
    """A Flax-style nested params dict maps to a same-shaped spec tree
    with /-joined names driving the match."""
    params = {
        "params": {
            "embedding": {"unit": np.zeros((16, 4), np.float32)},
            "dense_0": {
                "kernel": np.zeros((4, 4), np.float32),
                "bias": np.zeros((4,), np.float32),
            },
        },
        "step": np.zeros((), np.int32),
    }
    specs = match_partition_rules(DEFAULT_SERVE_RULES, params)
    assert specs["params"]["embedding"]["unit"] == PS("model", None)
    assert specs["params"]["dense_0"]["kernel"] == PS()
    assert specs["params"]["dense_0"]["bias"] == PS()
    # the scalar step counter is forced replicated before any rule
    assert specs["step"] == PS()
    # same tree shape: zipping the two trees must not raise
    jax.tree_util.tree_map(lambda a, b: None, params, specs)


def test_tree_path_name_joins_dict_keys():
    flat = jax.tree_util.tree_flatten_with_path(
        {"a": {"b": np.zeros((2,))}}
    )[0]
    (path, _leaf), = flat
    assert tree_path_name(path) == "a/b"


def test_shard_gather_round_trip_preserves_values_and_names():
    """shard_params -> gather_params is an identity on values AND tree
    structure (what the checkpoint writer needs back)."""
    rng = np.random.RandomState(0)
    params = {
        "emb": rng.randn(12, 4).astype(np.float32),
        "head": {"kernel": rng.randn(4, 3).astype(np.float32)},
    }
    on_device = shard_params(REPLICATED_RULES, params)
    assert isinstance(on_device["emb"], jax.Array)
    back = gather_params(REPLICATED_RULES, on_device)
    assert (
        jax.tree_util.tree_structure(back)
        == jax.tree_util.tree_structure(params)
    )
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(back)[0]:
        want = params
        for entry in key_path:
            want = want[entry.key]
        np.testing.assert_array_equal(np.asarray(leaf), want)


def test_match_partition_rules_emits_no_warning_when_covered():
    """The shipped default rules cover every param family the repo
    serves — matching them must be warning-free."""
    params = {
        "emb": np.zeros((8, 4), np.float32),
        "ctx": np.zeros((8, 4), np.float32),
        "unit": np.zeros((8, 4), np.float32),
        "kernel": np.zeros((4, 4), np.float32),
    }
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        match_partition_rules(DEFAULT_SERVE_RULES, params)


def test_parse_rules_json_form():
    rules = parse_rules([
        ["(^|/)unit$", ["model", None]],
        [".*", []],
    ])
    assert rules == [
        ("(^|/)unit$", PS("model", None)),
        (".*", PS()),
    ]
    # null axes == replicated
    assert parse_rules([[".*", None]]) == [(".*", PS())]


def test_parse_rules_rejects_bad_shapes():
    with pytest.raises(Exception):
        parse_rules([["(unclosed", ["model"]]])   # bad regex
    with pytest.raises(ValueError):
        parse_rules([[".*"]])                     # not a pair
    with pytest.raises(ValueError):
        parse_rules([[".*", "model"]])            # axes not a list
