"""Concurrency tier of graftcheck: threadflow role/lock model +
passes_concurrency findings (docs/STATIC_ANALYSIS.md "Concurrency
tier").

Covers the role resolver (Thread / callback / observer discovery, role
propagation through higher-order submissions), one planted-violation
fixture per pass firing exactly once, the ``shared=`` / ``disable=``
pragma round-trips, lock-order cycle witness rendering, the dead-budget
lint, and the no-gating-findings assertion over the triaged repo.
"""

import json
import textwrap

from gene2vec_tpu.analysis.budget_lint import budget_lint_findings
from gene2vec_tpu.analysis.findings import gating
from gene2vec_tpu.analysis.passes_concurrency import (
    CONCURRENCY_PASS_IDS,
    concurrency_findings,
)
from gene2vec_tpu.analysis.threadflow import (
    ROLE_LOOP,
    ROLE_MONITOR,
    ROLE_WORKER,
    build_model,
)


def _fixture(tmp_path, name, src):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return str(path)


def _func(model, qual):
    hits = [f for f in model.funcs.values() if f.qual == qual]
    assert len(hits) == 1, f"{qual}: {[f.key for f in model.funcs.values()]}"
    return hits[0]


# -- role resolver ----------------------------------------------------------


def test_thread_target_discovery_and_name_classification(tmp_path):
    p = _fixture(tmp_path, "fix_threads.py", """\
        import threading

        class Svc:
            def work(self):
                pass

            def watch(self):
                pass

            def start(self):
                threading.Thread(target=self.work, name="io-worker").start()
                threading.Thread(
                    target=self.watch, name="registry-monitor", daemon=True
                ).start()
        """)
    model = build_model(str(tmp_path), files=[p])
    assert ROLE_WORKER in _func(model, "Svc.work").roles
    assert ROLE_MONITOR in _func(model, "Svc.watch").roles
    assert _func(model, "Svc.start").roles == set()  # caller stays main
    assert model.roles_of(_func(model, "Svc.start")) == {"main"}


def test_callback_and_observer_discovery_and_hof_propagation(tmp_path):
    p = _fixture(tmp_path, "fix_callbacks.py", """\
        class Pool:
            def submit(self, fn):
                pass

        class Bus:
            def __init__(self):
                self.observers = []

            def add_observer(self, fn):
                self.observers.append(fn)

        class Svc:
            def __init__(self):
                self.pool = Pool()
                self.bus = Bus()
                self.jobs = []

            def kick(self):
                self.pool.submit(lambda: self.work())

            def wire(self):
                self.bus.add_observer(self.on_change)

            def work(self):
                self.jobs.append(1)

            def on_change(self):
                self.work()
        """)
    model = build_model(str(tmp_path), files=[p])
    # the lambda is the submitted entry; the role flows through the
    # higher-order hop into the method it closes over
    assert ROLE_WORKER in _func(model, "Svc.work").roles
    assert ROLE_WORKER in _func(model, "Svc.on_change").roles
    chain = model.role_chain(_func(model, "Svc.work"), ROLE_WORKER)
    assert any("callback registered" in hop for hop in chain)


def test_loop_role_via_thread_name(tmp_path):
    p = _fixture(tmp_path, "fix_loopname.py", """\
        import threading

        class Loop:
            def run(self):
                self.tick()

            def tick(self):
                pass

        def start():
            loop = Loop()
            threading.Thread(target=loop.run, name="fixture-eventloop").start()
        """)
    model = build_model(str(tmp_path), files=[p])
    assert ROLE_LOOP in _func(model, "Loop.run").roles
    assert ROLE_LOOP in _func(model, "Loop.tick").roles  # propagated


# -- planted fixtures: one finding per pass, exactly once -------------------


def test_lock_discipline_planted_violation_fires_exactly_once(tmp_path):
    p = _fixture(tmp_path, "fix_discipline.py", """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def from_worker(self):
                self.count += 1

            def reset(self):
                self.count = 0

            def start(self):
                threading.Thread(
                    target=self.from_worker, name="io-worker"
                ).start()
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-discipline"]
    )
    assert len(findings) == 1
    (f,) = findings
    assert f.pass_id == "lock-discipline"
    assert f.severity == "error"
    assert "Shared.count" in f.message
    assert sorted(f.data["roles"]) == ["main", "worker"]
    assert len(f.data["writes"]) == 2


def test_lock_discipline_common_lock_is_clean(tmp_path):
    p = _fixture(tmp_path, "fix_disciplined.py", """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def from_worker(self):
                with self._lock:
                    self.count += 1

            def reset(self):
                with self._lock:
                    self.count = 0

            def start(self):
                threading.Thread(
                    target=self.from_worker, name="io-worker"
                ).start()
        """)
    assert concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-discipline"]
    ) == []


def test_loop_thread_blocking_planted_violation_fires_exactly_once(tmp_path):
    p = _fixture(tmp_path, "fix_loopblock.py", """\
        import threading
        import time

        class Loop:
            def run(self):
                self.tick()

            def tick(self):
                time.sleep(0.01)

        def start():
            loop = Loop()
            threading.Thread(target=loop.run, name="fixture-eventloop").start()
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["loop-thread-blocking"]
    )
    assert len(findings) == 1
    (f,) = findings
    assert f.pass_id == "loop-thread-blocking"
    assert "time.sleep" in f.message
    # witness: entry -> ... -> blocking function, with the entry reason
    assert f.data["witness"][0].startswith("Loop.run [Thread target")
    assert "Loop.tick" in f.data["witness"][-1]


def test_blocking_while_locked_planted_violation_fires_exactly_once(tmp_path):
    p = _fixture(tmp_path, "fix_blocklock.py", """\
        import threading
        import time

        class Flusher:
            def __init__(self):
                self._lock = threading.Lock()

            def pump(self):
                with self._lock:
                    time.sleep(0.01)

            def start(self):
                threading.Thread(target=self.pump, name="io-worker").start()
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["blocking-while-locked"]
    )
    assert len(findings) == 1
    (f,) = findings
    assert f.pass_id == "blocking-while-locked"
    assert f.severity == "warning"
    assert "Flusher._lock" in f.message


def test_lock_order_cycle_fires_once_with_witness(tmp_path):
    p = _fixture(tmp_path, "fix_lockorder.py", """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-order"]
    )
    assert len(findings) == 1  # one cycle, canonically deduped
    (f,) = findings
    assert f.pass_id == "lock-order"
    assert "lock-acquisition cycle" in f.message
    assert "AB._a" in f.message and "AB._b" in f.message
    # per-edge witnesses: who acquired what while holding what
    assert len(f.data["witness"]) == 2
    assert all("while holding" in w for w in f.data["witness"])
    assert any("AB.ab" in w for w in f.data["witness"])
    assert any("AB.ba" in w for w in f.data["witness"])


def test_lock_order_interprocedural_cycle(tmp_path):
    p = _fixture(tmp_path, "fix_lockorder2.py", """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def outer(self):
                with self._a:
                    self.inner_b()

            def inner_b(self):
                with self._b:
                    pass

            def other(self):
                with self._b:
                    self.inner_a()

            def inner_a(self):
                with self._a:
                    pass
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-order"]
    )
    assert len(findings) == 1
    (f,) = findings
    # the witness path traverses the call, not just the lexical nesting
    assert any("inner_b" in w or "inner_a" in w for w in f.data["witness"])


def test_consistent_lock_order_is_clean(tmp_path):
    p = _fixture(tmp_path, "fix_lockorder3.py", """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-order"]
    ) == []


# -- pragma round-trips -----------------------------------------------------


def test_shared_pragma_round_trip(tmp_path):
    p = _fixture(tmp_path, "fix_pragma.py", """\
        import threading

        class Swap:
            def __init__(self):
                self.model = None  # graftcheck: shared=hot-swap by single reference; readers see old or new, never torn

            def refresh(self):
                self.model = object()

            def clear(self):
                self.model = None

            def start(self):
                threading.Thread(
                    target=self.refresh, name="registry-monitor"
                ).start()
        """)
    findings = concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["lock-discipline"]
    )
    # suppressed as gating, surfaced as info carrying the justification
    assert gating(findings) == []
    assert len(findings) == 1
    (f,) = findings
    assert f.severity == "info"
    assert f.data["justification"].startswith("hot-swap by single reference")
    assert "hot-swap" in f.message


def test_disable_pragma_suppresses_loop_blocking(tmp_path):
    p = _fixture(tmp_path, "fix_disable.py", """\
        import threading
        import time

        class Loop:
            def run(self):
                time.sleep(0.01)  # graftcheck: disable=loop-thread-blocking

        def start():
            loop = Loop()
            threading.Thread(target=loop.run, name="fixture-eventloop").start()
        """)
    assert concurrency_findings(
        repo_root=str(tmp_path), files=[p], select=["loop-thread-blocking"]
    ) == []


def test_unknown_pass_id_raises(tmp_path):
    try:
        concurrency_findings(select=["no-such-pass"])
    except ValueError as e:
        assert "no-such-pass" in str(e)
    else:
        raise AssertionError("unknown pass id must raise")


# -- dead-budget lint -------------------------------------------------------


def _lint_repo(tmp_path, budgets, consumer="", tests_src=""):
    (tmp_path / "gene2vec_tpu" / "analysis").mkdir(parents=True)
    (tmp_path / "gene2vec_tpu" / "analysis" / "budgets.json").write_text(
        json.dumps(budgets)
    )
    (tmp_path / "scripts").mkdir()
    (tmp_path / "tests").mkdir()
    if consumer:
        (tmp_path / "scripts" / "consume.py").write_text(consumer)
    (tmp_path / "tests" / "test_anchor.py").write_text(tests_src)
    return str(tmp_path)


def test_budget_lint_flags_stale_key_and_spares_consumed(tmp_path):
    root = _lint_repo(
        tmp_path,
        {"zz": {"stale_key": {}, "live_key": {}}},
        consumer='b = budgets.get("zz", {}).get("live_key")\n',
    )
    keys = [
        f.data["key"] for f in budget_lint_findings(root)
        if "key" in f.data
    ]
    assert "zz.stale_key" in keys
    assert "zz.live_key" not in keys


def test_budget_lint_iterated_section_counts_as_consumed(tmp_path):
    root = _lint_repo(
        tmp_path,
        {"zz": {"alpha": {}, "beta": {}}},
        consumer='for k, v in budgets["zz"].items():\n    pass\n',
    )
    assert [
        f for f in budget_lint_findings(root) if "key" in f.data
    ] == []


def test_budget_lint_flags_unanchored_pass(tmp_path):
    from gene2vec_tpu.analysis.runner import pass_ids

    anchored = [pid for pid in pass_ids()] + list(CONCURRENCY_PASS_IDS)
    anchored.append("budget-lint")
    missing = anchored.pop()  # drop one anchor -> it must be flagged
    root = _lint_repo(
        tmp_path, {}, tests_src=json.dumps(anchored)
    )
    flagged = [
        f.data["pass"] for f in budget_lint_findings(root)
        if "pass" in f.data
    ]
    assert flagged == [missing]


# -- the triaged repo -------------------------------------------------------


def test_repo_has_no_gating_concurrency_findings():
    """The whole-repo triage contract: every cross-role mutation is
    locked, queue-handed-off, fixed, or pragma-declared with a written
    justification; no loop-thread blocking or lock cycles remain."""
    findings = concurrency_findings()
    assert gating(findings) == []
    # the declared suppressions surface their justifications
    declared = [f for f in findings if f.severity == "info"]
    assert declared, "the shared= registry must surface declarations"
    for f in declared:
        assert f.data["justification"].strip()


def test_repo_budget_lint_is_clean():
    assert gating(budget_lint_findings()) == []


def test_all_concurrency_passes_registered_in_cli():
    from gene2vec_tpu.cli.analyze import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--list-passes"])
    assert rc == 0
    listed = buf.getvalue().split()
    for pid in CONCURRENCY_PASS_IDS:
        assert pid in listed
    assert "budget-lint" in listed


def test_cli_select_concurrency_pass_reports_by_pass_counts():
    from gene2vec_tpu.cli.analyze import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--json", "--select", "lock-discipline"])
    doc = json.loads(buf.getvalue())
    assert rc == 0
    assert doc["summary"]["by_pass"].get("lock-discipline", 0) >= 1
    # info-only on the triaged repo, every one carrying a justification
    for f in doc["findings"]:
        assert f["severity"] == "info"
        assert f["data"]["justification"]
