"""graftcheck tier-1: AST lint passes, planted-violation fixtures, the
findings schema, round-summary claim checking, and the repo-wide gate.

Each planted fixture must make its pass fire EXACTLY once (no
double-reporting through nested-scope walks), and the clean fixture must
produce zero findings — that pins both sensitivity and specificity.  The
expensive tiers live in tests/test_analysis_hlo.py (slow) and
tests/test_sanitizers.py (slow + sanitizer).
"""

import json
import subprocess
import sys

import pytest

from gene2vec_tpu.analysis import (
    ALL_PASSES,
    Finding,
    gating,
    pass_ids,
    run_ast_passes,
    select_passes,
    to_report,
)
from gene2vec_tpu.analysis.astpass import ModuleSource, traced_functions
from gene2vec_tpu.analysis.summaries import check_summaries, iter_claims

# -- planted violations -----------------------------------------------------

FIXTURES = {
    "host-sync-in-jit": """
import jax
import jax.numpy as jnp

@jax.jit
def forward(params, batch):
    loss = jnp.sum(params * batch)
    return loss.item()
""",
    "py-rng-in-trace": """
import jax
import jax.numpy as jnp
import numpy as np

def epoch(table, xs):
    def body(carry, x):
        noise = np.random.normal(size=4)
        return carry + x + noise.sum(), None
    out, _ = jax.lax.scan(body, table, xs)
    return out
""",
    "missing-donate": """
import jax
import jax.numpy as jnp

def train_step(params, batch):
    return params - 0.1 * batch

fast_step = jax.jit(train_step)
""",
    "jit-recompile-hazard": """
import jax
import jax.numpy as jnp

@jax.jit
def apply_model(params, x):
    return params["w"] @ x

def call(x):
    return apply_model({"w": x * 2}, x)
""",
    "tracer-leak": """
import jax

class Trainer:
    @jax.jit
    def forward(self, params, x):
        self.last_params = params
        return params * x
""",
    "bare-print": """
def report(x):
    print("loss:", x)
""",
    "ckpt-blocking-io": """
import os


class Writer:
    def submit(self, fd, payload):
        self._queue.append(payload)
        os.fsync(fd)
""",
    "span-hygiene": """
import jax

from gene2vec_tpu.obs.trace import ambient_span


@jax.jit
def score(x):
    with ambient_span("inner"):
        return x * 2
""",
    "event-loop-blocking": """
import time


class AcceptorLoop:
    def _on_timer(self, now):
        time.sleep(0.01)
        return now
""",
    "profiler-hook-in-jit": """
import time

import jax


@jax.jit
def scoring(params, batch):
    t0 = time.perf_counter()
    return params * batch + 0.0 * t0
""",
}

CLEAN_FIXTURE = """
import sys

import jax
import jax.numpy as jnp

@jax.jit
def _calibrate(x):
    return jnp.sum(x)

def make_epoch(num_batches):
    def train_epoch(params, pairs, key):
        def body(carry, step):
            k = jax.random.fold_in(key, step)
            noise = jax.random.normal(k, (4,))
            return carry + noise.sum(), None
        out, _ = jax.lax.scan(body, params, jnp.arange(num_batches))
        return out, pairs
    return jax.jit(train_epoch, donate_argnums=(0,))

def host_side(corpus):
    import numpy as np
    print("pairs:", len(corpus), file=sys.stderr)
    return np.asarray(corpus, np.int32)
"""


@pytest.mark.parametrize("pass_id", sorted(FIXTURES))
def test_planted_violation_fires_exactly_once(tmp_path, pass_id):
    path = tmp_path / f"fixture_{pass_id.replace('-', '_')}.py"
    path.write_text(FIXTURES[pass_id])
    findings = run_ast_passes(files=[str(path)], select=[pass_id])
    assert len(findings) == 1, [f.format() for f in findings]
    assert findings[0].pass_id == pass_id
    assert findings[0].line > 0
    # ... and no OTHER pass fires on this fixture either, except known
    # overlaps (a host RNG call in a trace is also a numpy host call)
    overlap = {
        "py-rng-in-trace": {"host-sync-in-jit"},
    }
    others = [
        f
        for f in run_ast_passes(files=[str(path)])
        if f.pass_id != pass_id
        and f.pass_id not in overlap.get(pass_id, set())
    ]
    assert others == [], [f.format() for f in others]


def test_inline_disable_pragma(tmp_path):
    """``# graftcheck: disable=<pass-id>`` on the finding's anchor line
    silences exactly that pass — the sanctioned false-positive escape
    for the name-heuristic passes (vs. weakening the repo gate)."""
    src = FIXTURES["missing-donate"].replace(
        "fast_step = jax.jit(train_step)",
        "fast_step = jax.jit(train_step)"
        "  # graftcheck: disable=missing-donate",
    )
    path = tmp_path / "fixture_pragma.py"
    path.write_text(src)
    assert run_ast_passes(files=[str(path)]) == []

    # a pragma naming a DIFFERENT pass does not silence this one
    src = FIXTURES["missing-donate"].replace(
        "fast_step = jax.jit(train_step)",
        "fast_step = jax.jit(train_step)  # graftcheck: disable=bare-print",
    )
    path.write_text(src)
    assert [f.pass_id for f in run_ast_passes(files=[str(path)])] == [
        "missing-donate"
    ]


def test_span_hygiene_unclosed_span(tmp_path):
    """Rule 2: a span context manager created outside `with` leaks on
    early return; the thin-wrapper `return <span call>` form (Run.span)
    and normal `with` usage stay clean.  A regex m.span() in a module
    that does NOT import obs is never flagged."""
    src = """
import sys

from gene2vec_tpu.obs.trace import ambient_span


def leaky():
    span = ambient_span("phase")
    return span.__enter__()


def wrapper():
    return ambient_span("ok")


def fine():
    with ambient_span("good"):
        print("x", file=sys.stderr)
"""
    path = tmp_path / "spans.py"
    path.write_text(src)
    fs = run_ast_passes(files=[str(path)], select=["span-hygiene"])
    assert len(fs) == 1, [f.format() for f in fs]
    assert "without `with`" in fs[0].message

    # no obs import => the .span attribute form is out of scope
    path2 = tmp_path / "regex_user.py"
    path2.write_text(
        "import re\n"
        "def find(text):\n"
        "    m = re.search('x', text)\n"
        "    return m.span()\n"
    )
    assert run_ast_passes(
        files=[str(path2)], select=["span-hygiene"]
    ) == []


def test_clean_fixture_zero_findings(tmp_path):
    path = tmp_path / "clean_module.py"
    path.write_text(CLEAN_FIXTURE)
    findings = run_ast_passes(files=[str(path)])
    assert findings == [], [f.format() for f in findings]


def test_hof_operand_name_collision_not_traced(tmp_path):
    """A scan carry whose local name collides with a module-level host
    function must NOT mark that function traced — only function-valued
    HOF argument positions count (TRACE_HOF_FN_ARGS)."""
    src = """
import sys

import jax
import numpy as np

def init(shape):
    print("seeding", file=sys.stderr)
    return np.random.randn(*shape)

def epoch(table, xs):
    def body(carry, x):
        return carry + x, None
    init = table.sum()
    out, _ = jax.lax.scan(body, init, xs)
    return out
"""
    path = tmp_path / "collision.py"
    path.write_text(src)
    mod = ModuleSource.load(str(path), str(tmp_path))
    names = {tf.name for tf in traced_functions(mod)}
    assert "body" in names and "init" not in names
    assert run_ast_passes(files=[str(path)]) == []


def test_def_name_collision_not_traced(tmp_path):
    """A host-side def sharing its name with a traced nested closure is
    NOT dragged into traced scope — wrapped names resolve per call site
    through lexical scopes, not by bare name across the module."""
    src = """
import sys

import jax
import numpy as np

def body(shape):
    print("host", file=sys.stderr)
    return np.random.randn(*shape)

def epoch(table, xs):
    def body(carry, x):
        return carry + x, None
    out, _ = jax.lax.scan(body, table, xs)
    return out
"""
    path = tmp_path / "defcollision.py"
    path.write_text(src)
    mod = ModuleSource.load(str(path), str(tmp_path))
    traced = traced_functions(mod)
    assert [tf.name for tf in traced] == ["body"]
    assert traced[0].node.col_offset == 4  # the nested one, not the host def
    assert run_ast_passes(files=[str(path)]) == []


def test_same_named_traced_functions_keep_own_params(tmp_path):
    """Two factories wrapping same-named inner functions: each nested
    body must inherit ITS enclosing function's params (outer links are
    by node identity), so the float()-coercion check fires in both."""
    src = """
import jax

def make_a():
    def train_epoch(alpha, xs):
        def body(c, x):
            return c + float(alpha), None
        return jax.lax.scan(body, alpha, xs)
    return jax.jit(train_epoch, donate_argnums=(0,))

def make_b():
    def train_epoch(beta, xs):
        def body(c, x):
            return c + float(beta), None
        return jax.lax.scan(body, beta, xs)
    return jax.jit(train_epoch, donate_argnums=(0,))
"""
    path = tmp_path / "samename.py"
    path.write_text(src)
    fs = run_ast_passes(files=[str(path)], select=["host-sync-in-jit"])
    assert len(fs) == 2, [f.format() for f in fs]
    assert {f.line for f in fs} == {7, 14}


def test_traced_scope_detection(tmp_path):
    path = tmp_path / "scopes.py"
    path.write_text(CLEAN_FIXTURE)
    mod = ModuleSource.load(str(path), str(tmp_path))
    names = {tf.name: tf.reason for tf in traced_functions(mod)}
    assert names["_calibrate"] == "decorator"
    assert names["train_epoch"].startswith("wrapped:jax.jit")
    assert names["body"] == "nested:train_epoch"
    assert "host_side" not in names
    assert "make_epoch" not in names


# -- repo gate --------------------------------------------------------------


def test_package_and_experiments_clean_at_head():
    """The acceptance gate: zero gating findings on the repo.  Anything
    this catches is either a real footgun (fix it) or a pass
    false-positive (fix the pass) — never weaken the test."""
    findings = gating(run_ast_passes())
    assert findings == [], "\n".join(f.format() for f in findings)


def test_select_and_skip_validation():
    with pytest.raises(ValueError):
        select_passes(select=["no-such-pass"])
    assert [p.id for p in select_passes(skip=["bare-print"])] == [
        pid for pid in pass_ids() if pid != "bare-print"
    ]


# -- findings schema --------------------------------------------------------


def test_findings_report_schema():
    fs = [
        Finding(pass_id="x", message="m", path="a.py", line=3),
        Finding(pass_id="y", message="i", severity="info"),
    ]
    doc = to_report(fs, meta={"k": 1})
    assert doc["schema"] == "gene2vec-tpu/findings/v1"
    assert doc["summary"] == {
        "total": 2, "gating": 1, "by_pass": {"x": 1, "y": 1},
    }
    assert doc["meta"] == {"k": 1}
    json.dumps(doc)  # must be serializable


# -- CLI --------------------------------------------------------------------


def test_analyze_cli_clean_and_json(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "gene2vec_tpu.cli.analyze", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["schema"] == "gene2vec-tpu/findings/v1"
    assert doc["summary"]["gating"] == 0

    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["bare-print"])
    proc = subprocess.run(
        [
            sys.executable, "-m", "gene2vec_tpu.cli.analyze",
            "--select", "bare-print", str(bad),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "bare print()" in proc.stdout


def test_bare_print_shim_still_works(tmp_path):
    """scripts/check_no_bare_prints.py stays a working entry point."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "check_no_bare_prints.py"),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


# -- round-summary claims ---------------------------------------------------


def test_claim_extraction():
    text = "159 → 163 tests green\nand 171 passed overall\n90+ tests\n"
    claims = list(iter_claims(text, "docs/X.md"))
    got = {(c.line, c.data["claimed"], c.data["at_least"]) for c in claims}
    assert got == {(1, 163, False), (2, 171, False), (3, 90, True)}


def test_summary_claim_violation(tmp_path):
    d = tmp_path / "docs"
    d.mkdir()
    (d / "ROUND9_SUMMARY.md").write_text("now 10000 tests green\n")
    fs = check_summaries(str(d), collected_count=200)
    assert [f.severity for f in fs] == ["error"]
    fs = check_summaries(str(d), collected_count=None)
    assert [f.severity for f in fs] == ["info"]


def test_round_summary_claims_vs_live_collection(request):
    """Cross-check every docs/ROUND*_SUMMARY.md test-count claim against
    THIS session's collected count (selected + deselected), recorded by
    tests/conftest.py.  Suites only grow, so no historical summary may
    claim more tests than exist now.  Skips on partial invocations
    (running a single file collects too few to judge)."""
    import os

    collected = getattr(request.config, "_gene2vec_collected", 0)
    if collected < 150:
        pytest.skip(
            f"partial collection ({collected} items) — claim check needs "
            "a full-suite run"
        )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = gating(check_summaries(os.path.join(repo, "docs"), collected))
    assert bad == [], "\n".join(f.format() for f in bad)
