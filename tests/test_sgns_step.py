"""SGNS step math vs an independent numpy oracle (SURVEY §7 step 2)."""

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.sgns.model import SGNSParams, init_params
from gene2vec_tpu.sgns.step import sgns_loss_and_grads, sgns_step


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def numpy_sgns_oracle(emb, ctx, centers, contexts, negs, lr):
    """Straight-line per-example SGNS with summed duplicate updates."""
    emb, ctx = emb.copy().astype(np.float64), ctx.copy().astype(np.float64)
    d_emb = np.zeros_like(emb)
    d_ctx = np.zeros_like(ctx)
    losses = []
    for e in range(len(centers)):
        c, o = centers[e], contexts[e]
        v, u = emb[c], ctx[o]
        pos = float(v @ u)
        loss = np.log1p(np.exp(-pos))
        g_pos = _sigmoid(pos) - 1.0
        dv = g_pos * u
        d_ctx[o] += g_pos * v
        for k in negs[e]:
            if k == o:  # collision with the positive target is skipped
                continue
            un = ctx[k]
            neg = float(v @ un)
            loss += np.log1p(np.exp(neg))
            g = _sigmoid(neg)
            dv += g * un
            d_ctx[k] += g * v
        d_emb[c] += dv
        losses.append(loss)
    return (
        np.mean(losses),
        emb - lr * d_emb,
        ctx - lr * d_ctx,
    )


def test_loss_and_grads_match_oracle():
    rng = np.random.RandomState(0)
    V, D, E, K = 20, 8, 16, 5
    emb = rng.randn(V, D).astype(np.float32) * 0.1
    ctx = rng.randn(V, D).astype(np.float32) * 0.1
    centers = rng.randint(0, V, E).astype(np.int32)
    contexts = rng.randint(0, V, E).astype(np.int32)
    negs = rng.randint(0, V, (E, K)).astype(np.int32)

    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    loss, _ = sgns_loss_and_grads(
        params, jnp.asarray(centers), jnp.asarray(contexts), jnp.asarray(negs)
    )
    exp_loss, _, _ = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, 0.0)
    np.testing.assert_allclose(float(loss), exp_loss, rtol=1e-5)


def test_step_updates_match_oracle():
    """Full step (both directions, fixed negatives) vs numpy SGD."""
    rng = np.random.RandomState(3)
    V, D, B, K, lr = 15, 6, 10, 4, 0.05
    emb = rng.randn(V, D).astype(np.float32) * 0.2
    ctx = rng.randn(V, D).astype(np.float32) * 0.2
    pairs = rng.randint(0, V, (B, 2)).astype(np.int32)

    # run the jax step with a known key, then replay its own sampled
    # negatives through the oracle
    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    cdf = jnp.linspace(1.0 / V, 1.0, V)  # uniform noise
    key = jax.random.PRNGKey(42)
    new_params, _ = sgns_step(params, jnp.asarray(pairs), cdf, key, lr, negatives=K)

    from gene2vec_tpu.data.negative_sampling import sample_negatives

    centers = np.concatenate([pairs[:, 0], pairs[:, 1]])
    contexts = np.concatenate([pairs[:, 1], pairs[:, 0]])
    negs = np.asarray(sample_negatives(cdf, key, (2 * B, K)))

    _, exp_emb, exp_ctx = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, lr)
    np.testing.assert_allclose(np.asarray(new_params.emb), exp_emb, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_params.ctx), exp_ctx, atol=1e-5)


def test_duplicate_indices_sum_contributions():
    """Batch with repeated center ids must accumulate, not overwrite."""
    V, D, K = 5, 4, 2
    emb = np.ones((V, D), np.float32)
    ctx = np.ones((V, D), np.float32) * 0.5
    pairs = np.array([[0, 1], [0, 2]], np.int32)  # center 0 twice (plus reverse)
    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    cdf = jnp.linspace(0.2, 1.0, V)
    key = jax.random.PRNGKey(0)
    new_params, _ = sgns_step(params, jnp.asarray(pairs), cdf, key, 0.1, negatives=K)

    from gene2vec_tpu.data.negative_sampling import sample_negatives

    centers = np.concatenate([pairs[:, 0], pairs[:, 1]])
    contexts = np.concatenate([pairs[:, 1], pairs[:, 0]])
    negs = np.asarray(sample_negatives(cdf, key, (4, K)))
    _, exp_emb, exp_ctx = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, 0.1)
    np.testing.assert_allclose(np.asarray(new_params.emb), exp_emb, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_params.ctx), exp_ctx, atol=1e-5)


def test_init_params_shapes_and_ranges():
    p = init_params(jax.random.PRNGKey(0), 30, 16)
    assert p.emb.shape == (30, 16) and p.ctx.shape == (30, 16)
    assert float(jnp.max(jnp.abs(p.emb))) <= 0.5 / 16
    assert float(jnp.max(jnp.abs(p.ctx))) == 0.0
