"""SGNS step math vs an independent numpy oracle (SURVEY §7 step 2)."""

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.data.negative_sampling import build_alias_table
from gene2vec_tpu.sgns.model import SGNSParams, init_params
from gene2vec_tpu.sgns.step import sgns_loss_and_grads, sgns_step


def _uniform_noise(v):
    return build_alias_table(np.ones(v) / v)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def numpy_sgns_oracle(emb, ctx, centers, contexts, negs, lr):
    """Straight-line per-example SGNS with summed duplicate updates."""
    emb, ctx = emb.copy().astype(np.float64), ctx.copy().astype(np.float64)
    d_emb = np.zeros_like(emb)
    d_ctx = np.zeros_like(ctx)
    losses = []
    for e in range(len(centers)):
        c, o = centers[e], contexts[e]
        v, u = emb[c], ctx[o]
        pos = float(v @ u)
        loss = np.log1p(np.exp(-pos))
        g_pos = _sigmoid(pos) - 1.0
        dv = g_pos * u
        d_ctx[o] += g_pos * v
        for k in negs[e]:
            if k == o:  # collision with the positive target is skipped
                continue
            un = ctx[k]
            neg = float(v @ un)
            loss += np.log1p(np.exp(neg))
            g = _sigmoid(neg)
            dv += g * un
            d_ctx[k] += g * v
        d_emb[c] += dv
        losses.append(loss)
    return (
        np.mean(losses),
        emb - lr * d_emb,
        ctx - lr * d_ctx,
    )


def test_loss_and_grads_match_oracle():
    rng = np.random.RandomState(0)
    V, D, E, K = 20, 8, 16, 5
    emb = rng.randn(V, D).astype(np.float32) * 0.1
    ctx = rng.randn(V, D).astype(np.float32) * 0.1
    centers = rng.randint(0, V, E).astype(np.int32)
    contexts = rng.randint(0, V, E).astype(np.int32)
    negs = rng.randint(0, V, (E, K)).astype(np.int32)

    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    loss, _, _ = sgns_loss_and_grads(
        params, jnp.asarray(centers), jnp.asarray(contexts), jnp.asarray(negs)
    )
    exp_loss, _, _ = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, 0.0)
    np.testing.assert_allclose(float(loss), exp_loss, rtol=1e-5)


def test_step_updates_match_oracle():
    """Full step (both directions, fixed negatives) vs numpy SGD."""
    rng = np.random.RandomState(3)
    V, D, B, K, lr = 15, 6, 10, 4, 0.05
    emb = rng.randn(V, D).astype(np.float32) * 0.2
    ctx = rng.randn(V, D).astype(np.float32) * 0.2
    pairs = rng.randint(0, V, (B, 2)).astype(np.int32)

    # run the jax step with a known key, then replay its own sampled
    # negatives through the oracle
    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    cdf = _uniform_noise(V)  # uniform noise
    key = jax.random.PRNGKey(42)
    new_params, _ = sgns_step(
        params,
        jnp.asarray(pairs),
        cdf,
        key,
        lr,
        negatives=K,
        combiner="sum",
        negative_mode="per_example",
    )

    from gene2vec_tpu.data.negative_sampling import sample_negatives

    centers = np.concatenate([pairs[:, 0], pairs[:, 1]])
    contexts = np.concatenate([pairs[:, 1], pairs[:, 0]])
    negs = np.asarray(sample_negatives(cdf, key, (2 * B, K)))

    _, exp_emb, exp_ctx = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, lr)
    np.testing.assert_allclose(np.asarray(new_params.emb), exp_emb, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_params.ctx), exp_ctx, atol=1e-5)


def test_duplicate_indices_sum_contributions():
    """Batch with repeated center ids must accumulate, not overwrite."""
    V, D, K = 5, 4, 2
    emb = np.ones((V, D), np.float32)
    ctx = np.ones((V, D), np.float32) * 0.5
    pairs = np.array([[0, 1], [0, 2]], np.int32)  # center 0 twice (plus reverse)
    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    cdf = _uniform_noise(V)
    key = jax.random.PRNGKey(0)
    new_params, _ = sgns_step(
        params,
        jnp.asarray(pairs),
        cdf,
        key,
        0.1,
        negatives=K,
        combiner="sum",
        negative_mode="per_example",
    )

    from gene2vec_tpu.data.negative_sampling import sample_negatives

    centers = np.concatenate([pairs[:, 0], pairs[:, 1]])
    contexts = np.concatenate([pairs[:, 1], pairs[:, 0]])
    negs = np.asarray(sample_negatives(cdf, key, (4, K)))
    _, exp_emb, exp_ctx = numpy_sgns_oracle(emb, ctx, centers, contexts, negs, 0.1)
    np.testing.assert_allclose(np.asarray(new_params.emb), exp_emb, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_params.ctx), exp_ctx, atol=1e-5)


import pytest


@pytest.mark.parametrize("combiner", ["mean", "capped"])
@pytest.mark.parametrize("negative_mode", ["shared", "per_example"])
def test_combiner_stable_under_hot_rows(combiner, negative_mode):
    """A skewed batch hammering one token must not blow up row norms.

    With combiner="sum", a token repeated R times per batch takes an R-times
    oversized step (all R gradients evaluated at stale params) and training
    diverges on Zipf-distributed corpora; "mean" and "capped" keep the
    hot-row step bounded."""
    rng = np.random.RandomState(0)
    V, D, B = 50, 16, 2048
    # 90% of pairs involve token 0
    a = np.where(rng.rand(B) < 0.9, 0, rng.randint(1, V, B))
    b = rng.randint(1, V, B)
    pairs = np.stack([a, b], 1).astype(np.int32)
    params = SGNSParams(
        jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.1),
    )
    cdf = _uniform_noise(V)
    key = jax.random.PRNGKey(1)
    p = params
    for s in range(20):
        p, loss = sgns_step(
            p,
            jnp.asarray(pairs),
            cdf,
            jax.random.fold_in(key, s),
            0.025,
            combiner=combiner,
            negative_mode=negative_mode,
        )
    assert np.isfinite(float(loss))
    assert float(jnp.max(jnp.abs(p.emb))) < 10.0


def test_shared_pool_positive_updates_not_crushed():
    """A context token that happens to sit in the noise pool must still get
    a near-full-size positive update (pool contributions count at their K/P
    importance weight, not 1 each)."""
    V, D, B = 100, 8, 512
    rng = np.random.RandomState(2)
    pairs = np.stack(
        [rng.randint(0, V, B), np.full(B, 7)], 1
    ).astype(np.int32)  # token 7 is every pair's context (forward direction)
    params = SGNSParams(
        jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.1),
    )
    noise = _uniform_noise(V)
    key = jax.random.PRNGKey(0)
    # shared_pool_auto=False keeps the small explicit pools this test is
    # about — auto sizing would override both to the same parity pool and
    # make the comparison vacuous
    p1, _ = sgns_step(
        params, jnp.asarray(pairs), noise, key, 0.05,
        both_directions=False, negative_mode="shared", shared_pool=64,
        shared_pool_auto=False, shared_groups=1,
    )
    # token 7 occurs B=512 times as positive context → capped divisor ≈ B/32;
    # the pool's extra weight is only ~ (5/64)·512·(64/V) ≈ tiny vs B. The
    # update must be within ~2x of the pure-positive capped magnitude, not
    # ~P/K ≈ 13x smaller.
    delta = float(jnp.linalg.norm(p1.ctx[7] - params.ctx[7]))
    p_ref, _ = sgns_step(
        params, jnp.asarray(pairs), noise, key, 0.05,
        both_directions=False, negative_mode="shared", shared_pool=5,
        shared_pool_auto=False, shared_groups=1,
    )
    delta_ref = float(jnp.linalg.norm(p_ref.ctx[7] - params.ctx[7]))
    assert delta > 0.25 * delta_ref


def test_mean_combiner_matches_sum_when_indices_unique():
    """With every row touched at most once, mean and sum are identical."""
    import pytest

    V, D, K = 400, 8, 3
    rng = np.random.RandomState(5)
    emb = rng.randn(V, D).astype(np.float32) * 0.1
    ctx = rng.randn(V, D).astype(np.float32) * 0.1
    pairs = np.array([[0, 1], [2, 3], [4, 5]], np.int32)
    params = SGNSParams(jnp.asarray(emb), jnp.asarray(ctx))
    cdf = _uniform_noise(V)
    key = jax.random.PRNGKey(7)

    from gene2vec_tpu.data.negative_sampling import sample_negatives

    # same key → sgns_step draws these same negatives in both calls below
    negs = np.asarray(sample_negatives(cdf, key, (3, K)))
    touched = np.concatenate([pairs[:, 1], negs.ravel()])
    if len(np.unique(touched)) != touched.size:
        pytest.skip("unlucky key: sampled negatives collide")

    out = {}
    for comb in ("mean", "sum"):
        p, _ = sgns_step(
            params,
            jnp.asarray(pairs),
            cdf,
            key,
            0.05,
            negatives=K,
            both_directions=False,
            combiner=comb,
            negative_mode="per_example",
        )
        out[comb] = p
    np.testing.assert_allclose(
        np.asarray(out["mean"].emb), np.asarray(out["sum"].emb), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out["mean"].ctx), np.asarray(out["sum"].ctx), atol=1e-6
    )


def test_init_params_shapes_and_ranges():
    p = init_params(jax.random.PRNGKey(0), 30, 16)
    assert p.emb.shape == (30, 16) and p.ctx.shape == (30, 16)
    assert float(jnp.max(jnp.abs(p.emb))) <= 0.5 / 16
    assert float(jnp.max(jnp.abs(p.ctx))) == 0.0
