"""Build the DMA row-gather kernel up from minimal pieces to find what
fails to compile on this backend."""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

V, D, E = 24576, 256, 4096

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def try_kernel(label, fn, *args):
    try:
        out = jax.jit(fn)(*args)
        s = float(_sum(out))
        print(f"{label:46s} OK (sum {s:.1f})", file=sys.stderr)
        return out
    except Exception as e:
        lines = [l for l in str(e).splitlines() if l.strip()][:3]
        print(f"{label:46s} FAIL: {' | '.join(l[:120] for l in lines)}", file=sys.stderr)
        return None


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, V, E).astype(np.int32))

    # 1. static single-row DMA from ANY-space input
    def k1(idx_ref, table_ref, out_ref):
        def body(scratch, sem):
            dma = pltpu.make_async_copy(
                table_ref.at[pl.ds(0, 1), :], scratch, sem
            )
            dma.start()
            dma.wait()
            out_ref[pl.ds(0, 1), :] = scratch[:]

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((1, D), jnp.float32),
            sem=pltpu.SemaphoreType.DMA,
        )

    def call1(idx, table):
        return pl.pallas_call(
            k1,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
        )(idx, table)

    try_kernel("1: static 1-row DMA", call1, idx, table)

    # 2. dynamic single-row DMA using prefetched scalar index
    def k2(idx_ref, table_ref, out_ref):
        def body(scratch, sem):
            dma = pltpu.make_async_copy(
                table_ref.at[pl.ds(idx_ref[0], 1), :], scratch, sem
            )
            dma.start()
            dma.wait()
            out_ref[pl.ds(0, 1), :] = scratch[:]

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((1, D), jnp.float32),
            sem=pltpu.SemaphoreType.DMA,
        )

    def call2(idx, table):
        return pl.pallas_call(
            k2,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
        )(idx, table)

    try_kernel("2: dynamic 1-row DMA via scalar prefetch", call2, idx, table)

    # 3. fori_loop of dynamic row DMAs, 1 in flight
    def k3(idx_ref, table_ref, out_ref):
        def body(scratch, sem):
            def loop(i, _):
                dma = pltpu.make_async_copy(
                    table_ref.at[pl.ds(idx_ref[i], 1), :], scratch, sem
                )
                dma.start()
                dma.wait()
                out_ref[pl.ds(i, 1), :] = scratch[:]
                return 0

            jax.lax.fori_loop(0, E, loop, 0)

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((1, D), jnp.float32),
            sem=pltpu.SemaphoreType.DMA,
        )

    def call3(idx, table):
        return pl.pallas_call(
            k3,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
        )(idx, table)

    out = try_kernel("3: fori of dynamic row DMAs (1 in flight)", call3, idx, table)
    if out is not None:
        want = np.asarray(table)[np.asarray(idx)]
        print("   max err:", np.abs(np.asarray(out) - want).max(), file=sys.stderr)

    # 4. ring with semaphore array, K in flight
    K = 8

    def k4(idx_ref, table_ref, out_ref):
        def body(scratch, sems):
            def get_dma(slot, i):
                return pltpu.make_async_copy(
                    table_ref.at[pl.ds(idx_ref[i], 1), :],
                    scratch.at[pl.ds(slot, 1), :],
                    sems.at[slot],
                )

            def warm(i, _):
                get_dma(i, i).start()
                return 0

            jax.lax.fori_loop(0, K, warm, 0)

            def loop(i, _):
                slot = jax.lax.rem(i, K)
                get_dma(slot, i).wait()
                out_ref[pl.ds(i, 1), :] = scratch[pl.ds(slot, 1), :]

                @pl.when(i + K < E)
                def _():
                    get_dma(slot, i + K).start()

                return 0

            jax.lax.fori_loop(0, E, loop, 0)

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((K, D), jnp.float32),
            sems=pltpu.SemaphoreType.DMA((K,)),
        )

    def call4(idx, table):
        return pl.pallas_call(
            k4,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
        )(idx, table)

    out = try_kernel(f"4: DMA ring K={K}", call4, idx, table)
    if out is not None:
        want = np.asarray(table)[np.asarray(idx)]
        print("   max err:", np.abs(np.asarray(out) - want).max(), file=sys.stderr)


if __name__ == "__main__":
    main()
