"""What streaming HBM bandwidth can this chip actually sustain?"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
SCAN = 50


def bench(label, loop, x, nbytes):
    out = loop(x)
    float(_sum(out))
    t0 = time.perf_counter()
    out = loop(x)
    float(_sum(out))
    dt = (time.perf_counter() - t0) / SCAN
    print(f"{label:46s} {dt * 1e6:9.1f} us/call  {nbytes / dt / 1e9:7.1f} GB/s", file=sys.stderr)


def xla_axpy_loop(shape, dtype):
    @jax.jit
    def loop(x):
        def body(c, _):
            return c * 1.0000001, ()
        c, _ = jax.lax.scan(body, x, jnp.arange(SCAN))
        return c
    return loop


def pallas_copy_loop(shape, dtype, block_rows):
    n, d = shape

    def kernel(in_ref, out_ref):
        out_ref[:] = in_ref[:] * 1.0000001

    def call(x):
        return pl.pallas_call(
            kernel,
            grid=(n // block_rows,),
            in_specs=[pl.BlockSpec((block_rows, d), lambda b: (b, 0), memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((block_rows, d), lambda b: (b, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(shape, dtype),
        )(x)

    @jax.jit
    def loop(x):
        def body(c, _):
            return call(c), ()
        c, _ = jax.lax.scan(body, x, jnp.arange(SCAN))
        return c

    return loop


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)

    for mb in (25, 100, 400):
        n = mb * 1024 * 1024 // 4 // 256
        x = jnp.asarray(rng.randn(n, 256).astype(np.float32))
        nbytes = n * 256 * 4 * 2  # read + write
        bench(f"XLA axpy f32 {mb}MB", xla_axpy_loop((n, 256), jnp.float32), x, nbytes)

    n = 100 * 1024 * 1024 // 4 // 256
    x = jnp.asarray(rng.randn(n, 256).astype(np.float32))
    nbytes = n * 256 * 4 * 2
    for br in (256, 1024, 4096):
        bench(f"pallas copy f32 100MB block={br}x256",
              pallas_copy_loop((n, 256), jnp.float32, br), x, nbytes)

    xb = x.astype(jnp.bfloat16)
    bench("XLA axpy bf16 50MB", xla_axpy_loop((n, 256), jnp.bfloat16), xb, nbytes // 2)


if __name__ == "__main__":
    main()
