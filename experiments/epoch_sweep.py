"""Careful XLA epoch benchmark: repetitions, batch sweep, unroll, shuffle
variants. Ground truth for the round-2 optimization baseline."""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NegativeSampler
from gene2vec_tpu.sgns.model import SGNSParams
from gene2vec_tpu.sgns.train import SGNSTrainer, make_train_epoch
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
import sys

V, D = 24447, 200
N = 4_000_000
REPS = 3


def make_corpus(rng):
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    pairs = rng.choice(V, size=(N, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=V).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(V)], counts), pairs)


def run(label, corpus, cfg):
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    params, loss = trainer.train_epoch(params, key)  # compile
    float(loss)
    rates = []
    for r in range(REPS):
        t0 = time.perf_counter()
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, r))
        float(loss)
        dt = time.perf_counter() - t0
        rates.append(trainer.num_batches * trainer.config.batch_pairs / dt)
    rs = ", ".join(f"{r / 1e6:6.2f}" for r in rates)
    print(f"{label:44s} [{rs}] M pairs/s  (best {max(rates)/1e6:.2f})", file=sys.stderr)


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    corpus = make_corpus(rng)

    run("B=16k offset (r1 default)", corpus, SGNSConfig(dim=D, batch_pairs=16384))
    run("B=16k noshuffle", corpus,
        SGNSConfig(dim=D, batch_pairs=16384, shuffle_each_iter=False))
    run("B=16k full", corpus,
        SGNSConfig(dim=D, batch_pairs=16384, shuffle_mode="full"))
    run("B=65k noshuffle", corpus,
        SGNSConfig(dim=D, batch_pairs=65536, shuffle_each_iter=False))
    run("B=65k full", corpus,
        SGNSConfig(dim=D, batch_pairs=65536, shuffle_mode="full"))
    run("B=262k noshuffle", corpus,
        SGNSConfig(dim=D, batch_pairs=262144, shuffle_each_iter=False))
    run("B=16k noshuffle perexample", corpus,
        SGNSConfig(dim=D, batch_pairs=16384, shuffle_each_iter=False,
                   negative_mode="per_example"))


if __name__ == "__main__":
    main()
