"""Round 5: sweep the second dense positive slab (config.positive_mid).

The single-level head sweep (positive_head_sweep.py) capped at H=512
because one-hot FLOPs grow with ALL head examples while coverage grows
logarithmically.  The mid slab [head, head+mid) pays its width only for
mid-band examples (Zipf: each octave past the head covers ~5-7% of
occurrences at a shrinking example count), so the trade is different:
expected win = covered tail row ops (32 ns/occurrence) minus the mid
one-hot contraction cost (E_mid x mid x (D+1) MACs x 4 ops).

Measures integrated-trainer throughput at the bench headline shape
(V=24,447 Zipf, 4M pairs, B=16,384, dim 200, stratified negatives).

Run: python experiments/positive_mid_sweep.py [--combos 512:0,512:4096]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import synth_corpus  # the bench's own corpus recipe
from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.sgns.train import SGNSTrainer


def measure(head: int, mid: int, v: int, n: int, b: int, dim: int,
            epochs: int = 3):
    corpus = synth_corpus(v, n)
    cfg = SGNSConfig(dim=dim, batch_pairs=b, positive_head=head,
                     positive_mid=mid)
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    pairs_per_epoch = trainer.num_batches * cfg.batch_pairs
    rates, loss = [], None
    for ep in range(epochs + 1):  # first epoch includes compile
        t0 = time.perf_counter()
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, ep))
        loss = float(loss)  # sync
        dt = time.perf_counter() - t0
        if ep:
            rates.append(pairs_per_epoch / dt)
    return {
        "head": head,
        "mid": mid,
        "pairs_per_sec": round(float(np.median(rates)), 1),
        "rates": [round(r, 1) for r in rates],
        "final_loss": round(loss, 4),
        "quotas": trainer.pos_quotas,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--combos",
        default="512:0,512:2048,512:4096,512:8192,1024:4096,256:4352,512:0",
        help="comma-separated head:mid pairs (trailing repeat of the "
             "baseline gauges in-process device-state drift)",
    )
    ap.add_argument("--vocab", type=int, default=24447)
    ap.add_argument("--pairs", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--dim", type=int, default=200)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument(
        "--out", default=os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "results", "positive_mid_r5.json",
        ),
    )
    args = ap.parse_args()
    results = []
    for combo in args.combos.split(","):
        head, mid = (int(x) for x in combo.split(":"))
        print(f"head={head} mid={mid} ...", flush=True, file=sys.stderr)
        r = measure(head, mid, args.vocab, args.pairs, args.batch,
                    args.dim, args.epochs)
        print(f"  {r['pairs_per_sec']:,.0f} pairs/s  loss={r['final_loss']}",
              flush=True, file=sys.stderr)
        results.append(r)
    with open(args.out, "w") as f:
        json.dump({"device": str(jax.devices()[0]), "results": results}, f,
                  indent=2)
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
