"""Probe (a) VMEM capacity on v5e, (b) manual-DMA row gather throughput with
a deep ring of outstanding copies vs XLA's gather."""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def sync(x):
    return float(_sum(x))


def vmem_probe(mb: int) -> bool:
    n = mb * 1024 * 1024 // 4 // 256
    x = jnp.ones((n, 256), jnp.float32)

    def kernel(in_ref, out_ref):
        out_ref[:] = in_ref[:] * 2.0

    try:
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, 256), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=128 * 1024 * 1024
            ),
        )(x)
        sync(out)
        return True
    except Exception as e:
        msg = str(e).split("\n")[0][:160]
        print(f"  {mb}MB in+out failed: {msg}", file=sys.stderr)
        return False


def dma_gather(table, idx, n_inflight=32, rows_per_copy=1):
    """Gather idx rows from HBM table via a ring of outstanding DMAs."""
    E = idx.shape[0]
    D = table.shape[1]

    def kernel(idx_ref, table_ref, out_ref):
        def body(scratch, sems):
            def get_dma(slot, i):
                return pltpu.make_async_copy(
                    table_ref.at[pl.ds(idx_ref[i], 1), :],
                    scratch.at[pl.ds(slot, 1), :],
                    sems.at[slot],
                )

            for i in range(n_inflight):
                get_dma(i, i).start()

            def loop(i, _):
                slot = jax.lax.rem(i, n_inflight)
                get_dma(slot, i).wait()
                out_ref[pl.ds(i, 1), :] = scratch[pl.ds(slot, 1), :]

                @pl.when(i + n_inflight < E)
                def _():
                    get_dma(slot, i + n_inflight).start()

                return 0

            jax.lax.fori_loop(0, E, loop, 0)

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((n_inflight, D), table.dtype),
            sems=pltpu.SemaphoreType.DMA((n_inflight,)),
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((E, D), table.dtype),
        grid_spec=grid_spec,
    )(idx, table)


def bench(label, fn, *args, iters=30):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:48s} {dt * 1e6:9.1f} us", file=sys.stderr)
    return out


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    print("VMEM capacity probe (in+out both VMEM, so ~2x the MB):", file=sys.stderr)
    for mb in (8, 16, 24, 32, 48, 56, 60):
        ok = vmem_probe(mb)
        print(f"  {mb}MB blocks x2: {'OK' if ok else 'FAIL'}", file=sys.stderr)
        if not ok:
            break

    V, D, E = 24576, 256, 32768
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, V, E).astype(np.int32))

    out_x = bench("XLA gather 32768 rows f32", jax.jit(lambda t, i: t[i]), table, idx)
    # calibrate dispatch overhead
    bench("noop (x*1.0 on (8,256))", jax.jit(lambda t: t * 1.0), table[:8])

    for k in (16, 64, 128):
        try:
            fn = jax.jit(functools.partial(dma_gather, n_inflight=k))
            out_p = bench(f"pallas DMA-ring gather k={k}", fn, table, idx)
            err = float(_sum(jnp.abs(out_p - out_x)))
            print(f"    abs err vs xla: {err}", file=sys.stderr)
        except Exception as e:
            print(f"  k={k} failed: {str(e).splitlines()[0][:160]}", file=sys.stderr)


if __name__ == "__main__":
    main()
