"""Pin down which dynamic-slice forms work: HBM-side dynamic DMA source,
aligned dynamic VMEM writes, and then build + time the aligned DMA-ring
row gather."""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

V, D = 24576, 256
_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def try_kernel(label, fn, *args):
    try:
        out = jax.jit(fn)(*args)
        float(_sum(out))
        print(f"{label:56s} OK", file=sys.stderr)
        return out
    except Exception as e:
        lines = [l for l in str(e).splitlines() if "Mosaic" in l or "INTERNAL" in l or "Error" in l][:1]
        print(f"{label:56s} FAIL: {lines[0][:110] if lines else str(e).splitlines()[0][:110]}", file=sys.stderr)
        return None


def main():
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))

    # g: dynamic-row DMA source from ANY (HBM) by prefetched scalar
    idx1 = jnp.asarray([7], dtype=jnp.int32)

    def kg(idx_ref, table_ref, out_ref):
        def body(scratch, sem):
            dma = pltpu.make_async_copy(
                table_ref.at[pl.ds(idx_ref[0], 1), :], scratch, sem
            )
            dma.start()
            dma.wait()
            out_ref[:] = jnp.broadcast_to(scratch[:], (8, D))

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((1, D), jnp.float32),
            sem=pltpu.SemaphoreType.DMA,
        )

    def callg(idx, table):
        return pl.pallas_call(
            kg,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((8, D), jnp.float32),
        )(idx, table)

    out = try_kernel("g: dynamic-row HBM DMA source", callg, idx1, table)
    if out is not None:
        print("   err:", np.abs(np.asarray(out)[0] - np.asarray(table)[7]).max(), file=sys.stderr)

    # h: aligned dynamic VMEM write in fori loop (start = 8*j)
    E = 1024

    def kh(in_ref, out_ref):
        def loop(j, _):
            s = pl.multiple_of(j * 8, 8)
            out_ref[pl.ds(s, 8), :] = in_ref[pl.ds(s, 8), :] * 2.0
            return 0

        jax.lax.fori_loop(0, E // 8, loop, 0)

    def callh(x):
        return pl.pallas_call(
            kh,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
        )(x)

    try_kernel("h: aligned dynamic VMEM write (8-row tiles)", callh, table[:E])

    # i: full aligned DMA-ring gather: tile of 8 rows via 8 DMAs into an
    # aligned (8, D) scratch slot, K slots in flight, aligned writes out.
    def make_gather(E, K):
        def ki(idx_ref, table_ref, out_ref):
            def body(scratch, sems):
                ntiles = E // 8

                def start_tile(slot, t):
                    base = t * 8
                    for r in range(8):
                        pltpu.make_async_copy(
                            table_ref.at[pl.ds(idx_ref[base + r], 1), :],
                            scratch.at[slot, pl.ds(r, 1), :],
                            sems.at[slot],
                        ).start()

                def wait_tile(slot):
                    # one semaphore accumulates 8 DMA completions
                    pltpu.semaphore_wait(sems.at[slot], 8)

                def warm(t, _):
                    start_tile(t, t)
                    return 0

                jax.lax.fori_loop(0, K, warm, 0)

                def loop(t, _):
                    slot = jax.lax.rem(t, K)
                    wait_tile(slot)
                    s = pl.multiple_of(t * 8, 8)
                    out_ref[pl.ds(s, 8), :] = scratch[slot]

                    @pl.when(t + K < ntiles)
                    def _():
                        start_tile(slot, t + K)

                    return 0

                jax.lax.fori_loop(0, ntiles, loop, 0)

            pl.run_scoped(
                body,
                scratch=pltpu.VMEM((K, 8, D), jnp.float32),
                sems=pltpu.SemaphoreType.DMA((K,)),
            )

        def call(idx, table):
            return pl.pallas_call(
                ki,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(1,),
                    in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                ),
                out_shape=jax.ShapeDtypeStruct((E, D), jnp.float32),
            )(idx, table)

        return call

    E = 8192
    idx = jnp.asarray(rng.randint(0, V, E).astype(np.int32))
    for K in (4, 16):
        call = make_gather(E, K)
        out = try_kernel(f"i: aligned DMA-ring gather E={E} K={K}", call, idx, table)
        if out is not None:
            want = np.asarray(table)[np.asarray(idx)]
            print("   err:", np.abs(np.asarray(out) - want).max(), file=sys.stderr)

    # timing inside a scan (amortize dispatch): compare vs XLA gather
    E = 32768
    idxb = jnp.asarray(rng.randint(0, V, E).astype(np.int32))
    call = make_gather(E, 16)

    @jax.jit
    def loop_pallas(table, idxb):
        def body(c, _):
            out = call(idxb, table)
            return c + out[0, 0] * 1e-9, ()
        c, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(20))
        return c

    @jax.jit
    def loop_xla(table, idxb):
        def body(c, _):
            out = table[idxb]
            return c + out[0, 0] * 1e-9, ()
        c, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(20))
        return c

    for label, loop in (("pallas DMA-ring", loop_pallas), ("xla gather", loop_xla)):
        try:
            out = loop(table, idxb)
            float(out)
            t0 = time.perf_counter()
            float(loop(table, idxb))
            dt = (time.perf_counter() - t0) / 20
            print(f"{label} gather 32768 rows: {dt * 1e6:8.1f} us/call  ({dt / E * 1e9:.1f} ns/row)", file=sys.stderr)
        except Exception as e:
            print(f"{label} FAIL: {str(e).splitlines()[0][:110]}", file=sys.stderr)


if __name__ == "__main__":
    main()
