"""Epoch sweep #2: in-place scatter step (new) x dtype x batch."""
from __future__ import annotations
import time
import numpy as np
import jax
from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.sgns.train import SGNSTrainer
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io.vocab import Vocab
import sys

V, D, N, REPS = 24447, 200, 4_000_000, 3

def make_corpus(rng):
    p = 1.0 / np.arange(1, V + 1); p /= p.sum()
    pairs = rng.choice(V, size=(N, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=V).astype(np.int64)
    return PairCorpus(Vocab([f"G{i}" for i in range(V)], counts), pairs)

def run(label, corpus, cfg):
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    params, loss = trainer.train_epoch(params, key); float(loss)
    rates = []
    for r in range(REPS):
        t0 = time.perf_counter()
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, r))
        lv = float(loss)
        dt = time.perf_counter() - t0
        rates.append(trainer.num_batches * trainer.config.batch_pairs / dt)
    rs = ", ".join(f"{r / 1e6:6.2f}" for r in rates)
    print(f"{label:40s} [{rs}] M pairs/s (best {max(rates)/1e6:.2f}, loss {lv:.4f})", file=sys.stderr)

def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    corpus = make_corpus(rng)
    run("inplace B=16k offset f32", corpus, SGNSConfig(dim=D, batch_pairs=16384))
    run("inplace B=16k nosh f32", corpus,
        SGNSConfig(dim=D, batch_pairs=16384, shuffle_each_iter=False))
    run("inplace B=16k nosh bf16", corpus,
        SGNSConfig(dim=D, batch_pairs=16384, shuffle_each_iter=False,
                   table_dtype="bfloat16", compute_dtype="bfloat16"))
    run("inplace B=65k nosh f32", corpus,
        SGNSConfig(dim=D, batch_pairs=65536, shuffle_each_iter=False))
    run("inplace B=65k nosh bf16", corpus,
        SGNSConfig(dim=D, batch_pairs=65536, shuffle_each_iter=False,
                   table_dtype="bfloat16", compute_dtype="bfloat16"))

if __name__ == "__main__":
    main()
