"""Round 4 geometry II: stratified tail (group, block) frontier AFTER the
dense-head positive split landed (docs/PERF_NOTES.md "Geometry II").

With positive row ops shrunk, the tail term's cost tracks BOTH the slice
count (E/group) and the total tail row traffic (E/group) x block.  This
sweep measures integrated-trainer throughput at the bench headline shape
per (group, block); quality (holdout AUC per the frozen gate protocol) is
measured separately — rates alone do NOT pick a default (two measured
points faster than the shipped default fall below oracle parity and were
rejected; QUALITY_NOTES §5).

Run: python experiments/geometry2_sweep.py \
        [--geometries 128:512,256:512,...] [--quality]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import synth_corpus
from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.sgns.train import SGNSTrainer, train_epochs

DEFAULT_GEOMS = "128:512,256:512,384:768,512:512,512:1024,768:768,768:1536"


def rate(group: int, block: int, v: int, n: int, b: int) -> dict:
    corpus = synth_corpus(v, n)
    cfg = SGNSConfig(
        dim=200, batch_pairs=b, strat_group=group, strat_block=block
    )
    tr = SGNSTrainer(corpus, cfg)
    params = tr.init()
    key = jax.random.PRNGKey(0)
    n_pairs = tr.num_batches * cfg.batch_pairs
    rates, loss = [], None
    for ep in range(4):
        t0 = time.perf_counter()
        params, loss = tr.train_epoch(params, jax.random.fold_in(key, ep))
        loss = float(loss)
        if ep:
            rates.append(n_pairs / (time.perf_counter() - t0))
    return {
        "group": group,
        "block": block,
        "pairs_per_sec": round(float(np.median(rates)), 1),
        "final_loss": round(loss, 4),
    }


def quality(group: int, block: int) -> float:
    from gene2vec_tpu.eval.holdout import holdout_cos_auc, load_holdout

    hcorpus, split = load_holdout("/root/reference/predictionData")
    emb, _ = train_epochs(
        hcorpus,
        SGNSConfig(
            dim=200, batch_pairs=16384, strat_group=group, strat_block=block
        ),
        50,
    )
    return round(float(holdout_cos_auc(hcorpus.vocab, emb, split)), 4)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--geometries", default=DEFAULT_GEOMS)
    ap.add_argument("--vocab", type=int, default=24447)
    ap.add_argument("--pairs", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument(
        "--quality", action="store_true",
        help="also run the (slow) holdout-AUC protocol per geometry",
    )
    ap.add_argument(
        "--out", default="experiments/results/geometry2_r4.json"
    )
    args = ap.parse_args()

    rows = []
    for spec in args.geometries.split(","):
        g, b = (int(x) for x in spec.split(":"))
        row = rate(g, b, args.vocab, args.pairs, args.batch)
        if args.quality:
            row["holdout_auc"] = quality(g, b)
        print(json.dumps(row), flush=True, file=sys.stdout)
        rows.append(row)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(rows, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    main()
