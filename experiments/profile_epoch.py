"""Round-2 experiment: why is the whole-epoch scan ~15x slower than the
isolated step? Times make_train_epoch variants at bench shapes."""

from __future__ import annotations

import dataclasses
import time

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NegativeSampler
from gene2vec_tpu.sgns.model import SGNSParams
from gene2vec_tpu.sgns.train import make_train_epoch
import sys

V, D, B = 24447, 200, 16384
N = 4_000_000


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    pairs_np = rng.choice(V, size=(N, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs_np.reshape(-1), minlength=V).astype(np.int64)
    noise = NegativeSampler(counts).table
    pairs = jnp.asarray(pairs_np)
    emb = jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.01)
    ctx = jnp.zeros((V, D), jnp.float32)

    num_batches = N // B

    for label, cfg in [
        ("offset shuffle (r1 default)", SGNSConfig(dim=D, batch_pairs=B)),
        ("no shuffle", SGNSConfig(dim=D, batch_pairs=B, shuffle_each_iter=False)),
        ("full shuffle", SGNSConfig(dim=D, batch_pairs=B, shuffle_mode="full")),
        ("offset B=262144", SGNSConfig(dim=D, batch_pairs=262144)),
        ("no shuffle B=262144", SGNSConfig(dim=D, batch_pairs=262144,
                                           shuffle_each_iter=False)),
    ]:
        nb = N // cfg.batch_pairs
        fn = make_train_epoch(N, nb, cfg)
        params = SGNSParams(emb=emb + 0, ctx=ctx + 0)
        key = jax.random.PRNGKey(0)
        params, loss = fn(params, pairs, noise, key)  # compile
        float(loss)
        t0 = time.perf_counter()
        params, loss = fn(params, pairs, noise, jax.random.fold_in(key, 1))
        float(loss)
        dt = time.perf_counter() - t0
        print(f"{label:28s}: {dt:7.3f}s/epoch -> {nb * cfg.batch_pairs / dt / 1e6:8.2f}M pairs/s", file=sys.stderr)


if __name__ == "__main__":
    main()
