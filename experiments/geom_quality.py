"""Round-4 stratified-geometry quality check on the real corpus.

experiments/step_ablate.py found throughput scales strongly with the
stratified tail GROUP SIZE (fewer vmapped dynamic slices per step):
group 32 -> 2.7 M pairs/s, group 128 -> 4.7 M.  This script answers the
only question that matters before changing the default: does the holdout
cosine AUC (gate metric, oracle 0.878, round-3 default 0.8965) survive
larger groups, and does growing the block size alongside (keeping
per-example repulsion rank) compensate the variance of shared draws?

Protocol: the canonical eval.holdout split (same seed/fraction as
bench.py's gate and REAL_AUC.json), embedding trained through
``train_epochs`` (per-epoch lr sweep included — hand loops read ~0.13
low, docs/PERF_NOTES.md round-3 caveat), 50 epochs, B=4096.

Usage: python experiments/geom_quality.py [group:head:block[:batch] ...]
(batch defaults to the run_real_auc protocol's 4096; pass 16384 to
reproduce the bench gate's configuration)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.eval.holdout import ORACLE_COS_AUC, holdout_cos_auc, load_holdout
from gene2vec_tpu.sgns.train import train_epochs

DATA_DIR = "/root/reference/predictionData"
EPOCHS = 50


def main():
    specs = sys.argv[1:] or [
        "32:256:128",    # round-3 default (control; expect ~0.8965)
        "64:256:256",
        "128:256:256",
        "128:512:128",
        "128:256:512",
        "256:256:512",
    ]
    corpus, split = load_holdout(DATA_DIR)
    print(
        f"corpus: {corpus.num_pairs} pairs, vocab {corpus.vocab_size}; "
        f"holdout {len(split.hold_pairs)} pairs; oracle {ORACLE_COS_AUC}",
        flush=True,
    file=sys.stderr)
    results = {}
    for s in specs:
        parts = [int(x) for x in s.split(":")]
        group, head, block = parts[:3]
        batch = parts[3] if len(parts) > 3 else 4096
        cfg = SGNSConfig(
            dim=200, batch_pairs=batch, negative_mode="stratified",
            strat_group=group, strat_head=head, strat_block=block,
        )
        t0 = time.perf_counter()
        emb, losses = train_epochs(corpus, cfg, EPOCHS)
        auc = holdout_cos_auc(corpus.vocab, emb, split)
        dt = time.perf_counter() - t0
        results[s] = {
            "auc": round(auc, 4),
            "loss_first": round(losses[0], 4),
            "loss_last": round(losses[-1], 4),
            "seconds": round(dt, 1),
        }
        print(f"g{group} h{head} s{block}: AUC {auc:.4f} "
              f"loss {losses[0]:.3f}->{losses[-1]:.3f} ({dt:.0f}s)", flush=True, file=sys.stderr)
    out = os.path.join(os.path.dirname(__file__), "results",
                       "geom_quality_r4.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results), file=sys.stdout)


if __name__ == "__main__":
    main()
