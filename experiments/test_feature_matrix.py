"""Which Pallas TPU feature crashes the axon compile helper?"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
N, D = 256, 256


def try_kernel(label, fn, *args):
    try:
        out = jax.jit(fn)(*args)
        float(_sum(out))
        print(f"{label:56s} OK", file=sys.stderr)
    except Exception as e:
        lines = [l for l in str(e).splitlines() if "Mosaic" in l or "NotImplemented" in l or "INTERNAL" in l][:1]
        print(f"{label:56s} FAIL: {lines[0][:110] if lines else str(e).splitlines()[0][:110]}", file=sys.stderr)


def main():
    x = jnp.asarray(np.random.RandomState(0).randn(N, D).astype(np.float32))
    idx = jnp.arange(N, dtype=jnp.int32)

    # a: PrefetchScalarGridSpec, trivial
    def ka(idx_ref, in_ref, out_ref):
        out_ref[:] = in_ref[:] * 2.0

    def calla(idx, x):
        return pl.pallas_call(
            ka,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(idx, x)

    try_kernel("a: PrefetchScalarGridSpec trivial", calla, idx, x)

    # b: pl.ANY input + DMA to VMEM scratch via run_scoped
    def kb(in_ref, out_ref):
        def body(scratch, sem):
            dma = pltpu.make_async_copy(in_ref, scratch, sem)
            dma.start()
            dma.wait()
            out_ref[:] = scratch[:]
        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((N, D), jnp.float32),
            sem=pltpu.SemaphoreType.DMA,
        )

    def callb(x):
        return pl.pallas_call(
            kb,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(x)

    try_kernel("b: ANY + run_scoped DMA", callb, x)

    # c: run_scoped scratch, no DMA
    def kc(in_ref, out_ref):
        def body(scratch):
            scratch[:] = in_ref[:] * 2.0
            out_ref[:] = scratch[:]
        pl.run_scoped(body, scratch=pltpu.VMEM((N, D), jnp.float32))

    def callc(x):
        return pl.pallas_call(
            kc,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(x)

    try_kernel("c: run_scoped scratch only", callc, x)

    # d: scratch_shapes arg with semaphore, explicit DMA
    def kd(in_ref, out_ref, scratch, sem):
        dma = pltpu.make_async_copy(in_ref, scratch, sem)
        dma.start()
        dma.wait()
        out_ref[:] = scratch[:]

    def calld(x):
        return pl.pallas_call(
            kd,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((N, D), jnp.float32),
                pltpu.SemaphoreType.DMA,
            ],
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(x)

    try_kernel("d: scratch_shapes + DMA", calld, x)

    # e: pl.ANY input, direct copy (no DMA — should fail gracefully or work)
    def ke(in_ref, out_ref):
        out_ref[:] = in_ref[:]

    def calle(x):
        return pl.pallas_call(
            ke,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(x)

    try_kernel("e: ANY input direct read", calle, x)

    # f: dynamic slice of VMEM input by SMEM scalar
    sidx = jnp.asarray([[3]], dtype=jnp.int32)

    def kf(s_ref, in_ref, out_ref):
        i = s_ref[0, 0]
        out_ref[pl.ds(0, 8), :] = in_ref[pl.ds(i, 8), :]
        out_ref[pl.ds(8, N - 8), :] = in_ref[pl.ds(0, N - 8), :]

    def callf(s, x):
        return pl.pallas_call(
            kf,
            in_specs=[
                pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, D), jnp.float32),
        )(s, x)

    try_kernel("f: SMEM scalar dynamic slice", callf, sidx, x)


if __name__ == "__main__":
    main()
