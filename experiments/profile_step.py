"""Round-2 experiment: where does the SGNS step spend its time on v5e?

Times isolated pieces of the shared-negative step at bench shapes
(V=24447, D=200, B=16384 -> E=32768, P=64) to decide what the Pallas
kernel must fuse. Run on the real TPU chip.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
import sys

V, D, B, P = 24447, 200, 16384, 64
E = 2 * B


def timeit(name, fn, *args, iters=30):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:42s} {dt * 1e3:8.3f} ms", file=sys.stderr)
    return dt


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    emb = jnp.asarray(rng.randn(V, D).astype(np.float32))
    ctx = jnp.asarray(rng.randn(V, D).astype(np.float32))
    centers = jnp.asarray(rng.randint(0, V, E).astype(np.int32))
    contexts = jnp.asarray(rng.randint(0, V, E).astype(np.int32))
    negs = jnp.asarray(rng.randint(0, V, P).astype(np.int32))
    grads = jnp.asarray(rng.randn(E, D).astype(np.float32))
    ones = jnp.ones(E, jnp.float32)

    # 1. gather E rows
    g1 = jax.jit(lambda t, i: t[i])
    timeit("gather (E,D) rows", g1, emb, centers)

    # 2. matmul (E,D)x(D,P)
    vrows = emb[centers]
    urows = ctx[negs]
    mm = jax.jit(lambda a, b: a @ b.T)
    timeit("matmul (E,D)x(D,P)", mm, vrows, urows)

    # 3. dense (V,D+1) scatter accumulator
    def scatter_acc(idx, g, w):
        payload = jnp.concatenate([g, w[:, None]], axis=1)
        return jnp.zeros((V, D + 1), jnp.float32).at[idx].add(payload)

    timeit("scatter-add E rows -> (V,D+1) zeros", jax.jit(scatter_acc), centers, grads, ones)

    # 3b. scatter without the concat payload (D only) + separate count
    def scatter_sep(idx, g, w):
        acc = jnp.zeros((V, D), jnp.float32).at[idx].add(g)
        cnt = jnp.zeros((V,), jnp.float32).at[idx].add(w)
        return acc, cnt

    timeit("scatter-add (V,D) + (V,) separate", jax.jit(scatter_sep), centers, grads, ones)

    # 3c. in-place scatter onto the table (donated) with pre-scaled grads
    def scatter_inplace(t, idx, g):
        return t.at[idx].add(g)

    timeit(
        "in-place scatter-add onto table (donated)",
        jax.jit(scatter_inplace, donate_argnums=(0,)),
        emb + 0,
        centers,
        grads,
    )

    # 4. dense table update t - lr*u
    upd = jnp.asarray(rng.randn(V, D).astype(np.float32))
    dense = jax.jit(lambda t, u: t - 0.01 * u, donate_argnums=(0,))
    timeit("dense (V,D) axpy (donated)", dense, emb + 0, upd)

    # 5. sort-based segment combine: sort idx, segment-sum, then scatter
    def sorted_scatter(t, idx, g):
        order = jnp.argsort(idx)
        return t.at[idx[order]].add(g[order])

    timeit(
        "argsort+scatter onto table (donated)",
        jax.jit(sorted_scatter, donate_argnums=(0,)),
        emb + 0,
        centers,
        grads,
    )

    # 6. the full current step, jitted alone (not in scan)
    from gene2vec_tpu.data.negative_sampling import NegativeSampler
    from gene2vec_tpu.sgns.model import SGNSParams
    from gene2vec_tpu.sgns.step import sgns_step

    counts = np.maximum(rng.zipf(1.5, V), 1)
    noise = NegativeSampler(counts).table
    params = SGNSParams(emb=emb, ctx=ctx)
    pairs = jnp.asarray(rng.randint(0, V, (B, 2)).astype(np.int32))
    key = jax.random.PRNGKey(0)

    step = jax.jit(
        lambda p, b, n, k: sgns_step(p, b, n, k, jnp.float32(0.01)),
        donate_argnums=(0,),
    )
    p2, _ = step(params, pairs, noise, key)
    jax.block_until_ready(p2)
    t0 = time.perf_counter()
    iters = 30
    for i in range(iters):
        p2, loss = step(p2, pairs, noise, jax.random.fold_in(key, i))
    jax.block_until_ready(p2)
    dt = (time.perf_counter() - t0) / iters
    print(f"{'FULL sgns_step (shared, donated)':42s} {dt * 1e3:8.3f} ms "
          f"-> {B / dt / 1e6:.2f}M pairs/s", file=sys.stderr)

    # 7. batch-size sweep of the full step
    for b in (4096, 16384, 65536, 262144):
        pairs_b = jnp.asarray(rng.randint(0, V, (b, 2)).astype(np.int32))
        p = SGNSParams(emb=emb + 0, ctx=ctx + 0)
        stepb = jax.jit(
            lambda p, bb, n, k: sgns_step(p, bb, n, k, jnp.float32(0.01)),
            donate_argnums=(0,),
        )
        p, _ = stepb(p, pairs_b, noise, key)
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        n = max(4, 2_000_000 // b)
        for i in range(n):
            p, _ = stepb(p, pairs_b, noise, jax.random.fold_in(key, i))
        jax.block_until_ready(p)
        dt = (time.perf_counter() - t0) / n
        print(f"  full step B={b:7d}: {dt * 1e3:8.3f} ms -> {b / dt / 1e6:7.2f}M pairs/s", file=sys.stderr)


if __name__ == "__main__":
    main()
