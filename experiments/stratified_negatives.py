"""Prototype + measurement for negative_mode="stratified" (round-3 perf).

Motivation (docs/PERF_NOTES.md round-3 section): at the quality-parity
pool size P = 0.8*E*K the shared-negative step spends ~2/3 of its row ops
on noise rows (gather + scatter of P random rows), capping the step at
~2M pairs/s.  Noise rows have no example coupling, so they can be
restructured into contiguous traffic:

* HEAD: the top-H vocab rows (frequency-sorted vocab) contribute their
  EXACT expectation term K*q_j*softplus(v.u_j) — a dense (E,D)x(D,H)
  matmul over a contiguous table slice; zero sampling variance for the
  q-mass the head covers, and the ctx update is a dense slice add.
* TAIL: the remaining vocab is partitioned into NB fixed blocks of S
  contiguous rows; each group of ~32 examples draws ONE block uniformly
  (importance weight T/S per row, T = tail size), an unbiased estimator
  of the tail mass served by dynamic-slice gathers and block-indexed
  scatter-adds — G block ops instead of G*S row ops.

Cap symmetry (QUALITY_NOTES invariant 1) is preserved by adding the noise
gradients AND their example-unit weights densely into the same (V, D+1)
accumulator the positive scatter uses, so each row still gets one divisor
over the sum of both.

Usage::

    python experiments/stratified_negatives.py --suite rate     # throughput
    python experiments/stratified_negatives.py --suite quality  # holdout AUC
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import noise_distribution
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.sgns.model import SGNSParams
from gene2vec_tpu.sgns.step import (
    _apply_row_updates,
    _examples_from_pairs,
    _row_divisor,
)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# the stratified step (prototype; integrated form goes into sgns/step.py)
# --------------------------------------------------------------------------


def stratified_step(
    params: SGNSParams,
    pairs,                 # (B, 2)
    q,                     # (V,) noise distribution
    key,
    lr,
    negatives: int = 5,
    head: int = 64,        # exact head rows
    block: int = 128,      # tail block size (rows per group)
    group: int = 32,       # examples per group
    combiner: str = "capped",
    compute_dtype=jnp.float32,
):
    emb_t, ctx_t = params.emb, params.ctx
    v_size, d = ctx_t.shape
    centers, contexts = _examples_from_pairs(pairs)
    e = centers.shape[0]
    g = e // group
    t = v_size - head
    nb = t // block                      # tail blocks (floor; tail rows
    #                                      beyond nb*block are never drawn —
    #                                      bias O(block/T), folded into head
    #                                      coverage in the integrated version)
    k = jnp.asarray(float(negatives), compute_dtype)

    v = emb_t[centers].astype(compute_dtype)          # (E, D)
    u_pos = ctx_t[contexts].astype(compute_dtype)     # (E, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0

    # ---- head: exact expectation over rows [0, H) ------------------------
    ctx_head = ctx_t[:head].astype(compute_dtype)     # (H, D) contiguous
    q_head = q[:head].astype(compute_dtype)           # (H,)
    head_logit = v @ ctx_head.T                       # (E, H) MXU
    head_mask = (
        jnp.arange(head)[None, :] != contexts[:, None]
    ).astype(compute_dtype)                           # gensim skip parity
    g_head = k * q_head[None, :] * jax.nn.sigmoid(head_logit) * head_mask
    loss_head = k * jnp.sum(
        q_head[None, :] * head_mask * jax.nn.softplus(head_logit), axis=-1
    )

    # ---- tail: one random block per group --------------------------------
    blocks = jax.random.randint(key, (g,), 0, nb)     # (G,)
    starts = head + blocks * block

    def slice_block(tbl, s):
        return jax.lax.dynamic_slice(tbl, (s, 0), (block, tbl.shape[1]))

    ctx_blk = jax.vmap(slice_block, in_axes=(None, 0))(
        ctx_t, starts
    ).astype(compute_dtype)                            # (G, S, D)
    q_blk = jax.vmap(
        lambda s: jax.lax.dynamic_slice(q, (s,), (block,))
    )(starts).astype(compute_dtype)                    # (G, S)

    vg = v.reshape(g, group, d)
    cg = contexts.reshape(g, group)
    tail_logit = jnp.einsum("ged,gsd->ges", vg, ctx_blk)  # (G, Eg, S) MXU
    row_ids = starts[:, None] + jnp.arange(block)[None, :]  # (G, S)
    tail_mask = (
        row_ids[:, None, :] != cg[:, :, None]
    ).astype(compute_dtype)
    w_tail = k * (t / block) * q_blk[:, None, :]          # importance weight
    g_tail = w_tail * jax.nn.sigmoid(tail_logit) * tail_mask
    loss_tail = jnp.sum(
        w_tail * tail_mask * jax.nn.softplus(tail_logit), axis=-1
    ).reshape(e)

    loss = jnp.mean(
        jax.nn.softplus(-pos_logit) + loss_head + loss_tail
    )

    # ---- center gradients (per-example; same scatter path as today) -----
    d_center = (
        g_pos[:, None] * u_pos
        + g_head @ ctx_head                                       # MXU
        + jnp.einsum("ges,gsd->ged", g_tail, ctx_blk).reshape(e, d)
    )
    emb = _apply_row_updates(
        emb_t, centers, d_center,
        jnp.ones_like(centers, compute_dtype), lr, combiner, compute_dtype,
    )

    # ---- ctx updates: positives scatter + dense noise adds ---------------
    acc_dtype = jnp.float32
    d_pos = g_pos[:, None] * v
    payload = jnp.concatenate(
        [d_pos.astype(acc_dtype), jnp.ones((e, 1), acc_dtype)], axis=1
    )
    acc = jnp.zeros((v_size, d + 1), acc_dtype).at[contexts].add(payload)

    # head noise: dense slice add (grads + example-unit weights)
    d_head_rows = g_head.T @ v                                    # (H, D) MXU
    u_head = jnp.sum(g_head, axis=0)                              # units ~ sigma-weighted
    acc = acc.at[:head, :d].add(d_head_rows.astype(acc_dtype))
    acc = acc.at[:head, d].add(u_head.astype(acc_dtype))

    # tail noise: block-indexed scatter-add of (S, D+1) payloads
    d_tail_rows = jnp.einsum("ges,ged->gsd", g_tail, vg)          # (G, S, D)
    u_tail = jnp.sum(g_tail, axis=1)                              # (G, S)
    tail_payload = jnp.concatenate(
        [d_tail_rows.astype(acc_dtype), u_tail[:, :, None].astype(acc_dtype)],
        axis=2,
    )
    tail_acc = jnp.zeros((nb, block, d + 1), acc_dtype).at[blocks].add(
        tail_payload
    )
    acc = acc.at[head : head + nb * block].add(
        tail_acc.reshape(nb * block, d + 1)
    )

    update = acc[:, :d] / _row_divisor(acc[:, d], combiner)[:, None]
    ctx = (
        ctx_t.astype(acc_dtype) - jnp.asarray(lr, acc_dtype) * update
    ).astype(ctx_t.dtype)
    return SGNSParams(emb=emb, ctx=ctx), loss


# --------------------------------------------------------------------------
# harness
# --------------------------------------------------------------------------


def synth_corpus(v=24447, n=4_000_000, seed=0):
    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, v + 1)
    p /= p.sum()
    pairs = rng.choice(v, size=(n, 2), p=p).astype(np.int32)
    from gene2vec_tpu.io.vocab import Vocab

    counts = np.bincount(pairs.reshape(-1), minlength=v).astype(np.int64)
    order = np.argsort(-counts, kind="stable")
    remap = np.empty(v, np.int64)
    remap[order] = np.arange(v)
    pairs = remap[pairs].astype(np.int32)
    return PairCorpus(
        Vocab([f"G{i}" for i in range(v)], counts[order]), pairs
    )


def make_epoch_fn(
    corpus, dim, batch_pairs, head, block, group,
    lr0=0.025, min_lr=1e-4,
):
    """Jitted epoch matching SGNSTrainer's discipline: per-epoch pair
    shuffle and the gensim-parity lr sweep lr0 -> min_lr across the epoch
    (sgns/train.py:69-70) — the prototype must not diverge from the
    baseline on anything but the negative estimator."""
    q = jnp.asarray(noise_distribution(corpus.vocab.counts))
    pairs = jnp.asarray(corpus.pairs)
    num_batches = corpus.num_pairs // batch_pairs

    @functools.partial(jax.jit, donate_argnums=0)
    def epoch(params, key):
        shuffle_key, step_key = jax.random.split(key)
        shuffled = pairs[
            jax.random.permutation(shuffle_key, pairs.shape[0])
        ]

        def body(carry, i):
            params = carry
            batch = jax.lax.dynamic_slice(
                shuffled, (i * batch_pairs, 0), (batch_pairs, 2)
            )
            frac = i.astype(jnp.float32) / max(num_batches, 1)
            lr = lr0 * (1.0 - frac) + min_lr * frac
            params, loss = stratified_step(
                params, batch, q, jax.random.fold_in(step_key, i), lr,
                head=head, block=block, group=group,
            )
            return params, loss

        params, losses = jax.lax.scan(
            body, params, jnp.arange(num_batches)
        )
        return params, jnp.mean(losses)

    return epoch, num_batches


def init_params(vocab_size, dim, seed=0):
    rng = np.random.RandomState(seed)
    emb = ((rng.rand(vocab_size, dim) - 0.5) / dim).astype(np.float32)
    ctx = np.zeros((vocab_size, dim), np.float32)
    return SGNSParams(emb=jnp.asarray(emb), ctx=jnp.asarray(ctx))


def suite_rate(args):
    corpus = synth_corpus()
    rows = []
    for name, head, block in (
        ("H=64 S=128", 64, 128),
        ("H=256 S=128", 256, 128),
        ("H=512 S=128", 512, 128),
    ):
        epoch, nbat = make_epoch_fn(
            corpus, 200, args.batch_pairs, head, block, 32
        )
        params = init_params(corpus.vocab_size, 200)
        key = jax.random.PRNGKey(0)
        for w in range(2):  # compile + relayout warmup
            params, loss = epoch(params, jax.random.fold_in(key, w))
            float(loss)
        rates = []
        for r in range(3):
            t0 = time.perf_counter()
            params, loss = epoch(params, jax.random.fold_in(key, 10 + r))
            float(loss)
            rates.append(nbat * args.batch_pairs / (time.perf_counter() - t0))
        rows.append(
            {"config": name,
             "pairs_per_sec_M": round(float(np.median(rates)) / 1e6, 2),
             "loss": round(float(loss), 4)}
        )
        log(f"{name}: {np.median(rates)/1e6:.2f}M pairs/s loss {float(loss):.3f}")
    # reference: current shared default
    from gene2vec_tpu.sgns.train import SGNSTrainer

    trainer = SGNSTrainer(corpus, SGNSConfig(dim=200, batch_pairs=args.batch_pairs))
    p = trainer.init()
    k = jax.random.PRNGKey(1)
    for w in range(2):
        p, loss = trainer.train_epoch(p, jax.random.fold_in(k, w))
        float(loss)
    rates = []
    for r in range(3):
        t0 = time.perf_counter()
        p, loss = trainer.train_epoch(p, jax.random.fold_in(k, 10 + r))
        float(loss)
        rates.append(
            trainer.num_batches * args.batch_pairs / (time.perf_counter() - t0)
        )
    rows.append(
        {"config": "shared default (P=0.8EK)",
         "pairs_per_sec_M": round(float(np.median(rates)) / 1e6, 2),
         "loss": round(float(loss), 4)}
    )
    log(f"shared default: {np.median(rates)/1e6:.2f}M pairs/s")
    return rows


def interleave_tail(corpus: PairCorpus, head: int, block: int):
    """Remap token ids so tail rows are dealt round-robin into blocks:
    old tail index j (frequency order) -> head + (j % nb) * block + j // nb.
    Any contiguous tail block then holds a stratified systematic sample of
    the whole tail frequency range instead of one narrow band.  Ids are
    arbitrary labels, so this is a free one-time relabeling; rows past
    head + nb*block stay put (and are never drawn — their q-mass is the
    same truncation the contiguous variant has)."""
    from gene2vec_tpu.io.vocab import Vocab

    v = corpus.vocab_size
    t = v - head
    nb = t // block
    remap = np.arange(v)
    j = np.arange(nb * block)
    remap[head : head + nb * block] = head + (j % nb) * block + j // nb
    inv = np.empty(v, np.int64)
    inv[remap] = np.arange(v)
    toks = [corpus.vocab.id_to_token[i] for i in inv]
    counts = corpus.vocab.counts[inv]
    vocab = Vocab.__new__(Vocab)
    vocab.id_to_token = toks
    vocab.token_to_id = {t_: i for i, t_ in enumerate(toks)}
    vocab.counts = counts
    return PairCorpus(vocab, remap[corpus.pairs].astype(np.int32))


def suite_quality(args):
    from gene2vec_tpu.eval.holdout import holdout_cos_auc, load_holdout

    base, split = load_holdout(args.data_dir)
    rows = []
    for name, head, block, il in (
        ("H=256 S=128 banded", 256, 128, False),
        ("H=512 S=128 banded", 512, 128, False),
        ("H=256 S=256 banded", 256, 256, False),
    ):
        corpus = interleave_tail(base, head, block) if il else base
        epoch, _ = make_epoch_fn(corpus, 200, args.batch_pairs, head, block, 32)
        params = init_params(corpus.vocab_size, 200)
        losses = []
        for it in range(1, args.epochs + 1):
            params, loss = epoch(
                params, jax.random.fold_in(jax.random.PRNGKey(0), it)
            )
            losses.append(float(loss))
        auc = holdout_cos_auc(corpus.vocab, np.asarray(params.emb), split)
        rows.append(
            {"config": name, "loss_first": round(losses[0], 4),
             "loss_last": round(losses[-1], 4),
             "holdout_cos_auc": round(auc, 4)}
        )
        log(f"{name}: loss {losses[0]:.3f}->{losses[-1]:.3f} AUC {auc:.4f}")
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", choices=("rate", "quality"), default="rate")
    ap.add_argument("--batch-pairs", type=int, default=16384)
    ap.add_argument("--epochs", type=int, default=50)
    ap.add_argument("--data-dir", default="/root/reference/predictionData")
    args = ap.parse_args()
    rows = {"rate": suite_rate, "quality": suite_quality}[args.suite](args)
    print(json.dumps({"suite": args.suite, "rows": rows}, indent=1))


if __name__ == "__main__":
    main()
