"""Measurements behind negative_mode="stratified" (round-3 perf design;
the estimator itself lives in gene2vec_tpu/sgns/step.py _step_stratified
and is what these suites drive — no separate prototype implementation).

Motivation (docs/PERF_NOTES.md round-3 section): at the quality-parity
pool size P = 0.8*E*K the shared-negative step spends ~2/3 of its row ops
on noise rows; noise rows have no example coupling, so the stratified
estimator restructures them into an exact frequency-head term plus
importance-weighted contiguous tail blocks.

Suites::

    python experiments/stratified_negatives.py --suite rate
        # head/block sweep vs the shared baseline, 4M-pair Zipf corpus
    python experiments/stratified_negatives.py --suite quality
        # holdout AUC per (head, block, tail layout), real corpus

Incident record (do not repeat): the first prototype of this estimator
measured holdout AUC 0.75-0.76 — entirely an artifact of a hand-rolled
training loop that skipped the trainer's per-epoch lr re-sweep
(0.025 -> 1e-4, sgns/train.py:72-73) and per-epoch shuffle.  With the
discipline matched the same estimator measured 0.886-0.895.  Estimator
experiments must train through SGNSTrainer/train_epochs (these suites
do); docs/QUALITY_NOTES.md §7 records the trap.

The quality suite also reproduces the tail-layout experiment: dealing
tail rows round-robin into blocks (interleave_tail) makes every
contiguous block a stratified systematic sample of the whole frequency
range.  Against the pre-unit-fix step it measured +0.004-0.007 AUC;
against the integrated sigma-free-units step it is neutral
(0.8957 vs 0.8965 banded) — recorded here so the option stays
reproducible, not integrated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from gene2vec_tpu.config import SGNSConfig  # noqa: E402
from gene2vec_tpu.data.pipeline import PairCorpus  # noqa: E402
from gene2vec_tpu.sgns.train import SGNSTrainer, train_epochs  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def synth_corpus(v=24447, n=4_000_000, seed=0):
    from gene2vec_tpu.io.vocab import Vocab

    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, v + 1)
    p /= p.sum()
    pairs = rng.choice(v, size=(n, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs.reshape(-1), minlength=v).astype(np.int64)
    order = np.argsort(-counts, kind="stable")
    remap = np.empty(v, np.int64)
    remap[order] = np.arange(v)
    return PairCorpus(
        Vocab([f"G{i}" for i in range(v)], counts[order]),
        remap[pairs].astype(np.int32),
    )


def interleave_tail(corpus: PairCorpus, head: int, block: int) -> PairCorpus:
    """Relabel token ids so tail rows are dealt round-robin into blocks:
    old tail index j (frequency order) -> head + (j % nb) * block + j // nb.
    Any contiguous tail block then holds a stratified systematic sample of
    the whole tail frequency range instead of one narrow band.  Ids are
    arbitrary labels, so this is a free one-time relabeling."""
    from gene2vec_tpu.io.vocab import Vocab

    v = corpus.vocab_size
    t = v - head
    nb = t // block
    remap = np.arange(v)
    j = np.arange(nb * block)
    remap[head : head + nb * block] = head + (j % nb) * block + j // nb
    inv = np.empty(v, np.int64)
    inv[remap] = np.arange(v)
    toks = [corpus.vocab.id_to_token[i] for i in inv]
    vocab = Vocab(toks, corpus.vocab.counts[inv])
    return PairCorpus(vocab, remap[corpus.pairs].astype(np.int32))


def measure_rate(corpus, cfg, reps=3):
    tr = SGNSTrainer(corpus, cfg)
    p = tr.init()
    k = jax.random.PRNGKey(0)
    for w in range(2):
        p, loss = tr.train_epoch(p, jax.random.fold_in(k, w))
        float(loss)
    rates = []
    for r in range(reps):
        t0 = time.perf_counter()
        p, loss = tr.train_epoch(p, jax.random.fold_in(k, 10 + r))
        float(loss)
        rates.append(
            tr.num_batches * tr.config.batch_pairs
            / (time.perf_counter() - t0)
        )
    return float(np.median(rates)), float(loss)


def suite_rate(args):
    corpus = synth_corpus()
    rows = []
    configs = [
        ("stratified H=64 S=128", dict(strat_head=64)),
        ("stratified H=256 S=128 (default)", dict()),
        ("stratified H=512 S=128", dict(strat_head=512)),
        ("shared auto (P=0.8EK)", dict(negative_mode="shared")),
    ]
    for name, kw in configs:
        cfg = SGNSConfig(dim=200, batch_pairs=args.batch_pairs, **kw)
        rate, loss = measure_rate(corpus, cfg)
        rows.append({"config": name,
                     "pairs_per_sec_M": round(rate / 1e6, 2),
                     "loss": round(loss, 4)})
        log(f"{name:36s} {rate/1e6:5.2f}M pairs/s loss {loss:.3f}")
    return rows


def suite_quality(args):
    from gene2vec_tpu.eval.holdout import holdout_cos_auc, load_holdout

    base, split = load_holdout(args.data_dir)
    rows = []
    configs = [
        ("H=64 S=128 banded", dict(strat_head=64), False),
        ("H=256 S=128 banded (default)", dict(), False),
        ("H=512 S=128 banded", dict(strat_head=512), False),
        ("H=256 S=128 interleaved", dict(), True),
    ]
    for name, kw, il in configs:
        cfg = SGNSConfig(dim=200, batch_pairs=args.batch_pairs, **kw)
        corpus = (
            interleave_tail(base, cfg.strat_head, cfg.strat_block)
            if il else base
        )
        emb, losses = train_epochs(corpus, cfg, args.epochs)
        auc = holdout_cos_auc(corpus.vocab, emb, split)
        rows.append({"config": name,
                     "loss_first": round(losses[0], 4),
                     "loss_last": round(losses[-1], 4),
                     "holdout_cos_auc": round(auc, 4)})
        log(f"{name:32s} loss {losses[0]:.3f}->{losses[-1]:.3f} AUC {auc:.4f}")
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", choices=("rate", "quality"), default="rate")
    ap.add_argument("--batch-pairs", type=int, default=16384)
    ap.add_argument("--epochs", type=int, default=50)
    ap.add_argument("--data-dir", default="/root/reference/predictionData")
    args = ap.parse_args()
    rows = {"rate": suite_rate, "quality": suite_quality}[args.suite](args)
    print(json.dumps({"suite": args.suite, "rows": rows}, indent=1), file=sys.stdout)


if __name__ == "__main__":
    main()
