"""VERDICT r3 item 5: measure the viz (L5) and corpus (L1) layers.

(a) t-SNE at the reference's real scale — N ~= 24,447 genes x 200d
    (``/root/reference/src/tsne_multi_core.py:42-52``: MulticoreTSNE,
    PCA-50, perplexity 30, six processes x 32 threads covering iteration
    counts {100, 5k, 10k, 20k, 50k, 100k}).  Here: the TPU exact t-SNE
    (``viz/tsne.py``) vs sklearn's Barnes-Hut t-SNE on the host CPU (the
    closest runnable stand-in for MulticoreTSNE; this env exposes one
    core, so a 32-thread linear extrapolation is also recorded, tagged
    extrapolated — same treatment as the hogwild SGNS denominator).

(b) corpus-builder correlation at GEO-study scale — 50 studies x
    (100 samples x 5,000 genes): the standardized-matmul
    ``abs_correlation`` (numpy BLAS and TPU jax backends) vs the
    reference's per-study ``data.corr()``
    (``/root/reference/src/generate_gene_pairs.py:49``).

Writes BENCH_VIZ_CORPUS_r04.json at the repo root (NOT BENCH_EXTRA.json — bench.py owns that name for its per-run secondary metrics).  Run from the repo root:

    python experiments/bench_viz_corpus.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_SWEEP = [100, 5000, 10000, 20000, 50000, 100000]


def bench_tsne(n: int, dim: int, seg: int, cpu_iters: int) -> dict:
    from gene2vec_tpu.config import TSNEConfig
    from gene2vec_tpu.viz.tsne import TSNE, pca_reduce

    rng = np.random.RandomState(0)
    # clustered data so the BH tree in the CPU baseline sees realistic
    # (non-uniform) geometry rather than an isotropic blob
    centers = rng.randn(200, dim) * 4.0
    x = (centers[rng.randint(0, 200, n)] + rng.randn(n, dim)).astype(
        np.float32
    )

    out: dict = {"n": n, "dim": dim, "pca_dims": 50}
    x50 = pca_reduce(x, 50)

    # --- TPU exact t-SNE -------------------------------------------------
    # Time COMPLETE fit() runs (snapshots materialize y on the host, so
    # the measurement is properly synchronous on the tunneled platform —
    # block_until_ready alone was observed returning early there).  Two
    # iteration counts separate the per-iteration rate from the fixed
    # cost (P calibration + compile amortization).
    model = TSNE(config=TSNEConfig(perplexity=30.0, pca_dims=0))
    lo, hi = seg, 3 * seg
    times = {}
    for iters in (lo, hi):
        model.fit(x50, snapshot_iters=[iters], log=lambda m: None)  # compile
        t0 = time.perf_counter()
        model.fit(x50, snapshot_iters=[iters], log=lambda m: None)
        times[iters] = time.perf_counter() - t0
        print(f"[tsne] full {iters}-iter run: {times[iters]:.2f}s",
              flush=True, file=sys.stderr)
    per_iter = (times[hi] - times[lo]) / (hi - lo)
    fixed = max(times[lo] - per_iter * lo, 0.0)
    out["tpu_run_s"] = {k: round(v, 2) for k, v in times.items()}
    out["tpu_iters_per_sec"] = round(1.0 / per_iter, 2)
    out["tpu_fixed_cost_s"] = round(fixed, 2)
    # one incremental run snapshots every count in the reference sweep,
    # so total work = max(sweep) iterations (+ the fixed cost, once)
    out["tpu_full_sweep_projected_s"] = round(
        fixed + max(REF_SWEEP) * per_iter, 1
    )

    # --- CPU Barnes-Hut baseline (sklearn) -------------------------------
    from sklearn.manifold import TSNE as SkTSNE

    kw = dict(
        n_components=2,
        perplexity=30.0,
        learning_rate=200.0,
        init="random",
        random_state=0,
        method="barnes_hut",
    )
    print(f"[tsne] sklearn BH baseline ({max(cpu_iters, 250)} iters)",
          flush=True, file=sys.stderr)
    t0 = time.perf_counter()
    try:
        sk = SkTSNE(max_iter=max(cpu_iters, 250), **kw)
    except TypeError:  # older sklearn spells it n_iter
        sk = SkTSNE(n_iter=max(cpu_iters, 250), **kw)
    sk.fit_transform(x50)
    cpu_total = time.perf_counter() - t0
    cpu_iters_done = max(cpu_iters, 250)
    out["cpu_bh_run_s"] = round(cpu_total, 2)
    out["cpu_bh_iters"] = cpu_iters_done
    out["cpu_bh_iters_per_sec_1core"] = round(cpu_iters_done / cpu_total, 2)
    # the reference's sweep re-runs all earlier iterations per process:
    # total BH iterations = sum(sweep); 6 procs x 32 threads.  Linear
    # 32-thread scaling is generous to the CPU (tree build serializes).
    out["cpu_sweep_iters_total"] = sum(REF_SWEEP)
    out["cpu_full_sweep_projected_s_1core"] = round(
        sum(REF_SWEEP) / out["cpu_bh_iters_per_sec_1core"], 1
    )
    out["cpu_full_sweep_projected_s_32thread"] = round(
        out["cpu_full_sweep_projected_s_1core"] / 32.0, 1
    )
    out["cpu_32thread_extrapolated"] = True
    out["tpu_vs_cpu_32thread_sweep"] = round(
        out["cpu_full_sweep_projected_s_32thread"]
        / out["tpu_full_sweep_projected_s"],
        2,
    )
    return out


def bench_umap(n: int, dim: int, iters: int) -> dict:
    """TPU UMAP at gene scale (round 5, VERDICT r4 item 8): time the
    full-batch layout and record the cluster-separation sanity the t-SNE
    bench uses (umap-learn itself is not installable in-image, so there
    is no in-situ CPU denominator — the reference's own docs put
    umap-learn at minutes for 24k x 50d)."""
    from gene2vec_tpu.viz.umap import UMAPConfig, umap_layout

    rng = np.random.RandomState(0)
    centers = rng.randn(200, dim) * 4.0
    labels = rng.randint(0, 200, n)
    x = (centers[labels] + rng.randn(n, dim)).astype(np.float32)

    cfg = UMAPConfig(n_iters=iters, pca_dims=50)
    t0 = time.perf_counter()
    y = umap_layout(x, cfg)
    total = time.perf_counter() - t0
    # per-iteration rate from a second, shorter run (compile now cached)
    cfg_lo = UMAPConfig(n_iters=max(iters // 3, 1), pca_dims=50)
    t0 = time.perf_counter()
    umap_layout(x, cfg_lo)
    lo_s = time.perf_counter() - t0
    per_iter = max((total - lo_s) / max(iters - cfg_lo.n_iters, 1), 1e-9)

    # separation sanity on a subsample: the full (N, N, 2) broadcast at
    # 24k would cost ~8 GB of host arrays for one scalar
    sub = np.random.RandomState(1).choice(n, size=min(n, 2000), replace=False)
    ys, ls = y[sub], labels[sub]
    same = ls[:, None] == ls[None, :]
    np.fill_diagonal(same, False)
    d = np.linalg.norm(ys[:, None] - ys[None, :], axis=-1)
    sep = float(
        d[~same & ~np.eye(len(sub), dtype=bool)].mean()
        / max(d[same].mean(), 1e-9)
    )
    print(f"[umap] {n}x{dim}: {total:.1f}s ({1.0/per_iter:.1f} it/s), "
          f"inter/intra = {sep:.2f}", flush=True, file=sys.stderr)
    return {
        "n": n, "dim": dim, "n_iters": iters,
        "total_s": round(total, 2),
        "iters_per_sec": round(1.0 / per_iter, 2),
        "inter_over_intra": round(sep, 2),
    }


def bench_corr(studies: int, samples: int, genes: int) -> dict:
    """End-to-end per-study co-expression mask extraction (what the
    corpus builder consumes): |corr| > 0.9 over all gene pairs.  The
    reference computes ``data.corr()`` then thresholds on the host
    (``src/generate_gene_pairs.py:49``); the TPU backend thresholds on
    device and downloads packed bits (32x less host-link traffic — on
    this tunneled chip the full-matrix download made the TPU path
    SLOWER than numpy: 496s vs 31s for this exact workload)."""
    import pandas as pd

    from gene2vec_tpu.corpus.builder import abs_correlation_mask

    rng = np.random.RandomState(1)
    mats = [
        rng.randn(samples, genes).astype(np.float64)
        for _ in range(studies)
    ]
    thr = 0.9
    out = {"studies": studies, "samples": samples, "genes": genes}

    t0 = time.perf_counter()
    n_pd = 0
    for m in mats:
        n_pd += int((pd.DataFrame(m).corr().abs().values > thr).sum())
    out["pandas_corr_s"] = round(time.perf_counter() - t0, 2)

    t0 = time.perf_counter()
    n_np = 0
    for m in mats:
        n_np += int(abs_correlation_mask(m, thr, backend="numpy").sum())
    out["numpy_matmul_s"] = round(time.perf_counter() - t0, 2)

    # jax/TPU backend: first call compiles; time a second full pass
    abs_correlation_mask(mats[0], thr, backend="jax")
    t0 = time.perf_counter()
    n_tpu = 0
    for m in mats:
        n_tpu += int(abs_correlation_mask(m, thr, backend="jax").sum())
    out["tpu_packed_mask_s"] = round(time.perf_counter() - t0, 2)
    out["mask_counts_agree"] = bool(n_pd == n_np == n_tpu)

    out["numpy_vs_pandas"] = round(
        out["pandas_corr_s"] / max(out["numpy_matmul_s"], 1e-9), 1
    )
    out["tpu_vs_pandas"] = round(
        out["pandas_corr_s"] / max(out["tpu_packed_mask_s"], 1e-9), 1
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes")
    ap.add_argument("--out", default="BENCH_VIZ_CORPUS_r04.json")
    args = ap.parse_args()

    if args.quick:
        tsne = bench_tsne(n=2000, dim=200, seg=50, cpu_iters=250)
        umap = bench_umap(n=2000, dim=200, iters=100)
        corr = bench_corr(studies=5, samples=100, genes=1000)
    else:
        tsne = bench_tsne(n=24447, dim=200, seg=100, cpu_iters=250)
        umap = bench_umap(n=24447, dim=200, iters=400)
        corr = bench_corr(studies=50, samples=100, genes=5000)

    result = {"tsne_24k": tsne, "umap_24k": umap, "corpus_corr": corr}
    print(json.dumps(result, indent=2), file=sys.stdout)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    main()
