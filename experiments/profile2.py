"""Redo microbenchmarks with REAL synchronization (scalar transfer), since
block_until_ready does not block on the axon tunnel backend."""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
import sys

V, D, B, P = 24447, 200, 16384, 64
E = 2 * B
NB = 50


_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def sync(x):
    """Force completion: pull one scalar to host."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    float(_sum(leaf))


def bench(label, fn, *args, iters=NB, pairs=None):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / iters
    extra = f" -> {pairs / dt / 1e6:8.2f}M pairs/s" if pairs else ""
    print(f"{label:46s} {dt * 1e3:8.3f} ms{extra}", file=sys.stderr)
    return dt


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    emb = jnp.asarray(rng.randn(V, D).astype(np.float32))
    ctx = jnp.asarray(rng.randn(V, D).astype(np.float32))
    centers = jnp.asarray(rng.randint(0, V, E).astype(np.int32))
    grads = jnp.asarray(rng.randn(E, D).astype(np.float32))
    ones = jnp.ones(E, jnp.float32)

    bench("gather (E,D) rows", jax.jit(lambda t, i: t[i]), emb, centers)
    vrows = emb[centers]
    urows = ctx[jnp.asarray(rng.randint(0, V, P).astype(np.int32))]
    bench("matmul (E,D)x(D,P)", jax.jit(lambda a, b: a @ b.T), vrows, urows)

    def scatter_acc(idx, g, w):
        payload = jnp.concatenate([g, w[:, None]], axis=1)
        return jnp.zeros((V, D + 1), jnp.float32).at[idx].add(payload)

    bench("scatter-add E rows -> (V,D+1) zeros", jax.jit(scatter_acc), centers, grads, ones)

    def scatter_plain(idx, g):
        return jnp.zeros((V, D), jnp.float32).at[idx].add(g)

    bench("scatter-add E rows -> (V,D) zeros", jax.jit(scatter_plain), centers, grads)

    def cnt_only(idx, w):
        return jnp.zeros((V,), jnp.float32).at[idx].add(w)

    bench("scatter-add E -> (V,) counts", jax.jit(cnt_only), centers, ones)

    bench(
        "in-place scatter onto table (donated)",
        jax.jit(lambda t, i, g: t.at[i].add(g), donate_argnums=(0,)),
        emb + 0, centers, grads,
    )
    upd = jnp.asarray(rng.randn(V, D).astype(np.float32))
    bench(
        "dense (V,D) axpy (donated)",
        jax.jit(lambda t, u: t - 0.01 * u, donate_argnums=(0,)),
        emb + 0, upd,
    )

    # sorted variants
    def sorted_scatter(t, idx, g):
        order = jnp.argsort(idx)
        return t.at[idx[order]].add(g[order])

    bench("argsort+inplace scatter (donated)",
          jax.jit(sorted_scatter, donate_argnums=(0,)), emb + 0, centers, grads)

    bench("argsort only (E,)", jax.jit(jnp.argsort), centers)

    # full current step
    from gene2vec_tpu.data.negative_sampling import NegativeSampler
    from gene2vec_tpu.sgns.model import SGNSParams
    from gene2vec_tpu.sgns.step import sgns_step

    counts = np.maximum(rng.zipf(1.5, V), 1)
    noise = NegativeSampler(counts).table

    for b in (16384, 65536, 262144):
        pairs_b = jnp.asarray(rng.randint(0, V, (b, 2)).astype(np.int32))
        stepb = jax.jit(
            lambda p, bb, n, k: sgns_step(p, bb, n, k, jnp.float32(0.01)),
            donate_argnums=(0,),
        )
        p = SGNSParams(emb=emb + 0, ctx=ctx + 0)
        key = jax.random.PRNGKey(0)
        p, _ = stepb(p, pairs_b, noise, key)
        sync(p)
        t0 = time.perf_counter()
        n = max(4, 1_000_000 // b)
        for i in range(n):
            p, _ = stepb(p, pairs_b, noise, jax.random.fold_in(key, i))
        sync(p)
        dt = (time.perf_counter() - t0) / n
        print(f"{'FULL step B=%d' % b:46s} {dt * 1e3:8.3f} ms -> {b / dt / 1e6:8.2f}M pairs/s", file=sys.stderr)

    # per_example mode for comparison
    pairs_b = jnp.asarray(rng.randint(0, V, (16384, 2)).astype(np.int32))
    step_pe = jax.jit(
        lambda p, bb, n, k: sgns_step(
            p, bb, n, k, jnp.float32(0.01), negative_mode="per_example"
        ),
        donate_argnums=(0,),
    )
    p = SGNSParams(emb=emb + 0, ctx=ctx + 0)
    key = jax.random.PRNGKey(0)
    p, _ = step_pe(p, pairs_b, noise, key)
    sync(p)
    t0 = time.perf_counter()
    for i in range(30):
        p, _ = step_pe(p, pairs_b, noise, jax.random.fold_in(key, i))
    sync(p)
    dt = (time.perf_counter() - t0) / 30
    print(f"{'FULL step per_example B=16384':46s} {dt * 1e3:8.3f} ms -> {16384 / dt / 1e6:8.2f}M pairs/s", file=sys.stderr)


if __name__ == "__main__":
    main()
