"""Is there a fixed per-iteration cost in lax.scan on this backend, and does
unroll amortize it?"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
import sys

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def bench(label, loop, x, iters_inside):
    out = loop(x)
    float(_sum(out))
    t0 = time.perf_counter()
    out = loop(x)
    float(_sum(out))
    dt = (time.perf_counter() - t0) / iters_inside
    print(f"{label:52s} {dt * 1e6:9.2f} us/iter", file=sys.stderr)


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)

    for n_iter in (100, 1000):
        @jax.jit
        def loop(x, n_iter=n_iter):
            def body(c, _):
                return c * 1.0000001 + 1e-9, ()
            c, _ = jax.lax.scan(body, x, jnp.arange(n_iter))
            return c
        bench(f"scalar scan x{n_iter}", loop, jnp.float32(1.0), n_iter)

    for unroll in (1, 4, 16):
        @jax.jit
        def loop(x, unroll=unroll):
            def body(c, _):
                return c * 1.0000001 + 1e-9, ()
            c, _ = jax.lax.scan(body, x, jnp.arange(1000), unroll=unroll)
            return c
        bench(f"scalar scan x1000 unroll={unroll}", loop, jnp.float32(1.0), 1000)

    # 25MB axpy scan with unroll
    n = 25 * 1024 * 1024 // 4 // 256
    x = jnp.asarray(rng.randn(n, 256).astype(np.float32))
    for unroll in (1, 4, 16):
        @jax.jit
        def loop(x, unroll=unroll):
            def body(c, _):
                return c * 1.0000001, ()
            c, _ = jax.lax.scan(body, x, jnp.arange(100), unroll=unroll)
            return c
        bench(f"25MB axpy scan x100 unroll={unroll} (50MB/iter)", loop, x, 100)

    # fori_loop comparison
    @jax.jit
    def floop(x):
        return jax.lax.fori_loop(0, 1000, lambda i, c: c * 1.0000001 + 1e-9, x)
    bench("scalar fori_loop x1000", floop, jnp.float32(1.0), 1000)


if __name__ == "__main__":
    main()
