"""Round-4 probe: primitive costs behind the (V, D+1) accumulator slice.

The round-3 step decomposition (docs/PERF_NOTES.md "Remaining account")
attributes ~1.6 ms of the 6.0 ms step to accumulator traffic.  Before
redesigning, measure the candidate primitives in isolation on the real
chip:

  a. (E,) scalar scatter-add into (V,) and (E,) scalar gather from (V,)
     — if these are ~free vs 800 B row ops, a two-pass "scale at scatter
     time" design (count pass -> inv-div gather -> direct table scatter)
     beats the accumulator; if they cost the same ~16 ns/row, it loses.
  b. windowed slab scatter-add (G slabs of (S, D+1) rows at dynamic row
     starts, lax.scatter_add with update_window_dims) directly into the
     (V, D+1) accumulator vs the current acc_blocks detour
     (zeros (NB,S,D+1) + block scatter + two static slice adds).
  c. full dense accumulator pass (zeros + finalize read/update) in f32
     vs bf16 payload — the dense side is bandwidth-bound, so bf16 should
     halve it (unlike the row-op side, where round 2 measured dtype
     independence).

Each timing: one jitted lax.scan of ITERS identical bodies, scalar
forced out, median of 3 — per docs/PERF_NOTES.md measurement discipline
(block_until_ready does not block on the axon tunnel).
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
import sys

V, D, E = 24447, 200, 32768
BLOCK = 128
HEAD = 256
G = 1024          # tail groups per step at E=32768, group 32
ITERS = 100
REPS = 3


def bench(fn, *args):
    out = jax.jit(fn)(*args)
    jax.tree_util.tree_map(lambda x: np.asarray(x.ravel()[0]), out)  # compile+force
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = jax.jit(fn)(*args)
        jax.tree_util.tree_map(lambda x: np.asarray(x.ravel()[0]), out)
        times.append((time.perf_counter() - t0) / ITERS)
    return sorted(times)[len(times) // 2]


def scanned(body):
    """Run `body` ITERS times with varying fold so XLA can't CSE it away."""

    @functools.wraps(body)
    def run(*args):
        def it(carry, i):
            return body(carry, i, *args[1:])[0], ()

        carry, _ = lax.scan(it, args[0], jnp.arange(ITERS))
        return carry

    return run


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    # Zipf-ish indices, like real batch rows
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    idx = jnp.asarray(rng.choice(V, size=(E,), p=p).astype(np.int32))
    rows = jnp.asarray(rng.randn(E, D).astype(np.float32))
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))
    acc0 = jnp.zeros((V, D + 1), jnp.float32)
    starts = jnp.asarray(
        (HEAD + rng.randint(0, (V - HEAD - BLOCK) // BLOCK, G) * BLOCK).astype(
            np.int32
        )
    )
    slabs = jnp.asarray(rng.randn(G, BLOCK, D + 1).astype(np.float32))

    # --- a. scalar scatter / gather --------------------------------------
    @scanned
    def scalar_scatter(carry, i, idx):
        return carry.at[idx + (i % 2)].add(1.0), None

    t = bench(lambda c, ix: scalar_scatter(c, ix), jnp.zeros(V), idx)
    print(f"a1 scalar scatter-add E={E} -> (V,): {t*1e3:.3f} ms "
          f"({t/E*1e9:.2f} ns/el)", file=sys.stderr)

    @scanned
    def scalar_gather(carry, i, tbl):
        return carry + tbl[idx + (i % 2)].sum(), None

    t = bench(lambda c, tbl: scalar_gather(c, tbl), jnp.zeros(()), jnp.ones(V))
    print(f"a2 scalar gather   E={E} <- (V,): {t*1e3:.3f} ms "
          f"({t/E*1e9:.2f} ns/el)", file=sys.stderr)

    # row scatter reference (the known ~16 ns/row-op)
    @scanned
    def row_scatter(carry, i, idx, rows):
        return carry.at[idx + (i % 2)].add(rows), None

    t = bench(lambda c, ix, r: row_scatter(c, ix, r),
              jnp.zeros((V, D)), idx, rows)
    print(f"a3 row scatter-add E={E} x {D}f32:  {t*1e3:.3f} ms "
          f"({t/E*1e9:.2f} ns/row)", file=sys.stderr)

    # --- b. slab scatter vs acc_blocks detour ----------------------------
    nb = (V - HEAD) // BLOCK + 1

    @scanned
    def via_blocks(acc, i, blocks_idx, slabs):
        blk = jnp.zeros((nb, BLOCK, D + 1), jnp.float32).at[
            (blocks_idx + i) % nb
        ].add(slabs)
        acc = acc.at[HEAD : HEAD + (nb - 1) * BLOCK].add(
            blk[:-1].reshape((nb - 1) * BLOCK, D + 1)
        )
        return acc.at[V - BLOCK :].add(blk[-1]), None

    blocks_idx = (starts - HEAD) // BLOCK
    t = bench(lambda a, b, s: via_blocks(a, b, s), acc0, blocks_idx, slabs)
    print(f"b1 acc_blocks detour G={G}: {t*1e3:.3f} ms", file=sys.stderr)

    @scanned
    def via_slab_scatter(acc, i, starts, slabs):
        dn = lax.ScatterDimensionNumbers(
            update_window_dims=(1, 2),
            inserted_window_dims=(),
            scatter_dims_to_operand_dims=(0,),
        )
        return lax.scatter_add(
            acc, ((starts + i * BLOCK) % (V - BLOCK))[:, None], slabs, dn
        ), None

    t = bench(lambda a, s, sl: via_slab_scatter(a, s, sl), acc0, starts, slabs)
    print(f"b2 windowed slab scatter G={G}x({BLOCK},{D+1}): {t*1e3:.3f} ms", file=sys.stderr)

    # --- c. dense accumulator pass, f32 vs bf16 --------------------------
    @scanned
    def dense_pass(tbl, i, acc):
        upd = acc[:, :D] / jnp.maximum(acc[:, D] / 32.0, 1.0)[:, None]
        return (tbl - 0.01 * upd.astype(tbl.dtype)), None

    accf = jnp.abs(jnp.asarray(rng.randn(V, D + 1).astype(np.float32)))
    t = bench(lambda tb, a: dense_pass(tb, a), table, accf)
    print(f"c1 finalize pass f32 acc: {t*1e3:.3f} ms", file=sys.stderr)
    t = bench(lambda tb, a: dense_pass(tb, a), table, accf.astype(jnp.bfloat16))
    print(f"c2 finalize pass bf16 acc: {t*1e3:.3f} ms", file=sys.stderr)

    @scanned
    def zeros_scatter(carry, i, idx, rows):
        acc = jnp.zeros((V, D + 1), jnp.float32).at[idx + (i % 2)].add(
            jnp.concatenate([rows, jnp.ones((E, 1), jnp.float32)], axis=1)
        )
        return carry + acc[0, 0], None

    t = bench(lambda c, ix, r: zeros_scatter(c, ix, r), jnp.zeros(()), idx, rows)
    print(f"c3 zeros+fused scatter f32 (V,D+1): {t*1e3:.3f} ms", file=sys.stderr)

    @scanned
    def zeros_scatter_bf16(carry, i, idx, rows):
        acc = jnp.zeros((V, D + 1), jnp.bfloat16).at[idx + (i % 2)].add(
            jnp.concatenate(
                [rows, jnp.ones((E, 1), jnp.float32)], axis=1
            ).astype(jnp.bfloat16)
        )
        return carry + acc[0, 0].astype(jnp.float32), None

    t = bench(lambda c, ix, r: zeros_scatter_bf16(c, ix, r),
              jnp.zeros(()), idx, rows)
    print(f"c4 zeros+fused scatter bf16 (V,D+1): {t*1e3:.3f} ms", file=sys.stderr)


if __name__ == "__main__":
    main()
