"""Round-4 ablation: where the stratified step's 6.0 ms actually goes,
and which restructurings move it.

VERDICT r3 item 1 names the (V, D+1) accumulator slice (~1.6 ms) as the
squeezable cost.  experiments/accum_probe.py killed the two named
micro-fixes (bf16 accumulator: no change — issue-bound, like round 2's
table-dtype result; windowed slab scatter: 3x worse than the acc_blocks
detour).  This ablation measures step-level restructurings instead, each
a local variant of _step_stratified run through the same whole-epoch
scan harness as experiments/epoch_sweep.py (steady-state, 3 reps):

  base      — gene2vec_tpu.sgns.step._step_stratified as shipped
  onehot    — tail-block aggregation as one-hot MXU matmul instead of the
              (NB, S, D+1) block-scatter detour: the detour writes ~105 MB
              of slab scatter-adds per step; a (NB, G) one-hot times the
              (G, S*(D+1)) payload is ~5e9 MACs (~free on MXU) and turns
              all of it into streaming matmul traffic
  bf16noise — head/tail logit+mask+sigmoid chains in bf16 (f32 accumulate
              via preferred_element_type): halves the (E, H) and
              (G, E/G, S) elementwise intermediates' bytes
  maskfree  — drop the (E, H) head mask materialization; correct the
              self-collision exactly per example using q[contexts]
              (the positive row's logit IS pos_logit, so the correction
              needs no extra row gathers if q[contexts] is cheap)
  merged    — one (2V, D+1) accumulator for emb+ctx: a single 2E-row
              scatter and one finalize pass instead of two of each
  sum       — scatter straight into the tables (combiner="sum"
              semantics, no accumulator/finalize at all): an UPPER BOUND
              on what any accumulator redesign could recover, not a
              candidate (capped combiner is a quality invariant)

Usage: python experiments/step_ablate.py [variant ...]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from gene2vec_tpu.data.negative_sampling import build_stratified_spec
from gene2vec_tpu.data.pipeline import PairCorpus, epoch_shuffle
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.model import SGNSParams, init_params
from gene2vec_tpu.sgns.step import (
    _acc_dtype_for,
    _apply_row_updates,
    _examples_from_pairs,
    _finalize_row_updates,
    _row_divisor,
    _scatter_accumulator,
    _step_stratified,
)

V, D = 24447, 200
N = 4_000_000
B = 16384
REPS = 3
K = 5.0
GROUP = 32


def stratified_variant(params, centers, contexts, spec, key, lr, variant):
    """_step_stratified with the ablation knobs; mirrors sgns/step.py."""
    onehot = variant in ("onehot", "all")
    bf16noise = variant in ("bf16noise", "all")
    maskfree = variant in ("maskfree", "all")
    merged = variant in ("merged",)
    direct_sum = variant in ("sum",)

    emb_t, ctx_t = params.emb, params.ctx
    v_size, d = ctx_t.shape
    e = centers.shape[0]
    g = e // GROUP
    head, block, nb = spec.head, spec.block, spec.nb
    noise_dtype = jnp.bfloat16 if bf16noise else jnp.float32
    k = jnp.asarray(K, jnp.float32)

    v = emb_t[centers]
    u_pos = ctx_t[contexts]
    pos_logit = jnp.sum(v * u_pos, axis=-1)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0

    # ---- head ----
    ctx_head = ctx_t[:head].astype(noise_dtype)
    q_head = spec.q[:head].astype(noise_dtype)
    head_logit = jax.lax.dot(
        v.astype(noise_dtype), ctx_head.T,
        preferred_element_type=jnp.float32,
    ).astype(noise_dtype)
    if maskfree:
        sig = jax.nn.sigmoid(head_logit)
        g_head = k.astype(noise_dtype) * q_head[None, :] * sig
        loss_head_raw = k * jnp.sum(
            (q_head[None, :] * jax.nn.softplus(head_logit)).astype(
                jnp.float32
            ),
            axis=-1,
        )
        # exact self-collision correction: head_logit[e, c_e] == pos_logit[e]
        q_ctx = spec.q[contexts]  # (E,) scalar gather
        in_head = (contexts < head).astype(jnp.float32)
        corr = k * q_ctx * in_head
        loss_head = loss_head_raw - corr * jax.nn.softplus(pos_logit)
        g_self = corr * jax.nn.sigmoid(pos_logit)  # (E,) to subtract
    else:
        head_mask = (
            jnp.arange(head)[None, :] != contexts[:, None]
        ).astype(noise_dtype)
        g_head = (
            k.astype(noise_dtype)
            * q_head[None, :]
            * jax.nn.sigmoid(head_logit)
            * head_mask
        )
        loss_head = k * jnp.sum(
            (q_head[None, :] * head_mask * jax.nn.softplus(head_logit)).astype(
                jnp.float32
            ),
            axis=-1,
        )
        g_self = None

    # ---- tail ----
    blocks = jax.random.randint(key, (g,), 0, nb)
    starts = jnp.minimum(head + blocks * block, v_size - block)

    def slice_rows(tbl, s):
        return jax.lax.dynamic_slice(tbl, (s, 0), (block, tbl.shape[1]))

    ctx_blk = jax.vmap(slice_rows, in_axes=(None, 0))(ctx_t, starts).astype(
        noise_dtype
    )
    w_blk = jax.vmap(
        lambda s: jax.lax.dynamic_slice(spec.tail_w, (s,), (block,))
    )(starts).astype(noise_dtype)

    vg = v.reshape(g, e // g, d)
    cg = contexts.reshape(g, e // g)
    tail_logit = jax.lax.dot_general(
        vg.astype(noise_dtype), ctx_blk,
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).astype(noise_dtype)  # (G, E/G, S)
    row_ids = starts[:, None] + jnp.arange(block)[None, :]
    tail_mask = (row_ids[:, None, :] != cg[:, :, None]).astype(noise_dtype)
    w_tail = k.astype(noise_dtype) * w_blk[:, None, :]
    g_tail = w_tail * jax.nn.sigmoid(tail_logit) * tail_mask
    loss_tail = jnp.sum(
        (w_tail * tail_mask * jax.nn.softplus(tail_logit)).astype(jnp.float32),
        axis=-1,
    ).reshape(e)

    loss = jnp.mean(jax.nn.softplus(-pos_logit) + loss_head + loss_tail)

    # ---- center gradients ----
    d_center = (
        g_pos[:, None] * u_pos
        + jax.lax.dot(
            g_head, ctx_head, preferred_element_type=jnp.float32
        )
        + jax.lax.dot_general(
            g_tail, ctx_blk, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(e, d)
    )
    if g_self is not None:
        d_center = d_center - g_self[:, None] * u_pos

    # ---- ctx/emb updates ----
    d_pos = g_pos[:, None] * v
    if g_self is not None:
        d_pos = d_pos - g_self[:, None] * v

    if direct_sum:
        emb = emb_t.at[centers].add(-lr * d_center)
        ctx = ctx_t.at[contexts].add(-lr * d_pos)
        d_head_rows = jax.lax.dot(
            g_head.T, v.astype(noise_dtype), preferred_element_type=jnp.float32
        )
        ctx = ctx.at[:head].add(-lr * d_head_rows)
        d_tail_rows = jax.lax.dot_general(
            g_tail, vg.astype(noise_dtype), (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (G, S, D)
        blk = jnp.zeros((nb, block, d), jnp.float32).at[blocks].add(d_tail_rows)
        if nb > 1:
            ctx = ctx.at[head : head + (nb - 1) * block].add(
                -lr * blk[:-1].reshape((nb - 1) * block, d)
            )
        ctx = ctx.at[v_size - block :].add(-lr * blk[-1])
        return SGNSParams(emb=emb, ctx=ctx), loss

    acc_dtype = jnp.float32
    if merged:
        idx2 = jnp.concatenate([centers, contexts + v_size])
        grads2 = jnp.concatenate([d_center, d_pos])
        acc = _scatter_accumulator(
            2 * v_size, idx2, grads2, jnp.ones((2 * e,), jnp.float32), acc_dtype
        )
    else:
        emb = _apply_row_updates(
            emb_t, centers, d_center, jnp.ones((e,), jnp.float32), lr,
            "capped", jnp.float32,
        )
        acc = _scatter_accumulator(
            v_size, contexts, d_pos, jnp.ones((e,), jnp.float32), acc_dtype
        )
    coff = v_size if merged else 0

    if maskfree:
        # unmasked dense units; the exact per-row correction folds into the
        # positive scatter (weight 1 - corr_e at row c_e) in a real impl —
        # cost-identical to the ones used here, so the ablation timing holds
        u_head = k * q_head.astype(jnp.float32) * e
    else:
        u_head = k * q_head.astype(jnp.float32) * jnp.sum(
            head_mask.astype(jnp.float32), axis=0
        )
    d_head_rows = jax.lax.dot(
        g_head.T, v.astype(noise_dtype), preferred_element_type=jnp.float32
    )
    acc = acc.at[coff : coff + head, :d].add(d_head_rows)
    acc = acc.at[coff : coff + head, d].add(u_head)

    d_tail_rows = jax.lax.dot_general(
        g_tail, vg.astype(noise_dtype), (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (G, S, D)
    u_tail = (w_tail[:, 0, :] * jnp.sum(tail_mask, axis=1)).astype(jnp.float32)
    tail_payload = jnp.concatenate(
        [d_tail_rows, u_tail[:, :, None]], axis=2
    )  # (G, S, D+1)

    if onehot:
        oh = (
            blocks[None, :] == jnp.arange(nb)[:, None]
        ).astype(jnp.bfloat16)  # (NB, G)
        agg = jax.lax.dot(
            oh,
            tail_payload.astype(jnp.bfloat16).reshape(g, block * (d + 1)),
            preferred_element_type=jnp.float32,
        ).reshape(nb, block, d + 1)
    else:
        agg = jnp.zeros((nb, block, d + 1), jnp.float32).at[blocks].add(
            tail_payload
        )
    if nb > 1:
        acc = acc.at[coff + head : coff + head + (nb - 1) * block].add(
            agg[:-1].reshape((nb - 1) * block, d + 1)
        )
    acc = acc.at[coff + v_size - block : coff + v_size].add(agg[-1])

    if merged:
        both = jnp.concatenate([emb_t, ctx_t], axis=0)
        both = _finalize_row_updates(both, acc, lr, "capped")
        return SGNSParams(emb=both[:v_size], ctx=both[v_size:]), loss
    ctx = _finalize_row_updates(ctx_t, acc, lr, "capped")
    return SGNSParams(emb=emb, ctx=ctx), loss


def make_epoch(variant, spec, num_batches):
    def epoch(params, pairs, key):
        shuffle_key, step_key = jax.random.split(key)
        shuffled = epoch_shuffle(pairs, shuffle_key, N, num_batches, B, "offset")

        def body(params, step):
            batch = jax.lax.dynamic_slice_in_dim(shuffled, step * B, B)
            centers, contexts = _examples_from_pairs(batch)
            lr = 0.025 * (1.0 - step.astype(jnp.float32) / num_batches)
            if variant in ("base", "g64", "g128"):
                gs = {"base": GROUP, "g64": 64, "g128": 128}[variant]
                return _step_stratified(
                    params, centers, contexts, spec,
                    jax.random.fold_in(step_key, step), 5, gs, lr,
                    jnp.float32, "capped",
                )
            return stratified_variant(
                params, centers, contexts, spec,
                jax.random.fold_in(step_key, step), lr, variant,
            )

        params, losses = jax.lax.scan(
            body, params, jnp.arange(num_batches, dtype=jnp.int32)
        )
        return params, jnp.mean(losses)

    return jax.jit(epoch, donate_argnums=(0,))


def make_geom_epoch(group, spec, num_batches):
    def epoch(params, pairs, key):
        shuffle_key, step_key = jax.random.split(key)
        shuffled = epoch_shuffle(pairs, shuffle_key, N, num_batches, B, "offset")

        def body(params, step):
            batch = jax.lax.dynamic_slice_in_dim(shuffled, step * B, B)
            centers, contexts = _examples_from_pairs(batch)
            lr = 0.025 * (1.0 - step.astype(jnp.float32) / num_batches)
            return _step_stratified(
                params, centers, contexts, spec,
                jax.random.fold_in(step_key, step), 5, group, lr,
                jnp.float32, "capped",
            )

        params, losses = jax.lax.scan(
            body, params, jnp.arange(num_batches, dtype=jnp.int32)
        )
        return params, jnp.mean(losses)

    return jax.jit(epoch, donate_argnums=(0,))


def main():
    variants = sys.argv[1:] or [
        "base", "onehot", "bf16noise", "maskfree", "merged", "all", "sum",
        "g64", "g128",
    ]
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    pairs_np = rng.choice(V, size=(N, 2), p=p).astype(np.int32)
    counts = np.bincount(pairs_np.reshape(-1), minlength=V).astype(np.int64)
    corpus = PairCorpus(Vocab([f"G{i}" for i in range(V)], counts), pairs_np)
    num_batches = N // B
    pairs = corpus.device_pairs()

    for variant in variants:
        # geometry variants: gG[.sS[.hH]] -> group G, block S, head H
        # through the shipped _step_stratified (e.g. g64.s256, g128.s512.h512)
        if variant.startswith("g") and "." in variant:
            parts = dict(
                (p[0], int(p[1:])) for p in variant.split(".")
            )
            spec_v = build_stratified_spec(
                counts, parts.get("h", 256), parts.get("s", 128)
            )
            gs = parts["g"]
            epoch = make_geom_epoch(gs, spec_v, num_batches)
        else:
            spec = build_stratified_spec(counts, 256, 128)
            epoch = make_epoch(variant, spec, num_batches)
        params = init_params(jax.random.PRNGKey(0), V, D, jnp.float32)
        key = jax.random.PRNGKey(1)
        params, loss = epoch(params, pairs, key)  # compile
        float(loss)
        rates, losses = [], []
        for r in range(REPS):
            t0 = time.perf_counter()
            params, loss = epoch(params, pairs, jax.random.fold_in(key, r))
            losses.append(float(loss))
            dt = time.perf_counter() - t0
            rates.append(num_batches * B / dt)
        rs = ", ".join(f"{r/1e6:5.2f}" for r in rates)
        print(
            f"{variant:10s} [{rs}] M pairs/s  (best {max(rates)/1e6:.2f})"
            f"  loss {losses[-1]:.4f}"
        , file=sys.stderr)


if __name__ == "__main__":
    main()
