"""Isolate lax.scan overhead on the axon TPU backend.

Hypotheses: (a) loop-invariant corpus buffer copied per iteration,
(b) carried table state copied per iteration, (c) per-iteration dispatch
round-trips over the tunnel.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
import sys

V, D, B = 24447, 200, 16384
NB = 244  # scan length


def bench(label, fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    print(f"{label:52s} {dt * 1e3:9.2f} ms total, {dt / NB * 1e3:7.3f} ms/iter", file=sys.stderr)


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))
    corpus = jnp.asarray(rng.randint(0, V, (NB * B, 2)).astype(np.int32))
    idx = jnp.asarray(rng.randint(0, V, 2 * B).astype(np.int32))
    grads = jnp.asarray(rng.randn(2 * B, D).astype(np.float32))

    # 1. trivial carry, no big buffers
    @jax.jit
    def scan_trivial(x):
        def body(c, i):
            return c + 1.0, ()
        c, _ = jax.lax.scan(body, x, jnp.arange(NB))
        return c
    bench("scan trivial scalar carry", scan_trivial, jnp.float32(0))

    # 2. big loop-invariant corpus, scalar carry, dynamic_slice per iter
    @jax.jit
    def scan_slice(corpus, x):
        def body(c, i):
            b = jax.lax.dynamic_slice_in_dim(corpus, i * B, B)
            return c + jnp.sum(b.astype(jnp.float32)), ()
        c, _ = jax.lax.scan(body, x, jnp.arange(NB))
        return c
    bench("scan + 32MB invariant + slice", scan_slice, corpus, jnp.float32(0))

    # 3. big (V,D) carry, axpy per iter (carried-table copy test)
    @jax.jit
    def scan_axpy(t):
        def body(t, i):
            return t * 0.9999 + 0.0001, ()
        t, _ = jax.lax.scan(body, t, jnp.arange(NB))
        return t
    bench("scan + (V,D) carry axpy", scan_axpy, table + 0)

    # 4. big carry + scatter-add per iter (the real update pattern)
    @jax.jit
    def scan_scatter(t, idx, grads):
        def body(t, i):
            return t.at[idx].add(0.0001 * grads), ()
        t, _ = jax.lax.scan(body, t, jnp.arange(NB))
        return t
    bench("scan + (V,D) carry scatter-add", scan_scatter, table + 0, idx, grads)

    # 5. big carry + zeros-accumulator scatter + dense update (r1 pattern)
    @jax.jit
    def scan_acc(t, idx, grads):
        def body(t, i):
            acc = jnp.zeros((V, D), jnp.float32).at[idx].add(grads)
            return t - 0.0001 * acc, ()
        t, _ = jax.lax.scan(body, t, jnp.arange(NB))
        return t
    bench("scan + zeros-acc scatter + dense", scan_acc, table + 0, idx, grads)

    # 6. same as 5 but as a host-side Python loop of jitted steps
    step = jax.jit(
        lambda t, idx, grads: t - 0.0001 * (jnp.zeros((V, D), jnp.float32).at[idx].add(grads)),
        donate_argnums=(0,),
    )
    t = table + 0
    t = step(t, idx, grads)
    jax.block_until_ready(t)
    t0 = time.perf_counter()
    for _ in range(NB):
        t = step(t, idx, grads)
    jax.block_until_ready(t)
    dt = time.perf_counter() - t0
    print(f"{'python loop of jitted zeros-acc steps':52s} {dt * 1e3:9.2f} ms total, {dt / NB * 1e3:7.3f} ms/iter", file=sys.stderr)

    # 7. gather per iter from carried table
    @jax.jit
    def scan_gather(t, idx):
        def body(t, i):
            g = t[idx]
            return t + 0.0 * jnp.sum(g), ()
        t, _ = jax.lax.scan(body, t, jnp.arange(NB))
        return t
    bench("scan + (V,D) carry + (E,D) gather", scan_gather, table + 0, idx)


if __name__ == "__main__":
    main()
