"""Round 4: sweep the dense-head positive split (config.positive_head).

Measures integrated-trainer throughput at the bench headline shape
(V=24,447 Zipf, 4M pairs, B=16,384, dim 200, stratified negatives) for a
range of positive_head sizes.  Head coverage of token occurrences under
Zipf(1) is ~H_H/H_V (~57% at H=256, ~70% at H=1024), so the expected win
is the covered fraction of the ~2.1 ms/step positive row-op cost minus the
one-hot matmul cost (which scales with H).

Run: python experiments/positive_head_sweep.py [--heads 0,256,512,1024]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import synth_corpus  # the bench's own corpus recipe
from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.sgns.train import SGNSTrainer


def measure(head: int, v: int, n: int, b: int, dim: int, epochs: int = 3):
    corpus = synth_corpus(v, n)
    cfg = SGNSConfig(dim=dim, batch_pairs=b, positive_head=head)
    trainer = SGNSTrainer(corpus, cfg)
    params = trainer.init()
    key = jax.random.PRNGKey(0)
    pairs_per_epoch = trainer.num_batches * cfg.batch_pairs
    rates, loss = [], None
    for ep in range(epochs + 1):  # first epoch includes compile
        t0 = time.perf_counter()
        params, loss = trainer.train_epoch(params, jax.random.fold_in(key, ep))
        loss = float(loss)  # sync
        dt = time.perf_counter() - t0
        if ep:
            rates.append(pairs_per_epoch / dt)
    if trainer.pos_quotas is not None:
        print(f"  quotas={trainer.pos_quotas}", file=sys.stderr)
    return {
        "head": head,
        "pairs_per_sec": round(float(np.median(rates)), 1),
        "rates": [round(r, 1) for r in rates],
        "final_loss": round(loss, 4),
        "quotas": trainer.pos_quotas,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--heads", default="0,128,256,512,1024,2048")
    ap.add_argument("--vocab", type=int, default=24447)
    ap.add_argument("--pairs", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--dim", type=int, default=200)
    ap.add_argument("--out", default="experiments/results/positive_head_r4.json")
    args = ap.parse_args()

    rows = []
    for h in [int(x) for x in args.heads.split(",")]:
        row = measure(h, args.vocab, args.pairs, args.batch, args.dim)
        print(json.dumps(row), flush=True, file=sys.stdout)
        rows.append(row)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(rows, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    main()
