"""Reproduce the measurements behind docs/QUALITY_NOTES.md.

Three suites, each selectable with ``--suite``:

* ``matrix``  — {negative_mode} x {combiner} x {batch, pool} on the real
  corpus holdout protocol (QUALITY_NOTES §2's failed-repair table and §4's
  P_total sweep).
* ``groups``  — group-size sweep at fixed total pool (the "quality is flat
  in group size" claim) plus the planted-cluster collapse metric
  (invariant 3).
* ``frontier`` — the quality/throughput frontier (§5) on an 8M-pair
  Zipf-ish synthetic corpus, one real chip.

Protocol (QUALITY_NOTES §1): hold out 20% of the reference train split's
pairs, train SGNS on the remaining positives, and rank held-out *in-vocab*
pairs by embedding cosine (the classifier-free, harder metric; the GGIPNN
stage lives in scripts/run_real_auc.py).

Usage::

    python experiments/quality_matrix.py --suite matrix [--epochs 50]
        [--data-dir /root/reference/predictionData] [--out -]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from gene2vec_tpu.config import SGNSConfig  # noqa: E402
from gene2vec_tpu.data.pipeline import PairCorpus  # noqa: E402
from gene2vec_tpu.eval.holdout import (  # noqa: E402
    HoldoutSplit,
    holdout_cos_auc,
    load_holdout,
)
from gene2vec_tpu.eval.planted import (  # noqa: E402
    cluster_cosines,
    planted_corpus,
)
from gene2vec_tpu.io.vocab import Vocab  # noqa: E402
from gene2vec_tpu.sgns.train import SGNSTrainer, train_epochs  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- protocol pieces ---------------------------------------------------------


def holdout_auc(vocab: Vocab, emb: np.ndarray, split: HoldoutSplit):
    """In-vocab holdout cosine AUC, or None when the embedding diverged
    (round(nan) would otherwise leak literal NaN into the JSON output)."""
    if not np.isfinite(emb).all():
        return None
    return round(holdout_cos_auc(vocab, emb, split), 4)


def train(corpus: PairCorpus, cfg: SGNSConfig, epochs: int):
    """Returns (emb, first loss, last loss) via the canonical shared loop
    (gene2vec_tpu.sgns.train.train_epochs — same seeding as the bench gate
    and the regression tests)."""
    emb, losses = train_epochs(corpus, cfg, epochs)
    return emb, losses[0], losses[-1]


def train_timed(corpus: PairCorpus, cfg: SGNSConfig, epochs: int):
    """Like train() but also measures post-compile wall time (the frontier
    suite's throughput column needs interleaved blocking)."""
    tr = SGNSTrainer(corpus, cfg)
    params = tr.init()
    losses = []
    t0 = None
    for it in range(1, epochs + 1):
        params, loss = tr.train_epoch(
            params, jax.random.fold_in(jax.random.PRNGKey(cfg.seed), it)
        )
        losses.append(float(loss))
        if it == 1:
            jax.block_until_ready(params.emb)
            t0 = time.perf_counter()
    jax.block_until_ready(params.emb)
    dt = time.perf_counter() - t0 if epochs > 1 else float("nan")
    return np.asarray(params.emb), losses[0], losses[-1], dt


def synthetic_big(v=24000, n=8_000_000, seed=0):
    rng = np.random.RandomState(seed)
    p = np.arange(1, v + 1) ** -0.8
    p /= p.sum()
    pairs = rng.choice(v, size=(n, 2), p=p).astype(np.int32)
    vocab = Vocab(
        [f"G{i}" for i in range(v)], np.bincount(pairs.reshape(-1), minlength=v)
    )
    return PairCorpus(vocab, pairs)


# -- suites ------------------------------------------------------------------


def degree_baseline(split: HoldoutSplit) -> float:
    """No-embedding degree-product baseline on the in-vocab holdout —
    the number frozen as eval.holdout.DEGREE_BASELINE_AUC (QUALITY_NOTES
    §8: this metric has a strong co-occurrence floor)."""
    from gene2vec_tpu.eval.metrics import roc_auc_score

    deg: dict = {}
    vocab_tokens = set()
    for a, b in split.fit_positives:
        deg[a] = deg.get(a, 0) + 1
        deg[b] = deg.get(b, 0) + 1
        vocab_tokens.update((a, b))
    scores, labels = [], []
    for (a, b), y in zip(split.hold_pairs, split.hold_labels):
        if a in vocab_tokens and b in vocab_tokens:
            scores.append(np.log1p(deg.get(a, 0)) + np.log1p(deg.get(b, 0)))
            labels.append(y)
    return roc_auc_score(np.asarray(labels), np.asarray(scores))


def suite_matrix(args) -> list:
    corpus, split = load_holdout(args.data_dir)
    rows = [
        {"config": "degree-product baseline (no embedding)",
         "holdout_cos_auc": round(degree_baseline(split), 4)}
    ]
    log(f"degree baseline AUC {rows[0]['holdout_cos_auc']}")
    shared = dict(negative_mode="shared")  # modes pinned explicitly: the
    # SGNSConfig default moved to "stratified" in round 3 and these rows
    # must keep measuring what their labels say
    configs = [
        ("stratified+capped B=4096 (default)",
         dict(negative_mode="stratified")),
        ("stratified+capped B=16384",
         dict(negative_mode="stratified", batch_pairs=16384)),
        ("shared+capped B=4096 auto", dict(**shared)),
        ("shared+capped B=16384 auto", dict(batch_pairs=16384, **shared)),
        ("per_example+capped B=4096", dict(negative_mode="per_example")),
        ("per_example+sum B=1024", dict(negative_mode="per_example",
                                        combiner="sum", batch_pairs=1024)),
        ("shared+sum B=4096 auto", dict(combiner="sum", **shared)),
        ("shared+mean B=4096 auto", dict(combiner="mean", **shared)),
        # the round-2 failure shape: tiny pool, example-unit capping
        ("round2: shared+capped B=16384 P=64",
         dict(batch_pairs=16384, shared_pool=64, shared_pool_auto=False,
              **shared)),
        # the P_total sweep (fractions of E*K at B=4096, E=8192)
        ("P=0.2*E*K", dict(shared_pool=8192, shared_pool_auto=False,
                           shared_groups=256, **shared)),
        ("P=0.4*E*K", dict(shared_pool=16384, shared_pool_auto=False,
                           shared_groups=256, **shared)),
        ("P=0.8*E*K (auto point)", dict(shared_pool=32768,
                                        shared_pool_auto=False,
                                        shared_groups=256, **shared)),
    ]
    for name, kw in configs:
        cfg = SGNSConfig(dim=200, num_iters=args.epochs, **kw)
        emb, l0, l1 = train(corpus, cfg, args.epochs)
        auc = holdout_auc(corpus.vocab, emb, split)
        rows.append(
            {"config": name, "loss_first": round(l0, 4),
             "loss_last": round(l1, 4) if np.isfinite(l1) else "diverged",
             "holdout_cos_auc": auc}
        )
        log(f"{name:42s} loss {l0:.3f}->{l1:.3f} AUC {auc}")
    return rows


def suite_groups(args) -> list:
    corpus, split = load_holdout(args.data_dir)
    vocab_p, corpus_p = planted_corpus()
    rows = []
    for sub in (32, 64, 128, 256):
        # fixed total pool P = 4E on both corpora
        cfg = SGNSConfig(dim=200, num_iters=args.epochs,
                         negative_mode="shared",
                         shared_groups=8192 // sub, shared_pool=32768,
                         shared_pool_auto=False)
        emb, _, l1 = train(corpus, cfg, args.epochs)
        auc = holdout_auc(corpus.vocab, emb, split)
        cfg_p = SGNSConfig(dim=64, num_iters=20, batch_pairs=1024,
                           negative_mode="shared",
                           shared_groups=2048 // sub, shared_pool=8192,
                           shared_pool_auto=False)
        emb_p, _, _ = train(corpus_p, cfg_p, 20)
        intra, inter = cluster_cosines(vocab_p, emb_p)
        rows.append({"sub_batch": sub, "holdout_cos_auc": auc,
                     "planted_intra": round(intra, 3),
                     "planted_inter": round(inter, 3)})
        log(f"sub={sub}: AUC {auc} intra {intra:.3f} inter {inter:.3f}")
    return rows


def suite_frontier(args) -> list:
    corpus = synthetic_big()
    corpus_r, split = load_holdout(args.data_dir)
    rows = []
    configs = [
        ("stratified (default)", dict(negative_mode="stratified")),
        ("shared auto (P=0.8*E*K)", dict(negative_mode="shared")),
        ("P=0.4*E*K", dict(negative_mode="shared", shared_pool=65536,
                           shared_pool_auto=False, shared_groups=1024)),
        ("P=0.2*E*K", dict(negative_mode="shared", shared_pool=32768,
                           shared_pool_auto=False, shared_groups=1024)),
        ("per_example", dict(negative_mode="per_example")),
        ("round2 broken (P=64)", dict(negative_mode="shared", shared_pool=64,
                                      shared_pool_auto=False)),
    ]
    for name, kw in configs:
        cfg = SGNSConfig(dim=200, num_iters=3, batch_pairs=16384, **kw)
        _, _, _, dt = train_timed(corpus, cfg, 3)
        rate = 2 * (corpus.num_pairs // 16384) * 16384 / dt
        # quality on the real corpus at the same relative pool settings
        cfg_r = SGNSConfig(dim=200, num_iters=args.epochs, **{
            **kw,
            **({"shared_pool": kw["shared_pool"] // 4,
                "shared_groups": 256}
               if "shared_pool" in kw and kw["shared_pool"] > 64 else {}),
        })
        emb, l0, l1 = train(corpus_r, cfg_r, args.epochs)
        auc = holdout_auc(corpus_r.vocab, emb, split)
        rows.append({"config": name, "pairs_per_sec_M": round(rate / 1e6, 2),
                     "holdout_cos_auc": auc,
                     "loss_last": round(l1, 4) if np.isfinite(l1) else "div"})
        log(f"{name:24s} {rate/1e6:6.2f}M pairs/s AUC {auc}")
    return rows


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", choices=("matrix", "groups", "frontier"),
                    default="matrix")
    ap.add_argument("--epochs", type=int, default=50)
    ap.add_argument("--data-dir", default="/root/reference/predictionData")
    ap.add_argument("--out", default="-")
    args = ap.parse_args()

    rows = {"matrix": suite_matrix, "groups": suite_groups,
            "frontier": suite_frontier}[args.suite](args)
    payload = json.dumps({"suite": args.suite, "epochs": args.epochs,
                          "rows": rows}, indent=1)
    if args.out == "-":
        print(payload, file=sys.stdout)
    else:
        with open(args.out, "w") as f:
            f.write(payload)


if __name__ == "__main__":
    main()
