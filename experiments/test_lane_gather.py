"""Verify the bucketed lane-gather primitive: dynamic_gather along lanes
(dim=1) with operand (D, 128) per bucket, plus the one-hot MXU lane-scatter.

Timing runs each kernel inside a lax.scan (table as carry) to amortize the
~4ms per-dispatch tunnel overhead.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

D, V, BUCKET = 256, 24576, 128
NBUCKETS = V // BUCKET  # 192
SCAN = 100

_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def sync(x):
    return float(_sum(x))


def bench_scan(label, call, table_t, *args):
    """Time `call(table, *args)` repeated SCAN times inside one jit."""

    @jax.jit
    def loop(table_t, *args):
        def body(t, _):
            return call(t, *args), ()
        t, _ = jax.lax.scan(body, table_t, jnp.arange(SCAN))
        return t

    out = loop(table_t, *args)
    sync(out)
    t0 = time.perf_counter()
    out = loop(table_t, *args)
    sync(out)
    dt = (time.perf_counter() - t0) / SCAN
    print(f"{label:52s} {dt * 1e6:9.1f} us/call", file=sys.stderr)
    return out


# --- gather ----------------------------------------------------------------
def gather_kernel(idx_ref, table_ref, out_ref):
    idx = jnp.broadcast_to(idx_ref[0][None, :], (D, BUCKET))
    out_ref[:] = jnp.take_along_axis(table_ref[:], idx, axis=1)


def bucketed_gather(table_t, offs):
    # offs: (8*NBUCKETS, BUCKET) — row 8b holds bucket b's offsets.
    return pl.pallas_call(
        gather_kernel,
        grid=(NBUCKETS,),
        in_specs=[
            pl.BlockSpec((8, BUCKET), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, V), table_t.dtype),
    )(offs, table_t)


# --- scatter ---------------------------------------------------------------
def scatter_kernel(idx_ref, grads_ref, table_ref, out_ref):
    onehot = (
        idx_ref[0][:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (BUCKET, BUCKET), 1)
    ).astype(grads_ref.dtype)
    out_ref[:] = table_ref[:] + jnp.dot(
        grads_ref[:], onehot, preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


def bucketed_scatter(table_t, grads, offs):
    return pl.pallas_call(
        scatter_kernel,
        grid=(NBUCKETS,),
        in_specs=[
            pl.BlockSpec((8, BUCKET), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
            pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, V), table_t.dtype),
    )(offs, grads, table_t)


def copy_kernel(table_ref, out_ref):
    out_ref[:] = table_ref[:] * 1.0000001


def stream_copy(table_t):
    return pl.pallas_call(
        copy_kernel,
        grid=(NBUCKETS,),
        in_specs=[pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((D, BUCKET), lambda b: (0, b), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, V), table_t.dtype),
    )(table_t)


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    table_np = rng.randn(D, V).astype(np.float32)
    table_t = jnp.asarray(table_np)
    offs_np = rng.randint(0, BUCKET, (8 * NBUCKETS, BUCKET)).astype(np.int32)
    offs = jnp.asarray(offs_np)

    # correctness, single call
    try:
        out = jax.jit(bucketed_gather)(table_t, offs)
        got = np.asarray(out)
        ref = table_np.reshape(D, NBUCKETS, BUCKET)
        want = np.stack(
            [ref[:, b, offs_np[8 * b]] for b in range(NBUCKETS)], axis=1
        ).reshape(D, V)
        print("gather max err:", np.abs(got - want).max(), file=sys.stderr)
    except Exception as e:
        print("gather FAILED:", str(e).splitlines()[0][:200], file=sys.stderr)
        return

    grads = jnp.asarray((rng.randn(D, V) * 0.01).astype(np.float32))
    try:
        out = jax.jit(bucketed_scatter)(table_t, grads, offs)
        g_np = np.asarray(grads).reshape(D, NBUCKETS, BUCKET)
        t_np = table_np.reshape(D, NBUCKETS, BUCKET).copy()
        for b in range(NBUCKETS):
            for j in range(BUCKET):
                t_np[:, b, offs_np[8 * b, j]] += g_np[:, b, j]
        got = np.asarray(out).reshape(D, NBUCKETS, BUCKET)
        print("scatter max err:", np.abs(got - t_np).max(), file=sys.stderr)
    except Exception as e:
        print("scatter FAILED:", str(e).splitlines()[0][:200], file=sys.stderr)

    bench_scan("stream copy f32 (roofline: 25MB r + 25MB w)", stream_copy, table_t)
    bench_scan("bucketed lane-gather f32", lambda t, o: bucketed_gather(t, o), table_t, offs)
    bench_scan("bucketed onehot-scatter f32", lambda t, g, o: bucketed_scatter(t, g, o), table_t, grads, offs)

    tb = table_t.astype(jnp.bfloat16)
    try:
        bench_scan("stream copy bf16", stream_copy, tb)
        bench_scan("bucketed lane-gather bf16", lambda t, o: bucketed_gather(t, o), tb, offs)
        bench_scan(
            "bucketed onehot-scatter bf16",
            lambda t, g, o: bucketed_scatter(t, g, o),
            tb, grads.astype(jnp.bfloat16), offs,
        )
    except Exception as e:
        print("bf16 FAILED:", str(e).splitlines()[0][:200], file=sys.stderr)

    # XLA row-gather equivalent inside scan, for comparison:
    # gather 24576 rows of width 256 from a (24576, 256) table.
    table_r = jnp.asarray(table_np.T.copy())
    idx = jnp.asarray(rng.randint(0, V, V).astype(np.int32))

    def xla_gather(t, idx):
        return t.at[idx].get() * 1.0000001

    @jax.jit
    def xla_loop(t, idx):
        def body(c, _):
            return xla_gather(c, idx), ()
        t, _ = jax.lax.scan(body, t, jnp.arange(SCAN))
        return t

    out = xla_loop(table_r, idx)
    sync(out)
    t0 = time.perf_counter()
    out = xla_loop(table_r, idx)
    sync(out)
    dt = (time.perf_counter() - t0) / SCAN
    print(f"{'XLA row-gather 24576 rows (V,256)':52s} {dt * 1e6:9.1f} us/call", file=sys.stderr)


if __name__ == "__main__":
    main()
