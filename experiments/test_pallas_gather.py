"""Does Mosaic's tpu.dynamic_gather handle a vocab-scale row gather, and how
fast is it vs XLA's row gather?

Kernel: operand (M, D) in VMEM, per-row indices (M,) broadcast across lanes,
out (M, D) = operand[idx[i], :].
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import sys

M, D = 32768, 256


def gather_kernel(idx_ref, table_ref, out_ref):
    idx = idx_ref[:]                      # (M,) int32
    idx2 = jnp.broadcast_to(idx[:, None], (M, D))
    out_ref[:] = jnp.take_along_axis(table_ref[:], idx2, axis=0)


@jax.jit
def pallas_gather(idx, table):
    return pl.pallas_call(
        gather_kernel,
        out_shape=jax.ShapeDtypeStruct((M, D), table.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(idx, table)


_sum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))


def sync(x):
    return float(_sum(x))


def bench(label, fn, *args, iters=50):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:44s} {dt * 1e6:10.1f} us", file=sys.stderr)
    return out


def main():
    print("device:", jax.devices()[0], file=sys.stderr)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(M, D).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, M, M).astype(np.int32))

    out_p = bench("pallas dynamic_gather (32768,256) f32", pallas_gather, idx, table)
    out_x = bench("xla row gather (32768,256) f32", jax.jit(lambda t, i: t[i]), table, idx)
    err = float(_sum(jnp.abs(out_p - out_x)))
    print("abs diff:", err, file=sys.stderr)

    tb = table.astype(jnp.bfloat16)
    bench("pallas dynamic_gather bf16", pallas_gather, idx, tb)
    bench("xla row gather bf16", jax.jit(lambda t, i: t[i]), tb, idx)


if __name__ == "__main__":
    main()
