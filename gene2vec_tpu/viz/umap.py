"""UMAP on TPU — replaces umap-learn for the reference's plot path
(``/root/reference/src/plot_gene2vec.py:124-133``), which is unavailable
in-image (no umap-learn wheel, zero egress).

TPU-first formulation: umap-learn's per-edge negative-sampled SGD is a
CPU design — millions of tiny dependent row updates, exactly the
issue-bound access pattern this framework avoids (docs/PERF_NOTES.md).
At gene scale (N ≈ 24k) the FULL-BATCH cross-entropy gradient is two
(N, N) elementwise passes and one force matmul per iteration — the same
MXU shape as the exact t-SNE iteration (`viz/tsne.py`, 253 it/s at 24k),
so a few hundred iterations cost seconds.  The graph construction
(exact kNN via one distance matmul + top_k, smooth-kNN calibration by
vectorized binary search, probabilistic t-conorm symmetrization) matches
McInnes et al. (2018) §3; the optimizer differs from umap-learn exactly
where sampling was a CPU workaround:

* attraction: p_ij · 2ab·u^{b-1} / (1 + a·u^b), u = |y_i − y_j|²  — the
  exact CE gradient, not per-epoch edge sampling;
* repulsion: (1 − p_ij) · 2b / ((u + ε)(1 + a·u^b)), every pair every
  iteration instead of ~5 random negatives per edge — scaled by
  ``repulsion`` (γ) with the same ±4 per-coordinate gradient clip
  umap-learn applies;
* init: PCA-2 scaled to the standard 10-unit extent (deterministic; the
  reference's spectral init needs a sparse eigensolver the TPU gains
  nothing from).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.viz.tsne import _squared_distances, pca_reduce

_HIGH = jax.lax.Precision.HIGHEST


def fit_ab(
    min_dist: float = 0.1,
    spread: float = 1.0,
    fixed_b: Optional[float] = None,
) -> Tuple[float, float]:
    """Fit the low-dim kernel 1/(1 + a·d^{2b}) to the piecewise target
    exp(−(d − min_dist)/spread) for d > min_dist, 1 otherwise — the same
    least-squares fit umap-learn performs with scipy.curve_fit, done with
    a coarse grid + Gauss-Newton polish (no scipy dependency).  For the
    defaults this lands on the canonical (a ≈ 1.58, b ≈ 0.90).

    ``fixed_b`` pins the exponent and fits only ``a`` — the fast-kernel
    path pins b = 7/8 so u^b lowers to a 3-rsqrt chain instead of a
    transcendental pow per (N, N) element (see :func:`umap_layout`)."""
    d = np.linspace(0, 3.0 * spread, 300)
    target = np.where(
        d <= min_dist, 1.0, np.exp(-(d - min_dist) / spread)
    )

    def resid(a, b):
        return 1.0 / (1.0 + a * d ** (2.0 * b)) - target

    b_grid = (
        [fixed_b] if fixed_b is not None else np.linspace(0.5, 2.0, 31)
    )
    best = (1.0, 1.0, np.inf)
    for a in np.linspace(0.5, 3.0, 26):
        for b in b_grid:
            s = float(np.sum(resid(a, b) ** 2))
            if s < best[2]:
                best = (a, b, s)
    a, b = best[0], best[1]
    for _ in range(40):  # Gauss-Newton on (a, b) (or a alone)
        u = d ** (2.0 * b)
        q = 1.0 / (1.0 + a * u)
        r = q - target
        da = -u * q * q
        if fixed_b is not None:
            step_a = float(np.dot(da, r) / (np.dot(da, da) + 1e-6))
            a = float(a - step_a)
        else:
            db = -a * u * np.log(np.maximum(d, 1e-12)) * 2.0 * q * q
            J = np.stack([da, db], axis=1)
            g = J.T @ r
            H = J.T @ J + 1e-6 * np.eye(2)
            step = np.linalg.solve(H, g)
            a, b = float(a - step[0]), float(b - step[1])
            b = min(max(b, 1e-2), 4.0)
        a = min(max(a, 1e-3), 10.0)
    return a, b


@dataclasses.dataclass(frozen=True)
class UMAPConfig:
    n_neighbors: int = 15
    min_dist: float = 0.1
    spread: float = 1.0
    n_iters: int = 400
    learning_rate: float = 1.0
    repulsion: float = 1.0      # γ — weight on the (1 − p) repulsive term
    fast_kernel: bool = True    # pin b = 7/8 (a refit to the same target
                                # curve): u^b becomes u·rsqrt³(u) — the
                                # (N, N) pow was the measured iteration
                                # bottleneck at 24k (PERF_NOTES round 5).
                                # False restores the exact 2-parameter fit.
    pca_dims: int = 50          # high-dim pre-reduction (t-SNE parity)
    init_scale: float = 10.0    # PCA-2 init rescaled to this max-extent
    seed: int = 0
    compute_dtype: str = "float32"  # (N, N) pass width; reductions f32


def _smooth_knn_weights(
    knn_d: jax.Array, n_neighbors: int, iters: int = 64
) -> jax.Array:
    """Per-point sigma binary search (smooth-kNN): find sigma_i with
    sum_j exp(−max(d_ij − rho_i, 0)/sigma_i) = log2(k); returns the
    (N, k) membership weights.  rho_i = nearest-neighbor distance."""
    rho = knn_d[:, :1]
    target = jnp.log2(jnp.float32(n_neighbors))
    shifted = jnp.maximum(knn_d - rho, 0.0)

    def body(carry, _):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        val = jnp.sum(jnp.exp(-shifted / mid), axis=1, keepdims=True)
        hi = jnp.where(val > target, mid, hi)
        lo = jnp.where(val > target, lo, mid)
        return (lo, hi), None

    n = knn_d.shape[0]
    init = (
        jnp.full((n, 1), 1e-6, jnp.float32),
        jnp.full((n, 1), 1e3, jnp.float32),
    )
    (lo, hi), _ = jax.lax.scan(body, init, None, length=iters)
    sigma = 0.5 * (lo + hi)
    return jnp.exp(-shifted / sigma)


def _fuzzy_graph(x: jax.Array, n_neighbors: int) -> jax.Array:
    """Dense symmetrized fuzzy simplicial weights P (N, N): exact kNN via
    one (N, N) distance pass + top_k, smooth-kNN weights, probabilistic
    t-conorm P = W + Wᵀ − W∘Wᵀ."""
    n = x.shape[0]
    d2 = _squared_distances(x)
    # self is not a neighbor; mask (never add) the diagonal — `d2 +
    # eye*inf` makes every OFF-diagonal entry 0*inf = NaN under eager/
    # disable_jit, where the multiply isn't fused away
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    neg_d2, idx = jax.lax.top_k(-d2, n_neighbors)
    knn_d = jnp.sqrt(jnp.maximum(-neg_d2, 0.0))
    w = _smooth_knn_weights(knn_d, n_neighbors)
    dense = jnp.zeros((n, n), jnp.float32)
    dense = dense.at[jnp.arange(n)[:, None], idx].set(w.astype(jnp.float32))
    return dense + dense.T - dense * dense.T


# module-level binding: a per-call ``jax.jit(_fuzzy_graph, ...)`` wrapper
# is a fresh callable each umap_layout() invocation and always misses the
# jit cache (graftcheck jit-recompile-hazard; same recipe as
# viz/tsne.py's _calibrate_points)
_fuzzy_graph_jit = jax.jit(_fuzzy_graph, static_argnums=1)


def umap_layout(
    emb: np.ndarray,
    config: UMAPConfig = UMAPConfig(),
    callback=None,
) -> np.ndarray:
    """(N, D) embedding → (N, 2) UMAP layout on the default device."""
    cfg = config
    a, b = fit_ab(
        cfg.min_dist, cfg.spread,
        fixed_b=0.875 if cfg.fast_kernel else None,
    )
    x = pca_reduce(np.asarray(emb, np.float32), cfg.pca_dims)
    # umap-learn clamps k to N-1 (with a warning) — top_k would error on
    # a matrix smaller than the neighbor count
    n_neighbors = max(1, min(int(cfg.n_neighbors), x.shape[0] - 1))
    p = _fuzzy_graph_jit(jnp.asarray(x), n_neighbors)

    y0 = pca_reduce(x, 2)
    y0 = y0 / max(np.abs(y0).max(), 1e-12) * cfg.init_scale
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    @jax.jit
    def iterate(y, p, it):
        # The iteration is HBM-bound, so ONE (N, N) array materializes:
        # at 2 components the pairwise distance is a 2-term broadcast sum
        # (no matmul), which lets XLA fuse distances → kernel → coef into
        # a single pass (the viz/tsne.py round-4 recipe, 49 → 253 it/s),
        # and a ones-column folds the rowsum into the force matmul so
        # coef is read exactly once.
        yc = y.astype(compute_dtype)
        y0, y1 = yc[:, 0], yc[:, 1]
        u = (y0[:, None] - y0[None, :]) ** 2 + (y1[:, None] - y1[None, :]) ** 2
        pb = p.astype(compute_dtype)
        um = jnp.maximum(u, 1e-12)
        if cfg.fast_kernel:
            # u^{7/8} = u · u^{−1/8}, three rsqrts — no transcendental pow
            ub = um * jax.lax.rsqrt(jax.lax.rsqrt(jax.lax.rsqrt(um)))
        else:
            ub = jnp.power(um, jnp.asarray(b, compute_dtype))
        q_inv = 1.0 + jnp.asarray(a, compute_dtype) * ub
        attract = (2.0 * a * b) * ub / jnp.maximum(u, 1e-12) / q_inv * pb
        repel = (
            jnp.asarray(2.0 * b * cfg.repulsion, compute_dtype)
            / ((u + 1e-3) * q_inv)
            * (1.0 - pb)
        )
        n = y.shape[0]
        coef = (attract - repel) * (1.0 - jnp.eye(n, dtype=compute_dtype))
        # force_i = Σ_j coef_ij (y_i − y_j) = rowsum_i·y_i − (coef @ y)_i
        aug = jnp.concatenate(
            [yc, jnp.ones((n, 1), compute_dtype)], axis=1
        )
        fr = jnp.matmul(coef, aug, precision=_HIGH).astype(jnp.float32)
        force = fr[:, 2:3] * y - fr[:, :2]
        # umap-learn clips per-coordinate sample gradients to ±4; the
        # full-batch analogue bounds each point's aggregated step
        force = jnp.clip(force, -4.0, 4.0)
        lr = cfg.learning_rate * (1.0 - it / cfg.n_iters)
        return y - lr * force

    y = jnp.asarray(y0, jnp.float32)
    for it in range(cfg.n_iters):
        y = iterate(y, p, jnp.float32(it))
        if callback is not None and (it + 1) % 50 == 0:
            callback(it + 1, np.asarray(y))
    out = np.asarray(y, np.float32)
    if not np.isfinite(out).all():
        raise FloatingPointError("UMAP layout diverged (non-finite coords)")
    return out
