"""GeneView dashboard — ``src/gene2vec_dash_app.py`` parity.

The reference's Dash app loads the plotly-JSON scatter exported by the plot
generator, shows GO-term and Reactome-pathway dropdowns in a fixed dark
sidebar (Darkly theme + ``src/assets/bootstrap.css`` dropdown overrides),
recolors member genes on selection (active yellow, inactive
near-invisible, ``src/gene2vec_dash_app.py:65,189-235``), and prints a
description panel per selected term (``:237-281``).

Design here: the data/logic layer — GO-DAG parsing (``go-basic.obo``),
``gene2go`` annotations, the Reactome table, marker restyling, and the
description text — is dependency-free and unit-tested (the formats are
plain text; goatools/ete3 are optional conveniences, not requirements).
Only ``serve()`` needs dash (gated); the dark styling ships as our own
``assets/geneview.css``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

#: the reference's exact marker colors (``src/gene2vec_dash_app.py:65``)
ACTIVE_COLOR = "rgba(226,255,0,1)"
INACTIVE_COLOR = "rgba(10, 10, 10, 0.01)"
BASE_COLOR = "#636efa"


def load_figure_json(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def parse_annotation_table(
    path: str, id_col: int = 0, gene_col: int = 1, name_col: Optional[int] = 2
) -> Tuple[Dict[str, List[str]], Dict[str, str]]:
    """TSV of (term id, gene, [description]) rows → (term → genes,
    term → description)."""
    members: Dict[str, List[str]] = {}
    descriptions: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) <= max(id_col, gene_col):
                continue
            term, gene = parts[id_col], parts[gene_col]
            if not term or not gene:
                continue
            members.setdefault(term, []).append(gene)
            if name_col is not None and len(parts) > name_col:
                descriptions.setdefault(term, parts[name_col])
    return members, descriptions


def load_gmt_terms(path: str) -> Tuple[Dict[str, List[str]], Dict[str, str]]:
    """MSigDB .gmt as (term → genes, term → url/description)."""
    members: Dict[str, List[str]] = {}
    descriptions: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            fields = line.rstrip("\n").split("\t")
            if len(fields) < 3:
                continue
            members[fields[0]] = [g for g in fields[2:] if g]
            descriptions[fields[0]] = fields[1]
    return members, descriptions


def highlight_genes(figure: dict, selected: Sequence[str]) -> dict:
    """Recolor the scatter: selected genes active-yellow, the rest
    near-invisible; empty selection restores the base color.  Pure function
    over the figure dict (the reference mutates the same fields in its
    callback, ``src/gene2vec_dash_app.py:189-235``)."""
    out = json.loads(json.dumps(figure))  # deep copy
    sel = set(selected)
    for trace in out.get("data", []):
        genes = trace.get("customdata") or trace.get("text") or []
        if not sel:
            trace.setdefault("marker", {})["color"] = BASE_COLOR
            continue
        trace.setdefault("marker", {})["color"] = [
            ACTIVE_COLOR if g in sel else INACTIVE_COLOR for g in genes
        ]
    return out


def term_options(
    members: Dict[str, List[str]], descriptions: Dict[str, str]
) -> List[dict]:
    """Dropdown options sorted by term id."""
    return [
        {
            "label": f"{term} — {descriptions.get(term, '')}".rstrip(" —"),
            "value": term,
        }
        for term in sorted(members)
    ]


@dataclasses.dataclass
class GOTerm:
    """One ``[Term]`` of a GO DAG with the fields the description panel
    shows (``src/gene2vec_dash_app.py:252-257``): level = shortest
    distance to a root, depth = longest."""

    id: str
    name: str = ""
    namespace: str = ""
    parents: Tuple[str, ...] = ()
    level: int = 0
    depth: int = 0


def parse_obo(path: str) -> Dict[str, GOTerm]:
    """Dependency-free ``go-basic.obo`` parser: ``[Term]`` stanzas with
    id/name/namespace/is_a, levels and depths computed over the ``is_a``
    DAG.  goatools' GODag offers the same (and is used by the reference,
    ``src/gene2vec_dash_app.py:30-44``); the format is 4 fields of plain
    text, so the framework does not require the package.  Obsolete terms
    are dropped, ``alt_id``s alias their term."""
    terms: Dict[str, GOTerm] = {}
    alt: Dict[str, str] = {}
    cur: Optional[dict] = None

    def flush(c):
        if c is None or "id" not in c or c.get("obsolete"):
            return
        terms[c["id"]] = GOTerm(
            id=c["id"],
            name=c.get("name", ""),
            namespace=c.get("namespace", ""),
            parents=tuple(c.get("is_a", ())),
        )
        for a in c.get("alt_id", ()):
            alt[a] = c["id"]

    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line == "[Term]":
                flush(cur)
                cur = {}
            elif line.startswith("[") and line.endswith("]"):  # [Typedef]…
                flush(cur)
                cur = None
            elif cur is not None and ": " in line:
                key, _, val = line.partition(": ")
                if key == "id":
                    cur["id"] = val
                elif key == "name":
                    cur["name"] = val
                elif key == "namespace":
                    cur["namespace"] = val
                elif key == "is_a":
                    cur.setdefault("is_a", []).append(val.split(" ! ")[0])
                elif key == "alt_id":
                    cur.setdefault("alt_id", []).append(val)
                elif key == "is_obsolete" and val == "true":
                    cur["obsolete"] = True
    flush(cur)

    level: Dict[str, int] = {}
    depth: Dict[str, int] = {}

    def walk(tid: str, acc: Dict[str, int], agg) -> int:
        if tid in acc:
            return acc[tid]
        acc[tid] = 0  # cycle guard (GO is acyclic; malformed input isn't)
        ps = [p for p in terms[tid].parents if p in terms]
        acc[tid] = agg(walk(p, acc, agg) for p in ps) + 1 if ps else 0
        return acc[tid]

    for tid, term in terms.items():
        terms[tid] = dataclasses.replace(
            term, level=walk(tid, level, min), depth=walk(tid, depth, max)
        )
    for a, tid in alt.items():
        terms.setdefault(a, terms[tid])
    return terms


def parse_gene2go(
    path: str, taxids: Optional[Sequence[int]] = None
) -> Dict[str, List[str]]:
    """NCBI ``gene2go`` TSV → GO id → member gene (Entrez) ids, optionally
    filtered to ``taxids`` (the reference filters to the figure's Tax ID
    column via goatools, ``src/gene2vec_dash_app.py:38-41``)."""
    keep = {str(t) for t in taxids} if taxids else None
    # dict-as-ordered-set per term: broad GO terms collect >10k genes and
    # real gene2go files are tens of millions of rows — list membership
    # scans would be quadratic per term
    members: Dict[str, dict] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if line.startswith("#") or not line.strip():
                continue
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 3:
                continue
            tax, gene, go_id = parts[0], parts[1], parts[2]
            if keep is not None and tax not in keep:
                continue
            members.setdefault(go_id, {})[gene] = None
    return {go_id: list(genes) for go_id, genes in members.items()}


def load_reactome_table(
    path: str, species: Optional[Sequence[str]] = None
) -> Tuple[Dict[str, List[str]], Dict[str, dict]]:
    """``NCBI2Reactome_All_Levels.txt`` (entrez, reactome id, url, name,
    evidence, species) → (pathway → entrez members, pathway → info);
    optional species filter (the reference translates the figure's taxids
    via ete3 and filters, ``src/gene2vec_dash_app.py:84-96``)."""
    keep = set(species) if species else None
    members: Dict[str, dict] = {}  # dict-as-ordered-set (see parse_gene2go)
    info: Dict[str, dict] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 6:
                continue
            entrez, rid, url, name, _, sp = parts[:6]
            if keep is not None and sp not in keep:
                continue
            members.setdefault(rid, {})[entrez] = None
            info.setdefault(
                rid, {"name": name, "url": url, "species": sp}
            )
    return {rid: list(g) for rid, g in members.items()}, info


def fetch_neighbors(
    serve_url: str, gene: str, k: int = 10, timeout_s: float = 2.0
) -> Optional[List[Tuple[str, float]]]:
    """Top-k neighbor list for ``gene`` from a running serve instance
    (``GET /v1/similar?gene=...&k=...``, see docs/SERVING.md).  Returns
    ``None`` on ANY failure — server down, unknown gene, bad URL — so
    callers fall back to the figure-json path instead of crashing the
    dashboard (stdlib urllib only; no client dependency)."""
    import urllib.parse
    import urllib.request

    url = (
        f"{serve_url.rstrip('/')}/v1/similar?"
        + urllib.parse.urlencode({"gene": gene, "k": k})
    )
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.load(resp)
        return [
            (n["gene"], float(n["score"]))
            for n in doc["results"][0]["neighbors"]
        ]
    except Exception:
        return None


def load_graph_neighbors(graph_dir: str):
    """Neighbor lookup over a PRECOMPUTED kNN graph — a finalized
    ``knn_graph`` batch artifact (gene2vec_tpu/batch/, docs/BATCH.md)
    — as a ``(gene, k) -> [(gene, score), ...] | None`` callable with
    the same contract as :func:`fetch_neighbors`.  The offline
    fallback for dashboards with no live ``--serve-url``: the graph
    was built through the serving stack, so the neighbors shown are
    exactly what the fleet would have answered.  Loads lazily on the
    first lookup and returns ``None`` per-gene on any failure
    (missing/corrupt artifact, unknown gene) so the dashboard
    degrades instead of crashing."""
    state = {}

    def lookup(gene, k=10):
        if "graph" not in state:
            try:
                from gene2vec_tpu.batch.artifact import load_graph

                tokens, ids, scores, _meta = load_graph(graph_dir)
                state["graph"] = (
                    {t: i for i, t in enumerate(tokens)},
                    tokens, ids, scores,
                )
            except Exception:
                state["graph"] = None
        if state["graph"] is None:
            return None
        index, tokens, ids, scores = state["graph"]
        row = index.get(gene)
        if row is None:
            return None
        n = min(int(k), ids.shape[1])
        return [
            (tokens[int(ids[row, j])], float(scores[row, j]))
            for j in range(n)
        ]

    return lookup


def go_description(
    term: GOTerm, member_genes: Sequence[str], gene_rep: str = "Gene Symbol"
) -> str:
    """The GO description panel text (``src/gene2vec_dash_app.py:252-257``)."""
    return (
        f"GO ID: {term.id}\nName: {term.name}\n"
        f"Namespace: {term.namespace}\nLevel: {term.level}\n"
        f"Depth: {term.depth}\n{gene_rep}: {', '.join(member_genes)}"
    )


def reactome_description(
    rid: str, info: dict, member_genes: Sequence[str],
    gene_rep: str = "Gene Symbol",
) -> str:
    """The Reactome description panel text (``:267-276``)."""
    return (
        f"Reactome ID: {rid}\nName: {info.get('name', '')}\n"
        f"Species: {info.get('species', '')}\nurl: {info.get('url', '')}\n"
        f"{gene_rep}: {', '.join(member_genes)}"
    )


def go_dag_descriptions(obo_path: str) -> Dict[str, str]:
    """GO id → name.  Uses goatools when installed (the reference's path,
    ``src/gene2vec_dash_app.py:30-44``); otherwise the built-in parser."""
    try:
        from goatools.obo_parser import GODag

        dag = GODag(obo_path, prt=None)
        return {go_id: term.name for go_id, term in dag.items()}
    except ImportError:
        return {tid: t.name for tid, t in parse_obo(obo_path).items()}


def build_app_state(
    figure_json: str,
    go_table: Optional[str] = None,
    reactome_table: Optional[str] = None,
    go_obo: Optional[str] = None,
    gene2go: Optional[str] = None,
    reactome_file: Optional[str] = None,
    taxids: Optional[Sequence[int]] = None,
    species: Optional[Sequence[str]] = None,
) -> dict:
    """Everything ``serve`` shows, assembled without dash: the figure, the
    per-source term→members tables, term descriptions (rich GOTerm/Reactome
    info when the obo/gene2go/reactome files are given, flat TSV tables
    otherwise), and dropdown options.  Unit-testable."""
    state = {
        "figure": load_figure_json(figure_json),
        "sources": {},  # kind -> {"members", "describe", "options"}
    }

    def add(kind, members, describe, label_desc):
        state["sources"][kind] = {
            "members": members,
            "describe": describe,
            "options": term_options(members, label_desc),
        }

    if go_obo and gene2go:
        dag = parse_obo(go_obo)
        members = parse_gene2go(gene2go, taxids)
        members = {t: g for t, g in members.items() if t in dag}

        def describe_go(term, genes, dag=dag):
            return go_description(dag[term], genes)

        add("GO", members, describe_go, {t: dag[t].name for t in members})
    elif go_table:
        members, desc = parse_annotation_table(go_table)
        add("GO", members, lambda t, g, d=desc: d.get(t, ""), desc)
    if reactome_file:
        members, info = load_reactome_table(reactome_file, species)

        def describe_r(term, genes, info=info):
            return reactome_description(term, info.get(term, {}), genes)

        add("Reactome", members, describe_r,
            {t: info[t]["name"] for t in members})
    elif reactome_table:
        members, desc = parse_annotation_table(reactome_table)
        add("Reactome", members, lambda t, g, d=desc: d.get(t, ""), desc)
    return state


def serve(
    figure_json: str,
    go_table: Optional[str] = None,
    reactome_table: Optional[str] = None,
    go_obo: Optional[str] = None,
    gene2go: Optional[str] = None,
    reactome_file: Optional[str] = None,
    taxids: Optional[Sequence[int]] = None,
    species: Optional[Sequence[str]] = None,
    host: str = "127.0.0.1",
    port: int = 8050,
    debug: bool = False,
    run: bool = True,
    serve_url: Optional[str] = None,
    serve_k: int = 10,
    graph_dir: Optional[str] = None,
):  # pragma: no cover - needs dash + a browser
    """Launch the GeneView dashboard (requires the dash package).

    Layout parity with the reference (``src/gene2vec_dash_app.py:100-186``):
    a fixed dark sidebar — GeneView title, Gene Ontology dropdown, Reactome
    dropdown, read-only description textarea — beside the scatter; dark
    dropdown styling ships as the package's own ``assets/geneview.css``
    (behavioral stand-in for the reference's Darkly overrides).  Pass
    ``run=False`` to get the wired app back without serving (tests).

    With ``serve_url`` (a running ``cli.serve`` instance) the sidebar
    grows a *Neighbors* search box: typing a gene highlights its live
    top-``serve_k`` cosine neighbors from ``/v1/similar`` and prints
    them in the description panel — no pre-exported similarity figure
    needed.  Lookup failures (server down, unknown gene) degrade to the
    base coloring; the figure-json annotation dropdowns keep working
    either way.

    ``graph_dir`` (a finalized ``knn_graph`` batch artifact,
    docs/BATCH.md) gives the same Neighbors box WITHOUT a live server
    — and serves as the fallback when ``serve_url`` is also set but
    unreachable: the precomputed graph answers what the fleet that
    built it would have."""
    try:
        import dash
        from dash import dcc, html
        from dash.dependencies import Input, Output, State
    except ImportError as e:
        raise ImportError(
            "the GeneView dashboard requires the dash package; the figure "
            "json/html exports from viz.plot work without it"
        ) from e

    state = build_app_state(
        figure_json, go_table, reactome_table, go_obo, gene2go,
        reactome_file, taxids, species,
    )
    figure = state["figure"]
    sources = state["sources"]

    app = dash.Dash(
        "GeneView",
        assets_folder=os.path.join(os.path.dirname(__file__), "assets"),
    )
    sidebar_children = [html.H2("GeneView", className="display-8"), html.Hr()]
    for kind, src in sources.items():
        sidebar_children += [
            html.Div(
                [
                    html.H4(
                        "Gene Ontology" if kind == "GO" else f"{kind} ID",
                        className="display-8",
                    ),
                    html.Hr(),
                    dcc.Dropdown(
                        id=f"dd-{kind.lower()}", options=src["options"]
                    ),
                ],
                className="geneview-dropdown",
            )
        ]
    neighbor_lookup = (
        load_graph_neighbors(graph_dir) if graph_dir else None
    )
    if serve_url or graph_dir:
        sidebar_children += [
            html.Div(
                [
                    html.H4("Neighbors", className="display-8"),
                    html.Hr(),
                    dcc.Input(
                        id="gene-search", type="text", debounce=True,
                        placeholder="gene symbol...",
                        className="geneview-search",
                    ),
                ],
                className="geneview-dropdown",
            )
        ]
    sidebar_children += [
        html.Div(
            [
                html.H5("Description", className="display-8"),
                html.Hr(),
                dcc.Textarea(
                    id="description", readOnly=True, value="",
                    className="geneview-description",
                ),
            ]
        )
    ]
    app.layout = html.Div(
        [
            html.Div(sidebar_children, className="geneview-sidebar"),
            dcc.Graph(
                id="scatter", figure=figure, className="geneview-graph"
            ),
        ],
        className="dash-bootstrap",
    )

    inputs = [Input(f"dd-{k.lower()}", "value") for k in sources]
    kinds = list(sources)
    if serve_url or graph_dir:
        inputs.append(Input("gene-search", "value"))

    def _selected(values):
        """(kind, term) for the triggering control; kind ``"__serve__"``
        when it was the neighbor search box; (None, None) when it was
        CLEARED (value None) — callers must reset, not no_update, or the
        near-invisible highlight state sticks forever."""
        ctx = dash.callback_context
        trigger = ctx.triggered[0]["prop_id"].split(".")[0]
        if (serve_url or graph_dir) and trigger == "gene-search":
            gene = values[-1]
            return ("__serve__", gene.strip()) if gene and gene.strip() \
                else (None, None)
        for kind, value in zip(kinds, values):
            if f"dd-{kind.lower()}" == trigger and value:
                return kind, value
        return None, None

    # both callbacks (figure + description) fire per keystroke; a short
    # TTL memo makes them share ONE /v1/similar round trip — and caches
    # failures too, so an unreachable server blocks one timeout, not two
    _neighbor_memo: Dict[str, tuple] = {}

    def _neighbor_genes(gene):
        """The gene + its live neighbors, or None when the serve lookup
        failed (fall back to base coloring rather than erroring)."""
        import time

        now = time.monotonic()
        cached = _neighbor_memo.get(gene)
        if cached is not None and now - cached[0] < 5.0:
            hits = cached[1]
        else:
            hits = (
                fetch_neighbors(serve_url, gene, serve_k)
                if serve_url else None
            )
            if hits is None and neighbor_lookup is not None:
                # no live server (or it failed): the precomputed
                # batch-built graph answers instead
                hits = neighbor_lookup(gene, serve_k)
            _neighbor_memo[gene] = (now, hits)
            while len(_neighbor_memo) > 64:
                _neighbor_memo.pop(next(iter(_neighbor_memo)))
        if hits is None:
            return None, None
        return [gene] + [g for g, _ in hits], hits

    if sources or serve_url or graph_dir:
        # figure-only dashboards have no callbacks
        @app.callback(
            Output("scatter", "figure"), inputs, State("scatter", "figure")
        )
        def show_genes(*args):
            values, fig = args[:-1], args[-1]
            kind, term = _selected(values)
            if kind is None:  # cleared: restore the base coloring
                return highlight_genes(fig or figure, [])
            if kind == "__serve__":
                genes, _ = _neighbor_genes(term)
                return highlight_genes(fig or figure, genes or [])
            genes = sources[kind]["members"].get(term, [])
            return highlight_genes(fig or figure, genes)

        @app.callback(Output("description", "value"), inputs)
        def show_description(*values):
            kind, term = _selected(values)
            if kind is None:
                return ""
            if kind == "__serve__":
                genes, hits = _neighbor_genes(term)
                if hits is None:
                    source = serve_url or f"graph {graph_dir}"
                    return (
                        f"{term}: neighbor lookup failed "
                        f"({source} unreachable or unknown gene)"
                    )
                return f"Nearest to {term}:\n" + "\n".join(
                    f"{g}\t{s:.4f}" for g, s in hits
                )
            genes = sources[kind]["members"].get(term, [])
            return sources[kind]["describe"](term, genes)

    if run:
        app.run(host=host, port=port, debug=debug)
    return app
