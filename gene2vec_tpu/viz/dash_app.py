"""GeneView dashboard — ``src/gene2vec_dash_app.py`` parity.

The reference's Dash app loads the plotly-JSON scatter exported by the plot
generator, adds GO-term and Reactome-pathway dropdowns, and recolors member
genes on selection (active yellow, inactive near-invisible,
``src/gene2vec_dash_app.py:65,189-235``).

The data/logic layer here (annotation tables, marker restyling) is
dependency-free and unit-tested; only ``serve()`` needs dash (gated), and
GO-DAG/taxid enrichment needs goatools/ete3 (gated separately).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

ACTIVE_COLOR = "#fcf803"          # the reference's highlight yellow
INACTIVE_COLOR = "rgba(100, 100, 100, 0.12)"
BASE_COLOR = "#636efa"


def load_figure_json(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def parse_annotation_table(
    path: str, id_col: int = 0, gene_col: int = 1, name_col: Optional[int] = 2
) -> Tuple[Dict[str, List[str]], Dict[str, str]]:
    """TSV of (term id, gene, [description]) rows → (term → genes,
    term → description)."""
    members: Dict[str, List[str]] = {}
    descriptions: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) <= max(id_col, gene_col):
                continue
            term, gene = parts[id_col], parts[gene_col]
            if not term or not gene:
                continue
            members.setdefault(term, []).append(gene)
            if name_col is not None and len(parts) > name_col:
                descriptions.setdefault(term, parts[name_col])
    return members, descriptions


def load_gmt_terms(path: str) -> Tuple[Dict[str, List[str]], Dict[str, str]]:
    """MSigDB .gmt as (term → genes, term → url/description)."""
    members: Dict[str, List[str]] = {}
    descriptions: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            fields = line.rstrip("\n").split("\t")
            if len(fields) < 3:
                continue
            members[fields[0]] = [g for g in fields[2:] if g]
            descriptions[fields[0]] = fields[1]
    return members, descriptions


def highlight_genes(figure: dict, selected: Sequence[str]) -> dict:
    """Recolor the scatter: selected genes active-yellow, the rest
    near-invisible; empty selection restores the base color.  Pure function
    over the figure dict (the reference mutates the same fields in its
    callback, ``src/gene2vec_dash_app.py:189-235``)."""
    out = json.loads(json.dumps(figure))  # deep copy
    sel = set(selected)
    for trace in out.get("data", []):
        genes = trace.get("customdata") or trace.get("text") or []
        if not sel:
            trace.setdefault("marker", {})["color"] = BASE_COLOR
            continue
        trace.setdefault("marker", {})["color"] = [
            ACTIVE_COLOR if g in sel else INACTIVE_COLOR for g in genes
        ]
    return out


def term_options(
    members: Dict[str, List[str]], descriptions: Dict[str, str]
) -> List[dict]:
    """Dropdown options sorted by term id."""
    return [
        {
            "label": f"{term} — {descriptions.get(term, '')}".rstrip(" —"),
            "value": term,
        }
        for term in sorted(members)
    ]


def go_dag_descriptions(obo_path: str) -> Dict[str, str]:
    """GO id → name via goatools (``src/gene2vec_dash_app.py:30-44``); gated."""
    try:
        from goatools.obo_parser import GODag
    except ImportError as e:
        raise ImportError(
            "GO-DAG descriptions require the goatools package; provide a "
            "TSV annotation table instead"
        ) from e
    dag = GODag(obo_path, prt=None)
    return {go_id: term.name for go_id, term in dag.items()}


def serve(
    figure_json: str,
    go_table: Optional[str] = None,
    reactome_table: Optional[str] = None,
    host: str = "127.0.0.1",
    port: int = 8050,
):  # pragma: no cover - needs dash + a browser
    """Launch the dashboard (requires the dash package)."""
    try:
        import dash
        from dash import dcc, html
        from dash.dependencies import Input, Output
    except ImportError as e:
        raise ImportError(
            "the GeneView dashboard requires the dash package; the figure "
            "json/html exports from viz.plot work without it"
        ) from e

    figure = load_figure_json(figure_json)
    tables = {}
    if go_table:
        tables["GO"] = parse_annotation_table(go_table)
    if reactome_table:
        tables["Reactome"] = parse_annotation_table(reactome_table)

    app = dash.Dash("GeneView")
    dropdowns = []
    for kind, (members, desc) in tables.items():
        dropdowns.append(html.Label(kind))
        dropdowns.append(
            dcc.Dropdown(
                id=f"dd-{kind.lower()}",
                options=term_options(members, desc),
                multi=False,
            )
        )
    app.layout = html.Div(
        [
            html.H2("GeneView — gene2vec embedding"),
            *dropdowns,
            dcc.Graph(id="scatter", figure=figure),
            html.Pre(id="description"),
        ]
    )

    for kind, (members, desc) in tables.items():
        @app.callback(
            Output("scatter", "figure", allow_duplicate=True),
            Output("description", "children", allow_duplicate=True),
            Input(f"dd-{kind.lower()}", "value"),
            prevent_initial_call=True,
        )
        def _update(term, members=members, desc=desc):
            if not term:
                return highlight_genes(figure, []), ""
            return (
                highlight_genes(figure, members.get(term, [])),
                desc.get(term, ""),
            )

    app.run(host=host, port=port)
    return app
