"""Visualization subsystem (reference L5, ``src/tsne_multi_core.py`` /
``src/plot_gene2vec.py`` / ``src/GTExFigure.py`` / ``src/gene2vec_dash_app.py``).

The 2-D projection (the compute-heavy part) runs on TPU as exact t-SNE
matmuls; figure/dashboard rendering is CPU-side and gated on the optional
plotting stacks (matplotlib in-image; plotly/umap/dash/mygene/goatools
import-gated with actionable errors).
"""

from gene2vec_tpu.viz.tsne import TSNE, pca_reduce  # noqa: F401
