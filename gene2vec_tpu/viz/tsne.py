"""Exact t-SNE on TPU — replaces MulticoreTSNE (C++/OpenMP Barnes-Hut).

The reference projects the ~24k-gene embedding with an external C++
Barnes-Hut library across 6 processes x 32 threads, one process per
iteration count (``src/tsne_multi_core.py:42-52``).  At N ≈ 24k the exact
O(N²) formulation is a pair of (N, N) matmuls per iteration — a textbook
MXU workload — so TPU needs neither the Barnes-Hut approximation nor the
process pool: ONE run snapshots the layout at every requested iteration
count (the reference's 6 runs redo all earlier work each time).

Implementation: standard t-SNE (van der Maaten & Hinton 2008) —
perplexity-calibrated Gaussian conditionals via vectorized binary search,
symmetrized P with early exaggeration, Student-t low-dim kernel, gradient
with per-coordinate adaptive gains and switched momentum, all inside jitted
``lax.fori_loop`` segments so snapshots cost one host sync each.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import TSNEConfig

_HIGH = jax.lax.Precision.HIGHEST


def pca_reduce(x: np.ndarray, dims: int = 50) -> np.ndarray:
    """Top-``dims`` principal components (the reference's PCA-50 pre-step,
    ``src/tsne_multi_core.py:31-33``).  Covariance is d x d (d = emb dim,
    e.g. 200), so eigh is trivial."""
    x = np.asarray(x, np.float64)
    x = x - x.mean(axis=0)
    cov = x.T @ x / max(x.shape[0] - 1, 1)
    vals, vecs = np.linalg.eigh(cov)
    top = vecs[:, np.argsort(vals)[::-1][: min(dims, x.shape[1])]]
    return (x @ top).astype(np.float32)


def _squared_distances(x: jax.Array) -> jax.Array:
    sq = jnp.sum(x * x, axis=1)
    d = sq[:, None] - 2.0 * jnp.matmul(x, x.T, precision=_HIGH) + sq[None, :]
    return jnp.maximum(d, 0.0)


def _calibrate_p(
    d2: jax.Array, perplexity: float, iters: int = 50
) -> jax.Array:
    """Per-point beta binary search so each conditional hits the target
    perplexity; returns the symmetrized, normalized P."""
    n = d2.shape[0]
    target = jnp.asarray(np.log(perplexity), jnp.float32)
    eye = jnp.eye(n, dtype=bool)

    def entropy_and_p(beta):
        w = jnp.where(eye, 0.0, jnp.exp(-d2 * beta[:, None]))
        sum_w = jnp.maximum(jnp.sum(w, axis=1), 1e-12)
        # H_i = log Z_i + beta_i * <d²>_i   (Shannon entropy of conditional)
        h = jnp.log(sum_w) + beta * jnp.sum(d2 * w, axis=1) / sum_w
        return h, w / sum_w[:, None]

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > target          # entropy too high → beta up
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(
            jnp.isinf(hi), beta * 2.0, (lo + hi) / 2.0
        )
        return beta, lo, hi

    beta0 = jnp.ones(n, jnp.float32)
    lo0 = jnp.zeros(n, jnp.float32)
    hi0 = jnp.full(n, jnp.inf, jnp.float32)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    _, p_cond = entropy_and_p(beta)
    p = (p_cond + p_cond.T) / (2.0 * n)
    return jnp.maximum(p, 1e-12)


@functools.partial(jax.jit, static_argnums=(1,))
def _calibrate_points(x: jax.Array, perplexity: float) -> jax.Array:
    """Jitted distance + calibration pipeline.  Module-level so the
    compilation caches across ``fit`` calls (a per-fit ``jax.jit(lambda)``
    always misses the cache — new callable identity — and the eager
    fori_loop dispatches poorly over remote-device tunnels: minutes
    instead of seconds at N = 24k)."""
    return _calibrate_p(_squared_distances(x), perplexity)


@dataclasses.dataclass
class TSNE:
    """Exact t-SNE with snapshot support.

    ``fit(x, snapshot_iters=[...])`` returns {n_iter: (N, 2) layout} — the
    multi-iteration sweep of ``src/tsne_multi_core.py`` in one run.
    """

    config: TSNEConfig = dataclasses.field(default_factory=TSNEConfig)
    n_components: int = 2

    def fit(
        self,
        x: np.ndarray,
        snapshot_iters: Optional[Sequence[int]] = None,
        log=print,
    ) -> Dict[int, np.ndarray]:
        cfg = self.config
        snapshots = sorted(set(snapshot_iters or [cfg.n_iter]))
        x = np.asarray(x, np.float32)
        if cfg.pca_dims and x.shape[1] > cfg.pca_dims:
            x = pca_reduce(x, cfg.pca_dims)

        p = _calibrate_points(jnp.asarray(x), cfg.perplexity)

        n = x.shape[0]
        rng = np.random.RandomState(cfg.seed)
        y = jnp.asarray(rng.randn(n, self.n_components) * 1e-4, jnp.float32)
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)

        out: Dict[int, np.ndarray] = {}
        done = 0
        for snap in snapshots:
            if snap > done:
                y, vel, gains = _segment(
                    cfg, self.n_components, p, y, vel, gains, done,
                    snap - done, n,
                )
                done = snap
            out[snap] = np.asarray(y)
            log(f"t-SNE: {done} iterations done (snapshot)")
        return out


@functools.partial(jax.jit, static_argnums=(0, 1, 7, 8))
def _segment(cfg: TSNEConfig, k: int, p, y, vel, gains, start, steps, n):
    """One jitted run of ``steps`` gradient iterations.  Module-level with
    the (frozen, hashable) config as a static argument, so repeated fits
    — including benchmark warm-up runs — share one compilation per
    (config, components, steps, n).

    The (N, N) arrays dominate HBM traffic at N ≈ 24k (2.4 GB each in
    f32), so the body materializes only TWO per iteration (num, g):

    * the Student-t kernel's diagonal (num_ii = 1) is NOT masked —
      diagonal terms cancel exactly in the gradient (the j = i term of
      Σ_j g_ij (y_i − y_j) is zero), and the partition sum just
      subtracts the n diagonal ones — which drops the per-iteration
      (1 − eye) mask pass of the classic formulation;
    * diag(rowsum) − g is never built: grad = rowsum(g)·y − g @ y.

    ``compute_dtype="bfloat16"`` halves (N, N) bytes; reductions stay
    f32 (a bf16 sum over N² elements loses the partition function).
    """
    dtype = jnp.dtype(cfg.compute_dtype)
    p = p.astype(dtype)

    def body(i, carry):
        y, vel, gains = carry
        it = start + i
        exaggeration = jnp.where(
            it < cfg.exaggeration_iters, cfg.early_exaggeration, 1.0
        ).astype(dtype)
        momentum = jnp.where(
            it < cfg.momentum_switch_iter,
            cfg.momentum_start,
            cfg.momentum_final,
        )
        # Student-t kernel in ONE fused (N, N) pass: at the layout's
        # tiny k (2 components) the y·yᵀ "matmul" is a k-term
        # broadcast sum, so spelling it elementwise lets XLA fuse
        # distances → kernel → cast and write ONLY the dtype-width
        # num — no f32 (N, N) distance matrix ever hits HBM.  The
        # cancellation-sensitive part (sqᵢ + sqⱼ − 2·yᵢ·yⱼ for near
        # points) stays f32; only the final kernel value is cast.
        sq = jnp.sum(y * y, axis=1)                    # (N,) f32
        d2 = sq[:, None] + sq[None, :]
        for c in range(k):
            d2 = d2 - 2.0 * y[:, c : c + 1] * y[:, c]
        num = (1.0 / (1.0 + jnp.maximum(d2, 0.0))).astype(dtype)
        z = jnp.sum(num, dtype=jnp.float32) - n        # excl. diagonal
        inv_z = (1.0 / z).astype(dtype)
        g = (exaggeration * p - inv_z * num) * num     # (N, N)
        # BOTH gradient terms must see the SAME (dtype-cast) y: the
        # rowsum_i·y_i term cancels g's diagonal and bulk against
        # g @ y term-by-term, and a mixed f32/bf16 y breaks that
        # cancellation catastrophically once the layout spreads.
        # The ones-column folds the rowsum reduction into the same
        # MXU pass, so g is read once, not twice.
        yb = y.astype(dtype)
        ext = jnp.concatenate(
            [yb, jnp.ones((n, 1), dtype)], axis=1
        )                                              # (N, k+1)
        gy_ext = jax.lax.dot(
            g, ext, preferred_element_type=jnp.float32
        )                                              # (N, k+1)
        rowsum = gy_ext[:, k]
        grad = 4.0 * (
            rowsum[:, None] * yb.astype(jnp.float32) - gy_ext[:, :k]
        )                                              # (N, k) f32
        # adaptive gains (classic implementation)
        same_sign = jnp.sign(grad) == jnp.sign(vel)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
        )
        vel = momentum * vel - cfg.learning_rate * gains * grad
        y = y + vel
        y = y - jnp.mean(y, axis=0)
        return y, vel, gains

    return jax.lax.fori_loop(0, steps, body, (y, vel, gains))


def run_tsne_sweep(
    emb_path: str,
    out_dir: str,
    iters: Sequence[int] = (100, 5000, 10000, 20000, 50000, 100000),
    config: TSNEConfig = TSNEConfig(),
    shuffle_seed: Optional[int] = 0,
    log=print,
) -> List[str]:
    """File-level parity with ``src/tsne_multi_core.py``: reads an embedding
    txt, writes ``labels.txt`` plus one 2-D coordinate file per requested
    iteration count."""
    import os

    from gene2vec_tpu.io.emb_io import load_embedding_any

    tokens, matrix = load_embedding_any(emb_path)
    if shuffle_seed is not None:  # the reference shuffles rows (:23-24)
        order = np.random.RandomState(shuffle_seed).permutation(len(tokens))
        tokens = [tokens[i] for i in order]
        matrix = matrix[order]

    os.makedirs(out_dir, exist_ok=True)
    label_path = os.path.join(out_dir, "labels.txt")
    with open(label_path, "w", encoding="utf-8") as f:
        f.write("\n".join(tokens) + "\n")

    layouts = TSNE(config=config).fit(matrix, snapshot_iters=iters, log=log)
    written = [label_path]
    for it, coords in layouts.items():
        path = os.path.join(out_dir, f"tsne_iter_{it}.txt")
        np.savetxt(path, coords)
        written.append(path)
    return written
