"""GTEx tissue-specificity figures — ``src/GTExFigure.py`` parity.

For each ``*specific_genes.txt`` file (gene + z-score per line), scatter all
genes at their t-SNE coordinates in silver and color that tissue's genes by
z clipped to [-1, 4] on a midpoint-shifted coolwarm colormap
(``src/GTExFigure.py:86-89``, ``shiftedColorMap`` ``:7-56``).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

Z_CLIP = (-1.0, 4.0)


def shifted_colormap(midpoint: float, name: str = "coolwarm"):
    """Colormap with its center moved to ``midpoint`` in [0, 1] — the
    reference's shiftedColorMap recipe."""
    import matplotlib
    import matplotlib.pyplot as plt
    from matplotlib.colors import LinearSegmentedColormap

    base = plt.get_cmap(name)
    reg = np.linspace(0.0, 1.0, 257)
    shift = np.hstack(
        [
            np.linspace(0.0, midpoint, 128, endpoint=False),
            np.linspace(midpoint, 1.0, 129),
        ]
    )
    colors = base(reg)
    cdict = {"red": [], "green": [], "blue": [], "alpha": []}
    for si, ri in zip(shift, reg):
        r, g, b, a = colors[int(ri * 256)]
        cdict["red"].append((si, r, r))
        cdict["green"].append((si, g, g))
        cdict["blue"].append((si, b, b))
        cdict["alpha"].append((si, a, a))
    cmap = LinearSegmentedColormap("shifted_" + name, cdict)
    try:
        matplotlib.colormaps.register(cmap, force=True)
    except Exception:
        pass
    return cmap


def load_tsne_layout(
    label_path: str, coord_path: str
) -> Tuple[List[str], np.ndarray]:
    with open(label_path, "r", encoding="utf-8") as f:
        labels = [line.strip() for line in f if line.strip()]
    coords = np.loadtxt(coord_path)
    if coords.shape[0] != len(labels):
        raise ValueError(
            f"{coord_path}: {coords.shape[0]} rows vs {len(labels)} labels"
        )
    return labels, coords


def load_tissue_zscores(path: str) -> Dict[str, float]:
    """gene → z from a ``*specific_genes.txt`` file (whitespace-separated)."""
    out: Dict[str, float] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                try:
                    out[parts[0]] = float(parts[1])
                except ValueError:
                    continue  # header line
    return out


def gtex_figure(
    labels: List[str],
    coords: np.ndarray,
    zscores: Dict[str, float],
    out_path: str,
    title: Optional[str] = None,
) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    z_lo, z_hi = Z_CLIP
    idx = [i for i, g in enumerate(labels) if g in zscores]
    z = np.clip([zscores[labels[i]] for i in idx], z_lo, z_hi)
    midpoint = (0.0 - z_lo) / (z_hi - z_lo)  # z=0 at the colormap center
    cmap = shifted_colormap(midpoint)

    fig, ax = plt.subplots(figsize=(12, 12))
    ax.scatter(coords[:, 0], coords[:, 1], s=1, c="silver", linewidths=0)
    if idx:
        sc = ax.scatter(
            coords[idx, 0], coords[idx, 1], s=3, c=z,
            cmap=cmap, vmin=z_lo, vmax=z_hi, linewidths=0,
        )
        fig.colorbar(sc, ax=ax, shrink=0.7)
    if title:
        ax.set_title(title)
    ax.set_xticks([])
    ax.set_yticks([])
    fig.savefig(out_path, dpi=200, bbox_inches="tight")
    plt.close(fig)
    return out_path


def run_gtex_figures(
    label_path: str,
    coord_path: str,
    tissue_glob: str,
    out_dir: str,
    log=print,
) -> List[str]:
    """One figure per tissue file, named after the tissue."""
    labels, coords = load_tsne_layout(label_path, coord_path)
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for path in sorted(glob.glob(tissue_glob)):
        tissue = os.path.basename(path).replace("specific_genes.txt", "").strip(
            "_. "
        ) or os.path.basename(path)
        out = os.path.join(out_dir, f"{tissue}.png")
        gtex_figure(labels, coords, load_tissue_zscores(path), out, title=tissue)
        log(f"wrote {out}")
        written.append(out)
    return written
