"""Interactive scatter of the embedding — ``src/plot_gene2vec.py`` parity.

Pipeline: load embedding → 2-D/3-D reduction (UMAP when installed, else
t-SNE on TPU, else PCA) → optional NCBI annotation via mygene (gated) →
figure exported as ``.html`` + ``.json`` when plotly is installed, else a
matplotlib ``.png`` plus the same ``.json`` payload (the dash app consumes
the json, ``src/gene2vec_dash_app.py:68``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from gene2vec_tpu.io.emb_io import load_embedding_any


def reduce_embedding(
    matrix: np.ndarray,
    method: str = "auto",
    n_components: int = 2,
    seed: int = 0,
) -> np.ndarray:
    """2-D/3-D coordinates via umap | tsne | pca (auto prefers umap, the
    reference's choice — served by the in-repo TPU UMAP, `viz/umap.py`;
    an installed umap-learn is used only for n_components != 2, which the
    full-batch TPU layout doesn't implement)."""
    if method == "auto":
        method = "umap" if n_components == 2 else "tsne"
    if method == "umap":
        if n_components == 2:
            from gene2vec_tpu.viz.umap import UMAPConfig, umap_layout

            return umap_layout(matrix, UMAPConfig(seed=seed))
        try:
            import umap
        except ImportError as e:
            raise ImportError(
                "method='umap' with n_components != 2 requires the "
                "umap-learn package; use method='tsne' (TPU) or "
                "method='pca'"
            ) from e
        return np.asarray(
            umap.UMAP(
                n_components=n_components, random_state=seed
            ).fit_transform(matrix),
            np.float32,
        )
    if method == "tsne":
        from gene2vec_tpu.config import TSNEConfig
        from gene2vec_tpu.viz.tsne import TSNE

        cfg = TSNEConfig(seed=seed, n_iter=1000)
        return TSNE(config=cfg, n_components=n_components).fit(
            matrix, log=lambda s: None
        )[cfg.n_iter]
    if method == "pca":
        from gene2vec_tpu.viz.tsne import pca_reduce

        return pca_reduce(matrix, n_components)
    raise ValueError(f"unknown reduction method {method!r}")


def infer_gene_rep(x) -> str:
    """Classify a gene identifier so annotation can pick the right query
    scope (``src/plot_gene2vec.py:62-72``): ints are Entrez IDs, strings
    containing ``ENS`` are Ensembl IDs, anything else is a gene symbol.
    Numeric strings (Entrez IDs read from a text embedding file) are also
    classified as Entrez."""
    if isinstance(x, (int, np.integer)):
        return "Entrez ID"
    if isinstance(x, str):
        if "ENS" in x:
            return "Ensembl ID"
        if x.isdigit():
            return "Entrez ID"
        return "Gene Symbol"
    raise TypeError(f"cannot infer gene representation of {type(x).__name__}")


#: mygene querymany scope per representation (``src/plot_gene2vec.py:84-96``)
_REP_SCOPE = {
    "Gene Symbol": "symbol",
    "Entrez ID": "entrezgene",
    "Ensembl ID": "ensembl.gene",
}


def query_gene_info(genes: Sequence[str]) -> Dict[str, dict]:
    """NCBI annotation via mygene (``src/plot_gene2vec.py:74-96``); the
    query scope follows :func:`infer_gene_rep` of the first gene; gated."""
    try:
        import mygene
    except ImportError as e:
        raise ImportError(
            "gene annotation requires the mygene package; pass "
            "annotate=False to skip"
        ) from e
    mg = mygene.MyGeneInfo()
    scope = _REP_SCOPE[infer_gene_rep(genes[0])] if genes else "symbol"
    res = mg.querymany(
        list(genes), scopes=scope, fields="name,summary,symbol,entrezgene",
        species="human",
    )
    return {r["query"]: r for r in res if not r.get("notfound")}


def scatter_payload(
    tokens: Sequence[str],
    coords: np.ndarray,
    info: Optional[Dict[str, dict]] = None,
) -> dict:
    """Plotly-figure-shaped dict (consumed by the dash app and exports)."""
    dims = coords.shape[1]
    hover: List[str] = []
    for t in tokens:
        meta = (info or {}).get(t)
        hover.append(
            f"{t}<br>{meta['name']}" if meta and "name" in meta else str(t)
        )
    trace = {
        "type": "scatter3d" if dims == 3 else "scattergl",
        "mode": "markers",
        "x": coords[:, 0].tolist(),
        "y": coords[:, 1].tolist(),
        "text": hover,
        "customdata": list(tokens),
        "marker": {"size": 3, "opacity": 0.8},
    }
    if dims == 3:
        trace["z"] = coords[:, 2].tolist()
    return {
        "data": [trace],
        "layout": {"title": {"text": "gene2vec embedding"}, "height": 800},
    }


def export_figure(payload: dict, out_prefix: str) -> List[str]:
    """Write ``<prefix>.json`` always; ``.html`` via plotly when installed,
    else a matplotlib ``.png`` fallback."""
    written = []
    json_path = out_prefix + ".json"
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    written.append(json_path)
    try:
        import plotly.graph_objects as go

        fig = go.Figure(payload)
        html = out_prefix + ".html"
        fig.write_html(html)
        written.append(html)
    except ImportError:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        trace = payload["data"][0]
        fig, ax = plt.subplots(figsize=(10, 10))
        ax.scatter(trace["x"], trace["y"], s=2, alpha=0.6)
        ax.set_title(payload["layout"]["title"]["text"])
        png = out_prefix + ".png"
        fig.savefig(png, dpi=150)
        plt.close(fig)
        written.append(png)
    return written


def plot_gene2vec(
    emb_path: str,
    out_prefix: str,
    method: str = "auto",
    n_components: int = 2,
    annotate: bool = False,
    seed: int = 0,
    log=print,
) -> List[str]:
    """End-to-end ``src/plot_gene2vec.py`` flow."""
    tokens, matrix = load_embedding_any(emb_path)
    log(f"{len(tokens)} genes loaded; reducing with {method}")
    coords = reduce_embedding(matrix, method, n_components, seed)
    info = query_gene_info(tokens) if annotate else None
    payload = scatter_payload(tokens, coords, info)
    os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)
    written = export_figure(payload, out_prefix)
    log(f"wrote {', '.join(written)}")
    return written
