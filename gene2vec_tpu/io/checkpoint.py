"""Per-iteration checkpoint/resume.

The reference's de-facto checkpoint mechanism is gensim ``model.save`` every
iteration plus reload-previous at the start of the next
(``src/gene2vec.py:71,86-88``) — a crash loses at most one iteration.  We
keep exactly that cadence and naming, with a portable ``.npz`` payload
(emb + ctx tables + meta) alongside the vocab, and the same two text exports
per iteration (matrix-txt and word2vec-format; formats in io/emb_io.py).

Layout in <export_dir>:
    vocab.tsv                               token \t count, id order
    gene2vec_dim_<D>_iter_<N>.npz           emb, ctx, meta json
    gene2vec_dim_<D>_iter_<N>.txt           matrix-txt export
    gene2vec_dim_<D>_iter_<N>_w2v.txt       word2vec-format export
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional, Tuple

import numpy as np

from gene2vec_tpu.io.emb_io import write_matrix_txt, write_word2vec_format
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.sgns.model import SGNSParams

_CKPT_RE = re.compile(r"^gene2vec_dim_(\d+)_iter_(\d+)\.npz$")
_W2V_RE = re.compile(r"^gene2vec_dim_(\d+)_iter_(\d+)_w2v\.txt$")


def iter_checkpoints(export_dir: str, text_fallback: bool = False):
    """Yield ``(dim, iteration, path)`` for every checkpoint in
    ``export_dir`` under this module's naming scheme — the discovery
    primitive the serve registry polls.  With ``text_fallback`` the
    word2vec-format text exports (``*_w2v.txt``) are yielded too, so
    export dirs produced by the reference scripts (text only, no
    ``.npz``) are still discoverable; npz checkpoints for the same
    (dim, iteration) shadow their text twin."""
    if not os.path.isdir(export_dir):
        return
    seen = set()
    names = sorted(os.listdir(export_dir))
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            key = (int(m.group(1)), int(m.group(2)))
            seen.add(key)
            yield (*key, os.path.join(export_dir, name))
    if text_fallback:
        for name in names:
            m = _W2V_RE.match(name)
            if m:
                key = (int(m.group(1)), int(m.group(2)))
                if key not in seen:
                    yield (*key, os.path.join(export_dir, name))


def ckpt_prefix(export_dir: str, dim: int, iteration: int) -> str:
    return os.path.join(export_dir, f"gene2vec_dim_{dim}_iter_{iteration}")


def save_iteration(
    export_dir: str,
    dim: int,
    iteration: int,
    params: SGNSParams,
    vocab: Vocab,
    txt_output: bool = True,
    meta: Optional[dict] = None,
) -> str:
    os.makedirs(export_dir, exist_ok=True)
    vocab_path = os.path.join(export_dir, "vocab.tsv")
    if os.path.exists(vocab_path):
        existing = Vocab.load(vocab_path)
        if existing.id_to_token != vocab.id_to_token:
            raise ValueError(
                f"{vocab_path} was written for a different corpus "
                f"({len(existing)} tokens vs {len(vocab)}); refusing to mix "
                "checkpoints with mismatched vocabularies in one export dir"
            )
    else:
        vocab.save(vocab_path)
    prefix = ckpt_prefix(export_dir, dim, iteration)
    # npz has no bfloat16 dtype: store f32 (a lossless upcast of bf16
    # tables — every bf16 value is exactly representable) and record the
    # training width so load_iteration can restore it
    table_dtype = str(params.emb.dtype)
    emb = np.asarray(params.emb, dtype=np.float32)
    ctx = np.asarray(params.ctx, dtype=np.float32)
    meta = dict(
        meta or {},
        dim=dim,
        iteration=iteration,
        vocab_size=len(vocab),
        table_dtype=table_dtype,
    )
    np.savez(prefix + ".npz", emb=emb, ctx=ctx, meta=json.dumps(meta))
    if txt_output:
        write_matrix_txt(prefix + ".txt", vocab.id_to_token, emb)
        write_word2vec_format(prefix + "_w2v.txt", vocab.id_to_token, emb)
    return prefix + ".npz"


def load_iteration(
    export_dir: str, dim: int, iteration: int,
    table_dtype: Optional[str] = None,
) -> Tuple[SGNSParams, Vocab, dict]:
    """Load one iteration's tables (+vocab, meta).

    ``table_dtype`` is the CALLER'S configured training width: on a
    mismatch with the checkpoint's recorded width the tables are cast to
    the configured one, with a warning — silently resuming at the
    checkpoint's width would undo exactly the config retreat the bf16
    small-scale-absorption caveat recommends (config.py table_dtype).
    ``None`` restores the recorded width as-is (inspection tools).  The
    file itself always stores f32 — a lossless upcast of bf16 tables.
    """
    import jax.numpy as jnp

    prefix = ckpt_prefix(export_dir, dim, iteration)
    with np.load(prefix + ".npz") as z:
        meta = json.loads(str(z["meta"]))
        saved = meta.get("table_dtype", "float32")
        if table_dtype is not None and table_dtype != saved:
            import warnings

            warnings.warn(
                f"checkpoint iteration {iteration} was saved with "
                f"table_dtype={saved}; resuming at the configured "
                f"{table_dtype}",
                stacklevel=2,
            )
        dtype = jnp.dtype(table_dtype if table_dtype is not None else saved)
        emb = jnp.asarray(z["emb"], dtype=dtype)
        ctx = jnp.asarray(z["ctx"], dtype=dtype)
    vocab = Vocab.load(os.path.join(export_dir, "vocab.tsv"))
    return SGNSParams(emb=emb, ctx=ctx), vocab, meta


def latest_iteration(export_dir: str, dim: int) -> int:
    """Highest saved iteration for ``dim`` in ``export_dir``, or 0."""
    best = 0
    if not os.path.isdir(export_dir):
        return 0
    for name in os.listdir(export_dir):
        m = _CKPT_RE.match(name)
        if m and int(m.group(1)) == dim:
            best = max(best, int(m.group(2)))
    return best
