"""Per-iteration checkpoint/resume.

The reference's de-facto checkpoint mechanism is gensim ``model.save`` every
iteration plus reload-previous at the start of the next
(``src/gene2vec.py:71,86-88``) — a crash loses at most one iteration.  We
keep exactly that cadence and naming, with a portable ``.npz`` payload
(emb + ctx tables + meta) alongside the vocab, and the same two text exports
per iteration (matrix-txt and word2vec-format; formats in io/emb_io.py).

Layout in <export_dir>:
    vocab.tsv                               token \t count, id order
    gene2vec_dim_<D>_iter_<N>.npz           emb, ctx, meta json
    gene2vec_dim_<D>_iter_<N>.txt           matrix-txt export
    gene2vec_dim_<D>_iter_<N>_w2v.txt       word2vec-format export
    gene2vec_dim_<D>_iter_<N>.vocab.tsv     per-iteration vocab SIDECAR —
                                            present only when this
                                            iteration's vocab is a TAIL
                                            EXTENSION of vocab.tsv (the
                                            continuous-learning loop's
                                            new-gene case, loop/ingest.py);
                                            readers prefer it via
                                            vocab_path_for()
    gene2vec_dim_<D>_iter_<N>.MANIFEST.json crc/size stamp (commit record)

Vocab evolution (docs/CONTINUOUS.md): ``vocab.tsv`` is immutable once
written — every older manifest CRC-covers it, so rewriting it would
retroactively "tear" the whole export history.  An iteration whose
vocab GREW (new genes appended at the tail; existing row ids stay
stable) therefore carries its own ``<prefix>.vocab.tsv`` sidecar,
covered by that iteration's manifest instead of the shared file.  Any
other vocab difference is still refused — only tail extension keeps
old row ids (and the fleet's gene→shard routing) meaningful.

Crash safety (docs/RESILIENCE.md): every file is written to a temp name
and atomically renamed into place, and the iteration's ``MANIFEST`` —
CRC32 + byte size of every artifact, written LAST — is the commit
record.  Discovery with ``verified_only`` skips any iteration whose
manifest is missing (killed mid-save), torn, or disagrees with the
bytes on disk (truncated/bit-rotted after commit), so a resuming
trainer or the serve watcher falls back to the newest iteration that
actually verifies.  Checkpoints that predate manifests (the reference
scripts' text-only layout, pre-upgrade export dirs) are accepted as-is
— per dim, an unmanifested iteration older than the dim's first
manifested one is legacy, not torn (see ``_verified_entries``).
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional, Tuple

import numpy as np

from gene2vec_tpu.io.emb_io import write_matrix_txt, write_word2vec_format
from gene2vec_tpu.io.vocab import Vocab
from gene2vec_tpu.resilience import snapshot as snap
from gene2vec_tpu.sgns.model import SGNSParams

_CKPT_RE = re.compile(r"^gene2vec_dim_(\d+)_iter_(\d+)\.npz$")
_W2V_RE = re.compile(r"^gene2vec_dim_(\d+)_iter_(\d+)_w2v\.txt$")
_MANIFEST_RE = re.compile(
    r"^gene2vec_dim_(\d+)_iter_(\d+)" + re.escape(snap.MANIFEST_SUFFIX) + r"$"
)


def _scan(export_dir: str, text_fallback: bool):
    """One directory listing → (candidate entries, manifested keys).
    Entries are ``(dim, iteration, path, prefix)`` in name order, with
    npz checkpoints shadowing their text twins (both share the same
    prefix, hence the same manifest); ``manifested`` is the set of
    (dim, iteration) keys that carry a manifest file."""
    names = sorted(os.listdir(export_dir))
    manifested = set()
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            manifested.add((int(m.group(1)), int(m.group(2))))
    entries = []
    seen = set()
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            key = (int(m.group(1)), int(m.group(2)))
            seen.add(key)
            path = os.path.join(export_dir, name)
            entries.append((*key, path, path[: -len(".npz")]))
    if text_fallback:
        for name in names:
            m = _W2V_RE.match(name)
            if m:
                key = (int(m.group(1)), int(m.group(2)))
                if key not in seen:
                    path = os.path.join(export_dir, name)
                    entries.append((*key, path, path[: -len("_w2v.txt")]))
    return entries, manifested


def _verified_entries(entries, manifested, verified_only: bool):
    """Lazily filter scan entries through the manifest contract.

    With ``verified_only``, an iteration that HAS a manifest must pass
    CRC/size verification (a torn export silently falls back to the
    previous one).  An iteration WITHOUT a manifest is either *legacy*
    — written before this dim adopted manifests, i.e. strictly older
    than the dim's first manifested iteration — and accepted as-is, or
    *uncommitted* — at/after the adoption point, meaning the writer
    died between the artifacts and the commit record — and skipped.
    Scoped per dim: another dim's manifests say nothing about this
    one's history.  Lazy on purpose: verification CRCs the artifact
    bytes, so consumers that stop at the first hit
    (``latest_iteration``, the registry's newest-first scan) pay for
    one checkpoint, not the whole history."""
    if not verified_only:
        for dim, it, path, _ in entries:
            yield (dim, it, path)
        return
    first_manifested: dict = {}
    for d, i in manifested:
        if d not in first_manifested or i < first_manifested[d]:
            first_manifested[d] = i
    for dim, it, path, prefix in entries:
        if (dim, it) in manifested:
            if snap.verify_manifest(prefix):
                yield (dim, it, path)
        elif dim not in first_manifested or it < first_manifested[dim]:
            yield (dim, it, path)  # legacy pre-manifest checkpoint
        # else: files without a commit record, newer than the dim's
        # manifest adoption → died mid-save → skip


def iter_checkpoints(
    export_dir: str,
    text_fallback: bool = False,
    verified_only: bool = False,
):
    """Yield ``(dim, iteration, path)`` for every checkpoint in
    ``export_dir`` under this module's naming scheme — the discovery
    primitive the serve registry polls.  With ``text_fallback`` the
    word2vec-format text exports (``*_w2v.txt``) are yielded too, so
    export dirs produced by the reference scripts (text only, no
    ``.npz``) are still discoverable; npz checkpoints for the same
    (dim, iteration) shadow their text twin.  ``verified_only`` applies
    the manifest contract (see :func:`_verified_entries`)."""
    if not os.path.isdir(export_dir):
        return
    entries, manifested = _scan(export_dir, text_fallback)
    yield from _verified_entries(entries, manifested, verified_only)


def iter_checkpoints_newest_first(
    export_dir: str,
    text_fallback: bool = False,
    verified_only: bool = False,
    dim: Optional[int] = None,
):
    """Like :func:`iter_checkpoints` but ordered newest first (highest
    iteration; ties broken by larger dim) and verified LAZILY — taking
    the first yielded candidate costs one manifest check, not a CRC
    sweep of the whole export history."""
    if not os.path.isdir(export_dir):
        return
    entries, manifested = _scan(export_dir, text_fallback)
    if dim is not None:
        entries = [e for e in entries if e[0] == dim]
    entries.sort(key=lambda e: (e[1], e[0]), reverse=True)
    yield from _verified_entries(entries, manifested, verified_only)


def ckpt_prefix(export_dir: str, dim: int, iteration: int) -> str:
    return os.path.join(export_dir, f"gene2vec_dim_{dim}_iter_{iteration}")


def vocab_path_for(ckpt_path: str) -> str:
    """The vocab file that describes ``ckpt_path``'s rows: the
    per-iteration ``<prefix>.vocab.tsv`` sidecar when present (a
    vocab-tail-extended iteration, see the module doc), else the export
    dir's shared ``vocab.tsv``.  Accepts an ``.npz`` path, a
    ``_w2v.txt`` path, or a bare checkpoint prefix."""
    if ckpt_path.endswith(".npz"):
        prefix = ckpt_path[: -len(".npz")]
    elif ckpt_path.endswith("_w2v.txt"):
        prefix = ckpt_path[: -len("_w2v.txt")]
    else:
        prefix = ckpt_path
    sidecar = prefix + ".vocab.tsv"
    if os.path.exists(sidecar):
        return sidecar
    return os.path.join(
        os.path.dirname(os.path.abspath(ckpt_path)), "vocab.tsv"
    )


def is_tail_extension(old_tokens, new_tokens) -> bool:
    """Whether ``new_tokens`` keeps every existing row id stable: the
    old id order is an exact PREFIX and new genes only append."""
    return (
        len(new_tokens) >= len(old_tokens)
        and list(new_tokens[: len(old_tokens)]) == list(old_tokens)
    )


def save_iteration(
    export_dir: str,
    dim: int,
    iteration: int,
    params: SGNSParams,
    vocab: Vocab,
    txt_output: bool = True,
    meta: Optional[dict] = None,
) -> str:
    os.makedirs(export_dir, exist_ok=True)
    prefix = ckpt_prefix(export_dir, dim, iteration)
    vocab_path = os.path.join(export_dir, "vocab.tsv")
    if os.path.exists(vocab_path):
        existing = Vocab.load(vocab_path)
        if existing.id_to_token != vocab.id_to_token:
            if is_tail_extension(existing.id_to_token, vocab.id_to_token):
                # vocab GREW at the tail (continuous-learning ingest):
                # vocab.tsv must stay untouched — every older manifest
                # CRC-covers it — so this iteration carries its own
                # sidecar, which vocab_path_for() prefers at load time
                vocab_path = prefix + ".vocab.tsv"
                snap.atomic_write_via(vocab.save, vocab_path)
            else:
                raise ValueError(
                    f"{vocab_path} was written for a different corpus "
                    f"({len(existing)} tokens vs {len(vocab)}, not a "
                    "tail extension); refusing to mix checkpoints with "
                    "mismatched vocabularies in one export dir"
                )
    else:
        snap.atomic_write_via(vocab.save, vocab_path)
    # npz has no bfloat16 dtype: store f32 (a lossless upcast of bf16
    # tables — every bf16 value is exactly representable) and record the
    # training width so load_iteration can restore it
    table_dtype = str(params.emb.dtype)
    emb = np.asarray(params.emb, dtype=np.float32)
    ctx = np.asarray(params.ctx, dtype=np.float32)
    meta = dict(
        meta or {},
        dim=dim,
        iteration=iteration,
        vocab_size=len(vocab),
        table_dtype=table_dtype,
    )
    # every artifact lands atomically (temp + fsync + rename), then the
    # manifest commits the iteration as a whole — a reader discovering
    # with verified_only never sees a half-written iteration
    snap.atomic_savez(prefix + ".npz", emb=emb, ctx=ctx, meta=json.dumps(meta))
    files = [prefix + ".npz", vocab_path]
    optional = []
    if txt_output:
        snap.atomic_write_via(
            lambda p: write_matrix_txt(p, vocab.id_to_token, emb),
            prefix + ".txt",
        )
        snap.atomic_write_via(
            lambda p: write_word2vec_format(p, vocab.id_to_token, emb),
            prefix + "_w2v.txt",
        )
        # optional: corruption of a text twin is detected while it
        # exists, but deleting the (large) convenience exports must not
        # un-commit the npz checkpoint
        optional = [prefix + ".txt", prefix + "_w2v.txt"]
        files += optional
    snap.write_manifest(prefix, files, meta=meta, optional=optional)
    return prefix + ".npz"


def publish_iteration(
    src_dir: str, dst_dir: str, dim: int, iteration: int
) -> str:
    """Atomically publish one VERIFIED iteration from ``src_dir`` (a
    continuous-learning candidate export, loop/promote.py) into
    ``dst_dir`` (the serving export the fleet watches).

    The npz lands via the snapshot primitives and the manifest is
    written LAST, so the serving watchers' manifest-verified discovery
    only ever sees the iteration fully committed — promotion then rides
    the existing swap machinery (per-replica atomic refresh, or the
    fleet's shard-atomic stage/flip) unchanged.  A candidate whose
    vocab tail-extends the serving vocab publishes a per-iteration
    sidecar (see the module doc); any other vocab difference refuses.
    Returns the destination npz path.  Raises if the source iteration
    does not verify — a torn candidate must never be promoted."""
    src_prefix = ckpt_prefix(src_dir, dim, iteration)
    res = snap.verify_manifest(src_prefix)
    if not res:
        raise IOError(
            f"refusing to publish unverified candidate "
            f"dim={dim} iter={iteration} from {src_dir!r}: {res.reason}"
        )
    vocab = Vocab.load(vocab_path_for(src_prefix + ".npz"))
    with np.load(src_prefix + ".npz") as z:
        meta = json.loads(str(z["meta"])) if "meta" in z.files else {}
    os.makedirs(dst_dir, exist_ok=True)
    dst_prefix = ckpt_prefix(dst_dir, dim, iteration)
    snap.atomic_copy(src_prefix + ".npz", dst_prefix + ".npz")
    files = [dst_prefix + ".npz"]
    dst_vocab = os.path.join(dst_dir, "vocab.tsv")
    if not os.path.exists(dst_vocab):
        snap.atomic_write_via(vocab.save, dst_vocab)
        files.append(dst_vocab)
    else:
        existing = Vocab.load(dst_vocab)
        if existing.id_to_token == vocab.id_to_token:
            files.append(dst_vocab)
        elif is_tail_extension(existing.id_to_token, vocab.id_to_token):
            sidecar = dst_prefix + ".vocab.tsv"
            snap.atomic_write_via(vocab.save, sidecar)
            files.append(sidecar)
        else:
            raise ValueError(
                f"candidate vocab ({len(vocab)} tokens) is not a tail "
                f"extension of {dst_vocab} ({len(existing)} tokens) — "
                "promotion would break existing row ids"
            )
    snap.write_manifest(dst_prefix, files, meta=meta)
    return dst_prefix + ".npz"


def read_npz_rows(path: str, name: str, start: int,
                  end: int) -> Tuple[np.ndarray, int]:
    """Read rows ``[start, end)`` of array ``name`` from an
    **uncompressed** npz (``np.savez``, which ``atomic_savez`` uses)
    WITHOUT materializing the whole array: the zip member is STORED,
    so after parsing the npy header the row range is one seek + one
    read.  Returns ``(rows, total_rows)``.

    This is what lets a shard replica sized for ``rows/num_shards``
    actually load (and hot-stage) its slice of a table that does not
    fit the host — ``serve/registry.py`` routes sharded npz loads
    through here.  Any structural surprise (compressed member, Fortran
    order, >2-D quirks) raises ``ValueError`` so the caller can fall
    back to the full load."""
    import struct
    import zipfile

    member = name if name.endswith(".npy") else name + ".npy"
    # called from the registry refresh path which holds _refresh_lock by
    # design (loads serialize; serve reads never take that lock)
    with open(path, "rb") as f:  # graftcheck: disable=blocking-while-locked
        with zipfile.ZipFile(f) as zf:
            try:
                info = zf.getinfo(member)
            except KeyError:
                raise ValueError(
                    f"{path}: no member {member!r}"
                ) from None
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"{path}:{member}: compressed member — cannot "
                    "seek a row range"
                )
        # the member's data offset: local file header (30 fixed bytes
        # + name + extra — the extra field can differ from the central
        # directory's, so it must be read from the LOCAL header)
        f.seek(info.header_offset)
        local = f.read(30)
        if len(local) != 30 or local[:4] != b"PK\x03\x04":
            raise ValueError(f"{path}:{member}: bad local zip header")
        name_len, extra_len = struct.unpack("<HH", local[26:30])
        f.seek(info.header_offset + 30 + name_len + extra_len)
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, fortran, dtype = (
                np.lib.format.read_array_header_1_0(f)
            )
        elif version == (2, 0):
            shape, fortran, dtype = (
                np.lib.format.read_array_header_2_0(f)
            )
        else:
            raise ValueError(
                f"{path}:{member}: unsupported npy version {version}"
            )
        if fortran or len(shape) < 1:
            raise ValueError(
                f"{path}:{member}: need a C-ordered array"
            )
        total = int(shape[0])
        start = max(0, int(start))
        end = min(total, int(end))
        n = max(0, end - start)
        row_bytes = int(dtype.itemsize * int(np.prod(shape[1:], dtype=np.int64)))
        f.seek(start * row_bytes, 1)
        buf = f.read(n * row_bytes)
        if len(buf) != n * row_bytes:
            raise ValueError(
                f"{path}:{member}: short read ({len(buf)} of "
                f"{n * row_bytes} bytes)"
            )
        rows = np.frombuffer(buf, dtype=dtype).reshape(
            (n,) + tuple(int(s) for s in shape[1:])
        )
        return rows.copy(), total


def load_iteration(
    export_dir: str, dim: int, iteration: int,
    table_dtype: Optional[str] = None,
) -> Tuple[SGNSParams, Vocab, dict]:
    """Load one iteration's tables (+vocab, meta).

    ``table_dtype`` is the CALLER'S configured training width: on a
    mismatch with the checkpoint's recorded width the tables are cast to
    the configured one, with a warning — silently resuming at the
    checkpoint's width would undo exactly the config retreat the bf16
    small-scale-absorption caveat recommends (config.py table_dtype).
    ``None`` restores the recorded width as-is (inspection tools).  The
    file itself always stores f32 — a lossless upcast of bf16 tables.
    """
    import jax.numpy as jnp

    prefix = ckpt_prefix(export_dir, dim, iteration)
    with np.load(prefix + ".npz") as z:
        meta = json.loads(str(z["meta"]))
        saved = meta.get("table_dtype", "float32")
        if table_dtype is not None and table_dtype != saved:
            import warnings

            warnings.warn(
                f"checkpoint iteration {iteration} was saved with "
                f"table_dtype={saved}; resuming at the configured "
                f"{table_dtype}",
                stacklevel=2,
            )
        dtype = jnp.dtype(table_dtype if table_dtype is not None else saved)
        emb = jnp.asarray(z["emb"], dtype=dtype)
        ctx = jnp.asarray(z["ctx"], dtype=dtype)
    # per-iteration sidecar vocab (tail-extended iterations) wins over
    # the shared vocab.tsv — the rows being loaded were trained on it
    vocab = Vocab.load(vocab_path_for(prefix + ".npz"))
    return SGNSParams(emb=emb, ctx=ctx), vocab, meta


def latest_iteration(
    export_dir: str, dim: int, verified_only: bool = True
) -> int:
    """Highest saved iteration for ``dim`` in ``export_dir``, or 0.

    Routed through the manifest check by default: a torn newest export
    (killed mid-save, truncated, bit-rotted) is skipped so resume picks
    the newest iteration that actually verifies — the fallback the
    chaos drill's kill-at-random-step relies on.  Newest-first + lazy,
    so the common case (intact newest) verifies exactly one
    checkpoint."""
    for _, it, _ in iter_checkpoints_newest_first(
        export_dir, verified_only=verified_only, dim=dim
    ):
        return it
    return 0
