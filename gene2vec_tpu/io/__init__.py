from gene2vec_tpu.io.vocab import Vocab  # noqa: F401
from gene2vec_tpu.io.pair_reader import read_pair_files, read_pair_lines  # noqa: F401
from gene2vec_tpu.io.emb_io import (  # noqa: F401
    write_matrix_txt,
    write_word2vec_format,
    read_matrix_txt,
    read_word2vec_format,
)
