"""Vocabulary: gene symbol ↔ contiguous int id, with counts.

Ordering follows the word2vec convention the reference inherits from gensim
(``src/gene2vec.py:70`` builds vocab inside ``gensim.models.Word2Vec``):
tokens sorted by corpus frequency, descending, ties broken by first
appearance (stable sort).  ``min_count`` drops rare tokens; the reference
always uses ``min_count=1`` so every gene is kept.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np


class Vocab:
    """Frequency-sorted token vocabulary."""

    __slots__ = ("id_to_token", "token_to_id", "counts")

    def __init__(self, id_to_token: List[str], counts: np.ndarray):
        if len(id_to_token) != len(counts):
            raise ValueError("token list and counts length mismatch")
        self.id_to_token = list(id_to_token)
        self.counts = np.asarray(counts, dtype=np.int64)
        self.token_to_id: Dict[str, int] = {
            tok: i for i, tok in enumerate(self.id_to_token)
        }

    # -- construction ------------------------------------------------------

    @classmethod
    def from_pairs(cls, pairs: Iterable[Sequence[str]], min_count: int = 1) -> "Vocab":
        """Build from an iterable of token sequences (usually 2-token pairs)."""
        counts: Dict[str, int] = {}
        for toks in pairs:
            for tok in toks:
                counts[tok] = counts.get(tok, 0) + 1
        return cls.from_counts(counts, min_count=min_count)

    @classmethod
    def from_counts(cls, counts: Dict[str, int], min_count: int = 1) -> "Vocab":
        # dict preserves insertion order → stable sort ties break by first
        # appearance, matching gensim's sort_vocab behavior.
        items = [(tok, c) for tok, c in counts.items() if c >= min_count]
        items.sort(key=lambda kv: kv[1], reverse=True)
        toks = [kv[0] for kv in items]
        cnts = np.array([kv[1] for kv in items], dtype=np.int64)
        return cls(toks, cnts)

    # -- encoding ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.id_to_token)

    def __contains__(self, tok: str) -> bool:
        return tok in self.token_to_id

    def encode_pairs(self, pairs: Iterable[Sequence[str]]) -> np.ndarray:
        """Encode 2-token pairs to an (N, 2) int32 array, dropping pairs with
        out-of-vocab tokens (only possible when min_count > 1)."""
        t2i = self.token_to_id
        out: List[Tuple[int, int]] = []
        for toks in pairs:
            if len(toks) != 2:
                continue
            a = t2i.get(toks[0])
            b = t2i.get(toks[1])
            if a is not None and b is not None:
                out.append((a, b))
        return np.asarray(out, dtype=np.int32).reshape(-1, 2)

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for tok, c in zip(self.id_to_token, self.counts):
                f.write(f"{tok}\t{int(c)}\n")

    @classmethod
    def load(cls, path: str) -> "Vocab":
        toks: List[str] = []
        cnts: List[int] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                tok, c = line.split("\t")
                toks.append(tok)
                cnts.append(int(c))
        return cls(toks, np.asarray(cnts, dtype=np.int64))
