"""Pair-corpus reading.

The reference loads every ``*.txt`` file in a directory with windows-1252
decoding and splits each line on whitespace (``src/gene2vec.py:36-47``).  We
keep that contract (directory + filename-suffix pattern, windows-1252
tolerant) and add a fast path: the native C++ reader in ``native/pairio.cpp``
(mmap + string interning) when its shared library has been built, falling
back to pure Python otherwise.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.io.vocab import Vocab


def iter_pair_files(source_dir: str, ending_pattern: str = "txt") -> List[str]:
    """Files in ``source_dir`` whose names end with ``ending_pattern``,
    sorted for determinism (the reference shuffles file order,
    ``src/gene2vec.py:33`` — order is irrelevant because the corpus is
    reshuffled afterwards anyway)."""
    names = sorted(n for n in os.listdir(source_dir) if n.endswith(ending_pattern))
    return [os.path.join(source_dir, n) for n in names]


def read_pair_lines(path: str, encoding: str = "windows-1252") -> Iterator[List[str]]:
    """Yield whitespace-split token lists, one per non-empty line."""
    with open(path, "r", encoding=encoding) as f:
        for line in f:
            toks = line.strip().split()
            if toks:
                yield toks


def read_pair_files(
    source_dir: str,
    ending_pattern: str = "txt",
    encoding: str = "windows-1252",
) -> List[List[str]]:
    """All pairs from all matching files, as token lists."""
    pairs: List[List[str]] = []
    for path in iter_pair_files(source_dir, ending_pattern):
        pairs.extend(read_pair_lines(path, encoding=encoding))
    return pairs


def load_corpus(
    source_dir: str,
    ending_pattern: str = "txt",
    min_count: int = 1,
    encoding: str = "windows-1252",
    use_native: bool = True,
) -> Tuple[Vocab, np.ndarray]:
    """Read a pair corpus directory → (Vocab, (N,2) int32 encoded pairs).

    Uses the native C++ reader (native/pairio.cpp) when its shared library
    has been built (``make -C native``); the Python fallback is
    behavior-identical.
    """
    if use_native:
        try:
            from gene2vec_tpu.io import native_pairio

            if native_pairio.available():
                return native_pairio.load_corpus(
                    iter_pair_files(source_dir, ending_pattern), min_count=min_count
                )
        except ImportError:
            pass
    token_pairs = read_pair_files(source_dir, ending_pattern, encoding=encoding)
    vocab = Vocab.from_pairs(token_pairs, min_count=min_count)
    encoded = vocab.encode_pairs(token_pairs)
    return vocab, encoded
