"""Embedding matrix file formats.

The reference emits two text formats the whole downstream pipeline keys on
(SURVEY §2.2 #4):

* **matrix-txt** — ``gene\\tv1 v2 ... vD \\n`` per gene, trailing space
  before the newline (``src/generateMatrix.py:19-23``);
* **word2vec-format** — a ``"<count> <dim>"`` header line then
  ``gene v1 ... vD`` rows, detected by the 2-field first line
  (``src/evaluation_target_function.py:20-25``) and loadable by gensim's
  ``load_word2vec_format``.

Both writers/readers are implemented here, plus helpers shared by the
GGIPNN harness (load an embedding file keyed by an external vocab with a
U(−0.25, 0.25) random fallback for missing genes, ``src/GGIPNN_util.py:3-16``).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def write_matrix_txt(path: str, tokens: Sequence[str], matrix: np.ndarray) -> None:
    matrix = np.asarray(matrix)
    with open(path, "w", encoding="utf-8") as f:
        for tok, row in zip(tokens, matrix):
            f.write(str(tok) + "\t" + " ".join(repr(float(v)) for v in row) + " \n")


def read_matrix_txt(path: str) -> Tuple[List[str], np.ndarray]:
    tokens: List[str] = []
    rows: List[np.ndarray] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            tok, _, rest = line.partition("\t")
            if not rest:  # tolerate space-separated matrix files
                parts = line.split()
                tok, rest = parts[0], " ".join(parts[1:])
            tokens.append(tok)
            rows.append(np.asarray(rest.split(), dtype=np.float32))
    return tokens, np.vstack(rows) if rows else np.zeros((0, 0), np.float32)


def write_word2vec_format(path: str, tokens: Sequence[str], matrix: np.ndarray) -> None:
    matrix = np.asarray(matrix)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{len(tokens)} {matrix.shape[1]}\n")
        for tok, row in zip(tokens, matrix):
            f.write(str(tok) + " " + " ".join(repr(float(v)) for v in row) + "\n")


def read_word2vec_format(path: str) -> Tuple[List[str], np.ndarray]:
    """Streaming reader: the ``"<count> <dim>"`` header preallocates the
    full (count, dim) matrix and rows parse straight into it — no Python
    row-list accumulation or final ``vstack`` copy, so peak memory is one
    matrix (the serve registry loads full-vocab exports through this
    path on its text-format fallback)."""
    tokens: List[str] = []
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        if len(header) != 2:
            raise ValueError(f"{path}: missing word2vec '<count> <dim>' header")
        count, dim = int(header[0]), int(header[1])
        matrix = np.empty((count, dim), dtype=np.float32)
        n = 0
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < dim + 1:
                continue
            if n < count:
                matrix[n] = np.asarray(parts[1 : dim + 1], dtype=np.float32)
                tokens.append(parts[0])
            n += 1
    if n != count:
        raise ValueError(f"{path}: header says {count} rows, found {n}")
    return tokens, matrix if count else np.zeros((0, dim), np.float32)


def load_embedding_any(path: str) -> Tuple[List[str], np.ndarray]:
    """Auto-detect matrix-txt vs word2vec-format by the first line."""
    with open(path, "r", encoding="utf-8") as f:
        first = f.readline().split()
    if len(first) == 2 and all(p.isdigit() for p in first):
        return read_word2vec_format(path)
    return read_matrix_txt(path)


def load_embedding_for_vocab(
    vocabulary: Dict[str, int],
    path: str,
    vector_size: int,
    rng: np.random.RandomState | None = None,
) -> np.ndarray:
    """Embedding matrix aligned to an external vocab.

    Missing genes keep a U(−0.25, 0.25) random init — the reference's
    deliberate fallback (``src/GGIPNN_util.py:6-14``, SURVEY §2.2 #6).
    """
    rng = rng or np.random.RandomState(0)
    out = rng.uniform(-0.25, 0.25, (len(vocabulary), vector_size)).astype(np.float32)
    tokens, matrix = load_embedding_any(path)
    for tok, row in zip(tokens, matrix):
        idx = vocabulary.get(tok)
        if idx is not None and row.shape[0] == vector_size:
            out[idx] = row
    return out
