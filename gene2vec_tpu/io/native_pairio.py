"""ctypes bridge to the native corpus reader (native/pairio.cpp).

The shared library is built with ``make -C native`` (plain g++, no
pybind11); if it is absent, :func:`available` triggers one silent build
attempt (disable with ``GENE2VEC_TPU_NO_NATIVE_BUILD=1``) and the pure
Python reader in pair_reader.py remains the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.io.vocab import Vocab

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpairio.so")

# must match PAIRIO_ABI_VERSION in native/pairio.cpp
_ABI_VERSION = 2

_lib: Optional[ctypes.CDLL] = None
_build_attempted = False


class _PairioResult(ctypes.Structure):
    _fields_ = [
        ("num_pairs", ctypes.c_int64),
        ("pairs", ctypes.POINTER(ctypes.c_int32)),
        ("vocab_size", ctypes.c_int64),
        ("counts", ctypes.POINTER(ctypes.c_int64)),
        # POINTER(c_char), NOT c_char_p: a c_char_p field auto-converts to
        # a temporary Python bytes on attribute access by scanning for a
        # NUL the C side never wrote (an over-read past the malloc), and
        # ctypes.cast() of that temporary does not keep it alive — the
        # pointer dangles once the temp is collected, and string_at then
        # reads reused heap (the state-dependent token/count-mismatch /
        # UnicodeDecodeError flake in test_parity_with_messy_lines).
        ("tokens", ctypes.POINTER(ctypes.c_char)),
        ("tokens_len", ctypes.c_int64),
        ("err_file", ctypes.c_int32),
        ("err_offset", ctypes.c_int64),
        ("err_byte", ctypes.c_uint8),
    ]


def _try_build() -> None:
    global _build_attempted
    if _build_attempted or os.environ.get("GENE2VEC_TPU_NO_NATIVE_BUILD"):
        return
    _build_attempted = True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            capture_output=True,
            timeout=120,
            check=False,
        )
    except Exception:
        pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    # always run make (a no-op when fresh): the Makefile's mtime dependency
    # rebuilds a STALE libpairio.so left by an older checkout — loading one
    # across an ABI change (e.g. the strict_cp1252 parameter) would call
    # the old entry point with the new signature and segfault
    _try_build()
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    # make can fail (missing toolchain, GENE2VEC_TPU_NO_NATIVE_BUILD set);
    # verify the loaded library speaks the ABI this wrapper was written for
    # rather than trusting mtimes — a stale .so with the old 4-arg
    # pairio_load_files called through the new 5-arg prototype is undefined
    # behavior, not a clean error.
    try:
        abi = lib.pairio_abi_version
    except AttributeError:
        return None  # pre-versioning build: fall back to the Python reader
    abi.argtypes = []
    abi.restype = ctypes.c_int64
    if abi() != _ABI_VERSION:
        return None
    lib.pairio_load_files.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.POINTER(_PairioResult),
    ]
    lib.pairio_load_files.restype = ctypes.c_int
    lib.pairio_free.argtypes = [ctypes.POINTER(_PairioResult)]
    lib.pairio_free.restype = None
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def load_corpus(
    paths: Sequence[str], min_count: int = 1, encoding: str = "windows-1252"
) -> Tuple[Vocab, np.ndarray]:
    """(Vocab, (N, 2) int32 pairs) — behavior-identical to the Python path.

    For the default windows-1252 encoding the reader rejects the five
    bytes cp1252 leaves undefined *inside its single scan* (the Python
    path's strict decoder raises on them anywhere in a file; a former
    wrapper-side pre-pass cost a full extra read of every file — round-2
    advisor finding)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native pairio library not available")
    paths = list(paths)
    strict = encoding.replace("-", "").lower() in ("windows1252", "cp1252")
    c_paths = (ctypes.c_char_p * len(paths))(
        *[p.encode("utf-8") for p in paths]
    )
    res = _PairioResult()
    rc = lib.pairio_load_files(
        c_paths, len(paths), min_count, int(strict), ctypes.byref(res)
    )
    if rc == -3:
        path, off, byte = paths[res.err_file], res.err_offset, res.err_byte
        lib.pairio_free(ctypes.byref(res))
        raise UnicodeDecodeError(
            "charmap", bytes([byte]), 0, 1,
            f"byte 0x{byte:02X} undefined in cp1252 ({path} offset {off})",
        )
    if rc != 0:
        lib.pairio_free(ctypes.byref(res))
        raise OSError(f"pairio_load_files failed with code {rc}")
    try:
        n = int(res.num_pairs)
        pairs = np.ctypeslib.as_array(res.pairs, shape=(n, 2)).copy() if n else (
            np.zeros((0, 2), np.int32)
        )
        v = int(res.vocab_size)
        counts = (
            np.ctypeslib.as_array(res.counts, shape=(v,)).copy()
            if v
            else np.zeros(0, np.int64)
        )
        # string_at on the live C buffer, length-bounded — runs before
        # pairio_free, copies exactly tokens_len bytes, never scans for a
        # terminator
        raw = ctypes.string_at(res.tokens, int(res.tokens_len))
        tokens: List[str] = (
            raw.decode(encoding).split("\n")[:-1] if res.tokens_len else []
        )
    finally:
        lib.pairio_free(ctypes.byref(res))
    if len(tokens) != v:
        raise RuntimeError(
            f"native reader token/count mismatch: {len(tokens)} vs {v}"
        )
    return Vocab(tokens, counts), pairs.astype(np.int32)
