"""Multi-tenant admission: per-tenant token buckets + weighted-fair
queuing primitives.

One abusive caller must never starve the rest of the fleet's tenants.
The serve front end tags every request with a tenant id (the
``X-Tenant`` header; untagged traffic is the ``default`` tenant) and
admission happens in two layers, both in this module:

* :class:`TenantAdmission` — a time-refilled :class:`RateBucket` per
  tenant.  A request whose tenant bucket is empty is rejected at the
  front door (HTTP 429) *before* it touches the batcher queue, and the
  rejection is counted with a tenant label
  (``serve_rejected_total{tenant=...}``) so the fleet view shows WHO is
  shedding.  The tenant table is bounded: beyond ``max_tenants``
  distinct ids, unknown tenants collapse into one shared ``other``
  bucket — a header-minting client cannot grow per-tenant state or
  metric cardinality.
* :class:`FairQueue` — per-tenant FIFO lanes drained by smooth weighted
  round-robin.  The micro-batcher dequeues through it, so even traffic
  that was *admitted* is interleaved by tenant weight when the queue is
  contended: a burst from one tenant fills its own lane, and a batch
  drains lanes proportionally instead of strictly by arrival order.

Quotas are per-replica by design (each replica enforces its own
buckets, so a fleet of N admits N x the configured rate in aggregate);
docs/SERVING.md#multi-tenant-admission covers sizing.  Everything here
is stdlib, lock-per-object, and clock-injectable for tests; with no
:class:`TenantPolicy` configured the serve path never touches any of
it.

On a multi-model fleet the tenant axis crosses with a MODEL axis:
``serve/catalog.py:ModelAdmission`` runs per-model :class:`RateBucket`
instances at the front door (bounded by the catalog table the way the
tenant table is bounded by ``max_tenants``), so a request must clear
both gates — its tenant's budget on the replica AND its model's budget
at the door.  A catalog replica shares ONE :class:`TenantAdmission`
across all of its per-model apps: a tenant's quota is a property of
the caller, not of which model they happen to query.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "BATCH_TENANT",
    "DEFAULT_BATCH_WEIGHT",
    "DEFAULT_TENANT",
    "FairQueue",
    "RateBucket",
    "TenantAdmission",
    "TenantPolicy",
    "TenantQuota",
    "sanitize_tenant",
]

#: the tenant every untagged request belongs to
DEFAULT_TENANT = "default"

#: the background-priority lane batch jobs (gene2vec_tpu/batch/) submit
#: on: a reserved tenant id, never assigned to external traffic, whose
#: FairQueue weight defaults to DEFAULT_BATCH_WEIGHT so a full-vocab
#: job drains at a few percent of a contended batch while interactive
#: lanes keep their shares (docs/BATCH.md#priority-tier-contract)
BATCH_TENANT = "batch"

#: the batch lane's default weighted-fair share when lanes are
#: contended (overridable per deployment via ServeConfig.batch_weight)
DEFAULT_BATCH_WEIGHT = 0.05

#: the shared lane/bucket unknown tenants collapse into once the
#: bounded tenant table is full
OVERFLOW_TENANT = "other"

_MAX_TENANT_CHARS = 64


def sanitize_tenant(raw: Optional[str]) -> str:
    """Header value -> tenant id: default for missing/empty, truncated
    to a bounded length (a tenant id is a label value — unbounded
    attacker-chosen strings must not reach the metrics registry)."""
    if not raw:
        return DEFAULT_TENANT
    raw = raw.strip()
    if not raw:
        return DEFAULT_TENANT
    return raw[:_MAX_TENANT_CHARS]


class RateBucket:
    """Time-refilled token bucket: ``rate`` tokens/second up to a
    ``burst`` cap.  Unlike the client's traffic-coupled retry budget
    (serve/client.py TokenBucket), this one meters *offered load
    against wall time* — the right shape for a tenant quota.  ``clock``
    is injectable so tests walk refills without sleeping."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst  # start full: a fresh tenant may burst
        self._last = clock()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission terms: sustained ``rate`` (requests/s),
    ``burst`` headroom, and the ``weight`` the fair queue drains its
    lane at."""

    rate: float
    burst: float
    weight: float = 1.0


class TenantPolicy:
    """The quota table: a default quota for every tenant plus explicit
    per-tenant overrides.  Parsed from CLI flags via
    :meth:`from_args` (``--tenant-override id:rate:burst[:weight]``)."""

    def __init__(self, default: TenantQuota,
                 overrides: Optional[Dict[str, TenantQuota]] = None):
        self.default = default
        self.overrides = dict(overrides or {})

    def quota(self, tenant: str) -> TenantQuota:
        return self.overrides.get(tenant, self.default)

    @classmethod
    def from_args(
        cls,
        default_rate: float,
        default_burst: Optional[float] = None,
        overrides: Sequence[str] = (),
    ) -> Optional["TenantPolicy"]:
        """CLI wiring: rate exactly 0 disables tenancy entirely
        (returns None); burst defaults to 2x the rate.  Override
        strings are ``id:rate[:burst[:weight]]``; a malformed one —
        including a NEGATIVE rate or burst, which is a typo, never a
        disable request — raises ``ValueError`` (a typo'd quota must
        fail at startup, not admit everything silently)."""
        if default_rate < 0:
            raise ValueError(
                f"tenant rate must be >= 0 (got {default_rate!r}; "
                "0 is the explicit tenancy-off sentinel)"
            )
        if default_burst is not None and default_burst < 0:
            raise ValueError(
                f"tenant burst must be >= 0 (got {default_burst!r})"
            )
        if default_rate == 0 and not overrides:
            return None
        parsed: Dict[str, TenantQuota] = {}
        for spec in overrides:
            parts = spec.split(":")
            if len(parts) < 2 or len(parts) > 4 or not parts[0]:
                raise ValueError(
                    f"--tenant-override must be id:rate[:burst[:weight]],"
                    f" got {spec!r}"
                )
            rate = float(parts[1])
            burst = float(parts[2]) if len(parts) > 2 else 2 * rate
            weight = float(parts[3]) if len(parts) > 3 else 1.0
            if rate <= 0 or burst <= 0 or weight <= 0:
                raise ValueError(
                    f"tenant override {spec!r}: rate/burst/weight must "
                    "be positive"
                )
            parsed[parts[0]] = TenantQuota(rate, burst, weight)
        if default_rate == 0:
            raise ValueError(
                "--tenant-override given but the default --tenant-quota "
                "is 0 (untagged traffic would be unmetered while named "
                "tenants are capped — set a default rate)"
            )
        default_burst = (
            2 * default_rate if default_burst is None or default_burst == 0
            else default_burst
        )
        return cls(TenantQuota(default_rate, default_burst), parsed)


class TenantAdmission:
    """Per-tenant token buckets with a bounded tenant table.

    :meth:`admit` is the front door's one call per request: it lazily
    creates the tenant's bucket (up to ``max_tenants`` distinct ids,
    then the shared overflow bucket), takes a token, and on rejection
    counts ``serve_rejected_total{tenant=...}``.  O(1), non-blocking,
    safe to run on the event-loop thread."""

    def __init__(
        self,
        policy: TenantPolicy,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
        max_tenants: int = 64,
    ):
        self.policy = policy
        self.metrics = metrics
        self._clock = clock
        self.max_tenants = int(max_tenants)
        self._buckets: Dict[str, RateBucket] = {}
        self._lock = threading.Lock()

    def resolve(self, tenant: str) -> str:
        """The id this tenant is accounted under: itself while the
        table has room (or an override names it), the shared overflow
        id after."""
        if tenant in self.policy.overrides or tenant == DEFAULT_TENANT:
            return tenant
        with self._lock:
            if tenant in self._buckets or (
                len(self._buckets) < self.max_tenants
            ):
                return tenant
        return OVERFLOW_TENANT

    def _bucket(self, tenant: str) -> RateBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                q = self.policy.quota(tenant)
                b = RateBucket(q.rate, q.burst, clock=self._clock)
                self._buckets[tenant] = b
            return b

    def admit(self, tenant: str) -> "tuple[bool, str]":
        """(admitted, resolved label).  The label — not the raw header
        value — is what callers key batcher lanes and metrics on, so
        minted tenant ids stay bounded everywhere downstream."""
        label = self.resolve(tenant)
        ok = self._bucket(label).take()
        if self.metrics is not None:
            self.metrics.counter(
                "serve_tenant_requests_total", labels={"tenant": label}
            ).inc()
            if not ok:
                # the tenant-labeled rejection series the drill and the
                # fleet view read; sums by NAME still merge with the
                # queue-full rejections into fleet_rejection_rate
                self.metrics.counter(
                    "serve_rejected_total", labels={"tenant": label}
                ).inc()
        return ok, label

    def weight(self, tenant: str) -> float:
        return self.policy.quota(tenant).weight


class FairQueue:
    """Per-tenant FIFO lanes + smooth weighted round-robin dequeue.

    NOT thread-safe by itself — the micro-batcher accesses it under its
    own condition-variable lock, exactly like the deque it replaces.
    ``weight_of`` maps a tenant id to its drain weight (default 1.0 for
    everyone = plain round-robin across lanes; a single-lane queue
    degenerates to FIFO, so untenanted deployments pay nothing but a
    dict lookup).

    The scheduler is the classic smooth-WRR: each :meth:`pop` credits
    every non-empty lane by its weight, drains the highest-credit lane,
    and debits the winner by the total weight in play — over a
    contended window lane ``i`` receives ``w_i / sum(w)`` of the pops
    regardless of arrival interleaving.  Credit is dropped when a lane
    empties, so an idle tenant cannot hoard scheduling debt and then
    monopolize a batch."""

    def __init__(self, weight_of: Optional[Callable[[str], float]] = None):
        self._weight_of = weight_of
        self._lanes: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        self._credit: Dict[str, float] = {}
        self._len = 0  # graftcheck: shared=externally synchronized; FairQueue is not thread-safe by contract — every caller holds the micro-batcher condition lock

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def _weight(self, tenant: str) -> float:
        if self._weight_of is None:
            return 1.0
        try:
            w = float(self._weight_of(tenant))
        except Exception:
            return 1.0
        return w if w > 0 else 1.0

    def push(self, tenant: str, item) -> None:
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = collections.deque()
            self._lanes[tenant] = lane
        lane.append(item)
        self._len += 1

    def pop(self):
        """The next item under weighted fairness; None when empty."""
        if self._len == 0:
            return None
        if len(self._lanes) == 1:
            # the common single-tenant case: plain FIFO, no credit math
            tenant, lane = next(iter(self._lanes.items()))
            item = lane.popleft()
            self._len -= 1
            if not lane:
                del self._lanes[tenant]
                self._credit.pop(tenant, None)
            return item
        total = 0.0
        best: Optional[str] = None
        best_credit = float("-inf")
        for tenant, lane in self._lanes.items():
            w = self._weight(tenant)
            total += w
            c = self._credit.get(tenant, 0.0) + w
            self._credit[tenant] = c
            if c > best_credit:
                best_credit = c
                best = tenant
        assert best is not None
        self._credit[best] -= total
        lane = self._lanes[best]
        item = lane.popleft()
        self._len -= 1
        if not lane:
            del self._lanes[best]
            self._credit.pop(best, None)
        return item

    def pop_upto(self, n: int) -> List:
        out = []
        while len(out) < n and self._len:
            out.append(self.pop())
        return out

    def drain(self) -> List:
        return self.pop_upto(self._len)
