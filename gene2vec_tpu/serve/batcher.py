"""Micro-batching request queue: admission policy, backpressure, caching.

Requests enqueue from HTTP handler threads and one worker thread drains
them in batches under a **max-delay / max-batch** admission policy: the
first waiting request opens a batch window; the batch closes when either
``max_batch`` requests have joined or ``max_delay_s`` has elapsed since
the window opened, whichever is first.  An idle queue therefore costs a
single request at most ``max_delay_s`` of added latency, while a busy
queue closes batches on size and never waits.

Overload never grows memory: the queue is bounded at ``max_queue`` and
:meth:`MicroBatcher.submit` rejects immediately (:class:`RejectedError`
-> HTTP 429) when full — callers shed load instead of stacking it.  Each
request carries a deadline; requests that expire while queued are failed
(:class:`DeadlineExceeded` -> HTTP 504) without spending compute on
them.  A bounded LRU keyed by (model version, query, k) serves repeat
lookups without touching the queue at all.

Requests carry a **tenant id** (``serve/tenancy.py``): the queue is a
:class:`~gene2vec_tpu.serve.tenancy.FairQueue` of per-tenant FIFO lanes
drained by smooth weighted round-robin, so when the queue is contended
a batch interleaves tenants by their configured weights instead of
strictly by arrival order — one tenant's admitted burst fills its own
lane, not the head of everyone's line.  With a single (default) tenant
the queue degenerates to plain FIFO.  Token-bucket *quotas* are
enforced upstream at the front end (server.py ``TenantAdmission``),
before a request ever reaches this queue.

Every batch runs under an obs span (``serve_batch`` wrapping
``serve_compute``), so a run's ``events.jsonl`` shows the
enqueue->batch->compute->respond pipeline per batch; counters/gauges
(queue depth, batch size, rejections, expirations, cache hits) land in
the same registry ``/metrics`` exports.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Hashable, List, Optional, Tuple

from gene2vec_tpu.obs import flight, tracecontext
from gene2vec_tpu.obs.trace import ambient_span, hop_span
from gene2vec_tpu.serve.tenancy import DEFAULT_TENANT, FairQueue


class RejectedError(RuntimeError):
    """Queue at capacity — explicit backpressure (HTTP 429)."""


class DeadlineExceeded(RuntimeError):
    """Request deadline passed before a result was ready (HTTP 504)."""


class _Pending:
    __slots__ = ("item", "k", "deadline", "event", "result", "error",
                 "ctx", "t0", "wait_s", "compute_s", "batch_n",
                 "on_done", "cache_key", "tenant")

    def __init__(self, item: Any, k: int, deadline: float,
                 t0: float = 0.0, on_done=None, cache_key=None,
                 tenant: str = DEFAULT_TENANT):
        self.item = item
        self.k = k
        self.deadline = deadline
        self.tenant = tenant
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        # distributed-tracing ticket state: the submitting request's
        # trace context (captured on the handler thread) plus the
        # queue-wait / compute timings the worker fills in
        self.ctx = tracecontext.current()
        self.t0 = t0
        self.wait_s: Optional[float] = None
        self.compute_s: Optional[float] = None
        self.batch_n: Optional[int] = None
        # completion callback (event-loop coalescing path): invoked by
        # the worker thread as ``on_done(result, error)`` AFTER the
        # result/error fields settle and the event is set — so a
        # non-blocking front end gets its answer without parking a
        # thread on Ticket.get
        self.on_done = on_done
        self.cache_key = cache_key


class LRUCache:
    """Bounded thread-safe LRU (size 0 disables)."""

    def __init__(self, max_size: int):
        self.max_size = max_size
        self._data: "collections.OrderedDict[Hashable, Any]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def get(self, key: Hashable):
        with self._lock:
            if key not in self._data:
                return None
            self._data.move_to_end(key)
            return self._data[key]

    def put(self, key: Hashable, value: Any) -> None:
        if self.max_size <= 0:
            return
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.max_size:
                self._data.popitem(last=False)

    def __len__(self) -> int:
        return len(self._data)


class Ticket:
    """Handle for one submitted request; :meth:`get` blocks for the
    result, raising the request's failure."""

    __slots__ = ("_batcher", "_pending", "_cache_key", "_t0", "_timeout_s",
                 "_cached")

    def __init__(self, batcher, pending, cache_key, t0,
                 timeout_s: float = 0.0, cached=None):
        self._batcher = batcher
        self._pending = pending
        self._cache_key = cache_key
        self._t0 = t0
        self._timeout_s = timeout_s
        self._cached = cached

    def get(self):
        if self._pending is None:
            return self._cached
        b = self._batcher
        remaining = (self._t0 + self._timeout_s) - time.monotonic()
        if not self._pending.event.wait(max(0.0, remaining)):
            b._count("serve_deadline_expired_total")
            raise DeadlineExceeded(
                f"no result within {self._timeout_s:.3f}s"
            )
        if self._pending.error is not None:
            raise self._pending.error
        b._observe("serve_request_seconds", time.monotonic() - self._t0)
        # ticket timings flow into the request's flight-recorder hop
        # sink (get() runs on the submitting handler thread)
        if self._pending.wait_s is not None:
            flight.add_hop("queue_wait_s", self._pending.wait_s)
        if self._pending.compute_s is not None:
            flight.add_hop("compute_s", self._pending.compute_s)
        if self._pending.batch_n is not None:
            flight.add_hop("batch", self._pending.batch_n)
        # the worker already cached successful results (_settle)
        return self._pending.result


class MicroBatcher:
    """Batches ``(item, k)`` requests into calls of
    ``compute(items, k_max) -> list-of-results`` on one worker thread.

    ``compute`` receives the batch's items and the max padded ``k`` over
    the batch and must return one result per item, in order.  Mixed-k
    batches compute at the largest k; each caller gets its own result
    back untouched (the compute fn crops per-item if it cares).
    """

    def __init__(
        self,
        compute: Callable[[List[Any], int], List[Any]],
        max_batch: int = 64,
        max_delay_s: float = 0.005,
        max_queue: int = 256,
        cache_size: int = 1024,
        default_timeout_s: float = 2.0,
        metrics=None,
        tenant_weights: Optional[Callable[[str], float]] = None,
        labels: Optional[dict] = None,
    ):
        self.compute = compute
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self.default_timeout_s = default_timeout_s
        self.cache = LRUCache(cache_size)
        self.metrics = metrics
        # extra label set on every instrument this batcher touches —
        # the multi-model catalog (serve/catalog.py) runs one batcher
        # per model against ONE shared registry, and ``{model=}``
        # labels are what keep sibling queues from fighting over the
        # same serve_queue_depth gauge.  None (single-model) keeps the
        # historical unlabeled series.
        self.labels = dict(labels) if labels else None
        # per-tenant lanes, weighted-fair drained; accessed only under
        # self._cv (FairQueue itself is lock-free by contract)
        self._q = FairQueue(weight_of=tenant_weights)
        self._cv = threading.Condition()
        self._stop = False
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._worker is None:
            self._stop = False
            self._worker = threading.Thread(
                target=self._run, name="micro-batcher", daemon=True
            )
            self._worker.start()
        return self

    def stop(self) -> None:
        if self._worker is None:
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=5.0)
        self._worker = None

    # -- metrics helpers ---------------------------------------------------

    def _count(self, name: str, amount: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name, labels=self.labels).inc(amount)

    def _observe(self, name: str, value: float) -> None:
        if self.metrics is not None:
            self.metrics.histogram(name, labels=self.labels).observe(value)

    def _gauge_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "serve_queue_depth", labels=self.labels
            ).set(len(self._q))

    # -- submission --------------------------------------------------------

    def submit_async(
        self,
        item: Any,
        k: int,
        cache_key: Optional[Hashable] = None,
        timeout_s: Optional[float] = None,
        on_done: Optional[Callable[[Any, Optional[BaseException]], None]]
        = None,
        tenant: str = DEFAULT_TENANT,
    ) -> "Ticket":
        """Enqueue one request and return a :class:`Ticket` immediately
        (so a multi-query HTTP request lands all its queries in the same
        batch window before blocking on any of them).

        ``on_done(result, error)`` — when given — is invoked by the
        worker thread once the request settles (result, per-batch
        failure, or expired-in-queue), so non-blocking callers (the
        event-loop front end's coalesced GETs) never park a thread on
        :meth:`Ticket.get`.  A cache hit invokes it synchronously.

        Raises :class:`RejectedError` right here when the queue is full
        — backpressure is decided at admission, never deferred.
        """
        self._count("serve_requests_total")
        if cache_key is not None:
            hit = self.cache.get(cache_key)
            if hit is not None:
                self._count("serve_cache_hits_total")
                ctx = tracecontext.current()
                if ctx is not None and ctx.sampled:
                    # a cached answer skips batcher+engine entirely —
                    # record the hop so the trace doesn't dead-end
                    hop_span("cache_hit", ctx.child(), dur=0.0)
                if on_done is not None:
                    on_done(hit, None)
                return Ticket(self, None, None, 0.0, cached=hit)
        timeout_s = (
            self.default_timeout_s if timeout_s is None else float(timeout_s)
        )
        t0 = time.monotonic()
        pending = _Pending(item, int(k), t0 + timeout_s, t0=t0,
                           on_done=on_done, cache_key=cache_key,
                           tenant=tenant)
        with self._cv:
            if self._worker is None:
                raise RuntimeError("MicroBatcher not started")
            if len(self._q) >= self.max_queue:
                self._count("serve_rejected_total")
                raise RejectedError(
                    f"queue full ({self.max_queue} waiting requests)"
                )
            self._q.push(tenant, pending)
            self._gauge_depth()
            self._cv.notify_all()
        return Ticket(self, pending, cache_key, t0, timeout_s=timeout_s)

    def submit(
        self,
        item: Any,
        k: int,
        cache_key: Optional[Hashable] = None,
        timeout_s: Optional[float] = None,
        tenant: str = DEFAULT_TENANT,
    ) -> Any:
        """Blocking :meth:`submit_async`: the result, or
        :class:`RejectedError` / :class:`DeadlineExceeded` /
        the compute failure."""
        return self.submit_async(
            item, k, cache_key=cache_key, timeout_s=timeout_s,
            tenant=tenant,
        ).get()

    # -- worker ------------------------------------------------------------

    def _gather(self) -> List[_Pending]:
        """Admission policy: block for the first request, then hold the
        window open until ``max_batch`` joined or ``max_delay_s`` passed."""
        with self._cv:
            while not self._q and not self._stop:
                self._cv.wait()
            if self._stop and not self._q:
                return []
            window_ends = time.monotonic() + self.max_delay_s
            batch: List[_Pending] = []
            while len(batch) < self.max_batch:
                # weighted-fair drain: lanes are interleaved by tenant
                # weight, FIFO within a tenant (serve/tenancy.py)
                batch.extend(self._q.pop_upto(self.max_batch - len(batch)))
                remaining = window_ends - time.monotonic()
                if remaining <= 0 or len(batch) >= self.max_batch:
                    break
                self._cv.wait(timeout=remaining)
                if self._stop and not self._q:
                    break
            self._gauge_depth()
            return batch

    def _settle(self, p: _Pending) -> None:
        """Publish one request's outcome: cache successful results,
        release the waiter, fire the completion callback.  Runs on the
        worker thread for every non-cache-hit request exactly once."""
        if p.error is None and p.cache_key is not None:
            self.cache.put(p.cache_key, p.result)
        p.event.set()
        if p.on_done is not None:
            try:
                p.on_done(p.result, p.error)
            except Exception:  # a callback bug must not kill the worker
                self._count("serve_callback_errors_total")

    def _run(self) -> None:
        while True:
            batch = self._gather()
            if not batch:
                if self._stop:
                    return
                continue
            now = time.monotonic()
            live: List[_Pending] = []
            for p in batch:
                if p.deadline <= now:
                    # expired while queued: fail it without computing
                    # (submit() already returned DeadlineExceeded; this
                    # keeps the slot from consuming batch capacity)
                    p.error = DeadlineExceeded("expired in queue")
                    self._settle(p)
                    self._count("serve_expired_in_queue_total")
                else:
                    live.append(p)
            if not live:
                continue
            self._observe("serve_batch_size", len(live))
            k_max = max(p.k for p in live)
            for p in live:
                p.wait_s = now - p.t0
                p.batch_n = len(live)
            traced = [
                p for p in live if p.ctx is not None and p.ctx.sampled
            ]
            try:
                with ambient_span(
                    "serve_batch", size=len(live), k=k_max
                ) as span:
                    t_c0 = time.monotonic()
                    with ambient_span("serve_compute"):
                        results = self.compute([p.item for p in live], k_max)
                    compute_s = time.monotonic() - t_c0
                    span["ok"] = True
                    for p in live:
                        p.compute_s = compute_s
                    if traced:
                        # the batch serves many traces at once: record
                        # which (bounded), and give each sampled item
                        # its own hop — emitted INSIDE the serve_batch
                        # span so the hop's process-local `span` field
                        # links the compute subtree per trace
                        span["traces"] = sorted(
                            {p.ctx.trace_id for p in traced}
                        )[:8]
                        for p in traced:
                            hop_span(
                                "batch_item", p.ctx.child(),
                                dur=compute_s,
                                queue_wait_s=round(p.wait_s, 6),
                                batch=len(live), k=k_max,
                            )
                if len(results) != len(live):
                    raise RuntimeError(
                        f"compute returned {len(results)} results for "
                        f"{len(live)} items"
                    )
                for p, r in zip(live, results):
                    p.result = r
                    self._settle(p)
            except BaseException as e:  # noqa: BLE001 — failures propagate per request
                for p in live:
                    if not p.event.is_set():
                        p.error = e
                        self._settle(p)
                self._count("serve_batch_errors_total")
