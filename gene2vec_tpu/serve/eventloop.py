"""Non-blocking HTTP/1.1 front end: selectors event loop + keep-alive.

The thread-per-connection ``ThreadingHTTPServer`` front end knees at
~150 offered rps on this host (BENCH_SERVE_r06): every request pays a
TCP handshake, a thread spawn, and per-request header/body assembly.
This module replaces that hot path with a classic reactor:

* an **acceptor event loop** (``selectors.DefaultSelector``, so epoll
  on Linux) owns every connection; sockets are non-blocking and all
  socket I/O happens through the loop's ``_fill``/``_flush`` I/O-path
  helpers — the graftcheck ``event-loop-blocking`` pass forbids
  blocking calls (``time.sleep``, ``sendall``/``recv``, ``json.dumps``)
  inside the ``_on_*`` callbacks themselves;
* **HTTP/1.1 keep-alive** with a bounded requests-per-connection cap
  (``max_conn_requests``) and an **idle timeout** — a fleet of clients
  reusing connections pays the handshake once, while idle or abusive
  connections cannot pin loop state forever;
* the **slow-loris read deadline** (serve/server.py's 408 contract)
  re-expressed as an event-loop deadline: once a request's first byte
  arrives, the whole request must arrive within ``read_timeout_s`` or
  the loop answers 408 and closes;
* **zero-copy response writes**: a response is a list of reusable
  ``bytes`` buffers (status/header fragments + a shared body) handed
  to ``socket.sendmsg`` — a cached hot response is one syscall over
  bytes objects that are never copied or re-encoded per request;
* optional **SO_REUSEPORT multi-acceptor** mode (``acceptors > 1``):
  N independent loops each bind the same port and the kernel spreads
  accepted connections across them — one loop's Python execution stops
  being the accept ceiling.

The application side plugs in as a *handler adapter*: a callable
``handler(request, peer) -> Optional[Response]``.  Returning a
:class:`Response` answers inline (the fast path — must not block);
returning ``None`` promises that ``peer.respond(...)`` will be called
later from another thread (a worker pool, the micro-batcher's
completion callback).  ``peer`` is a :class:`ConnHandle` whose
``respond``/``reset``/``close`` are thread-safe: off-loop calls post a
completion and wake the loop through a self-pipe.

Interface-compatible with the old ``ThreadingHTTPServer`` shell where
tests and CLIs touch it: ``serve_forever()`` / ``shutdown()`` /
``server_close()`` / ``server_address``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import queue as queue_mod
import selectors
import socket
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "BadRequest",
    "ConnHandle",
    "EventLoopConfig",
    "EventLoopHTTPServer",
    "HandlerPool",
    "HTTPRequest",
    "Response",
    "build_head",
]


class HandlerPool:
    """Bounded worker pool for an adapter's full-dispatch path.
    ``submit`` never blocks: a full queue returns False and the front
    end answers 429 — saturation sheds load exactly like the batcher
    queue does."""

    def __init__(self, workers: int, max_queue: int,
                 name: str = "http-worker"):
        self._q: "queue_mod.Queue[Optional[Callable[[], None]]]" = (
            queue_mod.Queue(maxsize=max_queue)
        )
        self._threads = [
            threading.Thread(
                target=self._run, name=f"{name}-{i}", daemon=True
            )
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                pass  # adapters answer their own 500s; never die

    def submit(self, fn: Callable[[], None]) -> bool:
        try:
            self._q.put_nowait(fn)
            return True
        except queue_mod.Full:
            return False

    def stop(self) -> None:
        for _ in self._threads:
            try:
                self._q.put_nowait(None)
            except queue_mod.Full:
                break
        for t in self._threads:
            t.join(timeout=2.0)


@dataclasses.dataclass(frozen=True)
class EventLoopConfig:
    """Front-end policy knobs (cli/serve.py + cli/fleet.py flags)."""

    #: slow-loris guard: a request whose first byte has arrived must
    #: arrive COMPLETELY within this window or the loop answers 408 and
    #: closes (the serve/server.py read-deadline contract)
    read_timeout_s: float = 10.0
    #: keep-alive connections idle longer than this are closed silently
    idle_timeout_s: float = 30.0
    #: requests served per connection before the loop answers the last
    #: one with ``Connection: close`` (0 = unbounded)
    max_conn_requests: int = 0
    #: number of acceptor loops; > 1 binds SO_REUSEPORT listening
    #: sockets so the kernel load-balances connections across loops
    acceptors: int = 1
    max_header_bytes: int = 32768
    max_body_bytes: int = 8 << 20
    #: hard cap on one dispatched request with no response (a lost
    #: completion must not leak the connection forever)
    inflight_timeout_s: float = 120.0
    backlog: int = 1024


class HTTPRequest:
    """One parsed request: method, raw target, lowercased header map,
    body bytes.  Header names are latin-1 decoded and lowercased;
    everything else stays bytes until the application needs it."""

    __slots__ = ("method", "target", "headers", "body", "version")

    def __init__(self, method: str, target: str, version: str,
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.target = target
        self.version = version
        self.headers = headers
        self.body = body


class Response:
    """One response: a status, a reusable body buffer, and a close
    flag.  The body is NOT copied — cached hot responses hand the same
    bytes object to every connection."""

    __slots__ = ("status", "body", "content_type", "close")

    def __init__(self, status: int, body: bytes,
                 content_type: bytes = b"application/json",
                 close: bool = False):
        self.status = status
        self.body = body
        self.content_type = content_type
        self.close = close


class BadRequest(Exception):
    """Protocol violation — the loop answers ``status`` (default 400)
    and closes.  ``body`` is a pre-encoded error document (the loop
    never runs json.dumps)."""

    def __init__(self, message: str, status: int = 400,
                 body: Optional[bytes] = None):
        super().__init__(message)
        self.status = status
        self.body = body


_STATUS_TEXT = {
    200: b"HTTP/1.1 200 OK\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    408: b"HTTP/1.1 408 Request Timeout\r\n",
    413: b"HTTP/1.1 413 Payload Too Large\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    502: b"HTTP/1.1 502 Bad Gateway\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
    504: b"HTTP/1.1 504 Gateway Timeout\r\n",
}

_CT_PREFIX = b"Content-Type: "
_CL_PREFIX = b"\r\nContent-Length: "
_KEEPALIVE_TAIL = b"\r\n\r\n"
_CLOSE_TAIL = b"\r\nConnection: close\r\n\r\n"

#: Content-Length values are tiny and repeat constantly under load —
#: pre-encode the common ones so the header build is pure concat
_CLEN_CACHE = tuple(str(n).encode("ascii") for n in range(4096))


def build_head(status: int, body_len: int,
               content_type: bytes = b"application/json",
               close: bool = False) -> bytes:
    """One response head from reusable fragments (no f-strings, no
    per-request dict walks — this runs on the loop thread)."""
    line = _STATUS_TEXT.get(status)
    if line is None:
        line = (b"HTTP/1.1 %d Status\r\n" % status)
    clen = (
        _CLEN_CACHE[body_len] if body_len < len(_CLEN_CACHE)
        else str(body_len).encode("ascii")
    )
    return b"".join((
        line, _CT_PREFIX, content_type, _CL_PREFIX, clen,
        _CLOSE_TAIL if close else _KEEPALIVE_TAIL,
    ))


#: pre-encoded loop-generated error bodies: the loop never runs
#: json.dumps (the event-loop-blocking contract)
_BODY_400 = b'{"error": "malformed HTTP request"}'
_BODY_408 = b'{"error": "request read timed out"}'
_BODY_413 = b'{"error": "request too large"}'
_BODY_504 = b'{"error": "handler timed out (inflight cap)"}'

#: bytes of pipelined input buffered per connection while a request is
#: in flight; beyond it the loop stops reading (kernel TCP window
#: backpressures the sender) until the response completes — a client
#: streaming garbage behind a slow request cannot grow our memory
_PIPELINE_BUF_CAP = 256 * 1024


def parse_json_body(req: "HTTPRequest"):
    """Decode a request's JSON object body: ``(body_dict, None)`` or
    ``(None, Response(400, ...))``.  Shared by the serve and fleet
    adapters so the error shape cannot drift between the two V1
    surfaces.  Runs on worker-pool threads, never on the loop."""
    try:
        body = json.loads(req.body.decode("utf-8")) if req.body else {}
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        return body, None
    except (ValueError, UnicodeDecodeError) as e:
        return None, Response(
            400,
            json.dumps({"error": f"bad JSON body: {e}"}).encode("utf-8"),
        )


class _Conn:
    """Per-connection state: read buffer + incremental parse state,
    write buffer, keep-alive bookkeeping, deadlines."""

    __slots__ = (
        "sock", "fd", "rbuf", "out", "header_end", "method", "target",
        "version", "headers", "content_length", "requests", "seq",
        "inflight", "closing", "deadline", "idle", "want_write",
        "advancing", "paused", "registered_mask",
    )

    def __init__(self, sock: socket.socket, idle_deadline: float):
        self.sock = sock
        self.fd = sock.fileno()
        self.rbuf = bytearray()
        #: outgoing buffers (memoryviews), oldest first
        self.out: Deque[memoryview] = collections.deque()
        self.header_end = -1
        self.method = ""
        self.target = ""
        self.version = ""
        self.headers: Dict[str, str] = {}
        self.content_length = 0
        self.requests = 0
        #: response-generation counter; stale completions are dropped
        self.seq = 0
        self.inflight = False
        self.closing = False
        self.deadline = idle_deadline
        self.idle = True
        self.want_write = False
        self.advancing = False
        self.paused = False
        self.registered_mask = 0


class ConnHandle:
    """The application's thread-safe handle to one in-flight request.
    ``respond`` may be called from any thread exactly once; late calls
    (the connection died, a newer request took over) are dropped."""

    __slots__ = ("_loop", "_conn", "_seq", "close_after")

    def __init__(self, loop: "_AcceptorLoop", conn: _Conn, seq: int,
                 close_after: bool):
        self._loop = loop
        self._conn = conn
        self._seq = seq
        #: the loop decided this request is the connection's last
        #: (request cap / Connection: close); adapters may OR into it
        self.close_after = close_after

    def respond(self, response: Response) -> None:
        self._loop.post(
            self._conn, self._seq, "respond",
            (response, self.close_after),
        )

    def reset(self) -> None:
        """TCP RST + close (fault injection's ``reset`` kind)."""
        self._loop.post(self._conn, self._seq, "reset", None)

    def close(self) -> None:
        """Close without answering (fault injection's blackhole end)."""
        self._loop.post(self._conn, self._seq, "close", None)


class _AcceptorLoop:
    """One selector loop: a listening socket, its connections, a
    self-pipe waker, and a completion queue fed by worker threads."""

    def __init__(self, server: "EventLoopHTTPServer",
                 lsock: socket.socket):
        self.server = server
        self.config = server.config
        self.handler = server.handler
        self.lsock = lsock
        self.sel = selectors.DefaultSelector()
        self.conns: Dict[int, _Conn] = {}
        # cross-thread completion handoff: workers append, the loop
        # drains after a self-pipe wake; deque.append/popleft are
        # GIL-atomic and stale entries are dropped by the seq check
        self._completions: Deque[Tuple[_Conn, int, str, object]] = (  # graftcheck: shared=GIL-atomic deque handoff; loop drains after self-pipe wake, seq check drops stale entries
            collections.deque()
        )
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._stop = threading.Event()
        self._thread_id: Optional[int] = None
        self._last_sweep = 0.0

    # -- cross-thread completion path -------------------------------------

    def post(self, conn: _Conn, seq: int, action: str,
             payload: object) -> None:
        """Queue a completion for the loop thread (direct-dispatch when
        already ON the loop thread — the inline fast path)."""
        if threading.get_ident() == self._thread_id:
            self._apply(conn, seq, action, payload)
            return
        self._completions.append((conn, seq, action, payload))
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wakeup is already pending

    def _drain_completions(self) -> None:
        while self._completions:
            conn, seq, action, payload = self._completions.popleft()
            self._apply(conn, seq, action, payload)

    def _apply(self, conn: _Conn, seq: int, action: str,
               payload: object) -> None:
        if conn.fd not in self.conns or seq != conn.seq:
            return  # connection gone or a newer request took over
        if action == "respond":
            resp, close_after = payload  # type: ignore[misc]
            self._queue_response(conn, resp, close_after)
        elif action == "reset":
            from gene2vec_tpu.resilience.faults import apply_reset

            try:
                apply_reset(conn.sock)
            except OSError:
                pass
            self._close(conn)
        elif action == "close":
            self._close(conn)

    # -- selector callbacks -------------------------------------------------
    # The _on_* callbacks below are the graftcheck event-loop-blocking
    # pass's jurisdiction: no sleeps, no blocking socket calls, no JSON
    # encoding — raw I/O lives in the _fill/_flush I/O-path helpers.

    def _on_accept(self) -> None:
        for _ in range(128):  # bounded accept burst per wakeup
            try:
                # non-blocking listener: accept() raises BlockingIOError
                # instead of waiting
                sock, _addr = self.lsock.accept()  # graftcheck: disable=loop-thread-blocking
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listening socket closed under us (shutdown)
            sock.setblocking(False)
            try:
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass  # AF_UNIX or exotic stacks: latency opt only
            conn = _Conn(
                sock, time.monotonic() + self.config.idle_timeout_s
            )
            self.conns[conn.fd] = conn
            self.sel.register(sock, selectors.EVENT_READ, conn)
            conn.registered_mask = selectors.EVENT_READ

    def _on_wake(self) -> None:
        self._drain_waker()

    def _on_readable(self, conn: _Conn) -> None:
        if conn.inflight and len(conn.rbuf) >= _PIPELINE_BUF_CAP:
            # backpressure: while a request is in flight, buffered
            # pipelined bytes are bounded — stop reading (the kernel's
            # TCP window throttles the sender) until the response lands
            self._set_paused(conn, True)
            return
        if not self._fill(conn):
            return
        self._advance(conn)

    def _on_writable(self, conn: _Conn) -> None:
        self._flush(conn)

    def _update_interest(self, conn: _Conn) -> None:
        """Reconcile the selector registration with the connection's
        desired interest: READ unless paused (backpressure), WRITE
        while the out-buffer has bytes.  A fully quiesced connection
        (paused, nothing to write) is unregistered until un-paused —
        the kernel's TCP window then throttles the sender."""
        if conn.fd not in self.conns:
            return
        mask = (0 if conn.paused else selectors.EVENT_READ) | (
            selectors.EVENT_WRITE if conn.want_write else 0
        )
        if mask == conn.registered_mask:
            return
        try:
            if mask == 0:
                self.sel.unregister(conn.sock)
            elif conn.registered_mask == 0:
                self.sel.register(conn.sock, mask, conn)
            else:
                self.sel.modify(conn.sock, mask, conn)
            conn.registered_mask = mask
        except (KeyError, ValueError, OSError):
            pass

    def _set_paused(self, conn: _Conn, paused: bool) -> None:
        if conn.paused != paused:
            conn.paused = paused
            self._update_interest(conn)

    # -- raw I/O (the writer/reader path; blocking-call pass exempt) -------

    def _drain_waker(self) -> None:
        """Drain the (non-blocking) self-pipe."""
        try:
            # non-blocking self-pipe read; loop exits on BlockingIOError
            while self._wake_r.recv(4096):  # graftcheck: disable=loop-thread-blocking
                pass
        except (BlockingIOError, InterruptedError):
            pass

    def _fill(self, conn: _Conn) -> bool:
        """Read what the socket has.  False when the connection died
        (and was cleaned up)."""
        try:
            # conn sockets are non-blocking (setblocking(False) at accept)
            chunk = conn.sock.recv(262144)  # graftcheck: disable=loop-thread-blocking
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            self._close(conn)
            return False
        if not chunk:
            self._close(conn)  # peer closed; nothing sensible to finish
            return False
        if conn.idle and not conn.inflight:
            # first byte of a new request: arm the slow-loris deadline
            conn.idle = False
            conn.deadline = time.monotonic() + self.config.read_timeout_s
        conn.rbuf += chunk
        return True

    def _flush(self, conn: _Conn) -> None:
        """Drain the write buffer; closes on completion when the
        connection is marked closing."""
        sock = conn.sock
        out = conn.out
        try:
            while out:
                if len(out) > 1:
                    n = sock.sendmsg(tuple(out)[:16])
                else:
                    n = sock.send(out[0])
                while n > 0 and out:
                    head = out[0]
                    if n >= len(head):
                        n -= len(head)
                        out.popleft()
                    else:
                        out[0] = head[n:]
                        n = 0
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(conn)
            return
        if out:
            if not conn.want_write:
                conn.want_write = True
                self._update_interest(conn)
        else:
            if conn.want_write:
                conn.want_write = False
                self._update_interest(conn)
            if conn.closing:
                self._close(conn)

    # -- request parsing / dispatch ----------------------------------------

    def _parse(self, conn: _Conn) -> Optional[HTTPRequest]:
        """One incremental parse step; None when more bytes are needed.
        Raises :class:`BadRequest` on protocol violations."""
        buf = conn.rbuf
        if conn.header_end < 0:
            idx = buf.find(b"\r\n\r\n")
            if idx < 0:
                if len(buf) > self.config.max_header_bytes:
                    raise BadRequest("headers exceed the size cap")
                return None
            head = bytes(buf[:idx])
            del buf[: idx + 4]
            lines = head.split(b"\r\n")
            parts = lines[0].split(b" ")
            if len(parts) != 3 or not parts[2].startswith(b"HTTP/1."):
                raise BadRequest("malformed request line")
            try:
                conn.method = parts[0].decode("ascii")
                conn.target = parts[1].decode("latin-1")
                conn.version = parts[2].decode("ascii")
            except UnicodeDecodeError:
                raise BadRequest("malformed request line") from None
            headers: Dict[str, str] = {}
            for ln in lines[1:]:
                name, sep, value = ln.partition(b":")
                if not sep:
                    raise BadRequest("malformed header line")
                headers[name.strip().lower().decode("latin-1")] = (
                    value.strip().decode("latin-1")
                )
            cl_raw = headers.get("content-length", "0")
            try:
                conn.content_length = int(cl_raw)
            except ValueError:
                raise BadRequest("malformed Content-Length") from None
            if conn.content_length < 0:
                raise BadRequest("negative Content-Length")
            if conn.content_length > self.config.max_body_bytes:
                raise BadRequest(
                    "body exceeds the size cap", status=413,
                    body=_BODY_413,
                )
            conn.headers = headers
            conn.header_end = 0
        if len(buf) < conn.content_length:
            return None
        body = bytes(buf[: conn.content_length])
        del buf[: conn.content_length]
        req = HTTPRequest(
            conn.method, conn.target, conn.version, conn.headers, body
        )
        conn.header_end = -1
        conn.content_length = 0
        conn.headers = {}
        return req

    def _advance(self, conn: _Conn) -> None:
        """Parse and dispatch as many buffered requests as possible.
        One request is in flight per connection at a time; buffered
        pipelined requests are picked up as each response completes.
        The ``advancing`` guard keeps inline responses (handler answers
        synchronously -> _queue_response -> _advance) iterative: the
        outer while drains pipelined requests without re-entering."""
        if conn.advancing:
            return
        conn.advancing = True
        try:
            self._advance_inner(conn)
        finally:
            conn.advancing = False

    def _advance_inner(self, conn: _Conn) -> None:
        while not conn.inflight and not conn.closing:
            try:
                req = self._parse(conn)
            except BadRequest as e:
                self._error_out(
                    conn, e.status,
                    e.body if e.body is not None else _BODY_400,
                )
                return
            if req is None:
                if conn.rbuf or conn.header_end >= 0:
                    pass  # mid-request: the read deadline stays armed
                else:
                    conn.idle = True
                    conn.deadline = (
                        time.monotonic() + self.config.idle_timeout_s
                    )
                return
            conn.requests += 1
            cap = self.config.max_conn_requests
            close_after = bool(cap and conn.requests >= cap)
            if req.headers.get("connection", "").lower() == "close":
                close_after = True
            elif req.version == "HTTP/1.0" and req.headers.get(
                "connection", ""
            ).lower() != "keep-alive":
                close_after = True
            conn.seq += 1
            conn.inflight = True
            conn.idle = False
            conn.deadline = (
                time.monotonic() + self.config.inflight_timeout_s
            )
            peer = ConnHandle(self, conn, conn.seq, close_after)
            try:
                resp = self.handler(req, peer)
            except Exception:
                resp = Response(500, b'{"error": "handler crashed"}')
            if resp is not None:
                self._queue_response(conn, resp, peer.close_after)

    def _queue_response(self, conn: _Conn, resp: Response,
                        close_after: Optional[bool] = None) -> None:
        close = resp.close or bool(close_after)
        head = build_head(
            resp.status, len(resp.body), resp.content_type, close
        )
        conn.out.append(memoryview(head))
        if resp.body:
            conn.out.append(memoryview(resp.body))
        conn.inflight = False
        if close:
            conn.closing = True
        else:
            conn.idle = not conn.rbuf
            conn.deadline = time.monotonic() + (
                self.config.idle_timeout_s if conn.idle
                else self.config.read_timeout_s
            )
            self._set_paused(conn, False)  # resume a backpressured reader
        self._flush(conn)
        if conn.fd in self.conns and not conn.closing:
            self._advance(conn)  # pipelined requests already buffered

    def _error_out(self, conn: _Conn, status: int, body: bytes) -> None:
        conn.closing = True
        conn.inflight = False
        conn.seq += 1  # orphan any in-flight completion
        conn.out.append(
            memoryview(build_head(status, len(body), close=True))
        )
        conn.out.append(memoryview(body))
        if self.server.on_protocol_error is not None:
            try:
                self.server.on_protocol_error(status)
            except Exception:
                pass  # accounting must never take the loop down
        self._flush(conn)

    # -- deadlines ----------------------------------------------------------

    def _sweep(self, now: float) -> None:
        if now - self._last_sweep < 0.05:
            return
        self._last_sweep = now
        expired = [
            c for c in self.conns.values() if now >= c.deadline
        ]
        for conn in expired:
            if conn.inflight:
                # a dispatched request whose completion never came back
                conn.seq += 1
                self._error_out(conn, 504, _BODY_504)
            elif conn.rbuf or conn.header_end >= 0:
                # slow loris: a started request that never finished
                self._error_out(conn, 408, _BODY_408)
            else:
                self._close(conn)  # idle keep-alive expiry

    # -- lifecycle ----------------------------------------------------------

    def _close(self, conn: _Conn) -> None:
        if self.conns.pop(conn.fd, None) is None:
            return
        if conn.registered_mask != 0:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn.registered_mask = 0
        try:
            conn.sock.close()
        except OSError:
            pass
        conn.seq += 1  # drop any straggler completions

    def run(self) -> None:
        self._thread_id = threading.get_ident()
        self.sel.register(self.lsock, selectors.EVENT_READ, "accept")
        self.sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        try:
            while not self._stop.is_set():
                events = self.sel.select(timeout=0.05)
                for key, mask in events:
                    data = key.data
                    if data == "accept":
                        self._on_accept()
                    elif data == "wake":
                        self._on_wake()
                    else:
                        conn = data
                        if conn.fd not in self.conns:
                            continue  # closed earlier this wakeup
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(conn)
                        if (
                            mask & selectors.EVENT_READ
                            and conn.fd in self.conns
                        ):
                            self._on_readable(conn)
                self._drain_completions()
                self._sweep(time.monotonic())
        finally:
            for conn in list(self.conns.values()):
                self._close(conn)
            try:
                self.sel.unregister(self.lsock)
            except (KeyError, ValueError, OSError):
                pass
            self.sel.close()
            self._wake_r.close()
            self._wake_w.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass


def _bind(host: str, port: int, reuseport: bool,
          backlog: int) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if reuseport:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, port))
    s.listen(backlog)
    s.setblocking(False)
    return s


class EventLoopHTTPServer:
    """N acceptor loops over one (host, port).  ``handler`` is the
    adapter callable; ``on_protocol_error`` (optional) is invoked with
    the status of loop-generated 400/408/413/504 responses so adapters
    can keep their error counters."""

    def __init__(
        self,
        handler: Callable[[HTTPRequest, ConnHandle], Optional[Response]],
        host: str = "127.0.0.1",
        port: int = 0,
        config: EventLoopConfig = EventLoopConfig(),
        on_protocol_error: Optional[Callable[[int], None]] = None,
    ):
        self.handler = handler
        self.config = config
        self.on_protocol_error = on_protocol_error
        n = max(1, int(config.acceptors))
        reuseport = n > 1 and hasattr(socket, "SO_REUSEPORT")
        first = _bind(host, port, reuseport, config.backlog)
        self.server_address = first.getsockname()
        socks = [first]
        for _ in range(n - 1):
            if not reuseport:
                break
            socks.append(_bind(
                host, self.server_address[1], True, config.backlog
            ))
        self._loops = [_AcceptorLoop(self, s) for s in socks]
        self._threads: List[threading.Thread] = []
        self._threads_lock = threading.Lock()
        self._started = threading.Event()
        self._stopped = threading.Event()

    # -- ThreadingHTTPServer-compatible surface ----------------------------

    def serve_forever(self) -> None:
        """Run every loop (extra loops on daemon threads, the first on
        the calling thread) until :meth:`shutdown`."""
        self._stopped.clear()
        self._started.set()
        # spawn under the lock: a shutdown() racing this loop would
        # otherwise join a partial list and leak later-started threads
        with self._threads_lock:
            for loop in self._loops[1:]:
                t = threading.Thread(
                    target=loop.run, name="http-eventloop", daemon=True
                )
                t.start()
                self._threads.append(t)
        try:
            self._loops[0].run()
        finally:
            self._stopped.set()

    def shutdown(self) -> None:
        for loop in self._loops:
            loop.stop()
        with self._threads_lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5.0)

    def server_close(self) -> None:
        self.shutdown()
        for loop in self._loops:
            try:
                loop.lsock.close()
            except OSError:
                pass
        closer = getattr(self.handler, "close", None)
        if closer is not None:
            closer()
