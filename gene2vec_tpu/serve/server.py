"""JSON HTTP API over the registry + engine + batcher stack.

Endpoints (all JSON; schema in docs/SERVING.md):

* ``POST /v1/similar``     — ``{"genes": [...]}`` or ``{"vectors":
  [[...]]}`` + ``"k"`` -> per-query neighbor lists (gene queries drop
  the query row itself from its own neighbors);
* ``POST /v1/embedding``   — raw embedding rows for named genes;
* ``POST /v1/interaction`` — GGIPNN softmax scores for gene pairs;
* ``GET  /v1/genes``       — a slice of the served vocab (loadgen uses
  this to draw realistic query keys);
* ``GET  /healthz``        — **readiness**: served model version + queue
  facts while a model is loaded, 503 ``not_ready`` until then (fleet
  supervisors and external probes must not route to an empty replica);
* ``GET  /livez``          — **liveness**: 200 whenever the process can
  answer HTTP at all, model or no model;
* ``GET  /metrics``        — the obs Prometheus registry, text format.

Status mapping: queue-full backpressure -> **429**, per-request deadline
-> **504**, unknown gene / malformed body -> **400**, no model loaded ->
**503**, stalled request body (slow loris) -> **408** + connection
close.  The handler layer is a thin stdlib ``ThreadingHTTPServer``
shell; every route is a method on :class:`ServeApp`, which tests drive
directly and through ephemeral-port HTTP.

Every connection runs under a read deadline (``ServeConfig.
read_timeout_s``): the socket timeout bounds each recv, and the body
read additionally runs under a per-request wall deadline, so a client
dripping one byte per poll cannot pin a handler thread past the
deadline either.  Fault injection (``resilience/faults.py``) hooks the
handler behind an explicit opt-in (``--faults`` /
``GENE2VEC_TPU_FAULTS``) and is entirely absent otherwise.

Each request runs under an obs span (``serve_request``), batches under
``serve_batch``/``serve_compute`` (batcher.py) — with a
:class:`~gene2vec_tpu.obs.run.Run` installed (cli/serve.py always makes
one) the whole enqueue->batch->compute->respond pipeline lands in that
run's ``events.jsonl`` and ``/metrics`` serves its registry.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from gene2vec_tpu.obs import flight as flight_mod
from gene2vec_tpu.obs import tracecontext
from gene2vec_tpu.obs.flight import FlightRecorder
from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.obs.tracecontext import Sampler, TraceContext
from gene2vec_tpu.serve.routes import V1_ROUTES
from gene2vec_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    RejectedError,
)
from gene2vec_tpu.serve.engine import SimilarityEngine
from gene2vec_tpu.serve.interaction import InteractionScorer
from gene2vec_tpu.serve.registry import ModelRegistry


class ApiError(Exception):
    """Route failure with an HTTP status."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine/batcher/queue policy knobs (cli/serve.py flags)."""

    max_batch: int = 64
    max_delay_ms: float = 5.0
    max_queue: int = 256
    cache_size: int = 4096
    timeout_ms: float = 2000.0
    max_k: int = 256
    max_queries_per_request: int = 64
    # per-connection read deadline: bounds both each socket recv and the
    # total wall time spent reading one request body (slow-loris guard;
    # expiry -> 408 + close)
    read_timeout_s: float = 10.0
    # root-trace sampling rate for requests WITHOUT a traceparent
    # header (0 = trace only when the caller propagates a sampled
    # context; sampled callers are always honored)
    trace_sample: float = 0.0


#: routes whose latency gets its own labeled histogram series; anything
#: else collapses into "other" so garbage paths can't mint label sets
_KNOWN_ROUTES = V1_ROUTES | frozenset((
    "/", "/livez", "/healthz", "/metrics",
))

#: powers-of-two seconds buckets, 0.5 ms .. ~8 s: fine enough that the
#: fleet aggregator's bucket-edge p50/p99 estimates are within 2x
_ROUTE_BUCKETS = tuple(0.0005 * (2 ** e) for e in range(15))


class ServeApp:
    """The route layer: owns the registry, engine, batcher, and scorer."""

    def __init__(
        self,
        registry: ModelRegistry,
        config: ServeConfig = ServeConfig(),
        metrics: Optional[MetricsRegistry] = None,
        ggipnn_checkpoint: Optional[str] = None,
        mesh=None,
        fault_injector=None,
    ):
        self.registry = registry
        self.config = config
        # resilience/faults.py FaultInjector — None means no fault code
        # runs at all (the production default)
        self.faults = fault_injector
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if self.faults is not None and self.faults.metrics is None:
            self.faults.metrics = self.metrics
        if registry.metrics is None:
            registry.metrics = self.metrics
        if registry.loaded:
            # the registry publishes these on swap; backfill for a model
            # loaded before the metrics registry was attached
            self.metrics.gauge("model_iteration").set(
                registry.model.iteration
            )
            self.metrics.gauge("model_vocab_size").set(len(registry.model))
        # mesh set => the two-stage distributed top-k over the
        # registry's row-sharded matrix (engine._make_topk_sharded)
        self.engine = SimilarityEngine(
            max_batch=config.max_batch, mesh=mesh
        )
        self.batcher = MicroBatcher(
            self._compute_batch,
            max_batch=config.max_batch,
            max_delay_s=config.max_delay_ms / 1000.0,
            max_queue=config.max_queue,
            cache_size=config.cache_size,
            default_timeout_s=config.timeout_ms / 1000.0,
            metrics=self.metrics,
        )
        self.ggipnn_checkpoint = ggipnn_checkpoint
        self._scorer: Optional[InteractionScorer] = None
        self._scorer_lock = threading.Lock()
        self._started = time.monotonic()
        # head sampler for headerless traffic; propagated sampled
        # contexts bypass it (the root already decided)
        self.sampler = (
            Sampler(config.trace_sample) if config.trace_sample > 0
            else None
        )
        # always-on bounded ring of recent requests; cli/serve.py sets
        # flight_dir (the run dir) and installs the SIGQUIT dump — a
        # 5xx burst dumps from the handler path below
        self.flight = FlightRecorder()
        self.flight_dir: Optional[str] = None

    def start(self) -> "ServeApp":
        self.batcher.start()
        return self

    def stop(self) -> None:
        self.batcher.stop()
        self.registry.stop_watcher()

    # -- batch compute (worker thread) ------------------------------------

    def _compute_batch(self, items: List[dict], k_max: int) -> List[dict]:
        """Resolve every queued query against ONE model snapshot and run
        the padded top-k.  Items resolved here (not at submit) so a hot
        swap mid-queue cannot mix two iterations inside one batch."""
        model = self.registry.model
        vectors: List[np.ndarray] = []
        self_rows: List[Optional[int]] = []
        for item in items:
            if "gene" in item:
                row = model.index.get(item["gene"])
                if row is None:
                    # swapped away between admission and compute —
                    # per-item failure, the rest of the batch proceeds
                    vectors.append(np.zeros(model.dim, np.float32))
                    self_rows.append(-2)
                    continue
                vectors.append(model.emb[row])
                self_rows.append(row)
            else:
                vectors.append(
                    np.asarray(item["vector"], dtype=np.float32)
                )
                self_rows.append(None)
        # gene queries ask one extra so dropping the self-hit still
        # leaves k neighbors
        kq = min(k_max + 1, len(model))
        neighbors = self.engine.similar_batch(model, vectors, kq)
        out: List[dict] = []
        for item, row, hits in zip(items, self_rows, neighbors):
            if row == -2:
                out.append(
                    {"error": f"gene {item['gene']!r} not in the "
                              f"served model (iteration "
                              f"{model.iteration})"}
                )
                continue
            if row is not None:
                gene = model.tokens[row]
                hits = [h for h in hits if h[0] != gene]
            out.append(
                {
                    "neighbors": [
                        {"gene": g, "score": round(s, 6)}
                        for g, s in hits[: item["k"]]
                    ],
                    "iteration": model.iteration,
                }
            )
        return out

    # -- routes ------------------------------------------------------------

    def _model_or_503(self):
        try:
            return self.registry.model
        except RuntimeError as e:
            raise ApiError(503, str(e)) from e

    def _validate_k(self, body: dict) -> int:
        k = body.get("k", 10)
        if not isinstance(k, int) or k < 1 or k > self.config.max_k:
            raise ApiError(
                400, f"k must be an int in [1, {self.config.max_k}]"
            )
        return k

    def similar(self, body: dict) -> dict:
        model = self._model_or_503()
        k = self._validate_k(body)
        timeout_s = self._timeout_s(body)
        genes = body.get("genes")
        vectors = body.get("vectors")
        if (genes is None) == (vectors is None):
            raise ApiError(
                400, "provide exactly one of 'genes' or 'vectors'"
            )
        queries: List[dict] = []
        if genes is not None:
            if not isinstance(genes, list) or not genes:
                raise ApiError(400, "'genes' must be a non-empty list")
            unknown = [g for g in genes if g not in model.index]
            if unknown:
                raise ApiError(
                    400,
                    f"unknown gene(s) {unknown[:5]!r} "
                    f"(model iteration {model.iteration})",
                )
            queries = [{"gene": g, "k": k} for g in genes]
        else:
            if not isinstance(vectors, list) or not vectors:
                raise ApiError(400, "'vectors' must be a non-empty list")
            for v in vectors:
                if not isinstance(v, list) or len(v) != model.dim:
                    raise ApiError(
                        400,
                        f"each vector must have dim {model.dim}",
                    )
            queries = [{"vector": v, "k": k} for v in vectors]
        if len(queries) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} queries "
                "per request",
            )
        # submit everything before waiting on anything, so one request's
        # queries share a batch window instead of paying it per query
        tickets = []
        try:
            for q in queries:
                cache_key = (
                    (model.version, "similar", q["gene"], k)
                    if "gene" in q else None
                )
                tickets.append(
                    (q, self.batcher.submit_async(
                        q, k, cache_key=cache_key, timeout_s=timeout_s
                    ))
                )
        except RejectedError as e:
            raise ApiError(429, str(e)) from e
        results = []
        for q, ticket in tickets:
            try:
                r = ticket.get()
            except DeadlineExceeded as e:
                raise ApiError(504, str(e)) from e
            if "error" in r:
                raise ApiError(400, r["error"])
            results.append(
                {"query": q.get("gene"), "neighbors": r["neighbors"]}
            )
        return {
            "model": {"dim": model.dim, "iteration": model.iteration},
            "results": results,
        }

    def embedding(self, body: dict) -> dict:
        model = self._model_or_503()
        genes = body.get("genes")
        if not isinstance(genes, list) or not genes:
            raise ApiError(400, "'genes' must be a non-empty list")
        if len(genes) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} genes "
                "per request",
            )
        rows = []
        for g in genes:
            row = model.index.get(g)
            if row is None:
                raise ApiError(
                    400,
                    f"unknown gene {g!r} (model iteration "
                    f"{model.iteration})",
                )
            rows.append(
                {"gene": g, "vector": [float(v) for v in model.emb[row]]}
            )
        return {
            "model": {"dim": model.dim, "iteration": model.iteration},
            "embeddings": rows,
        }

    def _get_scorer(self, model) -> InteractionScorer:
        """Scorer bound to the served iteration; rebuilt after hot swap."""
        with self._scorer_lock:
            if self._scorer is None or self._scorer.version != model.version:
                with ambient_span(
                    "scorer_build", iteration=model.iteration
                ):
                    self._scorer = InteractionScorer(
                        model, checkpoint_path=self.ggipnn_checkpoint
                    )
            return self._scorer

    def interaction(self, body: dict) -> dict:
        model = self._model_or_503()
        pairs = body.get("pairs")
        if not isinstance(pairs, list) or not pairs or not all(
            isinstance(p, list) and len(p) == 2 for p in pairs
        ):
            raise ApiError(
                400, "'pairs' must be a non-empty list of [gene, gene]"
            )
        if len(pairs) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} pairs "
                "per request",
            )
        scorer = self._get_scorer(model)
        try:
            scores = scorer.score([tuple(p) for p in pairs])
        except KeyError as e:
            raise ApiError(
                400,
                f"unknown gene {e.args[0]!r} (model iteration "
                f"{model.iteration})",
            ) from e
        self.metrics.counter("serve_interaction_pairs_total").inc(
            len(pairs)
        )
        return {
            "model": {"dim": model.dim, "iteration": model.iteration},
            "trained_head": scorer.trained,
            "scores": [
                {"pair": p, "score": round(s, 6)}
                for p, s in zip(pairs, scores)
            ],
        }

    @staticmethod
    def _int_param(query: Dict[str, List[str]], name: str,
                   default: int) -> int:
        raw = query.get(name, [str(default)])[0]
        try:
            return int(raw)
        except ValueError:
            raise ApiError(
                400, f"{name} must be an integer, got {raw!r}"
            ) from None

    def genes(self, query: Dict[str, List[str]]) -> dict:
        model = self._model_or_503()
        limit = self._int_param(query, "limit", 100)
        offset = self._int_param(query, "offset", 0)
        if limit < 0 or offset < 0:
            raise ApiError(400, "limit/offset must be >= 0")
        return {
            "total": len(model),
            "genes": list(model.tokens[offset : offset + limit]),
        }

    def livez(self) -> dict:
        """Liveness: the process answers HTTP.  Never inspects the
        registry — a replica mid-load (or quarantined with no fallback)
        is alive-but-not-ready, and restarting it would only lose the
        load progress."""
        return {
            "status": "alive",
            "uptime_s": round(time.monotonic() - self._started, 3),
        }

    def healthz(self) -> Tuple[int, dict]:
        """Readiness: 200 with model facts once a model is served; 503
        ``not_ready`` until then, so fleet routers and external probes
        never send traffic to an empty replica."""
        ready = self.registry.loaded
        out = {
            "status": "ok" if ready else "not_ready",
            "uptime_s": round(time.monotonic() - self._started, 3),
            "queue_depth": len(self.batcher._q),
            "max_queue": self.config.max_queue,
        }
        if not ready:
            quarantined = getattr(self.registry, "quarantined", {})
            out["reason"] = (
                "every discovered checkpoint is quarantined"
                if quarantined else "no model loaded yet"
            )
            return 503, out
        m = self.registry.model
        out["model"] = {
            "dim": m.dim,
            "iteration": m.iteration,
            "vocab_size": len(m),
            "source": m.source,
        }
        return 200, out

    def _timeout_s(self, body: dict) -> Optional[float]:
        t = body.get("timeout_ms")
        if t is None:
            return None
        if not isinstance(t, (int, float)) or t <= 0:
            raise ApiError(400, "timeout_ms must be a positive number")
        return float(t) / 1000.0

    # -- dispatch ----------------------------------------------------------

    def _dispatch(
        self, method: str, route: str, query: Dict[str, List[str]],
        body: Optional[dict],
    ) -> Tuple[int, dict]:
        if method == "GET" and route == "/livez":
            return 200, self.livez()
        if method == "GET" and route == "/healthz":
            status, doc = self.healthz()
            return status, doc
        if method == "GET" and route == "/v1/genes":
            return 200, self.genes(query)
        if method == "GET" and route == "/v1/similar":
            gene = query.get("gene", [None])[0]
            if gene is None:
                raise ApiError(400, "missing ?gene= parameter")
            k = self._int_param(query, "k", 10)
            return 200, self.similar({"genes": [gene], "k": k})
        if method == "POST" and route == "/v1/similar":
            return 200, self.similar(body or {})
        if method == "POST" and route == "/v1/embedding":
            return 200, self.embedding(body or {})
        if method == "POST" and route == "/v1/interaction":
            return 200, self.interaction(body or {})
        return 404, {"error": f"no route {method} {route}"}

    def handle(
        self, method: str, path: str, body: Optional[dict],
        traceparent: Optional[str] = None,
    ) -> Tuple[int, dict]:
        """(status, payload) for one request.  ``/metrics`` is the only
        non-JSON route and is dispatched by the handler directly.

        ``traceparent`` is the caller's propagated trace context: a
        sampled one makes this request (and its batcher/engine hops) a
        child span of the sender's attempt; without one, the server's
        own sampler may start a root.  Untraced requests pay one header
        parse and nothing else."""
        url = urlparse(path)
        route = url.path.rstrip("/") or "/"
        query = parse_qs(url.query)
        incoming = TraceContext.from_header(traceparent)
        ctx = incoming.child() if incoming is not None else (
            self.sampler.maybe_new_trace()
            if self.sampler is not None else None
        )
        t0 = time.monotonic()
        status = 500
        hops: Dict[str, float] = {}
        try:
            with tracecontext.use(ctx), flight_mod.collect_hops() as hops:
                with ambient_span("serve_request", route=route) as span:
                    status, doc = self._dispatch(method, route, query, body)
                    span["status"] = status
            return status, doc
        except ApiError as e:
            self.metrics.counter(
                f"serve_http_{e.status}_total"
            ).inc()
            status = e.status
            return e.status, {"error": str(e)}
        except Exception as e:  # route crash -> 500, server stays up
            self.metrics.counter("serve_http_500_total").inc()
            status = 500
            return 500, {"error": f"internal error: {e!r}"}
        finally:
            dur = time.monotonic() - t0
            self.metrics.histogram("serve_handle_seconds").observe(dur)
            self.metrics.histogram(
                "serve_route_seconds",
                buckets=_ROUTE_BUCKETS,
                labels={
                    "route": route if route in _KNOWN_ROUTES else "other"
                },
            ).observe(dur)
            burst = self.flight.record(
                route, status, dur,
                trace_id=ctx.trace_id if ctx is not None else None,
                hops=hops,
            )
            if burst and self.flight_dir:
                try:
                    self.flight.dump(self.flight_dir, "5xx-burst")
                except OSError:
                    pass  # a full disk must not take the handler down


class _Handler(BaseHTTPRequestHandler):
    # one keep-alive friendly protocol version; loadgen reuses sockets
    protocol_version = "HTTP/1.1"
    app: ServeApp  # set by make_server on the server class

    def setup(self) -> None:
        # the socket timeout is the slow-loris guard's first layer: it
        # bounds every recv (request line, headers, idle keep-alive) so
        # a silent client can't hold a handler thread past the deadline
        self.timeout = self.server.app.config.read_timeout_s  # type: ignore[attr-defined]
        super().setup()

    def finish(self) -> None:
        # a connection torn down mid-reply (client gone, injected RST)
        # must not traceback through socketserver's handle_error
        try:
            super().finish()
        except OSError:
            pass

    def log_message(self, format: str, *args) -> None:
        # default writes per-request lines to stderr; serve volume makes
        # that noise — request accounting lives in /metrics instead
        pass

    def _reply(self, status: int, payload: bytes, content_type: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, status: int, doc: dict) -> None:
        self._reply(
            status,
            json.dumps(doc).encode("utf-8"),
            "application/json",
        )

    def _inject_fault(self, route: str) -> bool:
        """Apply the configured fault decision for this request, if any.
        Returns True when the fault terminated the request (a reply was
        substituted, the connection was reset, or the response was
        blackholed) — the caller must not dispatch."""
        app = self.server.app  # type: ignore[attr-defined]
        if app.faults is None:
            return False
        decision = app.faults.decide(route)
        if decision is None:
            return False
        if decision.delay_s:
            time.sleep(decision.delay_s)
        if decision.kind is None:
            return False  # pure added latency; proceed normally
        self.close_connection = True
        if decision.kind == "error":
            self._reply_json(
                int(decision.arg),
                {"error": "injected fault (resilience drill)"},
            )
        elif decision.kind == "reset":
            from gene2vec_tpu.resilience.faults import apply_reset

            apply_reset(self.connection)
        elif decision.kind == "blackhole":
            # hold the socket open, answer nothing: the client's read
            # timeout is the only way out (bounded so the drill's own
            # handler threads drain)
            time.sleep(decision.arg)
        return True

    def _read_body(self, length: int) -> bytes:
        """Read exactly ``length`` body bytes under BOTH timeout layers:
        the per-recv socket timeout (already armed in :meth:`setup`) and
        a wall deadline of ``read_timeout_s`` for the whole body — a
        client dripping one byte per recv window defeats the former but
        not the latter."""
        deadline = time.monotonic() + self.server.app.config.read_timeout_s  # type: ignore[attr-defined]
        chunks: List[bytes] = []
        got = 0
        try:
            while got < length:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout(
                        "request body read deadline exceeded"
                    )
                self.connection.settimeout(min(remaining, self.timeout))
                # read1 = at most ONE underlying recv: a client dripping
                # single bytes returns here every drip, so the
                # wall-deadline check above actually runs (plain read(n)
                # loops inside the buffer until n bytes arrive and each
                # drip resets its recv window — the deadline would never
                # be consulted)
                chunk = self.rfile.read1(min(65536, length - got))
                if not chunk:
                    break  # client closed early; json parsing reports it
                chunks.append(chunk)
                got += len(chunk)
        finally:
            # keep-alive: the NEXT request on this connection gets the
            # full per-recv window back, not this body's leftover slice
            try:
                self.connection.settimeout(self.timeout)
            except OSError:
                pass  # connection already torn down mid-read
        return b"".join(chunks)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        app = self.server.app  # type: ignore[attr-defined]
        route = urlparse(self.path).path.rstrip("/") or "/"
        if self._inject_fault(route):
            return
        if route == "/metrics":
            self._reply(
                200,
                app.metrics.prometheus_text().encode("utf-8"),
                "text/plain; version=0.0.4",
            )
            return
        status, doc = app.handle(
            "GET", self.path, None,
            traceparent=self.headers.get("traceparent"),
        )
        self._reply_json(status, doc)

    def do_POST(self) -> None:  # noqa: N802
        app = self.server.app  # type: ignore[attr-defined]
        if self._inject_fault(urlparse(self.path).path.rstrip("/") or "/"):
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self._read_body(length) if length else b"{}"
            body = json.loads(raw.decode("utf-8")) if raw else {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except socket.timeout:
            # slow loris: the client stalled mid-body.  408, then close —
            # the handler thread is unpinned and the socket reaped.
            app.metrics.counter("serve_http_408_total").inc()
            self.close_connection = True
            try:
                self._reply_json(
                    408, {"error": "request body read timed out"}
                )
            except OSError:
                pass  # client is gone too; nothing to tell it
            return
        except (ValueError, UnicodeDecodeError) as e:
            self._reply_json(400, {"error": f"bad JSON body: {e}"})
            return
        status, doc = app.handle(
            "POST", self.path, body,
            traceparent=self.headers.get("traceparent"),
        )
        self._reply_json(status, doc)


def make_server(
    app: ServeApp, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """A ``ThreadingHTTPServer`` bound to (host, port) — port 0 picks an
    ephemeral one (``server.server_address[1]`` has it).  The caller owns
    the serve loop (``serve_forever`` on a thread for tests, blocking in
    cli/serve.py) and shutdown ordering: ``server.shutdown()`` then
    ``app.stop()``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.app = app  # type: ignore[attr-defined]
    return server
